"""Native Parquet page reader — footer/page parsing for the device decode
path.

Reference analog: GpuParquetScan's host side (SURVEY.md §3.4): the
reference parses footers and stitches row-group bytes ON THE HOST, then
hands buffers to cuDF's device decode kernels.  This module is that host
half for the TPU build: a thrift-compact FileMetaData/PageHeader parser,
page walker, and RLE/bit-packed-hybrid RUN SPLITTER.  The device half
(spark_rapids_tpu/pallas/decode.py) expands runs / unpacks bits / gathers
dictionaries with Pallas kernels.

Host work is O(#pages + #runs), never O(#values): run headers are varints
scanned on the host; the value bytes upload untouched.

Supported subset (else the scan silently falls back to the pyarrow host
decode): non-nested columns of INT32/INT64/DOUBLE/FLOAT/BOOLEAN plus
DICTIONARY-encoded BYTE_ARRAY strings (the dominant TPC-DS scan shape:
the small dict page parses on host into a padded char matrix, the
index stream expands + gathers on device), data pages v1 AND v2, PLAIN or
RLE_DICTIONARY/PLAIN_DICTIONARY encodings, UNCOMPRESSED, SNAPPY (from-
scratch block decoder, native/host_kernels.cpp) or ZSTD codec
(PLAIN byte_array data pages
interleave lengths with bytes and would need an O(values) host walk).
"""
from __future__ import annotations

import dataclasses
import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

MAGIC = b"PAR1"

# thrift compact type ids
_CT_STOP = 0
_CT_TRUE = 1
_CT_FALSE = 2
_CT_BYTE = 3
_CT_I16 = 4
_CT_I32 = 5
_CT_I64 = 6
_CT_DOUBLE = 7
_CT_BINARY = 8
_CT_LIST = 9
_CT_SET = 10
_CT_MAP = 11
_CT_STRUCT = 12


class _Thrift:
    """Minimal thrift compact-protocol reader -> {field_id: value} dicts."""

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def varint(self) -> int:
        shift = acc = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                return acc
            shift += 7

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) ^ -(v & 1)

    def read_value(self, ctype: int):
        if ctype in (_CT_TRUE, _CT_FALSE):
            return ctype == _CT_TRUE
        if ctype == _CT_BYTE:
            v = self.buf[self.pos]
            self.pos += 1
            return v - 256 if v >= 128 else v
        if ctype in (_CT_I16, _CT_I32, _CT_I64):
            return self.zigzag()
        if ctype == _CT_DOUBLE:
            v = struct.unpack_from("<d", self.buf, self.pos)[0]
            self.pos += 8
            return v
        if ctype == _CT_BINARY:
            n = self.varint()
            v = self.buf[self.pos:self.pos + n]
            self.pos += n
            return v
        if ctype == _CT_LIST or ctype == _CT_SET:
            head = self.buf[self.pos]
            self.pos += 1
            size = head >> 4
            etype = head & 0x0F
            if size == 15:
                size = self.varint()
            return [self.read_value(etype) for _ in range(size)]
        if ctype == _CT_STRUCT:
            return self.read_struct()
        if ctype == _CT_MAP:
            size = self.varint()
            if size == 0:
                return {}
            kv = self.buf[self.pos]
            self.pos += 1
            kt, vt = kv >> 4, kv & 0x0F
            return {self.read_value(kt): self.read_value(vt)
                    for _ in range(size)}
        raise ValueError(f"thrift compact type {ctype}")

    def read_struct(self) -> Dict[int, object]:
        out: Dict[int, object] = {}
        last_id = 0
        while True:
            head = self.buf[self.pos]
            self.pos += 1
            if head == _CT_STOP:
                return out
            delta = head >> 4
            ctype = head & 0x0F
            if delta == 0:
                fid = self.zigzag()
            else:
                fid = last_id + delta
            last_id = fid
            if ctype in (_CT_TRUE, _CT_FALSE):
                out[fid] = ctype == _CT_TRUE
            else:
                out[fid] = self.read_value(ctype)


# parquet enums (format/parquet.thrift)
TYPE_BOOLEAN, TYPE_INT32, TYPE_INT64 = 0, 1, 2
TYPE_FLOAT, TYPE_DOUBLE, TYPE_BYTE_ARRAY = 4, 5, 6
ENC_PLAIN, ENC_PLAIN_DICT, ENC_RLE = 0, 2, 3
ENC_RLE_DICT = 8
CODEC_UNCOMPRESSED, CODEC_SNAPPY, CODEC_ZSTD = 0, 1, 6
PAGE_DATA, PAGE_DICT, PAGE_DATA_V2 = 0, 2, 3


@dataclasses.dataclass
class ColumnInfo:
    name: str
    ptype: int
    optional: bool
    codec: int
    encodings: List[int]
    num_values: int
    data_page_offset: int
    dict_page_offset: Optional[int]
    total_compressed: int


@dataclasses.dataclass
class RowGroupInfo:
    num_rows: int
    columns: List[ColumnInfo]


@dataclasses.dataclass
class Run:
    """One RLE/bit-packed hybrid run (host-parsed header, device-expanded
    payload)."""

    is_packed: bool
    count: int        # values in the run
    value: int        # RLE repeated value (is_packed=False)
    byte_off: int     # payload offset into the level/index buffer
    nbytes: int


def read_footer(data: bytes) -> Tuple[List[RowGroupInfo], List[str]]:
    if len(data) < 12 or data[:4] != MAGIC or data[-4:] != MAGIC:
        raise ValueError("not a parquet file")
    flen = struct.unpack_from("<I", data, len(data) - 8)[0]
    if flen > len(data) - 12:
        err = ValueError(
            f"parquet footer truncated (footer length {flen} exceeds "
            f"file size {len(data)})")
        err.srt_offset = len(data) - 8
        raise err
    try:
        return _read_footer_meta(data, flen)
    except (IndexError, struct.error, KeyError, TypeError) as e:
        # byte-offset context for the fault classifier / quarantine
        err = ValueError(
            f"corrupt parquet footer metadata near byte "
            f"{len(data) - 8 - flen} ({type(e).__name__}: {e})")
        err.srt_offset = len(data) - 8 - flen
        raise err from e


def _read_footer_meta(data: bytes, flen: int):
    meta = _Thrift(data, len(data) - 8 - flen).read_struct()
    schema = meta[2]
    # schema[0] is the root; leaves follow in order (non-nested only)
    names, optional, ptypes = [], {}, {}
    for el in schema[1:]:
        name = el[4].decode()
        names.append(name)
        optional[name] = el.get(3, 0) == 1  # repetition OPTIONAL
        ptypes[name] = el.get(1)
    groups = []
    for rg in meta[4]:
        cols = []
        for cc in rg[1]:
            md = cc[3]
            path = b".".join(md[3]).decode()
            cols.append(ColumnInfo(
                name=path, ptype=md[1],
                optional=optional.get(path, True),
                codec=md[4], encodings=md[2], num_values=md[5],
                data_page_offset=md[9],
                dict_page_offset=md.get(11),
                total_compressed=md[7]))
        groups.append(RowGroupInfo(num_rows=rg[3], columns=cols))
    return groups, names


def _decompress(buf: bytes, codec: int, usize: int) -> bytes:
    if codec == CODEC_UNCOMPRESSED:
        return buf
    if codec == CODEC_SNAPPY:
        from spark_rapids_tpu.native import snappy_uncompress

        return snappy_uncompress(buf, usize)
    if codec == CODEC_ZSTD:
        try:
            import zstandard
        except ImportError:
            # no zstandard wheel: the pyarrow host decode reads zstd
            # fine, so this is the documented silent fallback — NOT a
            # decoder failure (an ImportError escaping here used to
            # count against the decoder and feed its breaker)
            raise _Unsupported("zstd: zstandard module unavailable")

        return zstandard.ZstdDecompressor().decompress(buf, max_output_size=usize)
    raise _Unsupported(f"codec {codec}")


class _Unsupported(Exception):
    """Feature outside the device-decode subset -> pyarrow fallback."""


def split_hybrid_runs(buf: bytes, bit_width: int,
                      total: int) -> List[Run]:
    """Parse RLE/bit-packed hybrid run headers (no value decode)."""
    runs: List[Run] = []
    t = _Thrift(buf)
    got = 0
    vbytes = (bit_width + 7) // 8
    while got < total and t.pos < len(buf):
        header = t.varint()
        if header & 1:
            groups = header >> 1
            count = groups * 8
            nbytes = groups * bit_width
            runs.append(Run(True, min(count, total - got), 0, t.pos,
                            nbytes))
            t.pos += nbytes
        else:
            count = header >> 1
            raw = buf[t.pos:t.pos + vbytes]
            value = int.from_bytes(raw, "little") if vbytes else 0
            runs.append(Run(False, min(count, total - got), value, t.pos,
                            vbytes))
            t.pos += vbytes
        got += runs[-1].count
    return runs


@dataclasses.dataclass
class PageData:
    """One decoded-on-host-STRUCTURE data page: raw bytes stay packed.

    The ``raw_*`` fields (ISSUE 6 compressed transfer) describe the page
    region AS STORED IN THE FILE so the device path can ship compressed
    bytes across the link and decompress them there: ``raw_values`` is
    the stored bytes covering the value stream (the whole page for v1;
    the separately-compressed values region for v2), ``raw_usize`` its
    decompressed size, ``value_off``/``def_off`` the byte offsets of the
    value / definition-level payloads inside the DECOMPRESSED region
    (``def_off`` None when the levels live outside it — v2, or a
    required column)."""

    num_values: int
    encoding: int
    def_runs: Optional[List[Run]]   # None: required column
    def_buf: Optional[bytes]
    value_buf: bytes                # PLAIN values or packed indices
    index_bit_width: int            # dictionary index width (dict pages)
    raw_values: Optional[bytes] = None
    raw_codec: int = CODEC_UNCOMPRESSED
    raw_usize: int = 0
    value_off: int = 0
    def_off: Optional[int] = None


@dataclasses.dataclass
class ColumnPages:
    info: ColumnInfo
    dictionary: Optional[np.ndarray]  # decoded dict values (PLAIN, host view)
    pages: List[PageData]
    # BYTE_ARRAY dictionaries: padded (ndict, width) uint8 + per-entry len
    dict_chars: Optional[np.ndarray] = None
    dict_lens: Optional[np.ndarray] = None


_PLAIN_DTYPES = {TYPE_INT32: np.int32, TYPE_INT64: np.int64,
                 TYPE_FLOAT: np.float32, TYPE_DOUBLE: np.float64}


def _parse_byte_array_dict(raw: bytes, n: int):
    """PLAIN byte_array dictionary page -> (padded chars, lengths)."""
    lens = np.empty(n, np.int32)
    offs = np.empty(n, np.int64)
    pos = 0
    for i in range(n):
        ln = struct.unpack_from("<I", raw, pos)[0]
        lens[i] = ln
        offs[i] = pos + 4
        pos += 4 + ln
    w = max(int(lens.max()) if n else 1, 1)
    chars = np.zeros((max(n, 1), w), np.uint8)
    buf = np.frombuffer(raw, np.uint8)
    for i in range(n):
        chars[i, :lens[i]] = buf[offs[i]: offs[i] + lens[i]]
    return chars, lens


def read_column_pages(data: bytes, info: ColumnInfo,
                      num_rows: int) -> ColumnPages:
    if (info.ptype not in _PLAIN_DTYPES
            and info.ptype not in (TYPE_BOOLEAN, TYPE_BYTE_ARRAY)):
        raise _Unsupported(f"parquet type {info.ptype}")
    start = (info.dict_page_offset
             if info.dict_page_offset is not None
             and 0 < info.dict_page_offset < info.data_page_offset
             else info.data_page_offset)
    pos = start
    end = start + info.total_compressed
    dictionary = None
    dict_chars = dict_lens = None
    pages: List[PageData] = []
    values_seen = 0
    while pos < end and values_seen < info.num_values:
        try:
            t = _Thrift(data, pos)
            header = t.read_struct()
        except (IndexError, struct.error) as e:
            # byte-offset context for the fault classifier / quarantine
            err = ValueError(
                f"corrupt parquet page header for column "
                f"{info.name!r} near byte {pos} "
                f"({type(e).__name__}: {e})")
            err.srt_offset = pos
            raise err from e
        pos = t.pos
        ptype = header[1]
        usize = header[2]
        csize = header[3]
        page_raw = data[pos:pos + csize]
        pos += csize
        if ptype == PAGE_DICT:
            raw = _decompress(page_raw, info.codec, usize)
            dph = header[7]
            n = dph[1]
            if info.ptype == TYPE_BOOLEAN:
                raise _Unsupported("boolean dictionary")
            if info.ptype == TYPE_BYTE_ARRAY:
                dict_chars, dict_lens = _parse_byte_array_dict(raw, n)
                dictionary = np.arange(n)  # presence marker
            else:
                dictionary = np.frombuffer(
                    raw, _PLAIN_DTYPES[info.ptype], count=n)
            continue
        if ptype == PAGE_DATA_V2:
            # v2: def/rep levels sit UNCOMPRESSED before the (optionally
            # compressed) values; def levels have no 4-byte length prefix
            dp2 = header[8]
            nvals = dp2[1]
            enc = dp2[4]
            dll = dp2.get(5, 0) or 0
            rll = dp2.get(6, 0) or 0
            if rll:
                raise _Unsupported("repetition levels (nested)")
            compressed = dp2.get(7, True)
            def_runs = None
            def_buf = None
            if info.optional and dll:
                def_buf = page_raw[:dll]
                def_runs = split_hybrid_runs(def_buf, 1, nvals)
            vraw_stored = page_raw[dll + rll:]
            vraw = vraw_stored
            if compressed:
                vraw = _decompress(vraw, info.codec, usize - dll - rll)
            off = 0
            ibw = 0
            if enc in (ENC_PLAIN_DICT, ENC_RLE_DICT):
                ibw = vraw[off]
                off += 1
            elif enc != ENC_PLAIN:
                raise _Unsupported(f"encoding {enc}")
            pages.append(PageData(
                nvals, enc, def_runs, def_buf, vraw[off:], ibw,
                raw_values=vraw_stored,
                raw_codec=info.codec if compressed else CODEC_UNCOMPRESSED,
                raw_usize=len(vraw), value_off=off, def_off=None))
            values_seen += nvals
            continue
        if ptype != PAGE_DATA:
            raise _Unsupported(f"page type {ptype}")
        raw = _decompress(page_raw, info.codec, usize)
        dp = header[5]
        nvals = dp[1]
        enc = dp[2]
        off = 0
        def_runs = None
        def_buf = None
        if info.optional:
            if dp[3] != ENC_RLE:
                raise _Unsupported("definition level encoding")
            dlen = struct.unpack_from("<I", raw, 0)[0]
            def_buf = raw[4:4 + dlen]
            def_runs = split_hybrid_runs(def_buf, 1, nvals)
            off = 4 + dlen
        ibw = 0
        if enc in (ENC_PLAIN_DICT, ENC_RLE_DICT):
            ibw = raw[off]
            off += 1
        elif enc != ENC_PLAIN:
            raise _Unsupported(f"encoding {enc}")
        pages.append(PageData(
            nvals, enc, def_runs, def_buf, raw[off:], ibw,
            raw_values=page_raw, raw_codec=info.codec, raw_usize=len(raw),
            value_off=off,
            def_off=4 if def_runs is not None else None))
        values_seen += nvals
    return ColumnPages(info, dictionary, pages, dict_chars, dict_lens)
