"""TpuConf — the typed configuration registry.

Reference analog: com/nvidia/spark/rapids/RapidsConf.scala (~3k LoC, ~200+
``spark.rapids.*`` configs built with a typed-builder DSL and auto-documented
into docs/configs.md).  We reproduce the same pattern: every knob is declared
once with ``conf("spark.rapids.x").doc(...).boolean_conf().create_with_default``
-style builders, every expression/exec gets a per-op kill switch
(``spark.rapids.sql.expression.<Name>`` / ``spark.rapids.sql.exec.<Name>``),
and docs/gen_configs.py walks the registry to emit the config reference.

Config keys keep the ``spark.rapids.`` prefix so a user of the reference finds
the same names; TPU-specific knobs live under ``spark.rapids.tpu.*``.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, List, Optional

_REGISTRY: "Dict[str, ConfEntry]" = {}


class ConfEntry:
    def __init__(self, key: str, doc: str, conv: Callable[[str], Any],
                 default: Any, typ: str, internal: bool = False,
                 checker: Optional[Callable[[Any], None]] = None):
        self.key = key
        self.doc = doc
        self.conv = conv
        self.default = default
        self.typ = typ
        self.internal = internal
        self.checker = checker

    def get(self, settings: Dict[str, str]) -> Any:
        raw = settings.get(self.key)
        if raw is None:
            raw = os.environ.get("SRT_" + self.key.replace(".", "_").upper())
        if raw is None:
            return self.default
        v = self.conv(raw) if isinstance(raw, str) else raw
        if self.checker is not None:
            self.checker(v)
        return v


class _Builder:
    def __init__(self, key: str):
        self.key = key
        self._doc = ""
        self._internal = False
        self._checker = None

    def doc(self, d: str) -> "_Builder":
        self._doc = d
        return self

    def internal(self) -> "_Builder":
        self._internal = True
        return self

    def check(self, fn: Callable[[Any], None]) -> "_Builder":
        self._checker = fn
        return self

    def _register(self, conv, default, typ):
        e = ConfEntry(self.key, self._doc, conv, default, typ,
                      self._internal, self._checker)
        _REGISTRY[self.key] = e
        return e

    def boolean_conf(self, default: bool) -> ConfEntry:
        return self._register(lambda s: s.strip().lower() in ("true", "1", "yes"),
                              default, "boolean")

    def integer_conf(self, default: int) -> ConfEntry:
        return self._register(lambda s: int(s), default, "integer")

    def long_conf(self, default: int) -> ConfEntry:
        return self._register(lambda s: int(s), default, "long")

    def double_conf(self, default: float) -> ConfEntry:
        return self._register(lambda s: float(s), default, "double")

    def string_conf(self, default: Optional[str]) -> ConfEntry:
        return self._register(lambda s: s, default, "string")

    def bytes_conf(self, default: int) -> ConfEntry:
        return self._register(_parse_bytes, default, "bytes")


def conf(key: str) -> _Builder:
    return _Builder(key)


_UNITS = {"b": 1, "k": 1 << 10, "kb": 1 << 10, "m": 1 << 20, "mb": 1 << 20,
          "g": 1 << 30, "gb": 1 << 30, "t": 1 << 40, "tb": 1 << 40}


def _parse_bytes(s: str) -> int:
    s = s.strip().lower()
    for suffix in sorted(_UNITS, key=len, reverse=True):
        if s.endswith(suffix):
            num = s[: -len(suffix)].strip()
            if num:
                return int(float(num) * _UNITS[suffix])
    return int(s)


# ---------------------------------------------------------------------------
# The registry (RapidsConf.scala analog).  Grouped as the reference groups its
# docs: general / memory / sql / io / shuffle / tpu runtime / testing.
# ---------------------------------------------------------------------------

SQL_ENABLED = conf("spark.rapids.sql.enabled").doc(
    "Master enable for plan rewriting onto the TPU.").boolean_conf(True)

EXPLAIN = conf("spark.rapids.sql.explain").doc(
    "NONE, NOT_ON_GPU, or ALL: log why (parts of) a plan did or did not run "
    "on the TPU. NOT_ON_GPU prints only fallback reasons.").string_conf("NONE")

INCOMPATIBLE_OPS = conf("spark.rapids.sql.incompatibleOps.enabled").doc(
    "Enable ops whose TPU results differ from Spark in corner cases "
    "(e.g. float ordering in aggregations).").boolean_conf(True)

ANSI_ENABLED = conf("spark.sql.ansi.enabled").doc(
    "Spark ANSI mode: overflow/invalid-cast raise instead of null/wrap."
).boolean_conf(False)

CASE_SENSITIVE = conf("spark.sql.caseSensitive").doc(
    "Spark column-name case sensitivity.").boolean_conf(False)

HAS_NANS = conf("spark.rapids.sql.hasNans").doc(
    "Assume floating point data may contain NaNs (affects min/max/joins)."
).boolean_conf(True)

IMPROVED_FLOAT_OPS = conf("spark.rapids.sql.improvedFloatOps.enabled").doc(
    "Allow float ops that may differ from Spark in ULPs.").boolean_conf(True)

VARIABLE_FLOAT_AGG = conf("spark.rapids.sql.variableFloatAgg.enabled").doc(
    "Allow float aggregation whose result may vary with parallelism "
    "(non-deterministic order of adds).").boolean_conf(True)

# --- memory / runtime (GpuDeviceManager / RapidsConf memory group) ---------

CONCURRENT_TPU_TASKS = conf("spark.rapids.sql.concurrentGpuTasks").doc(
    "How many tasks may hold the TPU concurrently (admission semaphore; "
    "reference: GpuSemaphore).").integer_conf(2)

BATCH_SIZE_BYTES = conf("spark.rapids.sql.batchSizeBytes").doc(
    "Target columnar batch size; coalescing goal (reference: "
    "GpuCoalesceBatches).").bytes_conf(1 << 30)

MAX_READER_BATCH_SIZE_ROWS = conf(
    "spark.rapids.sql.reader.batchSizeRows").doc(
    "Soft cap on rows per batch produced by readers.").integer_conf(2147483647)

MAX_READER_BATCH_SIZE_BYTES = conf(
    "spark.rapids.sql.reader.batchSizeBytes").doc(
    "Soft cap on bytes per batch produced by readers.").bytes_conf(1 << 31)

HBM_POOL_FRACTION = conf("spark.rapids.memory.gpu.allocFraction").doc(
    "Fraction of HBM the arena may use for batches.").double_conf(0.9)

HBM_RESERVE = conf("spark.rapids.memory.gpu.reserve").doc(
    "HBM bytes reserved for XLA temporaries outside the arena."
).bytes_conf(640 << 20)

HOST_SPILL_STORAGE_SIZE = conf("spark.rapids.memory.host.spillStorageSize").doc(
    "Host memory for spilled device batches before disk.").bytes_conf(1 << 31)

SPILL_DIR = conf("spark.rapids.memory.spillDir").doc(
    "Directory for disk spill (reference: RapidsDiskStore).").string_conf(None)

RETRY_MAX_ATTEMPTS = conf("spark.rapids.tpu.retry.maxAttempts").doc(
    "Max OOM-retry attempts per batch before giving up (reference: "
    "RmmRapidsRetryIterator).").integer_conf(8)

SPLIT_UNTIL_ROWS = conf("spark.rapids.tpu.retry.minSplitRows").doc(
    "Do not split batches below this many rows on SplitAndRetry."
).integer_conf(8)

# --- query lifecycle (admission control / deadlines / cancellation) --------

CONCURRENT_QUERIES = conf("spark.rapids.tpu.concurrentQueries").doc(
    "How many queries may be admitted (planning + executing) at once; "
    "further collect() calls wait in a FIFO admission queue "
    "(lifecycle/admission.py — the query-level analog of "
    "spark.rapids.sql.concurrentGpuTasks, which gates device access "
    "*within* an admitted query).  0 disables admission control."
).integer_conf(4)

ADMISSION_MAX_QUEUE = conf("spark.rapids.tpu.admission.maxQueueDepth").doc(
    "Bound on queries waiting for admission; a collect() arriving at a "
    "full queue fast-rejects with QueryRejected instead of piling an "
    "unbounded convoy onto the process (load-shedding beats collapse)."
).integer_conf(16)

ADMISSION_QUEUE_TIMEOUT_MS = conf(
    "spark.rapids.tpu.admission.queueTimeoutMs").doc(
    "Max time a query waits in the admission queue before rejecting "
    "with QueryRejected.  0 waits indefinitely (still cancellable and "
    "deadline-trippable).").long_conf(0)

QUERY_TIMEOUT_MS = conf("spark.rapids.tpu.query.timeoutMs").doc(
    "Per-query deadline armed at collect(): a daemon watchdog thread "
    "trips the query's CancelToken once the deadline passes, and every "
    "blocking site (batch pulls, semaphore/admission waits, retry "
    "backoffs, shuffle pool tasks, AOT compile waits) raises "
    "QueryDeadlineExceeded cooperatively.  0 disables.").long_conf(0)

QUERY_WATCHDOG_PERIOD_MS = conf(
    "spark.rapids.tpu.query.watchdogPeriodMs").doc(
    "Scan period of the deadline watchdog thread; an expired query is "
    "tripped within one period and blocked waits notice within one "
    "more (the 2x-period abort bound).").double_conf(50.0)

SEMAPHORE_ACQUIRE_TIMEOUT_MS = conf(
    "spark.rapids.tpu.semaphore.acquireTimeoutMs").doc(
    "Max time a task waits for a TPU semaphore permit before raising "
    "SemaphoreTimeout (classified transient: the fault domain retries "
    "with backoff, by which time the convoy may have drained).  "
    "0 waits indefinitely.").long_conf(0)

# --- overload governor (graceful degradation under sustained pressure) -----

GOVERNOR_ENABLED = conf("spark.rapids.tpu.governor.enabled").doc(
    "Enable the process-global overload governor (governor/): an "
    "EWMA-smoothed GREEN/YELLOW/RED pressure state machine fused from "
    "HBM-pool occupancy, admission queue depth, the active-query "
    "table, the rolling p95, and cost-model predicted walls.  YELLOW "
    "shrinks batch-size goals and exchange partition budgets, pauses "
    "scan-prefetch run-ahead, and defers background AOT compiles; RED "
    "adds deadline-aware load shedding at admission, hot-table-cache "
    "eviction, and cooperative pause-and-spill preemption of the "
    "newest-admitted running query.  Disabled (the default): one "
    "ambient check per site, zero governor calls.").boolean_conf(False)

GOVERNOR_UPDATE_PERIOD_MS = conf(
    "spark.rapids.tpu.governor.updatePeriodMs").doc(
    "Minimum interval between pressure recomputations.  The governor "
    "has no thread of its own: every consult site (admission, batch "
    "pulls, the telemetry sampler) triggers an update at most this "
    "often — a consult inside the window reads the cached state."
).double_conf(50.0)

GOVERNOR_EWMA_ALPHA = conf("spark.rapids.tpu.governor.ewmaAlpha").doc(
    "EWMA smoothing weight for the fused pressure signal (higher = "
    "reacts faster, flaps easier).  Smoothing plus the separate "
    "up/down thresholds is what keeps an oscillating signal from "
    "flapping the state machine.").double_conf(0.4)

GOVERNOR_YELLOW_UP = conf(
    "spark.rapids.tpu.governor.yellowUpThreshold").doc(
    "Smoothed pressure at (or above) which GREEN enters YELLOW."
).double_conf(0.65)

GOVERNOR_YELLOW_DOWN = conf(
    "spark.rapids.tpu.governor.yellowDownThreshold").doc(
    "Smoothed pressure at (or below) which YELLOW re-enters GREEN.  "
    "Must sit below yellowUpThreshold — the gap is the hysteresis band "
    "that prevents flapping.").double_conf(0.45)

GOVERNOR_RED_UP = conf("spark.rapids.tpu.governor.redUpThreshold").doc(
    "Smoothed pressure at (or above) which the governor enters RED."
).double_conf(0.85)

GOVERNOR_RED_DOWN = conf("spark.rapids.tpu.governor.redDownThreshold").doc(
    "Smoothed pressure at (or below) which RED de-escalates (to YELLOW, "
    "or straight to GREEN when also at or below yellowDownThreshold)."
).double_conf(0.60)

GOVERNOR_DEGRADE_FRACTION = conf(
    "spark.rapids.tpu.governor.degradeBatchFraction").doc(
    "Under YELLOW/RED, batch-size goals (coalesce targets, exchange "
    "drain chunks) and exchange partition budgets shrink to this "
    "fraction of their configured value — smaller working sets per "
    "step trade throughput for bounded residency.").double_conf(0.5)

GOVERNOR_MAX_PAUSE_MS = conf("spark.rapids.tpu.governor.maxPauseMs").doc(
    "Upper bound on one cooperative pause-and-spill preemption: the "
    "preempted query spills its unpinned device batches at its next "
    "batch-pull boundary and waits until pressure leaves RED or this "
    "many ms pass, then resumes — it is never cancelled."
).long_conf(2000)

GOVERNOR_SHED_MIN_RETRY_MS = conf(
    "spark.rapids.tpu.governor.shedMinRetryMs").doc(
    "Floor for the retry_after_ms hint carried by a shed "
    "QueryRejected — clients backing off sooner than this would "
    "re-arrive before any pressure could drain.").long_conf(100)

GOVERNOR_HOT_CACHE_EVICT_FRACTION = conf(
    "spark.rapids.tpu.governor.hotCacheEvictFraction").doc(
    "Fraction of hot-table-cache bytes evicted (LRU-first) on each "
    "entry into RED — cached convenience data is the first ballast "
    "overboard.").double_conf(0.5)

GOVERNOR_BACKLOG_TARGET_MS = conf(
    "spark.rapids.tpu.governor.backlogTargetMs").doc(
    "Normalization for the cost-model backlog signal: the summed "
    "PR 8 predicted walls of admitted queries, divided by the "
    "admission limit, reads as pressure 1.0 at this many ms.  0 "
    "disables the predicted-wall component (the memory/queue/latency "
    "signals still drive the state machine).").long_conf(0)

# --- multi-tenant serving tier (ISSUE 19) ----------------------------------

SERVING_ENABLED = conf("spark.rapids.tpu.serving.enabled").doc(
    "Enable the multi-tenant serving tier (serving/): named tenant "
    "sessions with hard-isolated conf / temp views / cache handles / "
    "result fragments, a weighted fair-share scheduler replacing the "
    "FIFO admission order, tenant-aware governor shed/preempt "
    "decisions, and a per-tenant result-fragment cache.  Disabled (the "
    "default): one ambient check per site, zero serving-module calls."
).boolean_conf(False)

SERVING_TENANT = conf("spark.rapids.tpu.serving.tenant").doc(
    "Tenant identity of queries run under this conf.  Serving sessions "
    "set it automatically; it rides the QueryContext so admission "
    "fair-share, per-tenant SLO series, and governor shed/preempt "
    "decisions all attribute the query to its tenant.  Empty = "
    "untenanted (weight 1, no quota).").string_conf("")

SERVING_WEIGHTS = conf("spark.rapids.tpu.serving.weights").doc(
    "Per-tenant fair-share weights as 'tenantA:4,tenantB:1'.  The "
    "scheduler admits the eligible waiter with the lowest "
    "usage/weight — a tenant with weight 4 earns 4x the admission "
    "throughput of a weight-1 tenant under contention.  Unlisted "
    "tenants get weight 1.").string_conf("")

SERVING_QUOTAS = conf("spark.rapids.tpu.serving.quotas").doc(
    "Per-tenant concurrent-running quotas as 'tenantA:2,tenantB:1'.  A "
    "tenant at its quota is ineligible for the next admission slot "
    "while any under-quota tenant waits (work-conserving: with only "
    "over-quota waiters the slot is still granted).  Under RED "
    "pressure the governor sheds over-quota tenants' queries first.  "
    "Unlisted tenants are unbounded.").string_conf("")

SERVING_USAGE_HALFLIFE_S = conf(
    "spark.rapids.tpu.serving.usageHalflifeS").doc(
    "Half-life of the per-tenant fair-share usage EWMA: charged usage "
    "(admissions + query wall seconds) decays by half every this many "
    "seconds, so an idle tenant's past consumption fades and it "
    "re-approaches its full share instead of being punished forever."
).double_conf(30.0)

SERVING_RESULT_CACHE_ENABLED = conf(
    "spark.rapids.tpu.serving.resultCache.enabled").doc(
    "Cache collected result rows per (plan signature, conf "
    "fingerprint, tenant) inside serving sessions — a repeated "
    "dashboard query returns without planning, compiling, or touching "
    "the device.  Entries are charged to the owning query's resource "
    "bill, scoped to (and dropped with) the owning tenant session, "
    "and evicted by the governor's RED ladder.").boolean_conf(True)

SERVING_RESULT_CACHE_MAX_BYTES = conf(
    "spark.rapids.tpu.serving.resultCache.maxBytes").doc(
    "LRU bound on estimated host bytes held by the serving "
    "result-fragment cache across all tenants; inserting past it "
    "evicts least-recently-used fragments first."
).long_conf(64 * 1024 * 1024)

# --- distributed cross-host execution tier (ISSUE 14) ----------------------

DISTRIBUTED_ENABLED = conf("spark.rapids.tpu.distributed.enabled").doc(
    "Route multi-partition exchanges through the cross-host worker "
    "tier (distributed/): a coordinator places reduce partitions over "
    "worker processes, blocks ship as CRC-framed TKU2 wire blocks, and "
    "the producer-side spill-backed partition queues retain every "
    "shipped block until the consuming stage commits — a worker lost "
    "mid-shuffle (missed heartbeats or dead socket) is recovered by "
    "re-placing its partitions on survivors and re-driving the "
    "retained blocks.  Requires a coordinator with live workers; with "
    "none joined, exchanges fall back to the in-process spill-backed "
    "path.").boolean_conf(False)

DISTRIBUTED_HEARTBEAT_MS = conf(
    "spark.rapids.tpu.distributed.heartbeatMs").doc(
    "Worker heartbeat period.  The coordinator's liveness monitor "
    "scans at the same period and counts a worker late "
    "(worker_heartbeat_misses) past two periods of silence."
).long_conf(200)

DISTRIBUTED_WORKER_LOST_MS = conf(
    "spark.rapids.tpu.distributed.workerLostMs").doc(
    "Heartbeat silence after which a worker is declared LOST: its "
    "partitions re-place onto survivors, the re-drive plan is queued, "
    "a per-worker circuit-breaker entry opens (flapping workers are "
    "quarantined on rejoin until the breaker TTL re-probe), and a "
    "flight-recorder post-mortem bundle captures the placement table "
    "and re-drive plan.").long_conf(1200)

DISTRIBUTED_OP_TIMEOUT_MS = conf(
    "spark.rapids.tpu.distributed.opTimeoutMs").doc(
    "Socket timeout for one data-plane operation (put / fetch / "
    "release) against a worker.  A timed-out op classifies TRANSIENT "
    "and retries up to putRetries times before the worker is declared "
    "lost.").long_conf(4000)

DISTRIBUTED_PUT_RETRIES = conf(
    "spark.rapids.tpu.distributed.putRetries").doc(
    "Bounded transient retries (reconnect + resend) per data-plane "
    "operation before the target worker is declared lost and the "
    "block layer switches to re-placement + re-drive."
).long_conf(2)

DISTRIBUTED_REDRIVE_MAX = conf(
    "spark.rapids.tpu.distributed.redriveMaxAttempts").doc(
    "How many times one reduce partition may be re-placed + re-driven "
    "(repeated worker losses) before WorkerLost escapes to the "
    "operator fault domain — which falls back to the CPU oracle "
    "without indicting the operator's breaker key.").long_conf(4)

DISTRIBUTED_WORKER_MEM = conf(
    "spark.rapids.tpu.distributed.workerMemoryBytes").doc(
    "Default per-worker block-store memory budget handed to spawned "
    "workers; blocks past it overflow to the worker's spill "
    "directory (the netty shuffle-file analog).").bytes_conf(64 << 20)

DISTRIBUTED_LOSS_BREAKER_THRESHOLD = conf(
    "spark.rapids.tpu.distributed.lossBreakerThreshold").doc(
    "Loss declarations that OPEN a worker's circuit-breaker entry.  "
    "The default (1) quarantines a killed-and-rejoined worker "
    "immediately: it heartbeats but receives no placements until the "
    "resilience breaker TTL admits a re-probe.").long_conf(1)

DISTRIBUTED_TRACE_ENABLED = conf(
    "spark.rapids.tpu.distributed.traceEnabled").doc(
    "Cluster-wide trace propagation (ISSUE 15): stamp the query's "
    "trace id (minted at lifecycle collect start) and the current "
    "operator's span id on every TKD1 control frame, so worker-side "
    "work (store puts/fetches, spill, re-drive serves) records into "
    "the worker-local diagnostics ring attributed to the originating "
    "query, heartbeats piggyback worker counter/ring deltas, and the "
    "driver merges driver+worker spans into one Chrome trace.  Off, "
    "frames carry no trace fields, so workers record no spans and no "
    "merge runs (counters still federate over heartbeats) — the bench "
    "rung4_dist A/B pins the on/off overhead <= 5%."
).boolean_conf(True)

DISTRIBUTED_TELEMETRY_RING = conf(
    "spark.rapids.tpu.distributed.telemetryRingSize").doc(
    "Capacity of the worker-local diagnostics ring (span events for "
    "store puts/fetches/spill/re-drive) AND of the per-worker mirror "
    "ring the coordinator folds heartbeat-shipped deltas into — the "
    "mirror is what a SIGKILLed worker's post-mortem bundle contains "
    "(its 'last-shipped' ring).  0 disables worker span recording "
    "(counters still federate).").long_conf(512)

# --- gray-failure resilience (ISSUE 20) ------------------------------------

DISTRIBUTED_HEDGE_ENABLED = conf(
    "spark.rapids.tpu.distributed.hedgeEnabled").doc(
    "Hedged fetches for the distributed exchange read path "
    "(docs/distributed.md): a paged TKD1 fetch that blows its per-"
    "worker soft deadline (softDeadlineFactor x the worker's p95 "
    "latency EWMA, floored at softDeadlineMinMs) races a hedge "
    "against the producer-side lineage buffer — partition_queues "
    "retains every framed slice until commit, so the hedge source is "
    "free — first-complete-wins, remote duplicates discarded by the "
    "store's per-seq idempotence.  Counters: fetch_hedges launches, "
    "hedges_won lineage wins.  The bench rung4_dist healthy-path A/B "
    "pins the on/off overhead <= 2% with hedges_won == 0."
).boolean_conf(True)

DISTRIBUTED_SOFT_DEADLINE_FACTOR = conf(
    "spark.rapids.tpu.distributed.softDeadlineFactor").doc(
    "Multiplier over a worker's p95-biased latency EWMA that sets its "
    "per-op soft deadline.  An op past the soft deadline is a 'miss' "
    "(counts toward DEGRADED demotion and, on the fetch path, "
    "launches a hedge); the hard stop stays opTimeoutMs."
).double_conf(3.0)

DISTRIBUTED_SOFT_DEADLINE_MIN_MS = conf(
    "spark.rapids.tpu.distributed.softDeadlineMinMs").doc(
    "Floor for the per-worker soft deadline, so an idle fleet with "
    "microsecond EWMAs does not hedge every op on scheduler jitter."
).long_conf(50)

DISTRIBUTED_SLOW_FACTOR = conf(
    "spark.rapids.tpu.distributed.slowFactor").doc(
    "A worker whose latency EWMA sits persistently past slowFactor x "
    "the fleet median (or that misses degradeAfterMisses consecutive "
    "soft deadlines) is declared DEGRADED: demoted in capacity-"
    "weighted placement, its pending partitions speculatively re-"
    "driven onto healthy survivors over the lineage contract — "
    "WITHOUT declaring it LOST or opening the quarantine breaker (a "
    "slow worker is not a dead one).").double_conf(4.0)

DISTRIBUTED_DEGRADE_AFTER_MISSES = conf(
    "spark.rapids.tpu.distributed.degradeAfterMisses").doc(
    "Consecutive soft-deadline misses on one worker's data-plane ops "
    "before the coordinator declares it DEGRADED.").long_conf(3)

DISTRIBUTED_PROMOTE_AFTER_OKS = conf(
    "spark.rapids.tpu.distributed.promoteAfterOks").doc(
    "Consecutive within-deadline observations (served ops or monitor "
    "pings) a DEGRADED worker must bank, with its EWMA back under "
    "slowFactor x the fleet median, before promotion to ALIVE — "
    "sustained recovery, not one lucky op.").long_conf(3)

# --- crash-consistent driver recovery (ISSUE 16) ---------------------------

RECOVERY_ENABLED = conf("spark.rapids.tpu.recovery.enabled").doc(
    "Crash-consistent driver recovery (docs/recovery.md): every "
    "collect() appends admission / stage-checkpoint / end records to a "
    "durable CRC-framed query journal (lifecycle/journal.py), "
    "materialized exchange outputs commit at stage boundaries (local: "
    "atomic tmp+rename checkpoint files keyed by plan-stage "
    "fingerprint; distributed: worker-held partitions pinned by a "
    "journal-recorded lease), and a restarted driver replays the "
    "journal to classify prior queries as completed / resumable / "
    "abandoned and to skip committed stages on re-execution "
    "(stages_recovered).  Off, the journal module is never imported — "
    "the hot path makes zero recovery calls.").boolean_conf(False)

RECOVERY_DIR = conf("spark.rapids.tpu.recovery.dir").doc(
    "Root directory for the query journal, stage checkpoints, and the "
    "coordinator endpoint file workers re-attach through.  Must be "
    "stable across driver restarts (recovery identity lives here).  "
    "Unset: <tmpdir>/srt_recovery.").string_conf(None)

RECOVERY_FSYNC = conf("spark.rapids.tpu.recovery.fsyncOnAppend").doc(
    "Journal durability: fsync the journal after every appended "
    "record (the spark.rapids.tpu.files.fsyncOnCommit discipline "
    "applied to the WAL).  Off by default — single-write atomic "
    "appends already keep the journal prefix-consistent; fsync adds a "
    "per-record syscall and protects against machine (not process) "
    "crashes.").boolean_conf(False)

RECOVERY_LEASE_TTL_MS = conf("spark.rapids.tpu.recovery.leaseTtlMs").doc(
    "How long a journal-recorded stage checkpoint (a distributed "
    "lease pinning worker-held partitions, or a local checkpoint "
    "directory) stays adoptable after the committing driver's death.  "
    "A reborn driver retires anything older (recovery_leases_expired) "
    "and re-executes from scratch — orphaned worker partitions must "
    "not pin memory forever.").long_conf(120_000)

RECOVERY_WORKER_REATTACH_MS = conf(
    "spark.rapids.tpu.recovery.workerReattachMs").doc(
    "How long a worker that lost its driver (heartbeat socket died) "
    "keeps its store alive and retries re-attaching through the "
    "recovery-dir endpoint file before giving up and exiting.  The "
    "re-HELLO enumerates held (exchange, partition, seq-range) "
    "inventory so the reborn coordinator can rebuild placement.  "
    "0 keeps the pre-recovery behavior: a dead control socket ends "
    "the worker.").long_conf(30_000)

# --- resilience (stage-level fault domains) --------------------------------

RESILIENCE_ENABLED = conf("spark.rapids.tpu.resilience.enabled").doc(
    "Wrap every exec operator in a fault domain that classifies escaping "
    "failures (device OOM / transient / deterministic), retries the "
    "recoverable classes, and falls the rest back to the CPU oracle at "
    "runtime (resilience/ package; reference: the RmmRapidsRetryIterator "
    "state machine plus CPU-Spark stage fallback).").boolean_conf(True)

RESILIENCE_MAX_TRANSIENT_RETRIES = conf(
    "spark.rapids.tpu.resilience.maxTransientRetries").doc(
    "Bounded restarts of an operator after a transient runtime error "
    "(UNAVAILABLE / DEADLINE_EXCEEDED style XLA failures) before it is "
    "treated as deterministic.").integer_conf(3)

RESILIENCE_BACKOFF_BASE_MS = conf(
    "spark.rapids.tpu.resilience.backoffBaseMs").doc(
    "Base delay for exponential backoff between transient retries "
    "(delay = base * 2^attempt + jitter in [0, base), capped at 2s); "
    "0 disables sleeping (tests).").double_conf(10.0)

RESILIENCE_RUNTIME_FALLBACK = conf(
    "spark.rapids.tpu.resilience.runtimeFallbackEnabled").doc(
    "On a deterministic failure, materialize the stage's inputs to host, "
    "execute the stage's plan-node twin through the CPU oracle, and "
    "continue the query on TPU (the mid-query analog of plan-time "
    "willNotWorkOnTpu tagging).  Also enables the whole-query oracle "
    "fallback of last resort in collect().").boolean_conf(True)

RESILIENCE_BREAKER_THRESHOLD = conf(
    "spark.rapids.tpu.resilience.breakerFailureThreshold").doc(
    "Deterministic failures of one (operator, expression-fingerprint) key "
    "before the circuit breaker opens and plan-time tagging routes that "
    "stage to the CPU oracle for subsequent queries.").integer_conf(3)

RESILIENCE_BREAKER_TTL_SEC = conf(
    "spark.rapids.tpu.resilience.breakerTtlSec").doc(
    "How long an open breaker entry holds its stage on CPU before a "
    "half-open probe re-admits it to the TPU (success closes the entry, "
    "failure re-opens with a fresh TTL).").double_conf(300.0)

RESILIENCE_TEST_INJECT = conf(
    "spark.rapids.tpu.resilience.testInject").doc(
    "Chaos-injection hook: 'kind:Operator[:count[:atBatch[:seed]]]' "
    "(kinds: compile, transient, poison, oom, file_corrupt, decode; "
    "';'-separated for multiple), "
    "armed at collect() time.  The force_retry_oom test API generalized "
    "to every failure class.").internal().string_conf("NONE")

AUTO_BROADCAST_JOIN_THRESHOLD = conf(
    "spark.sql.autoBroadcastJoinThreshold").doc(
    "Estimated build-side size below which joins broadcast instead of "
    "shuffling (Spark's conf; file-scan sizes come from file footers, "
    "local tables from their host columns).  -1 disables broadcasting."
).bytes_conf(10 << 20)

COMPILE_CACHE_DIR = conf("spark.rapids.tpu.compileCache.dir").doc(
    "Persistent XLA compile-cache directory, applied process-wide on the "
    "first TpuSession construction so tests/tools/bench all share compiled "
    "programs across processes (on the tunnel-relayed dev chip a single "
    "compile costs minutes; the cache makes it once).  Empty string or "
    "'0' disables.  Default: <repo>/.jax_compile_cache.  Legacy alias of "
    "spark.rapids.tpu.compile.cacheDir, which wins when set."
).string_conf(os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    ".jax_compile_cache"))

# --- compile cache / AOT pipeline (compilecache/) --------------------------

COMPILE_CACHE_DIR_V2 = conf("spark.rapids.tpu.compile.cacheDir").doc(
    "Persistent XLA executable cache directory "
    "(jax_compilation_cache_dir): a fresh process re-running the same "
    "plan deserializes executables instead of compiling.  Preferred "
    "spelling; unset falls back to spark.rapids.tpu.compileCache.dir "
    "(and its repo-local default).  Empty string or '0' disables."
).string_conf(None)

COMPILE_AOT_ENABLED = conf("spark.rapids.tpu.compile.aot.enabled").doc(
    "Plan-time AOT compilation: after overrides produce the exec tree, "
    "enumerate the (stage function x shape-bucket) programs the query "
    "will need and compile them concurrently on a bounded background "
    "pool, so batch 1 of operator 1 overlaps the compiles of everything "
    "downstream instead of serializing minute-long compiles between "
    "launches (compilecache/aot.py).").boolean_conf(True)

COMPILE_AOT_THREADS = conf("spark.rapids.tpu.compile.aot.threads").doc(
    "Background compile pool width.  On the tunnel-relayed dev relay "
    "compiles serialize behind one channel anyway; on a directly "
    "attached host XLA compiles are CPU-bound and parallelize well."
).integer_conf(4)

COMPILE_REGISTRY_ENABLED = conf(
    "spark.rapids.tpu.compile.registry.enabled").doc(
    "In-process executable registry: exec nodes share compiled stage "
    "programs keyed by semantic fingerprint (expressions + schemas + "
    "confs), so a re-planned query compiles nothing the process already "
    "built.  Off: every exec instance keeps private jits (the seed "
    "behavior).").boolean_conf(True)

COMPILE_REGISTRY_MAX_PROGRAMS = conf(
    "spark.rapids.tpu.compile.registry.maxPrograms").doc(
    "LRU bound on registered programs (each entry pins its compiled "
    "executables); evicted programs simply recompile on next use."
).integer_conf(1024)

SKEW_JOIN_ENABLED = conf("spark.sql.adaptive.skewJoin.enabled").doc(
    "AQE skew handling for the mesh join (Spark's OptimizeSkewedJoin "
    "analog): when one device's matched-pair total for a probe epoch "
    "exceeds skewedPartitionFactor x the device mean, the epoch splits "
    "in half and re-routes — bounding the per-device materialization "
    "capacity a hot key would otherwise inflate.").boolean_conf(True)

SKEW_JOIN_FACTOR = conf(
    "spark.sql.adaptive.skewJoin.skewedPartitionFactor").doc(
    "A device is skewed when its epoch output exceeds this factor times "
    "the device mean (Spark's default 5).").integer_conf(5)

SKEW_JOIN_MIN_ROWS = conf(
    "spark.rapids.tpu.mesh.skewJoin.minEpochRows").doc(
    "Epochs at or below this row count stop splitting (the floor of the "
    "skew ladder).").integer_conf(1024)

AGG_SMALL_GROUPS_CAP = conf("spark.rapids.tpu.agg.smallGroupsCap").doc(
    "Sort-based group-by emits results through a bounded-cardinality "
    "program when the group count fits this cap: boundary/cumsum forms "
    "replace the full-width segment scatters (~20x device time at 20M "
    "rows), with host-side growth to the next power of two on overflow "
    "(the output row count is synced anyway, so the check is free).  "
    "0 disables (always full-width).").integer_conf(65536)

# --- plan / exec switches --------------------------------------------------

ENABLE_CAST_FLOAT_TO_STRING = conf(
    "spark.rapids.sql.castFloatToString.enabled").doc(
    "Float->string cast may differ from Spark in digits.").boolean_conf(True)

ENABLE_CAST_STRING_TO_FLOAT = conf(
    "spark.rapids.sql.castStringToFloat.enabled").doc(
    "String->float cast compat switch.").boolean_conf(True)

ENABLE_CAST_STRING_TO_TIMESTAMP = conf(
    "spark.rapids.sql.castStringToTimestamp.enabled").doc(
    "String->timestamp cast compat switch (device civil parser; named "
    "timezones parse as null).").boolean_conf(True)

ENABLE_FLOAT_AGG = conf("spark.rapids.sql.castFloatToDecimal.enabled").doc(
    "Float->decimal cast compat switch.").boolean_conf(True)

STABLE_SORT = conf("spark.rapids.sql.stableSort.enabled").doc(
    "Force stable sort (adds row-index tiebreaker column).").boolean_conf(False)

SORT_OOC_ENABLED = conf("spark.rapids.sql.sort.outOfCore.enabled").doc(
    "Enable out-of-core sort (spill sorted runs + N-way merge; reference: "
    "GpuOutOfCoreSortIterator).").boolean_conf(True)

AGG_FALLBACK_PARTIALS = conf(
    "spark.rapids.sql.agg.skipAggPassReductionRatio").doc(
    "Skip partial agg when it is not reducing rows by at least this ratio."
).double_conf(0.9)

JOIN_SUBPARTITION_THRESHOLD = conf(
    "spark.rapids.sql.join.subPartition.numRowsThreshold").doc(
    "Build side larger than this triggers sub-partitioned join "
    "(reference: GpuSubPartitionHashJoin).").integer_conf(1 << 22)

# --- IO --------------------------------------------------------------------

PARQUET_READER_TYPE = conf("spark.rapids.sql.format.parquet.reader.type").doc(
    "PERFILE, COALESCING, MULTITHREADED, or AUTO (reference: "
    "GpuParquetScan readers).").string_conf("AUTO")

PARQUET_MULTITHREAD_READ_NUM_THREADS = conf(
    "spark.rapids.sql.multiThreadedRead.numThreads").doc(
    "Host threads fetching/decoding files in parallel.").integer_conf(20)

PARQUET_MAX_NUM_FILES_PARALLEL = conf(
    "spark.rapids.sql.format.parquet.multiThreadedRead.maxNumFilesParallel"
).doc("Cap on files in flight per task.").integer_conf(2147483647)

PARQUET_ENABLED = conf("spark.rapids.sql.format.parquet.enabled").doc(
    "Enable TPU parquet scan/write.").boolean_conf(True)

PARQUET_READ_ENABLED = conf("spark.rapids.sql.format.parquet.read.enabled").doc(
    "Enable TPU parquet scans.").boolean_conf(True)

PARQUET_WRITE_ENABLED = conf(
    "spark.rapids.sql.format.parquet.write.enabled").doc(
    "Enable TPU parquet writes.").boolean_conf(True)

CSV_ENABLED = conf("spark.rapids.sql.format.csv.enabled").boolean_conf(True)
CSV_READ_ENABLED = conf("spark.rapids.sql.format.csv.read.enabled").boolean_conf(True)
JSON_ENABLED = conf("spark.rapids.sql.format.json.enabled").boolean_conf(True)
JSON_READ_ENABLED = conf("spark.rapids.sql.format.json.read.enabled").boolean_conf(True)
ORC_ENABLED = conf("spark.rapids.sql.format.orc.enabled").boolean_conf(True)
AVRO_ENABLED = conf("spark.rapids.sql.format.avro.enabled").boolean_conf(True)
PARQUET_DEVICE_DECODE = conf(
    "spark.rapids.sql.format.parquet.decode.device").doc(
    "Decode Parquet pages with the Pallas kernels (bit-unpack + run "
    "expansion + dictionary gather on device; host parses only footers "
    "and run headers).  Files outside the supported subset (v2 pages, "
    "snappy, byte arrays, nested) silently fall back to the host pyarrow "
    "decode per file.  Off by default: correct on TPU, but the page "
    "pipeline dispatches eager device ops whose round-trips dominate "
    "over a tunneled chip (directly-attached TPU hosts amortize "
    "them).").boolean_conf(False)
PARQUET_DEVICE_ENCODE = conf(
    "spark.rapids.sql.format.parquet.encode.device").doc(
    "Encode Parquet pages with device kernels (dictionary build, k-bit "
    "index packing and def-level packing run as jitted programs; the "
    "host assembles thrift headers + snappy framing through the C "
    "compressor twin — io/parquet_encode.py, the decode pipeline's "
    "mirror).  Flat int/float/string schemas; others keep the pyarrow "
    "host encode.  Off by default for the same tunnel-dispatch reason "
    "as decode.device.").boolean_conf(False)

AVRO_READ_ENABLED = conf("spark.rapids.sql.format.avro.read.enabled").doc(
    "Enable TPU Avro scans (pure-python container decode, io/avro.py)."
).boolean_conf(True)

# --- transport-aware scan pipeline (ISSUE 6) -------------------------------

PARQUET_COMPRESSED_TRANSFER = conf(
    "spark.rapids.sql.format.parquet.transfer.compressed").doc(
    "With parquet decode.device on, ship eligible column chunks across "
    "the host->device link as RAW COMPRESSED page bytes and decompress "
    "(snappy block gather) + decode (RLE/bit-pack/dictionary) on device, "
    "so the link carries the smallest representation (the 5-40 MB/s "
    "tunnel is the standing scan bottleneck; BENCH_r05).  Chunks outside "
    "the device-decompressible subset (zstd codec, PLAIN byte_array "
    "pages) fall back PER CHUNK to the decoded-transfer device path "
    "(`chunk_decode_fallbacks`).  Physical link bytes land in "
    "`bytes_h2d`; the decoded size lands in `bytes_h2d_logical`."
).boolean_conf(True)

SCAN_PREFETCH_DEPTH = conf("spark.rapids.tpu.scan.prefetch.depth").doc(
    "Async H2D prefetch ring depth for the COALESCING/MULTITHREADED "
    "readers: up to this many upcoming batches are decoded+uploaded on a "
    "staging thread while the query computes on the current batch "
    "(double-buffering at the default 2).  Overlap efficiency is "
    "observable via `bytes_h2d_overlapped` / `prefetch_stall_ns` and the "
    "`scan_prefetch` diagnostics event.  0 disables (strictly "
    "sequential transfer-then-compute).").integer_conf(2)

SCAN_HOT_CACHE = conf("spark.rapids.tpu.scan.hotTableCache.enabled").doc(
    "Device-resident hot-table cache: completed file scans register "
    "their device batches (keyed by file fingerprints + column set + "
    "pushed filters + snapshot id) so a repeated query over the same "
    "table skips the read+decode+transfer entirely "
    "(`hot_cache_hits`/`hot_cache_misses`).  Entries are spillable "
    "(memory/spill.py): HBM pressure migrates them down-tier instead of "
    "OOMing, and `TpuSession.close()` drops them.  Off by default; "
    "serving-tier deployments replaying dashboards enable it."
).boolean_conf(False)

SCAN_HOT_CACHE_MAX_BYTES = conf(
    "spark.rapids.tpu.scan.hotTableCache.maxBytes").doc(
    "Device-bytes bound on the hot-table cache; inserting past it "
    "evicts least-recently-used entries (`hot_cache_evictions`).  A "
    "single scan larger than the bound is not cached.").bytes_conf(1 << 30)

# --- IO fault tolerance (io/faults.py — per-file scan fault domain) --------

IGNORE_CORRUPT_FILES = conf("spark.sql.files.ignoreCorruptFiles").doc(
    "Spark conf: skip files whose bytes fail to decode (corrupt / "
    "truncated / schema-drifted) instead of failing the query.  Each "
    "skip bumps files_skipped_corrupt, emits an io_fault diagnostics "
    "event, and lands in the per-query quarantine manifest "
    "(docs/io_resilience.md).").boolean_conf(False)

IGNORE_MISSING_FILES = conf("spark.sql.files.ignoreMissingFiles").doc(
    "Spark conf: skip files that vanished between planning and read "
    "(ENOENT) instead of failing the query; skips bump "
    "files_skipped_missing and are quarantined like corrupt files."
).boolean_conf(False)

TPU_IGNORE_CORRUPT_FILES = conf(
    "spark.rapids.tpu.files.ignoreCorruptFiles").doc(
    "Tri-state alias of spark.sql.files.ignoreCorruptFiles: set "
    "true/false to override the Spark conf for TPU scans only; unset "
    "defers to it.").string_conf(None)

TPU_IGNORE_MISSING_FILES = conf(
    "spark.rapids.tpu.files.ignoreMissingFiles").doc(
    "Tri-state alias of spark.sql.files.ignoreMissingFiles: set "
    "true/false to override the Spark conf for TPU scans only; unset "
    "defers to it.").string_conf(None)

FSYNC_ON_COMMIT = conf("spark.rapids.tpu.files.fsyncOnCommit").doc(
    "Writer durability: fsync every staged output file (and its "
    "directory) before the atomic commit rename, so a machine crash "
    "right after commit cannot surface zero-length files.  Off by "
    "default — rename-atomicity alone already guarantees readers never "
    "observe partial output; fsync adds a per-file syscall cost."
).boolean_conf(False)

# --- shuffle ---------------------------------------------------------------

ADAPTIVE_ENABLED = conf("spark.sql.adaptive.enabled").doc(
    "AQE analog: shuffled equi-joins re-plan themselves at execution time "
    "— the build side materializes first and, when its measured bytes sit "
    "under spark.sql.autoBroadcastJoinThreshold, the join runs broadcast "
    "with both planned exchanges elided (runtime stats beat static "
    "planning).").boolean_conf(True)

OPTIMIZER_ENABLED = conf("spark.rapids.sql.optimizer.enabled").doc(
    "Cost-based fallback (CostBasedOptimizer analog, default off like the "
    "reference): plans whose estimated input is below "
    "spark.rapids.sql.optimizer.smallPlanBytes stay on CPU — the device "
    "round-trip cannot pay for itself.").boolean_conf(False)

OPTIMIZER_SMALL_PLAN_BYTES = conf(
    "spark.rapids.sql.optimizer.smallPlanBytes").doc(
    "Cost-based fallback threshold (bytes).").integer_conf(32768)

ARROW_EVAL_ENABLED = conf("spark.rapids.sql.python.arrowEval.enabled").doc(
    "Run plain python UDFs inside the TPU plan through the host arrow-eval "
    "path (GpuArrowEvalPythonExec analog): batches cross to the host for "
    "the UDF only, everything else stays on device.  false: such stages "
    "fall back to CPU entirely.").boolean_conf(True)

UDF_COMPILER_ENABLED = conf("spark.rapids.sql.udfCompiler.enabled").doc(
    "Translate simple python UDFs into engine expressions at plan time by "
    "operator-overload tracing (the udf-compiler analog of the "
    "reference's bytecode decompiler); untranslatable functions keep the "
    "arrow-eval path.").boolean_conf(True)

PROFILE_ENABLED = conf("spark.rapids.profile.enabled").doc(
    "Wrap every operator's per-batch work in jax.profiler TraceAnnotations "
    "so XProf/Perfetto timelines attribute device time to plan operators "
    "(the NVTX-ranges analog; reference: nvtx_profiling.md + the CUPTI "
    "profiler module).").boolean_conf(False)

SHUFFLE_MODE = conf("spark.rapids.shuffle.mode").doc(
    "MULTITHREADED (serialize batches host-side, concat-friendly Kudo-style "
    "format), ICI (device-resident all-to-all over the TPU interconnect via "
    "XLA collectives — replaces the reference's UCX transport), or CACHE_ONLY."
).string_conf("MULTITHREADED")

MESH_ENABLED = conf("spark.rapids.tpu.mesh.enabled").doc(
    "Execute eligible plan stages SPMD over a jax.sharding.Mesh of all "
    "visible devices.  With shuffle.mode=ICI the partial-agg -> exchange -> "
    "final-agg stage pair compiles to ONE collective program per batch "
    "(scan shards rows, all-to-all repartitions by key hash over the "
    "interconnect).").boolean_conf(False)

SINGLE_DEVICE_SHUFFLE_COALESCE = conf(
    "spark.rapids.tpu.shuffle.singleDeviceCoalesce").doc(
    "On a single device with the host shuffle, collapse hash/round-robin "
    "exchanges to ONE partition (an AQE-style partition coalesce: per-"
    "partition program launches are pure overhead without a second chip; "
    "aggregation/join results are partition-count independent)."
).boolean_conf(True)

COMPLETE_AGG_COLLAPSE = conf(
    "spark.rapids.tpu.completeAggCollapse.enabled").doc(
    "When a two-phase aggregate's exchange runs on one device (mesh off "
    "or a single chip), collapse Final<-Coalesce<-Exchange<-Partial into "
    "ONE COMPLETE-mode aggregate: a single-batch input then aggregates and "
    "finalizes in one XLA program instead of three (the single-device "
    "analog of AQE's exchange elision — each saved launch is a saved host "
    "round trip).").boolean_conf(True)

JOIN_AGG_FUSION = conf("spark.rapids.tpu.joinAggFusion.enabled").doc(
    "Compile an aggregate sitting directly on an equi-join INTO the join's "
    "materialization program (and, when the build side's keys are unique — "
    "the dim-table case — run probe+gather+aggregate as ONE program with "
    "no pair-count host sync).  Each saved launch is a saved host round "
    "trip; joined rows feeding an aggregate never round-trip through HBM."
).boolean_conf(True)

WINDOW_CHAIN_FUSION = conf(
    "spark.rapids.tpu.windowChainFusion.enabled").doc(
    "Compile [COMPLETE aggregate ->] window [-> project/filter] chains "
    "into ONE XLA program (the window function already runs as a single "
    "jitted scan program; a grouped aggregate below and a stage above "
    "compose with it via device-scalar row counts — no host sync between "
    "operators).").boolean_conf(True)

FUSION_ENABLED = conf("spark.rapids.tpu.fusion.enabled").doc(
    "Whole-plan subtree fusion (ISSUE 17): compile each maximal "
    "pipeline-able chain of narrow operators (project/filter stages, "
    "expand) into ONE jitted XLA program routed through the compile "
    "cache registry — a 3-operator chain then costs one launch and zero "
    "intermediate host round trips instead of three launches with "
    "per-edge materialization.  Eligibility is the fusibility "
    "manifest's fusable set intersected with the cost model's predicted "
    "intermediate sizes (see fusion.maxIntermediateFraction)."
).boolean_conf(True)

FUSION_MAX_INTERMEDIATE_FRACTION = conf(
    "spark.rapids.tpu.fusion.maxIntermediateFraction").doc(
    "Fusion boundary rule: a pipeline chain fuses through an operator "
    "edge only while the cost-model-predicted intermediate at that edge "
    "(static AOT rows, else the calibration store's measured rows EWMA, "
    "else the capacity bound — exec/partition_sizing.py) stays within "
    "this fraction of the HBM pool.  A predicted-oversized intermediate "
    "splits the chain at that edge so the fused program's working set "
    "cannot blow the pool.").double_conf(0.5)

FUSION_COLLECT_SHRINK_MAX_WASTE = conf(
    "spark.rapids.tpu.fusion.collectShrinkMaxWasteBytes").doc(
    "Collect-boundary shrink elision: to_host_columns normally launches "
    "one slice program to shrink a padded batch to its tight capacity "
    "bucket before the device->host copy.  When the padding that would "
    "be transferred anyway is at most this many bytes, the shrink "
    "launch is elided (per-column to_host truncation already drops the "
    "padding rows on host) — one program and its host round trip saved "
    "per collect, and one fewer (in-capacity, out-capacity) shrink "
    "shape to compile (minutes per shape on a tunnel-relayed chip).  "
    "0 disables the elision.").bytes_conf(8 << 20)

MESH_DEVICES = conf("spark.rapids.tpu.mesh.devices").doc(
    "Number of mesh devices for ICI stages (0 = all visible devices).  "
    "Non-power-of-2 counts are supported; capacities pad to multiples of "
    "the device count.").integer_conf(0)

MESH_AGG_ENABLED = conf("spark.rapids.tpu.mesh.agg.enabled").doc(
    "Per-stage kill switch: run eligible aggregation stage pairs as ICI "
    "collective programs (requires mesh.enabled + shuffle.mode=ICI)."
).boolean_conf(True)

MESH_JOIN_ENABLED = conf("spark.rapids.tpu.mesh.join.enabled").doc(
    "Per-stage kill switch: run eligible shuffled equi-joins as ICI "
    "collective programs.").boolean_conf(True)

MESH_SORT_ENABLED = conf("spark.rapids.tpu.mesh.sort.enabled").doc(
    "Per-stage kill switch: run global sorts as the distributed "
    "range-exchange ICI sort.").boolean_conf(True)

MESH_WINDOW_ENABLED = conf("spark.rapids.tpu.mesh.window.enabled").doc(
    "Per-stage kill switch: run partitioned window stages as the "
    "distributed ICI window (hash all-to-all on PARTITION BY, then the "
    "single-chip window program per device).").boolean_conf(True)

MESH_REPARTITION_ENABLED = conf(
    "spark.rapids.tpu.mesh.repartition.enabled").doc(
    "Per-stage kill switch: lower remaining hash/round-robin shuffle "
    "exchanges (those no specialized ICI stage claims) to the generic "
    "mesh all-to-all repartition.").boolean_conf(True)

MESH_EPOCH_BYTES = conf("spark.rapids.tpu.mesh.epochTargetBytes").doc(
    "Input bytes gathered into one mesh collective epoch.  ICI stages "
    "stream the child's batches through the SPMD program in epochs of "
    "roughly this size instead of concatenating the whole input, so "
    "per-device memory stays bounded by (epoch shard + accumulator/build "
    "state).").integer_conf(1 << 28)

# --- out-of-core partitioned exchange (ISSUE 10) ---------------------------

EXCHANGE_SIZED_PARTITIONS = conf(
    "spark.rapids.tpu.exchange.sizedPartitions.enabled").doc(
    "Size-aware exchange partitioning: at plan time, estimate each "
    "shuffle exchange's input bytes from the AOT shape predictor "
    "(aot_output_rows/aot_output_caps — refined by the profiling cost "
    "model's calibrated per-operator output-bytes prediction when a "
    "store exists) and GROW the partition count so one partition's "
    "working set fits exchange.targetPartitionFraction of the HBM pool. "
    "Only ever raises the planned count (datasets far larger than HBM "
    "stream partition-by-partition instead of materializing whole); "
    "small inputs keep their planned counts.  Sized exchanges are "
    "exempt from the single-device partition collapse."
).boolean_conf(True)

EXCHANGE_TARGET_PARTITION_FRACTION = conf(
    "spark.rapids.tpu.exchange.targetPartitionFraction").doc(
    "Fraction of the HBM pool one exchange partition's working set "
    "should fit when sizedPartitions chooses a partition count "
    "(partitions = ceil(estimated bytes / (pool * fraction)))."
).double_conf(0.125)

EXCHANGE_MAX_PARTITIONS = conf(
    "spark.rapids.tpu.exchange.maxPartitions").doc(
    "Upper bound on the partition count sizedPartitions may choose "
    "(each partition costs a read-side program launch; on a "
    "compile-tunnel platform launches are hundreds of ms)."
).integer_conf(256)

EXCHANGE_SPILL_ENABLED = conf(
    "spark.rapids.tpu.exchange.spill.enabled").doc(
    "Stream shuffle exchange partitions through spill-backed partition "
    "queues (shuffle/partition_queues.py): map-side slices register "
    "with the SpillFramework up to exchange.deviceResidentBytes, and "
    "slices beyond the budget cross the host boundary as CRC-framed "
    "serializer blocks — device residency stays bounded instead of "
    "materializing the whole exchange input.  false: the legacy "
    "shuffle-manager path (serialize every slice host-side)."
).boolean_conf(True)

EXCHANGE_DEVICE_RESIDENT_BYTES = conf(
    "spark.rapids.tpu.exchange.deviceResidentBytes").doc(
    "Device bytes the spill-backed exchange queues may keep resident "
    "as SpillFramework handles before further slices serialize to "
    "CRC-framed host blocks.  0 (default) derives the budget from the "
    "pool: pool_bytes * exchange.targetPartitionFraction * 2."
).bytes_conf(0)

EXCHANGE_COALESCE_SMALL_BYTES = conf(
    "spark.rapids.tpu.exchange.coalesceSmallPartitionBytes").doc(
    "AQE shuffle-read coalescing threshold (SURVEY §2.4): adjacent "
    "reduce partitions below this byte size merge into one read window "
    "in TpuAdaptiveShuffleReaderExec (counted by partitions_coalesced); "
    "partitions at or above it emit alone.  The batch-size goal still "
    "caps each window.").bytes_conf(4 << 20)

# --- ICI multi-chip shuffle (ISSUE 10) -------------------------------------

ICI_HOST_BOUNDARY_CODEC = conf(
    "spark.rapids.tpu.ici.hostBoundaryCodec").doc(
    "Codec for CRC-framed blocks crossing the ICI/exchange host "
    "boundary (spill-backed partition queues, ici_host_frame).  Unset "
    "defers to spark.rapids.shuffle.compression.codec."
).string_conf(None)

ICI_CROSS_SLICE_HOSTS = conf(
    "spark.rapids.tpu.ici.crossSliceHosts").doc(
    "When > 0, the generic mesh repartition routes through a two-level "
    "(host x ici) mesh (parallel/crossslice.py): phase 1 moves rows to "
    "their destination's local device index over intra-slice ICI, "
    "phase 2 delivers each row across the host (DCN-analog) axis "
    "exactly once.  The device count must be divisible by this host "
    "count.  0 (default): the flat single-axis all-to-all."
).integer_conf(0)

SHUFFLE_MT_WRITER_THREADS = conf(
    "spark.rapids.shuffle.multiThreaded.writer.threads").integer_conf(20)
SHUFFLE_MT_READER_THREADS = conf(
    "spark.rapids.shuffle.multiThreaded.reader.threads").integer_conf(20)

SHUFFLE_PARTITIONS = conf("spark.sql.shuffle.partitions").doc(
    "Number of shuffle partitions.").integer_conf(16)

SHUFFLE_COMPRESSION_CODEC = conf(
    "spark.rapids.shuffle.compression.codec").doc(
    "Codec for serialized shuffle batches: none, lz4, zstd.").string_conf("lz4")

# --- metrics / debug -------------------------------------------------------

METRICS_LEVEL = conf("spark.rapids.sql.metrics.level").doc(
    "ESSENTIAL, MODERATE, or DEBUG.").string_conf("MODERATE")

# --- diagnostics (diagnostics/ — spans, event log, profile reports) --------

DIAGNOSTICS_ENABLED = conf("spark.rapids.tpu.diagnostics.enabled").doc(
    "Install a QueryDiagnostics recorder around every collect(): each "
    "operator's batch iteration, jit launch, logical host sync, "
    "inline/AOT compile, cache hit/miss, and resilience event is "
    "recorded as a span/event attributed to the current operator, with "
    "per-operator perf-counter deltas that sum exactly to the process-"
    "global deltas for the query.  Event verbosity follows "
    "spark.rapids.sql.metrics.level.  Disabled (default): every "
    "instrumentation site costs one ambient None-check per event."
).boolean_conf(False)

DIAGNOSTICS_EVENT_LOG_DIR = conf(
    "spark.rapids.tpu.diagnostics.eventLogDir").doc(
    "Directory for per-query JSONL structured event logs "
    "(query-<id>.jsonl, atomic tmp+rename flush per query, rotation via "
    "eventLog.maxFiles); consumed by tools/profile_report.py.  Unset: "
    "events stay in memory (explain('analyze') still works)."
).string_conf(None)

DIAGNOSTICS_TRACE_DIR = conf(
    "spark.rapids.tpu.diagnostics.chromeTraceDir").doc(
    "Directory for per-query Chrome-trace files (query-<id>.trace.json) "
    "rendering the operator timeline with launches/syncs/compiles "
    "nested per operator track — load in chrome://tracing or "
    "ui.perfetto.dev.  Unset: no trace files."
).string_conf(None)

DIAGNOSTICS_MAX_FILES = conf(
    "spark.rapids.tpu.diagnostics.eventLog.maxFiles").doc(
    "Rotation bound per diagnostics sink directory: after each flush, "
    "oldest files beyond this count are deleted.  <= 0 disables "
    "rotation.").integer_conf(64)

DIAGNOSTICS_MAX_EVENTS = conf(
    "spark.rapids.tpu.diagnostics.maxEvents").doc(
    "In-memory bound on recorded events per query: a launch-per-row "
    "pathological query must not hold GBs of event dicts until flush.  "
    "Overflow is counted into query_end's events_dropped field; operator "
    "summaries and query_start/end are always kept.").integer_conf(200000)

# --- telemetry (telemetry/ — always-on metrics, flight recorder, SLOs) -----

TELEMETRY_ENABLED = conf("spark.rapids.tpu.telemetry.enabled").doc(
    "Always-on telemetry tier: a process-global time-series metrics "
    "registry fed by a sampler thread (admission queue depth, HBM "
    "occupancy, spill tiers, cache hit rates, H2D bandwidth), "
    "per-plan-signature latency histograms with p50/p95 SLO tracking "
    "recorded at collect() exit, and the failure flight recorder.  The "
    "hub is built by the first TpuSession whose conf leaves this true; "
    "per-batch hot paths are never instrumented (docs/observability.md)."
).boolean_conf(True)

TELEMETRY_SAMPLE_PERIOD_MS = conf(
    "spark.rapids.tpu.telemetry.samplePeriodMs").doc(
    "Sampler thread period: every period the daemon snapshots the "
    "process singletons (peek-only — an idle tick creates nothing) into "
    "the time-series registry and the in-memory timeline.  0 disables "
    "the sampler (the registry, SLO histograms, and flight recorder "
    "still work; only the periodic gauges stop).").double_conf(500.0)

TELEMETRY_RETENTION = conf("spark.rapids.tpu.telemetry.retention").doc(
    "Ring-buffer bound on retained samples per time series (and on "
    "timeline rows): at the default 500ms period, 720 points is a "
    "six-minute sliding window.  A long-running process holds a window, "
    "never an unbounded history.").integer_conf(720)

TELEMETRY_PORT = conf("spark.rapids.tpu.telemetry.port").doc(
    "Bind a localhost-only (127.0.0.1) HTTP scrape endpoint serving GET "
    "/metrics in Prometheus exposition format.  0 disables (the "
    "default); telemetry.export() returns the same text in-process "
    "either way.  Fleet exposure belongs to a sidecar, not this "
    "library.").integer_conf(0)

TELEMETRY_JSONL_DIR = conf("spark.rapids.tpu.telemetry.jsonlDir").doc(
    "Directory for the periodic JSONL telemetry log "
    "(telemetry-<pid>.jsonl, one line per sampler tick) — the "
    "process-level companion of the per-query diagnostics event log.  "
    "Unset: samples stay in the in-memory timeline only."
).string_conf(None)

TELEMETRY_FLIGHT_ENABLED = conf(
    "spark.rapids.tpu.telemetry.flightRecorder.enabled").doc(
    "Always-on failure flight recorder: a fixed-size in-memory ring of "
    "recent query-level events (admitted/finished/cancelled/deadline/"
    "breaker — a handful of appends per QUERY, never per batch) that "
    "auto-dumps a post-mortem bundle (ring + all-thread stacks with the "
    "offending query's thread named + counter snapshot + active-query "
    "table) when a deadline trips, a query is cancelled mid-batch, a "
    "circuit breaker opens, or collect() raises.  On by default."
).boolean_conf(True)

TELEMETRY_FLIGHT_CAPACITY = conf(
    "spark.rapids.tpu.telemetry.flightRecorder.capacity").doc(
    "Flight-recorder ring size in events (oldest evicted first)."
).integer_conf(2048)

TELEMETRY_FLIGHT_DUMP_DIR = conf(
    "spark.rapids.tpu.telemetry.flightRecorder.dumpDir").doc(
    "Directory post-mortem bundles are written to (atomic tmp+rename "
    "JSON, postmortem-<ts>-<reason>[-<qid>].json).  Unset: bundles are "
    "kept in memory only (the last 8, telemetry.last_postmortem())."
).string_conf(None)

TELEMETRY_SLO_TARGET_P95_MS = conf(
    "spark.rapids.tpu.telemetry.slo.targetP95Ms").doc(
    "Per-query latency SLO target: any collect() slower than this bumps "
    "slo_violations and drops an slo_violation event into the flight "
    "ring.  0 disables (latency histograms still record; "
    "tools/bench_gate.py owns cross-run regression gating)."
).double_conf(0.0)

# --- profiling (profiling/ — calibration store, cost model, advisor) -------

PROFILE_DIR = conf("spark.rapids.tpu.profile.dir").doc(
    "Directory for the persistent operator calibration store "
    "(calibration.json, atomic merge-on-write).  When set, every "
    "diagnostics-recorded query folds its per-operator spans "
    "(self_wall_ns, syncs, H2D/D2H bytes, fallback/retry outcomes) into "
    "per-(operator, expr-fingerprint, shape-bucket) decaying EWMAs at "
    "query_end, and collect() annotates the plan with cost-model "
    "predictions (cost_model_hits/misses/cost_model_predicted_wall_ns "
    "counters, explain('cost')).  Unset (default): zero profiling-module "
    "calls per query — the disabled path is free."
).string_conf(None)

PROFILE_EWMA_ALPHA = conf("spark.rapids.tpu.profile.ewmaAlpha").doc(
    "Decay factor for the calibration store's exponentially weighted "
    "moving averages: new = alpha*obs + (1-alpha)*old.  Higher tracks "
    "drift faster; lower smooths noisy walls.  Clamped to (0, 1]."
).double_conf(0.25)

PROFILE_COST_MODEL_ENABLED = conf(
    "spark.rapids.tpu.profile.costModel.enabled").doc(
    "With profile.dir set, walk the planned exec tree before execution "
    "and predict per-operator wall / transfer bytes / confidence from "
    "the calibration store (explain('cost'), the cost_model diagnostics "
    "event, and the cost_model_* counters).  false: the store still "
    "accumulates observations but no plan-time prediction runs."
).boolean_conf(True)

PROFILE_ADVISOR_ENABLED = conf(
    "spark.rapids.tpu.profile.advisor.enabled").doc(
    "Consult the qualification advisory file (tools/qualify.py "
    "--advisory-out) at plan time: an operator class the profile shows "
    "as persistently fallback-heavy is routed to its native/CPU "
    "placement (advisor_plan_fallbacks counter) while every other class "
    "keeps its default placement.  Off by default — the seed of "
    "cost-based routing, opt-in until the cost model earns trust."
).boolean_conf(False)

PROFILE_ADVISOR_FILE = conf("spark.rapids.tpu.profile.advisor.file").doc(
    "Path of the advisory JSON the plan-time consult reads.  Unset: "
    "<spark.rapids.tpu.profile.dir>/advisory.json when profile.dir is "
    "set, else no advisory."
).string_conf(None)

# --- progress (progress/ — live per-operator progress, ETA, stalls) --------

PROGRESS_ENABLED = conf("spark.rapids.tpu.progress.enabled").doc(
    "Live query introspection: every lifecycle-managed collect() "
    "registers with the process-global progress tracker — per-operator "
    "batches/rows/bytes produced so far, percent-complete and ETA "
    "joined from the profiling cost model's predictions, and causal "
    "attribution of background work (AOT compiles, scan prefetch "
    "uploads, shuffle-write serialization) to the owning query.  "
    "Surfaced via session.progress(), live df.explain('analyze'), the "
    "/progress JSON route on the telemetry HTTP endpoint, and the "
    "sampler's progress_* gauges.  Disabled (default): every "
    "instrumentation site costs one ambient attribute check — zero "
    "calls into progress modules (docs/progress.md)."
).boolean_conf(False)

PROGRESS_STALL_MS = conf("spark.rapids.tpu.progress.stallMs").doc(
    "Heartbeat stall detector (requires progress.enabled): when NO "
    "operator of a live query advances — no batch pull completes and "
    "no background work is attributed — for this many ms, the "
    "watchdog's stall scan bumps stalls_detected, emits a query_stall "
    "diagnostics event naming the stuck operator (the innermost "
    "in-flight batch pull), and dumps a flight-recorder post-mortem "
    "embedding the live progress snapshot.  Re-arms after each "
    "advance, so a later wedge of the same query reports again.  "
    "0 disables stall detection.").long_conf(0)

PROGRESS_MAX_FINISHED = conf("spark.rapids.tpu.progress.maxFinished").doc(
    "Recently finished query snapshots the tracker retains for the "
    "/progress surface (oldest evicted first); live queries are always "
    "reported regardless.").integer_conf(32)

# --- accounting (accounting/ — per-query resource bills + sentinel) --------

ACCOUNTING_ENABLED = conf("spark.rapids.tpu.accounting.enabled").doc(
    "Per-query resource bills: every HBM registration/spill/release in "
    "the spill framework charges the owning query's ledger (device "
    "bytes charged/released, per-query peak, device-byte-seconds, "
    "spill traffic per tier with the draining exchange partition "
    "stamped), joined at collect end with the query's counter deltas "
    "(H2D/D2H bytes, launches, syncs, compile wall), progress "
    "background wall, and federated worker store bytes — emitted as a "
    "resource_bill diagnostics event plus bill_* telemetry gauges, and "
    "settled at lifecycle exit (a nonzero residual is a leak the test "
    "gate fails on).  Disabled (default): every charge site costs one "
    "ambient attribute check — zero calls into accounting modules "
    "(docs/accounting.md)."
).boolean_conf(False)

ACCOUNTING_RETAINED_BILLS = conf(
    "spark.rapids.tpu.accounting.retainedBills").doc(
    "Settled bills the ledger registry retains (oldest evicted first) "
    "for tools/history.py pages and bench.py columns.  An evicted "
    "bill's nonzero residual stays visible to the leak gate."
).integer_conf(64)

ACCOUNTING_SENTINEL_ENABLED = conf(
    "spark.rapids.tpu.accounting.sentinel.enabled").doc(
    "With accounting.enabled AND profile.dir set, compare each "
    "finished query's bill + wall against the calibration store's "
    "per-plan-signature EWMAs (wall, host syncs, spill bytes, "
    "compile-cache hit rate) at collect exit.  An excursion past the "
    "ratio/z thresholds bumps perf_regressions_flagged, emits a "
    "regression diagnostics event + flight-ring event, and dumps a "
    "post-mortem bundle carrying the offending bill, the violated "
    "baseline, and the per-operator self-wall delta table naming the "
    "regressed operator.  Flagged observations are NOT folded into "
    "the baseline; only clean status=ok queries calibrate."
).boolean_conf(True)

ACCOUNTING_SENTINEL_MIN_SAMPLES = conf(
    "spark.rapids.tpu.accounting.sentinel.minSamples").doc(
    "Observations a plan signature's baseline needs before the "
    "sentinel evaluates it — younger baselines only accumulate."
).integer_conf(3)

ACCOUNTING_SENTINEL_WALL_RATIO = conf(
    "spark.rapids.tpu.accounting.sentinel.wallRatio").doc(
    "Multiplicative excursion gate: a dimension must exceed its "
    "baseline EWMA by this factor to flag (wall additionally requires "
    "the z gate; syncs/spill additionally require absolute excess "
    "floors so tiny baselines cannot alarm on noise)."
).double_conf(2.0)

ACCOUNTING_SENTINEL_Z = conf("spark.rapids.tpu.accounting.sentinel.z").doc(
    "Z-score gate for the wall dimension: (observed - baseline) / "
    "deviation-EWMA must reach this many sigmas (deviation floored at "
    "5% of the baseline mean so near-constant history cannot make "
    "jitter look significant)."
).double_conf(4.0)

ACCOUNTING_SENTINEL_MIN_WALL_EXCESS_MS = conf(
    "spark.rapids.tpu.accounting.sentinel.minWallExcessMs").doc(
    "Absolute wall excess floor in ms: below this a ratio/z excursion "
    "on a sub-millisecond baseline is noise, not a regression."
).double_conf(5.0)

MEM_DEBUG = conf("spark.rapids.memory.gpu.debug").doc(
    "Log arena allocations.").boolean_conf(False)

TEST_RETRY_OOM_INJECTION_MODE = conf(
    "spark.rapids.sql.test.injectRetryOOM").doc(
    "Test hook: force a RetryOOM/SplitAndRetryOOM in retry blocks "
    "(reference: RmmSpark.forceRetryOOM).").string_conf("NONE")

# --- TPU-specific ----------------------------------------------------------

TPU_ROW_BUCKETS = conf("spark.rapids.tpu.batch.rowBuckets").doc(
    "Comma-separated pow2 row-capacity buckets batches are padded to, so XLA "
    "recompiles are bounded (static shapes).").string_conf(
    "1024,8192,65536,262144,1048576,4194304")

TPU_STRING_WIDTH_BUCKETS = conf("spark.rapids.tpu.string.widthBuckets").doc(
    "Char-width buckets for the padded string layout.").string_conf(
    "8,32,128,512,2048")

TPU_DONATE_BUFFERS = conf("spark.rapids.tpu.donateInputBuffers").doc(
    "Donate input HBM buffers to XLA where legal.").boolean_conf(True)

ORC_DEVICE_DECODE = conf(
    "spark.rapids.sql.format.orc.decode.device").doc(
    "Decode ORC stripe numerics on device: host parses protobuf footers "
    "and splits RLEv2 runs, the Pallas bit-unpack kernel expands DIRECT "
    "payloads (MSB packing bridged by byte/value bit-reversal), DELTA "
    "runs cumsum on device.  Unsupported shapes silently fall back to "
    "the pyarrow host decode.  Off by default for the same reason as the "
    "parquet knob: per-run eager dispatches round-trip the compile "
    "tunnel on this dev platform.").boolean_conf(False)

DECODE_LOG_FALLBACK = conf(
    "spark.rapids.sql.decode.logFallback").doc(
    "Log (stderr) why a file fell back from the device decode (parquet "
    "OR orc) to the host pyarrow decode — silent fallbacks are otherwise "
    "invisible.").boolean_conf(False)

TPU_SCAN_CACHE = conf("spark.rapids.tpu.scan.cacheDeviceBatches").doc(
    "Keep scanned batches resident in HBM across queries over the same "
    "table (the df.cache / ParquetCachedBatchSerializer analog).  Off by "
    "default; benchmarks of warm-data queries enable it.").boolean_conf(False)

TPU_WHOLESTAGE_FUSION = conf("spark.rapids.tpu.wholeStageFusion.enabled").doc(
    "Fuse chains of narrow operators (project/filter) into one jitted XLA "
    "program per stage.").boolean_conf(True)


class TpuConf:
    """Immutable snapshot view over a settings dict (RapidsConf analog)."""

    def __init__(self, settings: Optional[Dict[str, str]] = None):
        self.settings: Dict[str, str] = dict(settings or {})

    def get(self, entry: ConfEntry):
        return entry.get(self.settings)

    def get_key(self, key: str):
        e = _REGISTRY.get(key)
        if e is None:
            raise KeyError(f"unknown config {key}")
        return self.get(e)

    def is_op_enabled(self, op_name: str, kind: str = "expression") -> bool:
        """Per-op kill switch: spark.rapids.sql.<kind>.<OpName> (reference:
        RapidsConf.isOperatorEnabled)."""
        raw = self.settings.get(f"spark.rapids.sql.{kind}.{op_name}")
        if raw is None:
            return True
        return str(raw).strip().lower() in ("true", "1", "yes")

    def with_settings(self, **kv) -> "TpuConf":
        s = dict(self.settings)
        s.update({k.replace("__", "."): v for k, v in kv.items()})
        return TpuConf(s)

    def set(self, key: str, value) -> "TpuConf":
        s = dict(self.settings)
        s[key] = value
        return TpuConf(s)

    # -- convenience properties used throughout the codebase --
    @property
    def sql_enabled(self):
        return self.get(SQL_ENABLED)

    @property
    def ansi_enabled(self):
        return self.get(ANSI_ENABLED)

    @property
    def explain(self):
        return self.get(EXPLAIN)

    @property
    def batch_size_bytes(self):
        return self.get(BATCH_SIZE_BYTES)

    @property
    def concurrent_tpu_tasks(self):
        return self.get(CONCURRENT_TPU_TASKS)

    @property
    def shuffle_partitions(self):
        return self.get(SHUFFLE_PARTITIONS)

    @property
    def row_buckets(self) -> List[int]:
        return sorted(int(x) for x in self.get(TPU_ROW_BUCKETS).split(","))

    @property
    def string_width_buckets(self) -> List[int]:
        return sorted(int(x) for x in self.get(TPU_STRING_WIDTH_BUCKETS).split(","))


_lock = threading.Lock()
_active = TpuConf()
_tls = threading.local()


def get_conf() -> TpuConf:
    override = getattr(_tls, "override", None)
    return override if override is not None else _active


def set_conf(c: TpuConf) -> TpuConf:
    global _active
    with _lock:
        _active = c
    return c


class ambient_conf:
    """Thread-local conf override: background threads (the AOT compile
    pool) trace programs whose expressions read the ambient conf at trace
    time; pinning the conf captured at submit keeps a warm-up's trace
    consistent with its registry key even if the main thread re-plans a
    different session mid-compile."""

    def __init__(self, conf: TpuConf):
        self._conf = conf

    def __enter__(self):
        self._prev = getattr(_tls, "override", None)
        _tls.override = self._conf
        return self._conf

    def __exit__(self, *a):
        _tls.override = self._prev


def all_entries() -> List[ConfEntry]:
    """Walked by docs/gen_configs.py to emit the config reference table."""
    return [e for _, e in sorted(_REGISTRY.items())]
