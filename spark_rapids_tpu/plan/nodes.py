"""CPU physical plan nodes — the stand-in for Spark's SparkPlan tree.

The reference is a *plugin*: Spark hands it a physical plan and GpuOverrides
rewrites it (SURVEY.md §3.2).  This framework is standalone (no JVM in the
loop), so it carries its own Catalyst-shaped physical plan; the node names
deliberately mirror Spark's (ProjectExec, FilterExec, HashAggregateExec,
SortMergeJoinExec, ShuffleExchangeExec...) so that the overrides layer, the
fallback-explain output, and the tests read exactly like the reference's.

Every node can execute on CPU via the oracle (spark_rapids_tpu/cpu/) — that
CPU path plays the role CPU Spark plays for the reference: the golden
differential baseline AND the fallback target for untagged nodes.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import List, Optional, Sequence, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr.base import Alias, Expression
from spark_rapids_tpu.ops.sortkeys import SortSpec


class SparkPlan:
    """Base physical plan node (CPU side)."""

    def __init__(self, children: Sequence["SparkPlan"]):
        self.children: List[SparkPlan] = list(children)

    @property
    def output(self) -> T.StructType:
        raise NotImplementedError

    @property
    def node_name(self) -> str:
        return type(self).__name__

    def pretty(self, indent: int = 0) -> str:
        s = "  " * indent + self.describe()
        for c in self.children:
            s += "\n" + c.pretty(indent + 1)
        return s

    def describe(self) -> str:
        return self.node_name

    def with_new_children(self, children: Sequence["SparkPlan"]) -> "SparkPlan":
        import copy

        n = copy.copy(self)
        n.children = list(children)
        return n


class LocalTableScan(SparkPlan):
    def __init__(self, host_columns, schema: T.StructType):
        super().__init__([])
        self.host_columns = host_columns  # List[HostColumn]
        self._schema = schema

    @property
    def output(self):
        return self._schema

    def describe(self):
        return f"LocalTableScan {self._schema.simpleString}"


class CachedRelation(SparkPlan):
    """df.cache(): materialized child batches reused across actions.

    Reference analog: InMemoryRelation backed by the
    ParquetCachedBatchSerializer (SURVEY.md §2.8) — the plugin caches
    DataFrames as device-encodable batches.  Here the cache holds DEVICE
    batches registered with the spill framework, so cached data is
    reclaimable under memory pressure like any other batch."""

    def __init__(self, child: SparkPlan):
        super().__init__([child])
        self.cache_slot = {}       # filled by the exec / oracle on first run

    @property
    def child(self):
        return self.children[0]

    @property
    def output(self):
        return self.child.output

    def describe(self):
        return "InMemoryRelation [cached]"


class FileSourceScan(SparkPlan):
    def __init__(self, fmt: str, paths: List[str], schema: T.StructType,
                 pushed_filters: Optional[List[Expression]] = None,
                 options: Optional[dict] = None):
        super().__init__([])
        self.fmt = fmt
        self.paths = list(paths)
        self._schema = schema
        self.pushed_filters = list(pushed_filters or [])
        self.options = dict(options or {})

    @property
    def output(self):
        return self._schema

    def describe(self):
        return f"FileSourceScan {self.fmt} {len(self.paths)} files"


class RangeNode(SparkPlan):
    """spark.range(start, end, step) — GpuRangeExec analog."""

    def __init__(self, start: int, end: int, step: int = 1):
        super().__init__([])
        self.start, self.end, self.step = start, end, step

    @property
    def output(self):
        return T.StructType([T.StructField("id", T.LONG, nullable=False)])

    def describe(self):
        return f"Range ({self.start}, {self.end}, step={self.step})"


class Project(SparkPlan):
    def __init__(self, exprs: List[Expression], child: SparkPlan):
        super().__init__([child])
        self.exprs = exprs

    @property
    def child(self):
        return self.children[0]

    @property
    def output(self):
        return T.StructType([
            T.StructField(e.name, e.dataType, e.nullable) for e in self.exprs])

    def describe(self):
        return "Project [" + ", ".join(e.sql_string() for e in self.exprs) + "]"


class Filter(SparkPlan):
    def __init__(self, condition: Expression, child: SparkPlan):
        super().__init__([child])
        self.condition = condition

    @property
    def child(self):
        return self.children[0]

    @property
    def output(self):
        return self.child.output

    def describe(self):
        return f"Filter ({self.condition.sql_string()})"


class AggregateMode(enum.Enum):
    PARTIAL = "Partial"
    FINAL = "Final"
    COMPLETE = "Complete"


# central-moment aggregates sharing the (n, avg, m2) buffer form
# (reference: Spark CentralMomentAgg, GPU'd as GpuStddevPop etc. in
# org/apache/spark/sql/rapids/aggregate — SURVEY.md §2.4 hash aggregate)
VARIANCE_FUNCS = frozenset(
    {"var_pop", "var_samp", "stddev_pop", "stddev_samp"})

# higher central moments (Spark Skewness/Kurtosis: same CentralMomentAgg
# family, buffers extended with m3/m4)
HIGHER_MOMENT_FUNCS = frozenset({"skewness", "kurtosis"})

# two-input covariance family (Spark Covariance/Corr: n, xAvg, yAvg, ck
# buffers; corr adds xMk/yMk)
COVARIANCE_FUNCS = frozenset({"covar_pop", "covar_samp", "corr"})

# linear-regression family (Spark RegrCount/RegrAvgX/...): rides the same
# covariance buffers — regr_f(y, x) observes rows where BOTH are non-null
REGR_FUNCS = frozenset(
    {"regr_count", "regr_avgx", "regr_avgy", "regr_sxx", "regr_syy",
     "regr_sxy", "regr_slope", "regr_intercept", "regr_r2"})

# bitwise aggregates (Spark BitAndAgg/BitOrAgg/BitXorAgg)
BIT_AGG_FUNCS = frozenset({"bit_and", "bit_or", "bit_xor"})

# single-phase aggregates (planned COMPLETE after a hash exchange, like
# collect_list — their state is the whole group)
SINGLE_PHASE_FUNCS = frozenset(
    {"collect_list", "collect_set", "percentile", "approx_percentile",
     "median", "bloom_filter_agg"})

# PARTIAL-mode buffer field suffixes per moment-family func; every buffer
# column is DOUBLE
MOMENT_BUFFERS = {
    "var_pop": ("_n", "_avg", "_m2"),
    "var_samp": ("_n", "_avg", "_m2"),
    "stddev_pop": ("_n", "_avg", "_m2"),
    "stddev_samp": ("_n", "_avg", "_m2"),
    "skewness": ("_n", "_avg", "_m2", "_m3"),
    "kurtosis": ("_n", "_avg", "_m2", "_m3", "_m4"),
    "covar_pop": ("_n", "_xavg", "_yavg", "_ck"),
    "covar_samp": ("_n", "_xavg", "_yavg", "_ck"),
    "corr": ("_n", "_xavg", "_yavg", "_ck", "_xm2", "_ym2"),
    **{f: ("_n", "_xavg", "_yavg", "_ck", "_xm2", "_ym2")
       for f in ("regr_count", "regr_avgx", "regr_avgy", "regr_sxx",
                 "regr_syy", "regr_sxy", "regr_slope", "regr_intercept",
                 "regr_r2")},
}

# default register-count exponent for approx_count_distinct at Spark's
# default relativeSD=0.05 (p = ceil(2 * log2(1.106 / rsd)))
HLL_DEFAULT_P = 9


@dataclasses.dataclass
class AggregateExpression:
    """One aggregate: func name + input expr (resolved) + result name.

    func in {sum, count, min, max, avg, first, last, count_star,
    var_pop, var_samp, stddev_pop, stddev_samp}.
    """

    func: str
    child: Optional[Expression]  # None for count(*)
    result_name: str
    result_type: Optional[T.DataType] = None
    distinct: bool = False
    child2: Optional[Expression] = None   # corr/covar second input
    args: tuple = ()                      # literal extras (percentage, ...)

    def resolve(self, schema: T.StructType) -> "AggregateExpression":
        if self.child is not None:
            self.child = self.child.resolve(schema)
        if self.child2 is not None:
            self.child2 = self.child2.resolve(schema)
        self.result_type = self._compute_type()
        return self

    def _compute_type(self) -> T.DataType:
        if self.func in ("count", "count_star", "count_if",
                         "approx_count_distinct"):
            return T.LONG
        if self.func == "bloom_filter_agg":
            return T.ArrayType(T.LONG, containsNull=False)
        ct = self.child.dataType
        if self.func == "sum":
            if isinstance(ct, T.DecimalType):
                return T.DecimalType(min(ct.precision + 10, 38), ct.scale)
            if ct.is_integral:
                return T.LONG
            return T.DOUBLE
        if self.func == "avg":
            if isinstance(ct, T.DecimalType):
                return T.DecimalType(min(ct.precision + 4, 38),
                                     min(ct.scale + 4, 38))
            return T.DOUBLE
        if self.func in VARIANCE_FUNCS or self.func in HIGHER_MOMENT_FUNCS \
                or self.func in COVARIANCE_FUNCS:
            return T.DOUBLE
        if self.func == "regr_count":
            return T.LONG
        if self.func in REGR_FUNCS:
            return T.DOUBLE
        if self.func in ("percentile", "median"):
            return T.DOUBLE
        if self.func == "approx_percentile":
            return ct
        if self.func in ("collect_list", "collect_set"):
            return T.ArrayType(ct)
        return ct  # min/max/first/last

    def describe(self):
        inner = self.child.sql_string() if self.child is not None else "*"
        return f"{self.func}({inner}) AS {self.result_name}"


def partial_buffer_schema(grouping, aggregates) -> T.StructType:
    """PARTIAL-mode buffer schema for a grouping+aggregate set (what a
    PARTIAL HashAggregate outputs and a FINAL one consumes)."""
    fields = [T.StructField(g.name, g.dataType, g.nullable)
              for g in grouping]
    for a in aggregates:
        if a.func == "avg":
            fields.append(T.StructField(a.result_name + "_sum", T.DOUBLE
                          if not isinstance(a.result_type, T.DecimalType)
                          else T.DecimalType(38, a.child.dataType.scale)))
            fields.append(T.StructField(a.result_name + "_count", T.LONG))
        elif a.func in MOMENT_BUFFERS:
            for suffix in MOMENT_BUFFERS[a.func]:
                fields.append(T.StructField(
                    a.result_name + suffix, T.DOUBLE))
        elif a.func == "approx_count_distinct":
            fields.append(T.StructField(
                a.result_name + "_hll",
                T.ArrayType(T.INT, containsNull=False)))
        else:
            fields.append(T.StructField(a.result_name, a.result_type))
    return T.StructType(fields)


class HashAggregate(SparkPlan):
    def __init__(self, grouping: List[Expression],
                 aggregates: List[AggregateExpression],
                 mode: AggregateMode, child: SparkPlan):
        super().__init__([child])
        self.grouping = grouping
        self.aggregates = aggregates
        self.mode = mode

    @property
    def child(self):
        return self.children[0]

    @property
    def output(self):
        if self.mode == AggregateMode.PARTIAL:
            return partial_buffer_schema(self.grouping, self.aggregates)
        fields = [T.StructField(g.name, g.dataType, g.nullable)
                  for g in self.grouping]
        fields += [T.StructField(a.result_name, a.result_type)
                   for a in self.aggregates]
        return T.StructType(fields)

    def describe(self):
        g = ", ".join(e.sql_string() for e in self.grouping)
        a = ", ".join(a.describe() for a in self.aggregates)
        return f"HashAggregate({self.mode.value}) keys=[{g}] aggs=[{a}]"


class JoinType(enum.Enum):
    INNER = "Inner"
    LEFT_OUTER = "LeftOuter"
    RIGHT_OUTER = "RightOuter"
    FULL_OUTER = "FullOuter"
    LEFT_SEMI = "LeftSemi"
    LEFT_ANTI = "LeftAnti"
    CROSS = "Cross"


class _BaseJoin(SparkPlan):
    def __init__(self, left: SparkPlan, right: SparkPlan,
                 left_keys: List[Expression], right_keys: List[Expression],
                 join_type: JoinType,
                 condition: Optional[Expression] = None):
        super().__init__([left, right])
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.join_type = join_type
        self.condition = condition

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    @property
    def output(self):
        lt, rt = self.join_type, JoinType
        lf = list(self.left.output.fields)
        rf = list(self.right.output.fields)
        if self.join_type in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI):
            return T.StructType(lf)
        if self.join_type in (JoinType.LEFT_OUTER, JoinType.FULL_OUTER):
            rf = [T.StructField(f.name, f.dataType, True) for f in rf]
        if self.join_type in (JoinType.RIGHT_OUTER, JoinType.FULL_OUTER):
            lf = [T.StructField(f.name, f.dataType, True) for f in lf]
        return T.StructType(lf + rf)

    def describe(self):
        keys = ", ".join(
            f"{l.sql_string()}={r.sql_string()}"
            for l, r in zip(self.left_keys, self.right_keys))
        return f"{self.node_name} {self.join_type.value} [{keys}]"


class SortMergeJoin(_BaseJoin):
    pass


class ShuffledHashJoin(_BaseJoin):
    pass


class BroadcastHashJoin(_BaseJoin):
    def __init__(self, *args, build_side: str = "right", **kw):
        super().__init__(*args, **kw)
        self.build_side = build_side


class BroadcastNestedLoopJoin(_BaseJoin):
    """Join without equi-keys: every pair is checked against the condition.

    Reference analog: GpuBroadcastNestedLoopJoinExec (SURVEY.md §2.4)."""

    def __init__(self, left, right, join_type: JoinType,
                 condition: Optional[Expression]):
        super().__init__(left, right, [], [], join_type, condition)

    def describe(self):
        c = self.condition.sql_string() if self.condition is not None else ""
        return f"BroadcastNestedLoopJoin {self.join_type.value} [{c}]"


class Generate(SparkPlan):
    """explode/posexplode over an array column.

    Reference analog: GpuGenerateExec (SURVEY.md §2.4)."""

    def __init__(self, gen_expr: Expression, child: SparkPlan,
                 position: bool = False, outer: bool = False,
                 out_name: str = "col"):
        super().__init__([child])
        self.gen_expr = gen_expr
        self.position = position
        self.outer = outer
        self.out_name = out_name

    @property
    def child(self):
        return self.children[0]

    @property
    def output(self):
        fields = list(self.child.output.fields)
        if self.position:
            # posexplode_outer synthesizes NULL pos for empty/null arrays
            fields.append(T.StructField("pos", T.INT, self.outer))
        dt = self.gen_expr.dataType
        # non-array input is rejected at tag time; keep output well-formed
        # so tagging can reach the check
        et = dt.elementType if isinstance(dt, T.ArrayType) else dt
        fields.append(T.StructField(self.out_name, et, True))
        return T.StructType(fields)

    def describe(self):
        kind = "posexplode" if self.position else "explode"
        if self.outer:
            kind += "_outer"
        return f"Generate {kind}({self.gen_expr.sql_string()})"


class Expand(SparkPlan):
    """Emit one output row per projection set per input row (rollup/cube
    building block).  Reference analog: GpuExpandExec."""

    def __init__(self, projections: List[List[Expression]],
                 output_schema: T.StructType, child: SparkPlan):
        super().__init__([child])
        self.projections = projections
        self._output = output_schema

    @property
    def child(self):
        return self.children[0]

    @property
    def output(self):
        return self._output

    def describe(self):
        return f"Expand [{len(self.projections)} projections]"


class Sort(SparkPlan):
    def __init__(self, orders: List[Tuple[Expression, SortSpec]],
                 is_global: bool, child: SparkPlan):
        super().__init__([child])
        self.orders = orders
        self.is_global = is_global

    @property
    def child(self):
        return self.children[0]

    @property
    def output(self):
        return self.child.output

    def describe(self):
        o = ", ".join(
            f"{e.sql_string()} {'ASC' if s.ascending else 'DESC'}"
            for e, s in self.orders)
        return f"Sort [{o}] global={self.is_global}"


class SinglePartitioning:
    num_partitions = 1

    def describe(self):
        return "SinglePartition"


@dataclasses.dataclass
class HashPartitioning:
    keys: List[Expression]
    num_partitions: int

    def describe(self):
        k = ", ".join(e.sql_string() for e in self.keys)
        return f"hashpartitioning({k}, {self.num_partitions})"


@dataclasses.dataclass
class RangePartitioning:
    orders: List[Tuple[Expression, SortSpec]]
    num_partitions: int

    def describe(self):
        return f"rangepartitioning({self.num_partitions})"


@dataclasses.dataclass
class RoundRobinPartitioning:
    num_partitions: int

    def describe(self):
        return f"roundrobin({self.num_partitions})"


class Exchange(SparkPlan):
    """ShuffleExchangeExec analog."""

    def __init__(self, partitioning, child: SparkPlan):
        super().__init__([child])
        self.partitioning = partitioning

    @property
    def child(self):
        return self.children[0]

    @property
    def output(self):
        return self.child.output

    def describe(self):
        return f"Exchange {self.partitioning.describe()}"


class BroadcastExchange(SparkPlan):
    def __init__(self, child: SparkPlan):
        super().__init__([child])

    @property
    def output(self):
        return self.children[0].output


@dataclasses.dataclass
class WindowFunction:
    """window function spec: func over (partition, order, frame).

    lead/lag carry ``offset`` (+ optional literal ``default``); ntile
    carries ``buckets``; first_value/last_value carry ``ignore_nulls``."""

    func: str                      # row_number, rank, dense_rank, sum, ...
    child: Optional[Expression]
    result_name: str
    result_type: Optional[T.DataType] = None
    offset: int = 1                # lead/lag
    default: Optional[object] = None   # lead/lag literal default
    buckets: int = 2               # ntile
    ignore_nulls: bool = False     # first_value/last_value

    def resolve(self, schema):
        if self.child is not None:
            self.child = self.child.resolve(schema)
        if self.func in ("row_number", "rank", "dense_rank", "ntile"):
            self.result_type = T.INT
        elif self.func in ("percent_rank", "cume_dist"):
            self.result_type = T.DOUBLE
        elif self.func == "count":
            self.result_type = T.LONG
        elif self.func == "sum":
            ct = self.child.dataType
            if isinstance(ct, T.DecimalType):
                self.result_type = T.DecimalType(min(ct.precision + 10, 38), ct.scale)
            elif ct.is_integral:
                self.result_type = T.LONG
            else:
                self.result_type = T.DOUBLE
        elif self.func in ("avg", "var_pop", "var_samp",
                           "stddev_pop", "stddev_samp"):
            self.result_type = T.DOUBLE
        else:
            self.result_type = self.child.dataType
        return self


def normalize_frame(frame):
    """Canonical window-frame forms (GpuSpecifiedWindowFrame analog):

      "running"        ROWS  UNBOUNDED PRECEDING .. CURRENT ROW
      "range_running"  RANGE UNBOUNDED PRECEDING .. CURRENT ROW (Spark's
                       default frame when ORDER BY is present — includes
                       the current row's order-key peers)
      "unbounded"      the whole partition
      ("rows", a, b)   ROWS  BETWEEN a PRECEDING AND b FOLLOWING
      ("range", a, b)  RANGE BETWEEN a PRECEDING AND b FOLLOWING over a
                       single numeric order key

    A bare (a, b) tuple is legacy shorthand for ("rows", a, b)."""
    if isinstance(frame, tuple):
        if len(frame) == 2:
            return ("rows", frame[0], frame[1])
        if len(frame) == 3 and frame[0] in ("rows", "range"):
            return frame
        raise ValueError(f"bad window frame {frame!r}")
    if frame not in ("running", "range_running", "unbounded"):
        raise ValueError(f"bad window frame {frame!r}")
    return frame


class Window(SparkPlan):
    def __init__(self, functions: List[WindowFunction],
                 partition_by: List[Expression],
                 order_by: List[Tuple[Expression, SortSpec]],
                 child: SparkPlan,
                 frame: str = "running"):
        super().__init__([child])
        self.functions = functions
        self.partition_by = partition_by
        self.order_by = order_by
        self.frame = normalize_frame(frame)  # see normalize_frame

    @property
    def child(self):
        return self.children[0]

    @property
    def output(self):
        fields = list(self.child.output.fields)
        fields += [T.StructField(f.result_name, f.result_type)
                   for f in self.functions]
        return T.StructType(fields)

    def describe(self):
        fns = ", ".join(f.func for f in self.functions)
        return f"Window [{fns}] frame={self.frame}"


class LocalLimit(SparkPlan):
    def __init__(self, n: int, child: SparkPlan):
        super().__init__([child])
        self.n = n

    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        return f"LocalLimit {self.n}"


class GlobalLimit(LocalLimit):
    def describe(self):
        return f"GlobalLimit {self.n}"


class Sample(SparkPlan):
    """Bernoulli row sample (GpuSampleExec analog).  The keep decision is
    the engine's deterministic splitmix64 stream keyed on (seed, row) —
    both backends draw identical samples (Spark's sampler is
    XORShift-based; documented divergence, same statistics)."""

    def __init__(self, fraction: float, seed: int, child: SparkPlan):
        super().__init__([child])
        self.fraction = float(fraction)
        self.seed = int(seed)

    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        return f"Sample fraction={self.fraction} seed={self.seed}"


class Union(SparkPlan):
    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        return f"Union ({len(self.children)} children)"


class InsertIntoHadoopFsRelation(SparkPlan):
    """Write command (DataWritingCommand analog).

    Reference analog: InsertIntoHadoopFsRelationCommand wrapped by
    GpuDataWritingCommandExec via the dataWriteCmds registry
    (SURVEY.md §2.2 GpuOverrides.dataWriteCmds, §2.6 Writers)."""

    def __init__(self, fmt: str, path: str, child: SparkPlan,
                 partition_cols=None, mode: str = "overwrite",
                 options=None):
        super().__init__([child])
        self.fmt = fmt
        self.path = path
        self.partition_cols = list(partition_cols or [])
        self.mode = mode
        self.options = dict(options or {})

    @property
    def output(self):
        return T.StructType([])

    def describe(self):
        p = f" partitionBy={self.partition_cols}" if self.partition_cols else ""
        return f"InsertIntoHadoopFsRelation {self.fmt} {self.path}{p}"
