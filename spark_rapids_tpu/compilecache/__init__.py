"""Plan-time AOT compilation pipeline + persistent executable cache.

On a tunnel-relayed TPU every fresh XLA compile costs minutes, and the seed
engine compiled every stage program lazily on the first batch of the first
run — serialized, inside the query's critical path (BENCH_r05 dropped two
queries on exactly that).  This package moves compilation off the critical
path with two halves:

* ``registry`` — an in-process executable registry: every exec routes its
  ``tpu_jit`` creation through :func:`cached_program` keyed by a
  collision-safe fingerprint (expression SQL + schemas + mode + relevant
  confs), so a re-planned query (fresh session, same logical plan) reuses
  the already-compiled programs instead of re-tracing.  Counters:
  ``compile_cache_hits`` / ``compile_cache_misses`` / ``compile_wall_ns``.

* ``aot`` — plan-time enumeration: after overrides produce the exec tree,
  :func:`submit_plan` walks it, predicts each stage program's (function x
  shape-bucket) from the plan's static row estimates, and compiles them
  concurrently on a bounded background pool — batch 1 of operator 1
  overlaps the compiles of everything downstream.  The runtime lookup
  blocks only when it reaches a program whose AOT compile is still in
  flight.

The cross-process half rides JAX's on-disk compilation cache
(``jax_compilation_cache_dir``), pointed at ``spark.rapids.tpu.compile.
cacheDir`` by the session (see session._apply_compile_cache) — a fresh
process re-running the same plan deserializes executables instead of
compiling.  Every path that enables the on-disk cache must first call
:func:`ensure_atomic_cache_put` (crash-consistent entry publication —
see its docstring for why torn entries segfault).
"""
import os
import time

_ATOMIC_PUT_APPLIED = False


def ensure_atomic_cache_put() -> None:
    """Make jax's persistent compile-cache writes crash-consistent.

    Stock ``jax._src.lru_cache.LRUCache.put`` writes the serialized
    executable to its FINAL path with one plain ``write_bytes`` — no
    tmp+rename.  Two real failure modes follow: a process killed
    mid-write (a crashed driver; the --driver-kill harness lands
    SIGKILLs exactly there) leaves a truncated entry at the final
    path, and a concurrent reader — the AOT background pool in this
    process, or a worker process sharing the directory — can read a
    half-written file.  Either way ``deserialize_executable`` on torn
    bytes SEGFAULTS the reader, possibly a completely different
    process days later.  Re-bind ``put`` to stage the bytes beside the
    final path and publish with ``os.replace``, so an entry is either
    absent or complete — the same discipline as the recovery journal's
    checkpoint commit (docs/recovery.md).  Idempotent; a jax without
    this cache layout is left untouched.
    """
    global _ATOMIC_PUT_APPLIED
    if _ATOMIC_PUT_APPLIED:
        return
    try:
        from jax._src import lru_cache as _lru

        _lru.LRUCache  # noqa: B018 — layout probe
    except Exception:
        return

    def _atomic_put(self, key, val):
        if not key:
            raise ValueError("key cannot be empty")
        if self.eviction_enabled and len(val) > self.max_size:
            return
        cache_path = self.path / f"{key}{_lru._CACHE_SUFFIX}"
        atime_path = self.path / f"{key}{_lru._ATIME_SUFFIX}"
        if self.eviction_enabled:
            self.lock.acquire(timeout=self.lock_timeout_secs)
        try:
            if cache_path.exists():
                return
            self._evict_if_needed(additional_size=len(val))
            tmp = cache_path.with_name(
                cache_path.name + f".tmp.{os.getpid()}")
            try:
                tmp.write_bytes(val)
                os.replace(tmp, cache_path)
            except OSError:
                # a broken disk degrades caching, never the query
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return
            try:
                atime_path.write_bytes(
                    time.time_ns().to_bytes(8, "little"))
            except OSError:
                pass
        finally:
            if self.eviction_enabled:
                self.lock.release()

    _lru.LRUCache.put = _atomic_put
    _ATOMIC_PUT_APPLIED = True
from spark_rapids_tpu.compilecache.keys import (  # noqa: F401
    conf_fp,
    exprs_fp,
    fingerprint,
    schema_fp,
)
from spark_rapids_tpu.compilecache.registry import (  # noqa: F401
    ProgramEntry,
    cached_program,
    get_registry,
    registry_enabled,
    reset_registry,
)
from spark_rapids_tpu.compilecache.aot import (  # noqa: F401
    AotSubmission,
    maybe_submit_aot,
    submit_plan,
)
