"""Plan-time AOT compilation pipeline + persistent executable cache.

On a tunnel-relayed TPU every fresh XLA compile costs minutes, and the seed
engine compiled every stage program lazily on the first batch of the first
run — serialized, inside the query's critical path (BENCH_r05 dropped two
queries on exactly that).  This package moves compilation off the critical
path with two halves:

* ``registry`` — an in-process executable registry: every exec routes its
  ``tpu_jit`` creation through :func:`cached_program` keyed by a
  collision-safe fingerprint (expression SQL + schemas + mode + relevant
  confs), so a re-planned query (fresh session, same logical plan) reuses
  the already-compiled programs instead of re-tracing.  Counters:
  ``compile_cache_hits`` / ``compile_cache_misses`` / ``compile_wall_ns``.

* ``aot`` — plan-time enumeration: after overrides produce the exec tree,
  :func:`submit_plan` walks it, predicts each stage program's (function x
  shape-bucket) from the plan's static row estimates, and compiles them
  concurrently on a bounded background pool — batch 1 of operator 1
  overlaps the compiles of everything downstream.  The runtime lookup
  blocks only when it reaches a program whose AOT compile is still in
  flight.

The cross-process half rides JAX's on-disk compilation cache
(``jax_compilation_cache_dir``), pointed at ``spark.rapids.tpu.compile.
cacheDir`` by the session (see session._apply_compile_cache) — a fresh
process re-running the same plan deserializes executables instead of
compiling.
"""
from spark_rapids_tpu.compilecache.keys import (  # noqa: F401
    conf_fp,
    exprs_fp,
    fingerprint,
    schema_fp,
)
from spark_rapids_tpu.compilecache.registry import (  # noqa: F401
    ProgramEntry,
    cached_program,
    get_registry,
    registry_enabled,
    reset_registry,
)
from spark_rapids_tpu.compilecache.aot import (  # noqa: F401
    AotSubmission,
    maybe_submit_aot,
    submit_plan,
)
