"""Plan-time AOT compilation — warm every stage program before its batch.

After overrides produce the exec tree, :func:`submit_plan` walks it in
execution order (post-order: the operators that run first submit first),
asks each exec for its :meth:`aot_programs` — the (stage function x
shape-bucket) programs the query will need, predicted from the plan's
static row estimates (``aot_output_rows``) — and compiles them on a
bounded background thread pool.  Batch 1 of operator 1 then overlaps the
compiles of everything downstream instead of serializing minute-long
compiles between launches; the runtime registry lookup blocks only when
it reaches a program whose background compile is still in flight.

Shape prediction is deliberately conservative: a program is enumerated
only when its input schema is fully static (flat numeric/decimal/bool/
date/timestamp columns — string widths and nested element widths are
data-dependent) and its input row count is derivable from the plan
(local/range scans and the narrow operators above them; anything below an
exchange or aggregate output is unknown).  A wrong guess only wastes one
background compile; a skipped program just compiles inline as before.

Warm-ups run ``jitted.lower(*abstract).compile()`` over ShapeDtypeStruct
operands — no device memory is allocated and nothing executes, so the
pool never competes with the query for HBM or bypasses the admission
semaphore.  The XLA compile lands in the persistent on-disk cache
(``spark.rapids.tpu.compile.cacheDir``, on by default), so the runtime's
first dispatch — and every future process — deserializes the executable
instead of compiling it: the minutes-long XLA build happens exactly once,
off the critical path.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future  # annotation only; pool is daemon
from typing import Callable, List, Optional, Sequence, Tuple

from spark_rapids_tpu import perfcounters as PC
from spark_rapids_tpu import types as T
from spark_rapids_tpu.compilecache.registry import (
    ProgramEntry,
    cached_program,
    registry_enabled,
)


class AotProgram:
    """One enumerable program: registry key parts + builder + dummy args.

    ``args_factory() -> list of concrete arg tuples`` — one per predicted
    shape bucket; the jitted program is shape-polymorphic, so one entry
    warms every bucket it will serve."""

    __slots__ = ("key_parts", "factory", "args_factory", "label")

    def __init__(self, key_parts, factory, args_factory, label: str):
        self.key_parts = key_parts
        self.factory = factory        # () -> (jitted, aux)
        self.args_factory = args_factory  # () -> [args, ...] (may be [])
        self.label = label


# ---------------------------------------------------------------------------
# dummy-batch construction (the abstract operand for the warm-up call)
# ---------------------------------------------------------------------------

def _static_field(dt: T.DataType) -> bool:
    """True when the device layout of this type is fully determined by the
    schema (no data-dependent widths)."""
    if isinstance(dt, (T.StringType, T.ArrayType, T.MapType, T.StructType)):
        return False
    return True


def abstract_scalar(dtype):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct((), jnp.dtype(dtype))


def abstract_array(shape, dtype):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))


def dummy_columns(schema: T.StructType, capacity: int):
    """ABSTRACT device columns (jax.ShapeDtypeStruct leaves) of
    ``capacity`` for a static schema, or None when any field's layout is
    data-dependent.  Abstract operands let the warm-up ``lower().
    compile()`` without allocating a byte of device memory or executing
    anything — the pool never competes with the query for HBM and never
    bypasses the admission semaphore."""
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar.column import DeviceColumn

    cols = []
    for f in schema.fields:
        dt = f.dataType
        if not _static_field(dt):
            return None
        validity = abstract_array((capacity,), jnp.bool_)
        if isinstance(dt, T.DecimalType) and dt.is_128:
            data = abstract_array((capacity, 2), jnp.int64)
        else:
            try:
                sdt = T.storage_dtype(dt)
            except Exception:
                return None
            data = abstract_array((capacity,), sdt)
        cols.append(DeviceColumn(dt, validity, data=data))
    return tuple(cols)


def dummy_batch_args(schema: T.StructType, rows: int):
    """The canonical (cols, num_rows) call signature most stage programs
    take, at the bucket capacity ``rows`` rounds up to."""
    import jax.numpy as jnp

    cols = dummy_columns(schema, bucket_of(rows))
    if cols is None:
        return None
    return (cols, abstract_scalar(jnp.int32))


def bucket_of(rows: int) -> int:
    # DEFAULT_ROW_BUCKETS, not the conf ladder: the runtime paths this
    # predicts for (from_host_columns, Range, concat) all bucket with the
    # module default — predicting from the conf would warm shapes nothing
    # ever dispatches whenever the conf differs
    from spark_rapids_tpu.columnar.column import (
        DEFAULT_ROW_BUCKETS,
        round_up_bucket,
    )

    return round_up_bucket(max(int(rows), 1), DEFAULT_ROW_BUCKETS)


def batch_caps(node):
    """Predicted per-batch capacities of an exec's output, or None."""
    fn = getattr(node, "aot_output_caps", None)
    return fn() if fn is not None else None


def concat_caps(node):
    """Predicted capacity list for the CONCATENATION of an exec's output
    batches: from its row estimate, or its capacity estimate when it is
    known to emit a single batch."""
    rows_fn = getattr(node, "aot_output_rows", None)
    rows = rows_fn() if rows_fn is not None else None
    if rows:
        return [bucket_of(sum(rows))]
    single = getattr(node, "aot_emits_single_batch", None)
    if single is not None and single():
        return batch_caps(node)
    return None


def single_word_keys(key_exprs) -> bool:
    """True when every join-key expression packs to exactly one sort-key
    word (flat <=64-bit types) — the precondition for predicting the
    probe program's build-words operand shape at plan time."""
    for e in key_exprs or []:
        dt = getattr(e, "dataType", None)
        if dt is None or not _static_field(dt):
            return False
        if isinstance(dt, T.DecimalType) and dt.is_128:
            return False
    return True


# ---------------------------------------------------------------------------
# the background pool
# ---------------------------------------------------------------------------

class _DaemonPool:
    """Minimal daemon-thread worker pool.  concurrent.futures joins its
    non-daemon workers at interpreter exit, which would make a short
    script hang for the duration of every queued speculative compile
    (minutes each on the tunnel platform); daemon workers just die —
    abandoned jobs' entries stay 'inflight', which only runtime lookups
    in this (already exiting) process would ever wait on."""

    def __init__(self, n: int):
        import queue

        self._q: "queue.Queue" = queue.Queue()
        for i in range(max(1, n)):
            t = threading.Thread(target=self._work,
                                 name=f"srt-aot-{i}", daemon=True)
            t.start()

    def _work(self):
        while True:
            fn, args = self._q.get()
            try:
                fn(*args)
            except Exception:
                pass
            finally:
                self._q.task_done()

    def submit(self, fn, *args):
        self._q.put((fn, args))
        return None

    def quiesce(self, timeout_s: float) -> bool:
        """Bounded wait for the queue to drain (all submitted jobs
        finished).  Daemon workers dying MID-COMPILE at interpreter
        exit can abort the whole process inside XLA's C++ teardown, so
        batch drivers that submit speculative compiles near their exit
        (the overload stress harness, ISSUE 13) drain here first.
        True when the pool went idle within the timeout."""
        import time as _time

        deadline = _time.monotonic() + max(timeout_s, 0.0)
        while _time.monotonic() < deadline:
            if self._q.unfinished_tasks == 0:
                return True
            _time.sleep(0.05)
        return self._q.unfinished_tasks == 0


_POOL: Optional[_DaemonPool] = None
_POOL_LOCK = threading.Lock()


def quiesce_aot(timeout_s: float = 30.0) -> bool:
    """Drain the background AOT pool if one exists (bounded); see
    :meth:`_DaemonPool.quiesce`."""
    pool = _POOL
    return pool.quiesce(timeout_s) if pool is not None else True


def _get_pool() -> _DaemonPool:
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            from spark_rapids_tpu.config import COMPILE_AOT_THREADS, get_conf

            _POOL = _DaemonPool(int(get_conf().get(COMPILE_AOT_THREADS)))
        return _POOL


def _compile_job(entry: ProgramEntry,
                 args_factory: Callable[[], Optional[tuple]],
                 label: str, conf=None, token=None,
                 owner_qid: Optional[str] = None) -> None:
    """Warm one program via the AOT API: ``jitted.lower(*abstract).
    compile()`` on the RAW jitted (bypassing the launch/compile perf
    counters — a background warm-up is not an engine launch).  Operands
    are abstract (ShapeDtypeStructs), so nothing allocates on device and
    nothing executes; the trace + XLA compile also land in JAX's
    lowering/executable caches and (when configured) the persistent
    on-disk cache, which is where the runtime's own dispatch finds them.
    The submitting query's conf is pinned thread-locally for the trace
    (expressions read conf at trace time; the main thread may re-plan
    another session meanwhile)."""
    import contextlib

    from spark_rapids_tpu.config import ambient_conf

    from spark_rapids_tpu.compilecache.registry import get_registry

    # claim the entry: a runtime lookup may have STOLEN a still-queued
    # job (compiling inline beats waiting behind the pool) — then this
    # job is a no-op
    with get_registry()._lock:
        if entry.aot_state != "queued":
            entry.ready_event.set()
            return
        entry.aot_state = "compiling"
    scope = ambient_conf(conf) if conf is not None \
        else contextlib.nullcontext()
    try:
        with scope:
            # a cancelled submitter's speculative warm-ups are dead work:
            # skip them (the runtime path compiles inline if ever needed)
            if token is not None and token.cancelled:
                return
            arg_sets = args_factory() or []
            if arg_sets and not isinstance(arg_sets, list):
                arg_sets = [arg_sets]
            raw = getattr(entry.jitted, "_jitted", entry.jitted)
            for args in arg_sets:
                if args is None:
                    continue
                if token is not None and token.cancelled:
                    return
                t0 = time.perf_counter_ns()
                raw.lower(*args).compile()
                dt = time.perf_counter_ns() - t0
                entry.compiled_by = "aot"
                PC.bump("aot_compiles")
                from spark_rapids_tpu.diagnostics import context as _DIAG

                rec = _DIAG.RECORDER
                if rec is not None:
                    rec.aot_compile(label, dt)
                # separate counter: compile_wall_ns is the CRITICAL-PATH
                # (inline) compile wall; folding background wall into it
                # would double-count every warmed program (the runtime's
                # first dispatch still pays the cache-deserialize there)
                PC.bump("aot_compile_wall_ns", dt)
                # live progress (ISSUE 12): the pool thread's wall
                # shows up under the SUBMITTING query, not nowhere
                from spark_rapids_tpu.progress import context as _PROG

                if _PROG.TRACKER is not None:
                    _PROG.TRACKER.add_background(
                        owner_qid, "aot_compile", dt)
    except Exception:
        # a failed warm-up must never hurt the query: the runtime path
        # compiles inline exactly as it would have without AOT
        PC.bump("aot_compile_errors")
    finally:
        entry.aot_state = "ready"
        entry.ready_event.set()


class AotSubmission:
    """Handle over one plan's submitted warm-ups."""

    def __init__(self):
        self.items: List[Tuple[str, ProgramEntry, Optional[Future]]] = []
        self.skipped: List[str] = []

    def add(self, label: str, entry: ProgramEntry, fut: Optional[Future]):
        self.items.append((label, entry, fut))

    @property
    def programs(self) -> List[str]:
        return [label for label, _, _ in self.items]

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted compile finished; True if all did.
        Cancellable: raises if the current query's token trips while
        waiting."""
        from spark_rapids_tpu.lifecycle.context import current_token

        token = current_token()
        deadline = None if timeout is None else time.monotonic() + timeout
        for _, entry, _fut in self.items:
            if entry.aot_state is None:
                continue   # was already compiled before this submission
            while True:
                left = None if deadline is None \
                    else max(deadline - time.monotonic(), 0.0)
                slice_s = 0.05 if token is not None else left
                if left is not None:
                    slice_s = min(slice_s, left) if slice_s is not None \
                        else left
                if entry.ready_event.wait(slice_s):
                    break
                if token is not None:
                    token.check()
                if deadline is not None and time.monotonic() >= deadline:
                    return False
        return True

    def states(self) -> dict:
        out = {}
        for label, entry, _ in self.items:
            out[label] = entry.aot_state or (
                "ready" if entry.traced() else "cold")
        return out

    def summary(self) -> str:
        st = self.states()
        ready = sum(1 for v in st.values() if v == "ready")
        return (f"aot: {ready}/{len(st)} programs ready, "
                f"{len(self.skipped)} skipped")


def submit_plan(root, wait: bool = False) -> AotSubmission:
    """Enumerate and background-compile every predictable program of an
    exec tree.  Post-order: the programs the iterator needs first are
    submitted (and thus likely finish) first."""
    sub = AotSubmission()
    if not registry_enabled():
        return sub
    # the lower().compile() warm-up does NOT populate the jit dispatch
    # cache (verified on jax 0.4.37: _cache_size() stays 0); its product
    # reaches the runtime THROUGH the persistent on-disk cache, which the
    # first dispatch deserializes.  Without a configured cache dir the
    # pool would double every compile and save nothing — skip entirely
    try:
        import jax

        if not getattr(jax.config, "jax_compilation_cache_dir", None):
            sub.skipped.append("persistent cache disabled: AOT would "
                              "double compile work")
            return sub
    except Exception:
        return sub
    from spark_rapids_tpu.config import get_conf
    from spark_rapids_tpu.lifecycle.context import current, current_token

    conf = get_conf()   # pinned for every background trace of this plan
    token = current_token()   # the submitting query's cancel token
    ctx = current()           # ...and its id, for progress attribution
    owner_qid = ctx.query_id if ctx is not None else None
    pool = _get_pool()
    seen_keys = set()
    for node in _post_order(root):
        progs = ()
        try:
            progs = node.aot_programs()
        except Exception:
            sub.skipped.append(f"{type(node).__name__}: enumeration failed")
            continue
        for prog in progs or ():
            if prog.key_parts is None:
                sub.skipped.append(prog.label)
                continue
            from spark_rapids_tpu.compilecache.keys import fingerprint

            # dedup BEFORE the registry lookup: a duplicate's non-waiting
            # hit would clear the original's handoff flag and miscount
            # the query's own first runtime claim as a cache hit
            fp = fingerprint(*prog.key_parts)
            if fp in seen_keys:
                continue
            seen_keys.add(fp)
            try:
                # non-blocking: the submitter must never sleep on another
                # plan's (or a duplicate program's) in-flight compile —
                # only runtime lookups wait for executables
                created: list = []
                entry = cached_program(prog.key_parts, prog.factory,
                                       prog.label, wait_inflight=False,
                                       created_out=created)
            except Exception:
                sub.skipped.append(prog.label)
                continue
            if not (created and created[0]):
                # ONLY entries this submission itself created are
                # background-compiled: an entry another (possibly
                # concurrently executing) query created may be mid-trace
                # on its thread — racing a second trace of the same fn
                # would corrupt shared trace-time aux state
                sub.add(prog.label, entry, None)
                continue
            entry.aot_state = "queued"
            entry.ready_event.clear()
            try:
                fut = pool.submit(_compile_job, entry, prog.args_factory,
                                  prog.label, conf, token, owner_qid)
            except Exception:
                # a failed submit (e.g. executor shutting down) must not
                # leave a queued entry nobody will ever mark ready —
                # the runtime lookup would block on it forever
                entry.aot_state = None
                entry.ready_event.set()
                sub.skipped.append(prog.label)
                continue
            sub.add(prog.label, entry, fut)
    if wait:
        sub.wait()
    return sub


def _post_order(node):
    for c in getattr(node, "children", []) or []:
        if hasattr(c, "aot_programs") or getattr(c, "children", None):
            yield from _post_order(c)
    if hasattr(node, "aot_programs"):
        yield node


def maybe_submit_aot(root, conf) -> Optional[AotSubmission]:
    """collect()-time hook: submit once per planned exec tree, never let a
    warm-up failure reach the query."""
    from spark_rapids_tpu.config import COMPILE_AOT_ENABLED

    try:
        if not conf.get(COMPILE_AOT_ENABLED):
            return None
        # overload governor (ISSUE 13): under YELLOW/RED, background
        # compiles DEFER — the pool threads' trace work and executable
        # memory are speculation pressure can reclaim.  Nothing is
        # stamped on the root, so a later collect under GREEN submits
        # normally.
        from spark_rapids_tpu.governor import context as _GOV

        gov = _GOV.GOVERNOR
        if gov is not None and gov.pause_background():
            return None
        existing = getattr(root, "_aot_submission", None)
        if existing is not None:
            return existing
        sub = submit_plan(root)
        try:
            root._aot_submission = sub
        except Exception:
            pass
        return sub
    except Exception:
        return None
