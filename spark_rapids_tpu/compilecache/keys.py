"""Program-key fingerprints — the cache-key anatomy.

A registry key must capture EVERYTHING a stage function's trace depends on:
a collision returns another stage's executable and silently corrupts
results, so keys err on the side of including too much (a spurious
difference only costs a hit).  Every key is built from:

  * the expression list (SQL string + result type per node — literals print
    their values, so constant-folding differences key apart),
  * the input/output schemas (name, type, nullability per field),
  * static mode flags (ansi, aggregate mode, join type, frame, ...) passed
    by the call site,
  * the ambient conf fingerprint (sorted settings) — conf knobs are read at
    trace time (hasNans, groups-cap, ...), so two sessions with different
    settings never share an executable.

Expressions that close over arbitrary Python state (UDFs, host-kernel
callbacks) are NOT fingerprintable: two different lambdas can print the
same SQL.  ``exprs_fp`` returns None for those and the call site falls
back to per-instance jit caching (correct, just not shared).
"""
from __future__ import annotations

import hashlib
from typing import Iterable, Optional

from spark_rapids_tpu import types as T


def fingerprint(*parts) -> str:
    """Stable digest of an arbitrary (repr-able) part tuple."""
    h = hashlib.sha1(repr(parts).encode("utf-8", "replace"))
    return h.hexdigest()


def schema_fp(schema: Optional[T.StructType]):
    """Schema fingerprint: (name, type) per field.  Nullability is
    deliberately EXCLUDED: materialized batches upgrade plan-declared
    nullable=False fields to True, traced programs never read the flag
    (validity vectors always exist; output nullability comes from the
    expressions), and keying on it would make every plan-time AOT key
    miss its runtime twin for non-nullable inputs."""
    if schema is None:
        return None
    return tuple((f.name, str(f.dataType)) for f in schema.fields)


# expressions whose trace bakes ambient per-instance/per-batch state that
# sql_string() cannot capture (row_offset, global current-file, ...)
_UNSAFE_EXPR_CLASSES = frozenset({
    "MonotonicallyIncreasingID", "SparkPartitionID", "InputFileName",
    "InputFileBlockStart", "InputFileBlockLength", "Rand", "Uuid",
})


def _expr_unsafe(e) -> bool:
    """True when the expression's trace depends on Python state its SQL
    string cannot capture: python UDF callables, host-kernel callbacks
    (jax.pure_callback closures), seeded nondeterministic streams
    (rand/uuid bake their seed and row offset at trace time), and
    ambient-state readers (monotonically_increasing_id, input_file_name)."""
    if callable(getattr(e, "fn", None)):
        return True
    if getattr(e, "is_host_kernel", False):
        return True
    if type(e).__name__ in _UNSAFE_EXPR_CLASSES:
        return True
    if hasattr(e, "captured_micros"):
        # current_date()/current_timestamp() capture the wall clock at
        # construction and bake it into the trace as a constant; sharing
        # the executable would freeze the first query's clock
        return True
    for c in getattr(e, "children", []) or []:
        if _expr_unsafe(c):
            return True
    return False


def exprs_fp(exprs: Optional[Iterable]):
    """Fingerprint parts for an expression list, or None when any
    expression is not safely fingerprintable (caller must then keep a
    per-instance jit instead of sharing through the registry)."""
    parts = []
    for e in exprs or []:
        if e is None:
            parts.append(None)
            continue
        if _expr_unsafe(e):
            return None
        try:
            sql = e.sql_string()
        except Exception:
            return None
        try:
            dt = str(e.dataType)
        except Exception:
            dt = type(e).__name__
        # deterministic numeric parameters that sql_string may not print
        # (hash seeds, anywhere in the tree) are part of the identity
        parts.append((type(e).__name__, sql, dt, _nested_seeds(e)))
    return tuple(parts)


def _nested_seeds(e, acc=None):
    acc = acc if acc is not None else []
    seed = getattr(e, "seed", None)
    if isinstance(seed, int):
        acc.append((type(e).__name__, seed))
    for c in getattr(e, "children", []) or []:
        _nested_seeds(c, acc)
    return tuple(acc)


def conf_fp() -> str:
    """Fingerprint of the ambient execution conf (config.get_conf()) —
    trace-time conf reads (hasNans, smallGroupsCap, buckets...) make the
    settings part of the program identity."""
    from spark_rapids_tpu.config import get_conf

    settings = get_conf().settings
    return fingerprint(tuple(sorted((str(k), str(v))
                                    for k, v in settings.items())))


def window_fns_fp(functions) -> Optional[tuple]:
    """Fingerprint parts for a WindowFunction list (plan/nodes.py)."""
    parts = []
    for wf in functions or []:
        child_fp = exprs_fp([wf.child] if wf.child is not None else [])
        if child_fp is None and wf.child is not None:
            return None
        parts.append((wf.func,
                      child_fp,
                      getattr(wf, "result_name", None),
                      str(getattr(wf, "result_type", None)),
                      getattr(wf, "offset", None),
                      repr(getattr(wf, "default", None)),
                      getattr(wf, "buckets", None),
                      bool(getattr(wf, "ignore_nulls", False))))
    return tuple(parts)


def aggs_fp(aggregates) -> Optional[tuple]:
    """Fingerprint parts for an AggregateExpression list."""
    parts = []
    for a in aggregates or []:
        kids = [a.child] if a.child is not None else []
        if getattr(a, "child2", None) is not None:
            kids.append(a.child2)
        kfp = exprs_fp(kids)
        if kfp is None and kids:
            return None
        parts.append((a.func, kfp, a.result_name,
                      str(getattr(a, "result_type", None)),
                      tuple(getattr(a, "args", ()) or ())))
    return tuple(parts)


def stage_ops_fp(ops) -> Optional[tuple]:
    """Fingerprint parts for a _StageOp list (exec/basic.py)."""
    parts = []
    for op in ops or []:
        efp = exprs_fp(list(getattr(op, "exprs", []) or [])
                       + ([op.condition]
                          if getattr(op, "condition", None) is not None
                          else []))
        if efp is None:
            return None
        parts.append((type(op).__name__, efp))
    return tuple(parts)
