"""In-process executable registry — the same-process half of the cache.

Reference analog: none — libcudf kernels are precompiled, so the reference
never thinks about executable identity.  On TPU the XLA compile IS the
kernel build step; this registry makes a compiled stage program a
process-wide asset keyed by its semantic fingerprint instead of a private
of whichever exec instance happened to trace it first.  A re-planned query
(fresh DataFrame, fresh session with equal settings, breaker-forced
re-plan) therefore compiles nothing the process has already built.

Entries hold the ``tpu_jit`` wrapper (shape-polymorphic: jax's own cache
keys the per-bucket executables under it) plus ``aux`` — trace-time
metadata the builder produced (e.g. a fused stage's ANSI error messages,
which fill as a tracing side effect and must travel WITH the executable).

Concurrency contract with the AOT pool (aot.py): while a background
compile of an entry is in flight, a runtime ``cached_program`` lookup for
the same key BLOCKS on the entry's ready event — the iterator waits only
when it reaches a program that is not ready yet, never races a duplicate
compile.

Bounded: ``spark.rapids.tpu.compile.registry.maxPrograms`` LRU-evicts so a
long test session cannot pin every executable it ever built.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Optional, Tuple

from spark_rapids_tpu import perfcounters as PC
from spark_rapids_tpu.diagnostics import context as _DIAG


class ProgramEntry:
    """One registered program: jitted callable + trace-time aux data."""

    __slots__ = ("key", "label", "jitted", "aux", "aot_state",
                 "ready_event", "compiled_by", "created_at", "hits",
                 "handoff_pending")

    def __init__(self, key: str, jitted, aux, label: str = ""):
        self.key = key
        self.label = label
        self.jitted = jitted
        self.aux = aux
        # None = never touched by the AOT pool; "inflight" = a background
        # compile owns it; "ready" = background compile finished (ok or not)
        # None = never touched by the AOT pool (or stolen back by the
        # runtime); "queued" = submitted, job not started; "compiling" =
        # a pool worker owns the trace; "ready" = job finished (ok or not)
        self.aot_state: Optional[str] = None
        self.ready_event = threading.Event()
        self.compiled_by = "inline"
        self.created_at = time.monotonic()
        self.hits = 0
        # True while an AOT-created entry awaits its OWN query's first
        # runtime lookup — that handoff is not reuse and must not count
        self.handoff_pending = False

    def traced(self) -> bool:
        """True once at least one shape specialization exists."""
        try:
            return self.jitted._cache_size() > 0
        except Exception:
            return True  # unknown cache API: assume warm, never re-submit


class ProgramRegistry:
    def __init__(self, max_programs: int = 1024):
        self._lock = threading.RLock()
        self._entries: "OrderedDict[str, ProgramEntry]" = OrderedDict()
        self.max_programs = max_programs

    def lookup(self, key: str,
               factory: Callable[[], Tuple[Any, Any]],
               label: str = "",
               wait_inflight: bool = True,
               created_out: Optional[list] = None) -> ProgramEntry:
        """Return the entry for ``key``, building it via ``factory`` on a
        miss.  ``factory() -> (jitted, aux)`` must be cheap (closure + jit
        wrapper creation; no tracing/compiling happens here).
        ``wait_inflight=False`` (the AOT submitter) returns immediately
        even when a background compile owns the entry — only RUNTIME
        lookups block for the executable.  ``created_out`` (a list)
        receives True/False for miss/hit."""
        with self._lock:
            e = self._entries.get(key)
            created = e is None
            if e is not None:
                self._entries.move_to_end(key)
                e.hits += 1
                # not reuse, so not a hit: the AOT submitter's own
                # re-lookups, and the first RUNTIME claim of an entry the
                # same plan's AOT pass just created (the handoff) —
                # otherwise every cold query would report hits == misses
                if wait_inflight:
                    if e.handoff_pending:
                        e.handoff_pending = False
                    else:
                        PC.bump("compile_cache_hits")
                        rec = _DIAG.RECORDER
                        if rec is not None:
                            rec.cache_event(True, label or e.label)
                else:
                    # a LATER submission touching the entry means the
                    # original query is done with it: any future runtime
                    # claim is genuine reuse
                    e.handoff_pending = False
                # steal: a background job still QUEUED (not compiling)
                # should not make the runtime wait behind unrelated pool
                # work — compiling inline now is strictly faster; the job
                # sees the state flip and becomes a no-op
                if wait_inflight and e.aot_state == "queued":
                    e.aot_state = None
                    e.ready_event.set()
            else:
                jitted, aux = factory()
                e = ProgramEntry(key, jitted, aux, label)
                e.handoff_pending = not wait_inflight
                self._entries[key] = e
                PC.bump("compile_cache_misses")
                rec = _DIAG.RECORDER
                if rec is not None:
                    rec.cache_event(False, label)
                # LRU bound; never evict an entry a background compile
                # still owns (the recompile would double minutes of work)
                excess = len(self._entries) - max(self.max_programs, 1)
                if excess > 0:
                    for k in list(self._entries):
                        if excess <= 0:
                            break
                        cand = self._entries[k]
                        if cand.aot_state in ("queued", "compiling"):
                            continue
                        del self._entries[k]
                        excess -= 1
            if created_out is not None:
                created_out.append(created)
        # outside the lock: a hit on an entry whose AOT compile is
        # actively running waits for it (the "iterator blocks only if the
        # program is not ready yet" contract); the job sets the event in
        # a finally.  Bounded as a last-resort guard — if the event never
        # fires (killed pool, interpreter teardown) the caller proceeds
        # and compiles inline, which is always safe
        # generous cap: proceeding while the pool worker is mid-trace of
        # the SAME fn would race the shared trace-time aux (ANSI message
        # store) — blocking longer is strictly safer than corrupting it,
        # and "compiling" is only ever set by an actively running job.
        # Cancellable (ISSUE 4): a cancelled/deadline-tripped query must
        # not sit behind minutes of pool compile work, so inside a query
        # the wait polls the CancelToken in short slices
        from spark_rapids_tpu.lifecycle.context import current_token

        token = current_token()
        waited = 0.0
        while wait_inflight and e.aot_state == "compiling" \
                and waited < 7200.0:
            slice_s = 0.05 if token is not None else 30.0
            if e.ready_event.wait(slice_s):
                break
            waited += slice_s
            if token is not None:
                token.check()
        return e

    def peek(self, key: str) -> Optional[ProgramEntry]:
        with self._lock:
            return self._entries.get(key)

    def stats(self) -> dict:
        with self._lock:
            states = {}
            for e in self._entries.values():
                states[e.aot_state or "inline"] = \
                    states.get(e.aot_state or "inline", 0) + 1
            return {"programs": len(self._entries), "by_state": states}

    def entries(self):
        with self._lock:
            return list(self._entries.values())

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()


_REGISTRY = ProgramRegistry()


def get_registry() -> ProgramRegistry:
    return _REGISTRY


def reset_registry() -> None:
    _REGISTRY.reset()


def registry_enabled() -> bool:
    from spark_rapids_tpu.config import COMPILE_REGISTRY_ENABLED, get_conf

    return bool(get_conf().get(COMPILE_REGISTRY_ENABLED))


def cached_program(key_parts, factory: Callable[[], Tuple[Any, Any]],
                   label: str = "",
                   wait_inflight: bool = True,
                   created_out: Optional[list] = None) -> ProgramEntry:
    """The exec-layer entry point: fingerprint ``key_parts``, return the
    shared entry (or an unregistered one when the registry kill switch is
    off, or when key_parts is None — i.e. the caller's expressions were
    not safely fingerprintable)."""
    from spark_rapids_tpu.compilecache.keys import fingerprint

    if key_parts is None or not registry_enabled():
        jitted, aux = factory()
        if created_out is not None:
            created_out.append(True)
        return ProgramEntry("<unregistered>", jitted, aux, label)
    from spark_rapids_tpu.config import COMPILE_REGISTRY_MAX_PROGRAMS, \
        get_conf

    _REGISTRY.max_programs = int(get_conf().get(
        COMPILE_REGISTRY_MAX_PROGRAMS))
    return _REGISTRY.lookup(fingerprint(*key_parts), factory, label,
                            wait_inflight=wait_inflight,
                            created_out=created_out)


def cached_jit_program(key_parts, builder, label: str = "", **jit_kwargs):
    """The shared exec-layer wrapper most call sites want: a ``tpu_jit``
    of ``builder`` shared through the registry when ``key_parts`` is
    fingerprintable, instance-private otherwise.  Returns the jitted
    callable."""
    from spark_rapids_tpu.perfcounters import tpu_jit

    if key_parts is None:
        return tpu_jit(builder, **jit_kwargs)
    return cached_program(
        key_parts, lambda: (tpu_jit(builder, **jit_kwargs), None),
        label=label).jitted
