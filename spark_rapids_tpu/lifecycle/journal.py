"""Durable query journal + stage checkpoints — crash-consistent driver
recovery (ISSUE 16, docs/recovery.md).

Reference analog: Spark's driver survives executor loss but not its own
death; Theseus (arXiv:2508.05029) makes materialized stage outputs the
recovery unit so a restarted control plane resumes from the last
committed data movement instead of re-running the world.  This module is
the driver-side durability tier:

  * **query journal** — a write-ahead log of CRC-framed records (the
    ``TKU2``/``TKD1`` framing discipline: magic + crc32 + length-prefixed
    payload, one ``os.write`` per record so the file is always
    prefix-consistent).  Records: query admission (trace id + conf
    snapshot), plan identity (``compilecache/keys.py`` fingerprints),
    stage-boundary checkpoint commits, stage serves, and query end.
    ``spark.rapids.tpu.recovery.fsyncOnAppend`` mirrors the
    ``files.fsyncOnCommit`` durability knob.
  * **stage checkpoints** — one exchange's materialized output made
    durable at its stage boundary.  Local: the partition queues' framed
    blobs land as length-prefixed part files committed by an atomic
    tmp+rename of the whole checkpoint directory, manifest CRCs pinning
    every byte.  Distributed: the worker-held partitions are the
    checkpoint; the journal records a LEASE (wire exchange id, placement,
    per-partition block counts, expiry) pinning them past driver death.
  * **recovery replay** — a reborn driver (the next ``QueryJournal``
    opened on the same ``recovery.dir``) rotates the prior incarnation's
    WAL, replays it damage-tolerantly (a truncated tail, a flipped bit,
    or a newer schema version each degrade to clean full re-execution —
    ``journal_recovery_discards``), classifies every journaled query as
    completed / resumable / abandoned, retires checkpoints past
    ``recovery.leaseTtlMs`` (``recovery_leases_expired``), and carries
    still-adoptable checkpoints forward into the new WAL.  Exchanges
    whose plan-stage fingerprint matches an adoptable checkpoint serve
    the committed output instead of re-executing their child
    (``stages_recovered`` / ``queries_resumed``).

Disabled path: with ``spark.rapids.tpu.recovery.enabled`` off nothing
imports this module on the hot path — one ambient conf check per site,
zero journal calls (cProfile-pinned by tests/test_recovery.py).
"""
from __future__ import annotations

import json
import os
import shutil
import struct
import tempfile
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Set, Tuple

from spark_rapids_tpu import perfcounters as PC

MAGIC = b"TKJ1"
SCHEMA_VERSION = 1

WAL_NAME = "journal.wal"
REPLAY_NAME = "journal.replay"
ENDPOINT_NAME = "coordinator.endpoint"
CHECKPOINT_DIR = "checkpoints"

LOCAL = "local"
LEASE = "lease"

COMPLETED = "completed"
RESUMABLE = "resumable"
ABANDONED = "abandoned"

# test hook: called as hook(kind, n_records_this_incarnation) after every
# WAL append — the driver-kill harness SIGKILLs itself here to land
# kills exactly at admit/commit boundaries
TEST_RECORD_HOOK: Optional[Callable[[str, int], None]] = None

_lock = threading.Lock()
_journal: "Optional[QueryJournal]" = None
# every recovery root a journal touched in this process — the conftest
# leak gate sweeps these (leftover checkpoint dirs / un-ended journaled
# queries fail the owning test)
_ACTIVE_ROOTS: Set[str] = set()


# ---------------------------------------------------------------------------
# record framing (TKU2 discipline: magic + crc + length-prefixed payload)
# ---------------------------------------------------------------------------

def frame_record(rec: Dict) -> bytes:
    """One journal frame: ``MAGIC + u32 crc32(payload) + u32 len +
    payload`` (payload = compact JSON).  Written with a single
    ``os.write`` on an O_APPEND fd, so a crash mid-append leaves at
    worst one torn TAIL frame — which replay discards."""
    payload = json.dumps(rec, sort_keys=True,
                         separators=(",", ":")).encode("utf-8")
    return (MAGIC + struct.pack("<I", zlib.crc32(payload) & 0xFFFFFFFF)
            + struct.pack("<I", len(payload)) + payload)


def parse_frames(data: bytes) -> Tuple[List[Dict], bool]:
    """Replay one journal file's bytes.  Returns (records, damaged):
    parsing stops at the first bad magic, CRC mismatch, torn tail, or
    record from a NEWER schema version — everything before the damage
    is the trusted prefix, everything after is discarded (the WAL
    contract: appends are atomic, so damage can only be a tail or rot,
    and either way the clean degrade is full re-execution)."""
    out: List[Dict] = []
    off = 0
    n = len(data)
    while off < n:
        if n - off < 12 or data[off:off + 4] != MAGIC:
            return out, True
        crc, ln = struct.unpack_from("<II", data, off + 4)
        if n - off - 12 < ln:
            return out, True          # torn tail record
        payload = data[off + 12:off + 12 + ln]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            return out, True          # bit rot
        try:
            rec = json.loads(payload.decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            return out, True
        if not isinstance(rec, dict) \
                or int(rec.get("v", 0)) > SCHEMA_VERSION:
            # a journal written by a newer engine: nothing from here on
            # is interpretable — degrade to full re-execution
            return out, True
        out.append(rec)
        off += 12 + ln
    return out, False


def _read_journal_file(path: str) -> Tuple[List[Dict], bool]:
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return [], True
    if not data:
        return [], False
    return parse_frames(data)


# ---------------------------------------------------------------------------
# roots / endpoint file
# ---------------------------------------------------------------------------

def resolve_root(conf) -> str:
    from spark_rapids_tpu.config import RECOVERY_DIR

    root = conf.get(RECOVERY_DIR)
    if not root:
        root = os.path.join(tempfile.gettempdir(), "srt_recovery")
    return root


def write_endpoint(root: str, host: str, port: int) -> str:
    """Publish the coordinator's control endpoint under the recovery
    root (atomic tmp+rename) so workers that outlived a dead driver can
    re-attach to its successor."""
    os.makedirs(root, exist_ok=True)
    path = os.path.join(root, ENDPOINT_NAME)
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(f"{host}:{port}\n")
    os.replace(tmp, path)
    return path


def read_endpoint(root: str) -> Optional[Tuple[str, int]]:
    try:
        with open(os.path.join(root, ENDPOINT_NAME)) as f:
            host, port = f.read().strip().rsplit(":", 1)
        return host, int(port)
    except (OSError, ValueError):
        return None


def plan_tree_fp(node) -> tuple:
    """Plan-identity fingerprint parts for one exec subtree: (class,
    describe) per node in preorder.  ``describe()`` prints expressions,
    partitioning, and scan paths, so two different child plans that
    happen to share an exchange's output schema + partitioning key
    apart — a checkpoint must never serve another subtree's rows."""
    from spark_rapids_tpu.lifecycle import QueryCancelled

    parts = []

    def walk(n):
        try:
            d = n.describe()
        except QueryCancelled:
            raise
        except Exception:
            d = ""
        parts.append((type(n).__name__, d))
        for c in getattr(n, "children", []) or []:
            walk(c)

    walk(node)
    return tuple(parts)


# ---------------------------------------------------------------------------
# local checkpoint store (atomic tmp+rename, manifest-CRC-pinned)
# ---------------------------------------------------------------------------

def _ckpt_root(root: str) -> str:
    return os.path.join(root, CHECKPOINT_DIR)


def _ckpt_dir(root: str, fp: str) -> str:
    return os.path.join(_ckpt_root(root), fp)


def _write_local_checkpoint(root: str, fp: str, qid: str,
                            parts: Dict[int, List[bytes]],
                            fsync: bool) -> Dict:
    """Write one stage's partitions as length-prefixed framed blobs +
    a manifest, then atomically rename the whole directory into place.
    Returns the manifest dict (raises on I/O failure — the caller
    treats a failed commit as 'stage not checkpointed', never as a
    query error)."""
    base = _ckpt_root(root)
    os.makedirs(base, exist_ok=True)
    tmp = os.path.join(base, f".tmp.{fp}.{os.getpid()}")
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    manifest: Dict = {"v": SCHEMA_VERSION, "fp": fp, "q": qid,
                      "ts": time.time(), "parts": {}}
    try:
        for pid, blobs in parts.items():
            path = os.path.join(tmp, f"part_{pid}.bin")
            buf = b"".join(struct.pack("<I", len(b)) + b for b in blobs)
            with open(path, "wb") as f:
                f.write(buf)
                if fsync:
                    f.flush()
                    os.fsync(f.fileno())
            manifest["parts"][str(pid)] = {
                "n": len(blobs), "bytes": len(buf),
                "crc": zlib.crc32(buf) & 0xFFFFFFFF}
        mpath = os.path.join(tmp, "MANIFEST.json")
        with open(mpath, "w") as f:
            json.dump(manifest, f, sort_keys=True)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        final = _ckpt_dir(root, fp)
        shutil.rmtree(final, ignore_errors=True)
        os.rename(tmp, final)
        if fsync:
            dfd = os.open(base, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return manifest


def load_local_stage(root: str, fp: str
                     ) -> Optional[Dict[int, List[bytes]]]:
    """Read back one committed local checkpoint, verifying every part
    file against the manifest CRC.  None on ANY damage (missing file,
    size/CRC mismatch, unreadable manifest) — the caller counts a
    discard and re-executes."""
    d = _ckpt_dir(root, fp)
    try:
        with open(os.path.join(d, "MANIFEST.json")) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    if int(manifest.get("v", 0)) > SCHEMA_VERSION \
            or manifest.get("fp") != fp:
        return None
    out: Dict[int, List[bytes]] = {}
    for pid_s, meta in (manifest.get("parts") or {}).items():
        try:
            with open(os.path.join(d, f"part_{pid_s}.bin"), "rb") as f:
                buf = f.read()
        except OSError:
            return None
        if len(buf) != int(meta.get("bytes", -1)) \
                or (zlib.crc32(buf) & 0xFFFFFFFF) != int(meta.get("crc", -1)):
            return None
        blobs: List[bytes] = []
        off = 0
        while off < len(buf):
            if len(buf) - off < 4:
                return None
            (ln,) = struct.unpack_from("<I", buf, off)
            off += 4
            if len(buf) - off < ln:
                return None
            blobs.append(buf[off:off + ln])
            off += ln
        if len(blobs) != int(meta.get("n", -1)):
            return None
        out[int(pid_s)] = blobs
    return out


# ---------------------------------------------------------------------------
# recovery state (the replay product)
# ---------------------------------------------------------------------------

class RecoveryState:
    """What one rotation's replay produced: the prior incarnation's
    query classification and the still-adoptable stage checkpoints."""

    def __init__(self):
        self.classification: Dict[str, str] = {}
        # fp -> the adoptable ckpt record ({"ckind": local|lease, ...})
        self.pending: Dict[str, Dict] = {}
        self.replayed_records = 0
        self.discards = 0
        self.expired = 0
        # queries of THIS incarnation that adopted >= 1 stage
        self._resumed_qids: Set[str] = set()


def _build_recovery(root: str, records: List[Dict],
                    damaged_files: int, lease_ttl_s: float
                    ) -> RecoveryState:
    st = RecoveryState()
    st.replayed_records = len(records)
    st.discards += damaged_files
    queries: Dict[str, Dict] = {}
    ckpts: Dict[str, Dict] = {}
    served: Set[str] = set()
    for r in records:
        kind = r.get("kind")
        q = str(r.get("q", ""))
        if kind == "admit":
            queries.setdefault(q, {"ended": None, "ckpts": set()})
        elif kind == "end":
            queries.setdefault(q, {"ended": None, "ckpts": set()})
            queries[q]["ended"] = str(r.get("status", "ok"))
        elif kind == "ckpt":
            fp = str(r.get("fp", ""))
            if fp:
                ckpts[fp] = r
                queries.setdefault(
                    q, {"ended": None, "ckpts": set()})["ckpts"].add(fp)
        elif kind == "served":
            served.add(str(r.get("fp", "")))
    now = time.time()
    for fp, rec in ckpts.items():
        q = str(rec.get("q", ""))
        owner = queries.get(q)
        if fp in served or (owner is not None
                            and owner["ended"] is not None):
            continue            # superseded: the query finished cleanly
        expires = float(rec.get("expires", 0.0) or 0.0)
        if expires and now > expires:
            st.expired += 1
            PC.bump("recovery_leases_expired")
            if rec.get("ckind") == LOCAL:
                shutil.rmtree(_ckpt_dir(root, fp), ignore_errors=True)
            continue
        if rec.get("ckind") == LOCAL:
            # validate eagerly: a damaged checkpoint must degrade HERE,
            # not mid-query
            if load_local_stage(root, fp) is None:
                st.discards += 1
                PC.bump("journal_recovery_discards")
                shutil.rmtree(_ckpt_dir(root, fp), ignore_errors=True)
                continue
        st.pending[fp] = rec
    # classify every journaled query
    for q, info in queries.items():
        if not q:
            continue
        if info["ended"] is not None:
            st.classification[q] = COMPLETED
        elif any(fp in st.pending for fp in info["ckpts"]):
            st.classification[q] = RESUMABLE
        else:
            st.classification[q] = ABANDONED
    if damaged_files:
        PC.bump("journal_recovery_discards", damaged_files)
    # orphan sweep: checkpoint dirs with no adoptable record (a crash
    # between the dir rename and its journal append, or a serve whose
    # delete failed) are unreachable — purge them
    base = _ckpt_root(root)
    try:
        names = os.listdir(base)
    except OSError:
        names = []
    for name in names:
        if name in st.pending:
            continue
        victim = os.path.join(base, name)
        if not name.startswith(".tmp."):
            st.discards += 1
            PC.bump("journal_recovery_discards")
        shutil.rmtree(victim, ignore_errors=True)
    return st


# ---------------------------------------------------------------------------
# the journal
# ---------------------------------------------------------------------------

class QueryJournal:
    """One incarnation's WAL over one recovery root.  Construction IS
    recovery: any prior WAL rotates to ``journal.replay``, replays into
    a :class:`RecoveryState`, still-adoptable checkpoints are carried
    forward into the fresh WAL, and the replay file is deleted — the
    new WAL is always the single source of truth."""

    def __init__(self, root: str, fsync: bool = False,
                 lease_ttl_ms: int = 120_000):
        self.root = root
        self.fsync = bool(fsync)
        self.lease_ttl_s = max(float(lease_ttl_ms), 0.0) / 1000.0
        self._lock = threading.Lock()
        self._n_records = 0
        # this incarnation's live bookkeeping for end-of-query GC and
        # the leak gate: qid -> [(ckind, fp)], and un-ended admits
        self._committed: Dict[str, List[Tuple[str, str]]] = {}
        self._active_qids: Set[str] = set()
        os.makedirs(root, exist_ok=True)
        with _lock:
            _ACTIVE_ROOTS.add(root)

        wal = os.path.join(root, WAL_NAME)
        replay = os.path.join(root, REPLAY_NAME)
        records: List[Dict] = []
        damaged = 0
        # a leftover journal.replay means the PREVIOUS recovery crashed
        # mid-rotation: fold it first (it is older than the wal)
        for path in (replay, wal):
            if os.path.exists(path):
                recs, bad = _read_journal_file(path)
                records.extend(recs)
                damaged += 1 if bad else 0
        if os.path.exists(wal):
            os.replace(wal, replay)
        self.recovery = _build_recovery(root, records, damaged,
                                        self.lease_ttl_s)
        self._fd = os.open(wal, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                           0o644)
        # carry still-adoptable checkpoints forward so a crash of THIS
        # incarnation before serving them keeps them recoverable
        for rec in self.recovery.pending.values():
            self._append(dict(rec))
        try:
            os.unlink(replay)
        except OSError:
            pass

    # -- append ----------------------------------------------------------
    def _append(self, rec: Dict) -> None:
        rec.setdefault("v", SCHEMA_VERSION)
        rec.setdefault("ts", time.time())
        frame = frame_record(rec)
        with self._lock:
            if self._fd is None:
                return
            os.write(self._fd, frame)
            if self.fsync:
                os.fsync(self._fd)
            self._n_records += 1
            n = self._n_records
        PC.bump("journal_records_written")
        hook = TEST_RECORD_HOOK
        if hook is not None:
            hook(str(rec.get("kind", "")), n)

    # -- query lifecycle records ----------------------------------------
    def admit(self, qid: str, trace_id: str, conf) -> None:
        settings = dict(getattr(conf, "settings", {}) or {})
        from spark_rapids_tpu.compilecache.keys import fingerprint

        self._active_qids.add(qid)
        self._append({
            "kind": "admit", "q": qid, "trace": trace_id,
            "conf_fp": fingerprint(tuple(sorted(
                (str(k), str(v)) for k, v in settings.items()))),
            "conf": {str(k): str(v) for k, v in settings.items()}})

    def plan(self, qid: str, root_exec) -> None:
        from spark_rapids_tpu.compilecache.keys import fingerprint

        self._append({"kind": "plan", "q": qid,
                      "plan_fp": fingerprint(plan_tree_fp(root_exec))})

    def end(self, qid: str, status: str) -> None:
        self._append({"kind": "end", "q": qid, "status": status})
        self._active_qids.discard(qid)
        # the query finished cleanly: its checkpoints are garbage (a
        # restart would classify it completed and never adopt them)
        for ckind, fp in self._committed.pop(qid, []):
            if ckind == LOCAL:
                shutil.rmtree(_ckpt_dir(self.root, fp),
                              ignore_errors=True)
        try:
            self._reconcile_worker_holdings()
        # tpulint: disable=cancel-swallow (durability isolation: a
        # failed orphan sweep must never fail query teardown)
        except Exception:
            pass

    def _reconcile_worker_holdings(self) -> None:
        """Release worker-held partitions a dead incarnation shipped but
        never lease-committed (or whose lease was retired) — orphans no
        replay will ever adopt.  Runs at query end, the first driver-side
        point where re-attaching workers have certainly enumerated their
        holdings; still-pending leases stay pinned."""
        from spark_rapids_tpu.distributed import peek_coordinator

        coord = peek_coordinator()
        if coord is None:
            return
        keep = {int(rec.get("wire", -1))
                for rec in self.recovery.pending.values()
                if rec.get("ckind") == LEASE}
        n = coord.release_orphan_holdings(keep)
        if n:
            self._diag("orphans_released", "-", f"wires={n}", n)

    # -- stage checkpoints ----------------------------------------------
    def commit_local_stage(self, fp: str, qid: str,
                           parts: Dict[int, List[bytes]]) -> bool:
        """Commit one local stage: part files + manifest land under a
        tmp dir, the dir renames into place atomically, THEN the
        journal records the commit — a crash anywhere leaves either a
        fully-adoptable checkpoint or an orphan the next replay
        purges."""
        try:
            _write_local_checkpoint(self.root, fp, qid, parts,
                                    self.fsync)
        except OSError:
            return False
        self._committed.setdefault(qid, []).append((LOCAL, fp))
        n_blobs = {str(p): len(b) for p, b in parts.items()}
        self._append({"kind": "ckpt", "ckind": LOCAL, "q": qid,
                      "fp": fp, "parts": n_blobs,
                      "expires": time.time() + self.lease_ttl_s})
        self._diag("stage_committed", fp,
                   f"local n_parts={len(parts)}", len(parts))
        return True

    def commit_lease(self, fp: str, qid: str, wire: int,
                     placement: Dict[int, str],
                     counts: Dict[int, int]) -> None:
        """Commit one distributed stage: the worker-held partitions ARE
        the checkpoint; this lease record pins them past driver death
        (workers re-attach and re-enumerate them) until it expires."""
        self._committed.setdefault(qid, []).append((LEASE, fp))
        self._append({"kind": "ckpt", "ckind": LEASE, "q": qid,
                      "fp": fp, "wire": int(wire),
                      "placement": {str(p): w
                                    for p, w in placement.items()},
                      "counts": {str(p): int(n)
                                 for p, n in counts.items()},
                      "expires": time.time() + self.lease_ttl_s})
        self._diag("stage_committed", fp,
                   f"lease wire={wire} n_parts={len(counts)}",
                   len(counts))

    # -- recovery lookup / serve ----------------------------------------
    def lookup_stage(self, fp: str):
        """An adoptable prior-incarnation checkpoint for this plan-stage
        fingerprint, or None.  Returns ``("local", {pid: [blobs]})`` or
        ``("lease", wire, {pid: wid}, {pid: n_blocks})`` — for a lease
        the coordinator's worker inventory must fully cover the
        recorded block counts (workers re-HELLOed what they hold), and
        adoption registers the placement under the original wire id."""
        rec = self.recovery.pending.get(fp)
        if rec is None:
            return None
        if rec.get("ckind") == LOCAL:
            parts = load_local_stage(self.root, fp)
            if parts is None:
                self.discard_stage(fp, "checkpoint damaged")
                return None
            return (LOCAL, parts)
        # lease: match recorded counts against live worker inventory
        from spark_rapids_tpu.distributed import peek_coordinator

        coord = peek_coordinator()
        if coord is None:
            return None
        wire = int(rec.get("wire", -1))
        counts = {int(p): int(n)
                  for p, n in (rec.get("counts") or {}).items()}
        inv = coord.worker_inventory()
        placement: Dict[int, str] = {}
        for pid, need in counts.items():
            owner = None
            for wid, held in inv.items():
                for exch, hpid, n, _mx in held:
                    if exch == wire and hpid == pid and n >= need:
                        owner = wid
                        break
                if owner is not None:
                    break
            if owner is None:
                return None     # not (yet) covered — workers may still
                                # be re-attaching; the lease stays pending
            placement[pid] = owner
        coord.adopt_exchange(wire, placement, counts)
        return (LEASE, wire, placement, counts)

    def mark_recovered(self, fp: str, qid: str, n_parts: int) -> None:
        """One stage was served from its checkpoint instead of
        re-executing."""
        rec = self.recovery.pending.pop(fp, None)
        self._append({"kind": "served", "fp": fp, "q": qid})
        if rec is not None and rec.get("ckind") == LOCAL:
            shutil.rmtree(_ckpt_dir(self.root, fp), ignore_errors=True)
        PC.bump("stages_recovered")
        if qid not in self.recovery._resumed_qids:
            self.recovery._resumed_qids.add(qid)
            PC.bump("queries_resumed")
            self._diag("query_resumed", fp, f"query={qid}", 1)
        self._diag("stage_recovered", fp, f"query={qid}", n_parts)

    def discard_stage(self, fp: str, reason: str) -> None:
        rec = self.recovery.pending.pop(fp, None)
        if rec is not None:
            self._append({"kind": "served", "fp": fp, "q": "-"})
            if rec.get("ckind") == LOCAL:
                shutil.rmtree(_ckpt_dir(self.root, fp),
                              ignore_errors=True)
        PC.bump("journal_recovery_discards")
        self._diag("checkpoint_discarded", fp, reason, 0)

    def retire_expired(self) -> int:
        """Drop pending checkpoints past their expiry (callable from
        tooling/long-lived services; replay already retires anything
        expired at rotation time).  Returns how many retired."""
        now = time.time()
        victims = [fp for fp, rec in self.recovery.pending.items()
                   if float(rec.get("expires", 0) or 0) and
                   now > float(rec.get("expires", 0))]
        for fp in victims:
            rec = self.recovery.pending.pop(fp)
            self._append({"kind": "served", "fp": fp, "q": "-"})
            if rec.get("ckind") == LOCAL:
                shutil.rmtree(_ckpt_dir(self.root, fp),
                              ignore_errors=True)
            PC.bump("recovery_leases_expired")
            self._diag("checkpoint_discarded", fp, "lease expired", 0)
        return len(victims)

    # -- observability / hygiene ----------------------------------------
    def _diag(self, kind: str, fp: str, detail: str, n: int) -> None:
        from spark_rapids_tpu.diagnostics import context as _DIAG

        rec = _DIAG.RECORDER
        if rec is not None:
            rec.recovery(kind, fp, detail, n)

    def startup_postmortem(self) -> Optional[Dict]:
        """The crashed-incarnation post-mortem (telemetry satellite):
        when replay found un-completed queries, bundle the
        classification + the journal tail into a flight-recorder dump
        so the crash is investigable from the reborn process.  None
        when telemetry is off or nothing crashed."""
        crashed = {q: c for q, c in self.recovery.classification.items()
                   if c != COMPLETED}
        if not crashed:
            return None
        from spark_rapids_tpu.telemetry import context as TEL

        hub = TEL.HUB
        if hub is None:
            return None
        try:
            return hub.postmortem(
                "driver_crash", detail=f"{len(crashed)} queries "
                f"un-completed at restart", force=True,
                extra={"classification": self.recovery.classification,
                       "pending_stages": sorted(self.recovery.pending),
                       "replayed_records":
                           self.recovery.replayed_records,
                       "journal_discards": self.recovery.discards})
        # tpulint: disable=cancel-swallow (telemetry isolation: a dump
        # failure must never break recovery)
        except Exception:
            return None

    def leak_lines(self) -> List[str]:
        out = []
        for qid in sorted(self._active_qids):
            out.append(f"LEAK: recovery journal query {qid} admitted "
                       f"but never ended")
        for fp in sorted(self.recovery.pending):
            out.append(f"LEAK: recovery checkpoint {fp} still pending "
                       f"(never served nor retired)")
        base = _ckpt_root(self.root)
        try:
            names = sorted(os.listdir(base))
        except OSError:
            names = []
        live = {fp for lst in self._committed.values() for _, fp in lst}
        live |= set(self.recovery.pending)
        for name in names:
            if name not in live:
                out.append(f"LEAK: recovery checkpoint dir {name} "
                           f"left on disk")
        return out

    def close(self, purge: bool = False) -> None:
        with self._lock:
            fd, self._fd = self._fd, None
        if fd is not None:
            try:
                os.close(fd)
            except OSError:
                pass
        if purge:
            for name in (WAL_NAME, REPLAY_NAME, ENDPOINT_NAME):
                try:
                    os.unlink(os.path.join(self.root, name))
                except OSError:
                    pass
            shutil.rmtree(_ckpt_root(self.root), ignore_errors=True)
            with _lock:
                _ACTIVE_ROOTS.discard(self.root)


# ---------------------------------------------------------------------------
# module singleton + lifecycle hooks
# ---------------------------------------------------------------------------

def get_journal(conf) -> QueryJournal:
    """The process journal, opened (= recovered) on first use.  A conf
    pointing at a DIFFERENT root swaps the singleton (tests; a real
    driver has one root for its lifetime)."""
    global _journal
    from spark_rapids_tpu.config import (
        RECOVERY_FSYNC,
        RECOVERY_LEASE_TTL_MS,
    )

    root = resolve_root(conf)
    with _lock:
        if _journal is not None and _journal.root == root:
            return _journal
        old, _journal = _journal, None
    if old is not None:
        old.close()
    j = QueryJournal(root, fsync=bool(conf.get(RECOVERY_FSYNC)),
                     lease_ttl_ms=int(conf.get(RECOVERY_LEASE_TTL_MS)))
    j.startup_postmortem()
    with _lock:
        _journal = j
    return j


def peek_journal() -> Optional[QueryJournal]:
    return _journal


def reset_journal(purge: bool = False) -> None:
    """Close (and optionally purge) the journal singleton.  With
    ``purge`` every active root this process touched is swept —
    the leaked-state recovery path, so one leaky test cannot poison
    the next."""
    global _journal
    with _lock:
        j, _journal = _journal, None
    if j is not None:
        j.close(purge=purge)
    if purge:
        with _lock:
            roots = list(_ACTIVE_ROOTS)
        for root in roots:
            for name in (WAL_NAME, REPLAY_NAME, ENDPOINT_NAME):
                try:
                    os.unlink(os.path.join(root, name))
                except OSError:
                    pass
            shutil.rmtree(_ckpt_root(root), ignore_errors=True)
            with _lock:
                _ACTIVE_ROOTS.discard(root)


def journal_admit(ctx, conf) -> None:
    """lifecycle.__enter__ hook (one ambient conf check guards the
    call site — this function only runs with recovery enabled)."""
    get_journal(conf).admit(ctx.query_id,
                            getattr(ctx, "trace_id", "") or "", conf)


def journal_plan(ctx, root_exec, conf) -> None:
    j = peek_journal()
    if j is None:
        j = get_journal(conf)
    j.plan(ctx.query_id, root_exec)


def journal_end(ctx, status: str) -> None:
    j = peek_journal()
    if j is not None:
        j.end(ctx.query_id, status)


def recovery_report() -> Dict[str, str]:
    """The prior incarnation's query classification (completed /
    resumable / abandoned) — what the driver-kill harness pins: every
    journaled query gets exactly one class."""
    j = peek_journal()
    return dict(j.recovery.classification) if j is not None else {}


def journal_leak_report() -> List[str]:
    """lifecycle.leak_report_all hook: leftover checkpoint dirs or
    never-ended journaled queries fail the owning test.  Peek-only —
    reports nothing unless this process opened a journal."""
    j = _journal
    return j.leak_lines() if j is not None else []
