"""Query lifecycle layer (ISSUE 4): admission control, deadlines, and
cooperative cancellation — what makes N concurrent ``collect()`` calls
safe, bounded, and killable.

Reference analog: the reference plugin leans on Spark's task framework
for admission (GpuSemaphore), task kill, and resource release on task
completion (SURVEY.md §2.3); Theseus (arXiv:2508.05029) and "Rethinking
Analytical Processing in the GPU Era" (arXiv:2508.04701) both argue an
accelerator engine lives or dies on controlled concurrency and bounded
device-memory occupancy under load.  This standalone engine has no task
framework, so the lifecycle layer supplies the missing pieces:

  * context.py   — QueryContext (one per collect, in a contextvar) +
                   CancelToken, the one object every blocking layer
                   observes; QueryCancelled / QueryDeadlineExceeded /
                   QueryRejected.
  * admission.py — FIFO admission gate (spark.rapids.tpu.
                   concurrentQueries) with a bounded wait queue and
                   queue-full fast-reject.
  * watchdog.py  — one daemon thread trips queries past
                   spark.rapids.tpu.query.timeoutMs.

``query_lifecycle`` (used by ``DataFrame.collect``) ties them together:
admission BEFORE planning, deadline armed at entry, and on exit —
success, error, or mid-batch unwind — guaranteed cleanup: residual
semaphore permits released, the query's tracked spillables closed, its
shuffle registrations dropped, and the admission slot returned.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

from spark_rapids_tpu.lifecycle.context import (
    CURRENT,
    CancelToken,
    QueryCancelled,
    QueryContext,
    QueryDeadlineExceeded,
    QueryRejected,
    check_cancel,
    current,
    current_token,
)
from spark_rapids_tpu.lifecycle.admission import (
    AdmissionController,
    get_admission,
    reset_admission,
)
from spark_rapids_tpu.lifecycle import watchdog as _watchdog

active_queries = _watchdog.active_queries

_tls = threading.local()


def last_query_stats() -> Optional[dict]:
    """Lifecycle stats of the calling thread's most recent collect()
    (bench/stress harness hook): query_id, admission_wait_ns, wall_ns,
    status."""
    return getattr(_tls, "last", None)


class query_lifecycle:
    """Context manager around one ``collect()``.

    Yields the new :class:`QueryContext`, or None when the lifecycle
    layer does not apply: sql disabled (oracle runs need no admission)
    or a nested collect (the inner one shares the outer query's context,
    token, and admission slot)."""

    def __init__(self, conf):
        self._conf = conf
        self._ctx: Optional[QueryContext] = None
        self._ctl: Optional[AdmissionController] = None
        self._cv_token = None
        self._journaled = False

    def __enter__(self) -> Optional[QueryContext]:
        from spark_rapids_tpu.config import (
            ADMISSION_MAX_QUEUE,
            ADMISSION_QUEUE_TIMEOUT_MS,
            CONCURRENT_QUERIES,
            QUERY_TIMEOUT_MS,
            QUERY_WATCHDOG_PERIOD_MS,
        )

        conf = self._conf
        if not conf.sql_enabled or current() is not None:
            return None
        period_s = max(float(conf.get(QUERY_WATCHDOG_PERIOD_MS)), 1.0) / 1000.0
        ctx = QueryContext(watchdog_period_s=period_s)
        # multi-tenant serving (ISSUE 19): stamp the owning tenant from
        # the session conf — a plain conf read, no serving-module call
        from spark_rapids_tpu.config import SERVING_TENANT

        ctx.tenant = str(conf.get(SERVING_TENANT) or "")
        # deadline armed and watchdog registered BEFORE the admission
        # wait: a query stuck in the queue must be deadline-trippable and
        # visible to active_queries() cancel tooling (the acquire loop
        # polls ctx.token), not just once it starts running
        timeout_ms = int(conf.get(QUERY_TIMEOUT_MS))
        if timeout_ms > 0:
            ctx.deadline_ns = time.monotonic_ns() + timeout_ms * 1_000_000
        _watchdog.register(ctx)
        limit = int(conf.get(CONCURRENT_QUERIES))
        if limit > 0:
            ctl = get_admission(limit, int(conf.get(ADMISSION_MAX_QUEUE)))
            try:
                # admission BEFORE planning: a rejected query must cost
                # the process nothing, and a queued one must not pin
                # plan state
                ctx.admission_wait_ns = ctl.acquire(
                    ctx, int(conf.get(ADMISSION_QUEUE_TIMEOUT_MS)))
            except BaseException as e:
                from spark_rapids_tpu import perfcounters as PC

                _watchdog.unregister(ctx)
                if isinstance(e, QueryCancelled):
                    PC.bump("queries_cancelled")
                # rejection raises HERE, before the telemetry collect
                # wrapper ever runs — record the overload event at the
                # only site that sees it (ISSUE 7)
                if isinstance(e, QueryRejected):
                    from spark_rapids_tpu.telemetry import context as TEL

                    hub = TEL.HUB
                    if hub is not None:
                        try:
                            hub.record_event(
                                "query_rejected",
                                query_id=ctx.query_id,
                                detail=str(e)[:300])
                        # tpulint: disable=cancel-swallow (telemetry
                        # isolation; QueryRejected re-raised below)
                        except Exception:
                            pass
                raise
            self._ctl = ctl
        self._cv_token = CURRENT.set(ctx)
        self._ctx = ctx
        # crash-consistent recovery (ISSUE 16): journal the admission so
        # a dead driver's successor can classify this query.  One
        # ambient conf check — with recovery off the journal module is
        # never imported (cProfile-pinned)
        from spark_rapids_tpu.config import RECOVERY_ENABLED

        if bool(conf.get(RECOVERY_ENABLED)):
            from spark_rapids_tpu.lifecycle import journal as _journal

            try:
                _journal.journal_admit(ctx, conf)
                self._journaled = True
            # tpulint: disable=cancel-swallow (durability isolation: a
            # journal that cannot append voids the recovery guarantee
            # for this query but must not fail its admission)
            except Exception:
                pass
        return ctx

    def __exit__(self, exc_type, exc, tb):
        ctx = self._ctx
        if ctx is None:
            return False
        from spark_rapids_tpu import perfcounters as PC

        try:
            CURRENT.reset(self._cv_token)
            _watchdog.unregister(ctx)
            if exc is not None and isinstance(exc, QueryCancelled):
                PC.bump("queries_cancelled")
            _cleanup_query(ctx)
            if self._journaled:
                from spark_rapids_tpu.lifecycle import journal as _journal

                status = ("ok" if exc_type is None else
                          "cancelled" if isinstance(exc, QueryCancelled)
                          else getattr(exc_type, "__name__", "error"))
                try:
                    _journal.journal_end(ctx, status)
                # tpulint: disable=cancel-swallow (durability isolation:
                # the end record is a GC optimization — replay treats a
                # missing one as a crash, which is the safe default)
                except Exception:
                    pass
        finally:
            if self._ctl is not None:
                self._ctl.release(ctx.tenant)
            wall_ns = time.monotonic_ns() - ctx.started_ns
            # fair-share usage feedback (ISSUE 19): charge the tenant's
            # consumed wall so long-running queries weigh against its
            # share (one module-attribute check; None when serving off)
            from spark_rapids_tpu.lifecycle import admission as _adm

            if _adm.SCHEDULER is not None:
                _adm.SCHEDULER.note_query_end(ctx.tenant, wall_ns)
            # overload governor (ISSUE 13): feed the wall EWMA the shed
            # predictor falls back on, and clear this query's
            # predicted-wall backlog entry (one ambient check)
            from spark_rapids_tpu.governor import context as _GOV

            gov = _GOV.GOVERNOR
            if gov is not None:
                gov.note_query_end(ctx.query_id, wall_ns)
            _tls.last = {
                "query_id": ctx.query_id,
                "admission_wait_ns": ctx.admission_wait_ns,
                "wall_ns": wall_ns,
                "status": ("ok" if exc_type is None else
                           getattr(exc_type, "__name__", "error")),
            }
        return False


def _cleanup_query(ctx: QueryContext) -> None:
    """Release everything the query may still hold after its exec tree
    unwound (possibly mid-batch).  Every step peeks the singleton —
    nothing is created during cleanup — and every step is idempotent."""
    # 0. query-registered cleanup hooks (ISSUE 5: the writer's staging
    #    -dir abort) — run FIRST so a cancelled mid-write query deletes
    #    its _temporary dir before anything else is torn down
    while ctx.cleanup_hooks:
        fn = ctx.cleanup_hooks.pop()
        try:
            fn()
        # tpulint: disable=cancel-swallow (cleanup-hook contract: hooks
        # are idempotent + best-effort; the query's own exception — incl.
        # a tripped token's — is re-raised by the main unwind path)
        except Exception:
            pass
    # 1. residual semaphore permit: the collect-level scope released one
    #    depth; exec code that failed between acquire and its finally can
    #    leave extra depth, which would starve every other query
    from spark_rapids_tpu.memory import semaphore as _sem

    sem = _sem.peek_semaphore()
    if sem is not None:
        sem.force_release_current_thread()
    # 2. spillable handles tracked (and not yet closed) by this query —
    #    cache handles are marked persistent and survive
    from spark_rapids_tpu.memory import spill as _spill

    fw = _spill.peek_spill_framework()
    if fw is not None:
        fw.close_owned_by(ctx.query_id)
    # 3. shuffle registrations this query's exchanges left behind
    from spark_rapids_tpu.shuffle import manager as _shuffle

    mgr = _shuffle.peek_shuffle_manager()
    if mgr is not None:
        mgr.unregister_owned(ctx.query_id)
    # 4. settle the query's resource bill (ISSUE 18) — AFTER
    #    close_owned_by swept leftover handles, so their releases land
    #    on the bill and a nonzero residual means truly-unreleased
    #    charged bytes (persistent df.cache handles excluded)
    from spark_rapids_tpu.accounting import context as _acct

    if _acct.LEDGERS is not None:
        _acct.LEDGERS.settle(ctx.query_id)


# ---------------------------------------------------------------------------
# leak reporting (conftest gate + TpuSession.close)
# ---------------------------------------------------------------------------

def leak_report_all() -> List[str]:
    """Aggregate leak report across the process singletons: unclosed
    non-persistent spillables, held/lost semaphore permits, and live
    shuffle registrations.  Empty after a well-behaved query (pinned by
    the autouse tests/conftest.py gate and the stress harness)."""
    out: List[str] = []
    from spark_rapids_tpu.memory import spill as _spill

    fw = _spill.peek_spill_framework()
    if fw is not None:
        out.extend(fw.leak_report())
    from spark_rapids_tpu.memory import semaphore as _sem

    sem = _sem.peek_semaphore()
    if sem is not None:
        out.extend(sem.leak_report())
    from spark_rapids_tpu.shuffle import manager as _shuffle

    mgr = _shuffle.peek_shuffle_manager()
    if mgr is not None:
        for sid in mgr.active_shuffles():
            out.append(f"LEAK: shuffle {sid} still registered")
    # 3b. partitions still PLACED on remote workers (ISSUE 14): a
    #     distributed exchange that unwound without its release
    #     broadcast leaves blocks pinned in another process's store
    from spark_rapids_tpu import distributed as _dist

    out.extend(_dist.leak_report())
    # 4. writer staging dirs never committed nor aborted (ISSUE 5): a
    #    leftover _temporary/<uuid> means a write unwound without its
    #    commit protocol running — visible-partial-output risk
    from spark_rapids_tpu.io import writer as _writer

    out.extend(_writer.staging_leak_report())
    # 5. recovery journal hygiene (ISSUE 16): a journaled query that
    #    never ended, or a checkpoint dir left on disk, means a real
    #    run would mis-classify at the next restart — fail the test
    from spark_rapids_tpu.lifecycle import journal as _journal

    out.extend(_journal.journal_leak_report())
    # 6. resource-bill residuals (ISSUE 18): a settled bill whose
    #    charged device bytes were never released (persistent handles
    #    excluded) is the accounting-side view of a handle leak
    from spark_rapids_tpu.accounting import context as _acct

    if _acct.LEDGERS is not None:
        out.extend(_acct.LEDGERS.leak_report())
    # 7. serving-tier hygiene (ISSUE 19): unclosed tenant sessions and
    #    result-cache fragments that outlived their session — a
    #    sys.modules peek, so a process that never enabled serving
    #    makes zero serving-module calls (the cProfile-pinned
    #    disabled-path contract)
    import sys as _sys

    srv = _sys.modules.get("spark_rapids_tpu.serving")
    if srv is not None:
        out.extend(srv.leak_report())
    return out


def reset_leaked_state() -> None:
    """Best-effort recovery after a detected leak so ONE leaky test/query
    cannot poison everything after it: close leaked handles, rebuild the
    semaphore, drop orphaned shuffle registrations."""
    from spark_rapids_tpu.memory import semaphore as _sem
    from spark_rapids_tpu.memory import spill as _spill
    from spark_rapids_tpu.shuffle import manager as _shuffle

    fw = _spill.peek_spill_framework()
    if fw is not None:
        fw.close_all(include_persistent=False)
    sem = _sem.peek_semaphore()
    if sem is not None and sem.leak_report():
        _sem.reset_semaphore()
    mgr = _shuffle.peek_shuffle_manager()
    if mgr is not None:
        for sid in mgr.active_shuffles():
            try:
                mgr.unregister_shuffle(sid)
            # tpulint: disable=cancel-swallow (leaked-state recovery in
            # tests; no query is running when this sweeps)
            except Exception:
                pass
    from spark_rapids_tpu.io import writer as _writer

    _writer.reset_leaked_staging()
    # remote placements an unregistered/leaked exchange left behind
    # (ISSUE 14) — release everywhere so one leaky test cannot pin
    # blocks in worker stores for the rest of the session
    from spark_rapids_tpu import distributed as _dist

    coord = _dist.peek_coordinator()
    if coord is not None:
        try:
            coord.release_all()
        # tpulint: disable=cancel-swallow (leaked-state recovery in
        # tests; no query is running when this sweeps)
        except Exception:
            pass
    from spark_rapids_tpu.accounting import context as _acct

    if _acct.LEDGERS is not None:
        _acct.LEDGERS.reset_residuals()
    # journal + checkpoint artifacts (ISSUE 16): purge every recovery
    # root this process touched so one leaky test's WAL cannot seed a
    # bogus "resumable" classification in the next test's replay
    from spark_rapids_tpu.lifecycle import journal as _journal

    _journal.reset_journal(purge=True)
    # serving tier (ISSUE 19): tear down leaked tenant sessions so one
    # test's unclosed session cannot hold cached batches, temp views,
    # or result fragments across the rest of the run
    import sys as _sys

    srv = _sys.modules.get("spark_rapids_tpu.serving")
    if srv is not None and srv.peek_serving() is not None:
        try:
            srv.shutdown_serving()
        # tpulint: disable=cancel-swallow (leaked-state recovery in
        # tests; no query is running when this sweeps)
        except Exception:
            pass


__all__ = [
    "CancelToken", "QueryCancelled", "QueryContext",
    "QueryDeadlineExceeded", "QueryRejected",
    "active_queries", "check_cancel", "current", "current_token",
    "get_admission", "reset_admission", "last_query_stats",
    "leak_report_all", "reset_leaked_state", "query_lifecycle",
]
