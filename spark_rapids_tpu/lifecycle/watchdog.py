"""The deadline watchdog — ONE daemon thread trips expired queries.

Every lifecycle-managed query registers here for the duration of its
collect(); the watchdog scans the registry every
``spark.rapids.tpu.query.watchdogPeriodMs`` (the minimum across active
queries) and trips the CancelToken of any query past its deadline with
:class:`QueryDeadlineExceeded`.  Trip + event-based backoff wakeups +
50ms wait-slice polling together bound the abort latency of a blocked
query at roughly 2x the watchdog period.

The registry is also the process's view of in-flight queries
(:func:`active_queries`) — what a stress harness or an operator console
uses to find and cancel a wedged query.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

from spark_rapids_tpu.lifecycle.context import (
    QueryContext,
    QueryDeadlineExceeded,
)

_COND = threading.Condition()
_ACTIVE: "set[QueryContext]" = set()
_THREAD: Optional[threading.Thread] = None
_IDLE_PERIOD_S = 0.5


def register(ctx: QueryContext) -> None:
    global _THREAD
    with _COND:
        _ACTIVE.add(ctx)
        if _THREAD is None or not _THREAD.is_alive():
            _THREAD = threading.Thread(
                target=_run, name="srt-query-watchdog", daemon=True)
            _THREAD.start()
        _COND.notify_all()


def unregister(ctx: QueryContext) -> None:
    with _COND:
        _ACTIVE.discard(ctx)
        _COND.notify_all()


def active_queries() -> List[QueryContext]:
    """Snapshot of in-flight lifecycle-managed queries."""
    with _COND:
        return list(_ACTIVE)


def _run() -> None:
    from spark_rapids_tpu import perfcounters as PC

    while True:
        with _COND:
            targets = list(_ACTIVE)
            period = min(
                [c.watchdog_period_s for c in targets] or [_IDLE_PERIOD_S])
        now = time.monotonic_ns()
        for ctx in targets:
            if ctx.deadline_expired(now) and not ctx.token.cancelled:
                over_ms = (now - ctx.deadline_ns) / 1e6
                if ctx.token.trip(
                        QueryDeadlineExceeded,
                        f"{ctx.query_id} exceeded "
                        f"spark.rapids.tpu.query.timeoutMs "
                        f"(deadline passed {over_ms:.0f}ms ago)"):
                    PC.bump("deadline_trips")
                    # Flight recorder (ISSUE 7): dump the post-mortem
                    # NOW, while the offending query's thread is still
                    # blocked wherever it is stuck — its stack is the
                    # bundle's whole point, and it unwinds as soon as
                    # the cooperative cancel is noticed
                    from spark_rapids_tpu.telemetry import context as TEL

                    hub = TEL.HUB
                    if hub is not None:
                        try:
                            hub.deadline_tripped(ctx)
                        # tpulint: disable=cancel-swallow (telemetry
                        # isolation: a flight-recorder failure must not
                        # break the watchdog loop)
                        except Exception:
                            pass
        # Progress stall scan (ISSUE 12): one ambient attribute read
        # per period; with a live tracker installed, flag every query
        # whose progress.stallMs elapsed with no operator advance —
        # query_stall event + stalls_detected + a post-mortem naming
        # the stuck operator.  scan_stalls never raises.
        from spark_rapids_tpu.progress import context as _PROG

        trk = _PROG.TRACKER
        if trk is not None:
            trk.scan_stalls(time.monotonic_ns())
        with _COND:
            _COND.wait(max(period, 0.005))
