"""QueryContext / CancelToken — the per-query lifecycle state.

One :class:`QueryContext` exists per admitted ``collect()`` (installed in
a contextvar by ``lifecycle.query_lifecycle``); it carries the admission
slot, the optional deadline, and the :class:`CancelToken` every blocking
layer observes.  The reference plugin gets task kill / resource release
for free from Spark's task framework (SURVEY.md §2.3: RmmSpark task
tracking + GpuSemaphore release on task completion); this standalone
engine has no task framework, so the token is the one thing a wedged
query's every wait — batch pulls, semaphore and admission queues, retry
backoffs, shuffle pool tasks, AOT compile waits — must observe.

Cancellation is COOPERATIVE: ``trip()`` never interrupts a thread, it
sets an event that each blocking site polls (or sleeps on); the tripped
site raises :class:`QueryCancelled` / :class:`QueryDeadlineExceeded`,
which ``resilience/classify.py`` treats as PROPAGATE — never retried,
never CPU-fallbacked, never counted by the circuit breaker.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from contextvars import ContextVar
from typing import Optional, Tuple, Type


class QueryCancelled(RuntimeError):
    """The query's CancelToken was tripped (user cancel()); classified
    PROPAGATE — surfaces to the caller unchanged."""


class QueryDeadlineExceeded(QueryCancelled):
    """The query ran past spark.rapids.tpu.query.timeoutMs and the
    watchdog tripped its token."""


class QueryRejected(RuntimeError):
    """Admission fast-reject: the wait queue was full, the queue wait
    timed out, or the overload governor shed the query (ISSUE 13).
    Raised before any planning/device work happened, so the caller can
    shed load or retry later.

    Structured backoff fields (ISSUE 13 satellite — populated by
    ``lifecycle/admission.py`` on the queue-full, queue-timeout, and
    governor-shed paths so callers can implement client-side backoff
    without parsing the message):

    * ``queue_depth``    — admission queue depth at rejection time.
    * ``retry_after_ms`` — the computed backoff hint (predicted time
      for the queue to drain a slot; None when no governor/latency
      history could compute one).
    * ``pressure_state`` — the governor state at rejection ("GREEN" /
      "YELLOW" / "RED", or "" when the governor is disabled).
    """

    def __init__(self, msg: str, queue_depth: Optional[int] = None,
                 retry_after_ms: Optional[int] = None,
                 pressure_state: str = ""):
        super().__init__(msg)
        self.queue_depth = queue_depth
        self.retry_after_ms = retry_after_ms
        self.pressure_state = pressure_state


class CancelToken:
    """A trip-once cancellation flag blocking layers sleep on.

    ``trip(exc_type, reason)`` stores the exception CLASS + message and
    sets the event; each observer raises a FRESH instance from
    ``check()`` so tracebacks point at the site that noticed, not at the
    tripper."""

    __slots__ = ("_evt", "_lock", "_exc")

    def __init__(self):
        self._evt = threading.Event()
        self._lock = threading.Lock()
        self._exc: Optional[Tuple[Type[BaseException], str]] = None

    def trip(self, exc_type: Type[BaseException], reason: str) -> bool:
        """Arm the token; returns True if this call tripped it (False:
        already tripped — first reason wins)."""
        with self._lock:
            if self._exc is not None:
                return False
            self._exc = (exc_type, reason)
        self._evt.set()
        return True

    @property
    def cancelled(self) -> bool:
        return self._evt.is_set()

    def check(self) -> None:
        """Raise the tripped exception (no-op while untripped)."""
        if self._evt.is_set():
            exc_type, reason = self._exc
            raise exc_type(reason)

    def wait(self, timeout: Optional[float]) -> bool:
        """Block up to ``timeout`` seconds or until tripped; True when
        tripped (the caller should then ``check()``)."""
        return self._evt.wait(timeout)

    def sleep_or_raise(self, seconds: float) -> None:
        """A cancellable time.sleep: wakes immediately on trip and
        raises."""
        if self._evt.wait(seconds):
            self.check()


_QUERY_SEQ = itertools.count(1)


def mint_trace_id(seq: int) -> str:
    """The cluster-wide trace identifier minted at collect start
    (ISSUE 15).  ``query_id`` ("q3") is readable but only unique within
    one driver process; the trace id adds a wall-clock millisecond and
    the driver pid so worker-local diagnostics rings — which outlive
    queries and survive driver restarts on disk — attribute spans to
    exactly one collect across every process that ever touched it.
    Carried on every TKD1 control frame (``trace``/``span`` header
    fields) and stamped into the diagnostics event log header."""
    return f"{int(time.time() * 1000):x}-{os.getpid():x}-{seq:x}"


class QueryContext:
    """Everything the lifecycle layer tracks for one collect()."""

    __slots__ = ("query_id", "trace_id", "token", "admission_seq",
                 "admission_wait_ns",
                 "deadline_ns", "watchdog_period_s", "started_ns",
                 "owner_thread", "cleanup_hooks", "tenant")

    def __init__(self, watchdog_period_s: float = 0.05):
        n = next(_QUERY_SEQ)
        self.query_id = f"q{n}"
        self.trace_id = mint_trace_id(n)
        self.token = CancelToken()
        # admission order doubles as semaphore priority: a LOWER seq was
        # admitted earlier (already running, already holding memory) and
        # outranks newly admitted queries at the device semaphore so it
        # finishes and releases instead of convoying
        self.admission_seq = n
        self.admission_wait_ns = 0
        self.deadline_ns: Optional[int] = None   # time.monotonic_ns basis
        self.watchdog_period_s = watchdog_period_s
        self.started_ns = time.monotonic_ns()
        self.owner_thread = threading.get_ident()
        # multi-tenant serving (ISSUE 19): the owning tenant, from
        # spark.rapids.tpu.serving.tenant at lifecycle entry.  "" =
        # untenanted; fair-share admission, per-tenant SLO series, and
        # tenant-aware governor shed/preempt all key on it
        self.tenant = ""
        # idempotent callables run by lifecycle._cleanup_query when the
        # query's exec tree unwinds (success, error, or cancel trip) —
        # e.g. the writer's staging-dir abort (ISSUE 5): a killed
        # mid-write query must leave zero visible partial output
        self.cleanup_hooks: list = []

    def add_cleanup(self, fn) -> None:
        """Register an idempotent cleanup callable (exceptions are
        swallowed at cleanup time)."""
        self.cleanup_hooks.append(fn)

    # -- cancellation ----------------------------------------------------
    def cancel(self, reason: str = "query cancelled") -> bool:
        """User-facing abort: trip the token (idempotent)."""
        return self.token.trip(
            QueryCancelled, f"{self.query_id}: {reason}")

    def check_cancel(self) -> None:
        self.token.check()

    def deadline_expired(self, now_ns: Optional[int] = None) -> bool:
        if self.deadline_ns is None:
            return False
        return (now_ns if now_ns is not None
                else time.monotonic_ns()) >= self.deadline_ns


# the active QueryContext of the current (logical) thread of execution.
# A contextvar, not a threading.local: the exec iterator chain runs on
# the query thread, and explicitly captured tokens travel to helper
# threads (shuffle pool, AOT pool) via closures.
CURRENT: "ContextVar[Optional[QueryContext]]" = ContextVar(
    "srt_query_context", default=None)


def current() -> Optional[QueryContext]:
    """The active QueryContext, or None outside a lifecycle-managed
    collect (ONE ambient check — safe on every hot path)."""
    return CURRENT.get()


def current_token() -> Optional[CancelToken]:
    ctx = CURRENT.get()
    return ctx.token if ctx is not None else None


def check_cancel() -> None:
    """Raise if the current query's token is tripped; no-op outside a
    query or while untripped."""
    ctx = CURRENT.get()
    if ctx is not None:
        ctx.token.check()
