"""Admission control — a gate on concurrently-running queries.

Reference analog: GpuSemaphore bounds how many *tasks* touch the device
(SURVEY.md §2.3); Theseus (arXiv:2508.05029) argues accelerator query
engines must additionally bound how many *queries* hold planning state
and device memory at once, because N queries each spilling the others'
working set livelocks the pool.  ``spark.rapids.tpu.concurrentQueries``
admits at most L queries; up to ``admission.maxQueueDepth`` more wait in
the queue, and anything beyond that fast-rejects with
:class:`QueryRejected` — shedding load at the door beats collapsing the
whole process.

Ordering is FIFO by default.  When the multi-tenant serving tier
(ISSUE 19) is active it installs a weighted fair-share policy into the
module-level :data:`SCHEDULER` slot, and the next free slot goes to the
eligible waiter whose tenant has the lowest normalized usage
(usage/weight) instead of the queue head — one module-attribute check
per wait iteration, zero cost while serving is off.  Usage is charged
only on ADMISSION (and query wall at lifecycle exit), never for time
spent waiting, so a rejected or timed-out query costs its tenant's
fair share nothing.

Waiters poll in short slices so a tripped CancelToken (user cancel or
watchdog deadline) aborts the wait within ~50ms.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional, Tuple

from spark_rapids_tpu.lifecycle.context import (
    QueryContext,
    QueryRejected,
)

_POLL_S = 0.05

# ISSUE 19: the fair-share policy slot.  None (the default) keeps plain
# FIFO ordering; serving.ensure_serving installs a FairShareScheduler
# and shutdown_serving clears it.  Read as ONE module attribute on the
# admission paths — the disabled-path contract.
SCHEDULER = None


class _Ticket:
    """One queued waiter: its tenant (fair-share key) and FIFO arrival
    order (the tie-break)."""

    __slots__ = ("tenant",)

    def __init__(self, tenant: str):
        self.tenant = tenant


class AdmissionController:
    def __init__(self, limit: int, max_queue: int):
        self.limit = max(1, int(limit))
        self.max_queue = max(0, int(max_queue))
        self._cond = threading.Condition()
        self._running = 0
        self._waiters: "deque[_Ticket]" = deque()   # FIFO arrival order
        self._running_by: Dict[str, int] = {}       # tenant -> running

    # -- introspection ---------------------------------------------------
    def stats(self) -> dict:
        """Depth/running plus the per-tenant breakdown the telemetry
        sampler and serving tier read (ISSUE 19 satellite)."""
        with self._cond:
            queued_by: Dict[str, int] = {}
            for t in self._waiters:
                queued_by[t.tenant] = queued_by.get(t.tenant, 0) + 1
            tenants = {
                name: {"running": self._running_by.get(name, 0),
                       "queued": queued_by.get(name, 0)}
                for name in set(self._running_by) | set(queued_by)
            }
            return {"running": self._running, "queued": len(self._waiters),
                    "limit": self.limit, "max_queue": self.max_queue,
                    "tenants": tenants}

    # -- internals (caller holds self._cond) -----------------------------
    def _admit_locked(self, tenant: str, sched) -> None:
        self._running += 1
        self._running_by[tenant] = self._running_by.get(tenant, 0) + 1
        if sched is not None:
            sched.on_admit(tenant)

    def _next_locked(self, sched) -> Optional[_Ticket]:
        """The waiter the next free slot belongs to: queue head under
        FIFO, the fair-share pick when a scheduler is installed."""
        if not self._waiters:
            return None
        if sched is None:
            return self._waiters[0]
        return sched.select(self._waiters, self._running_by)

    # -- the gate --------------------------------------------------------
    def acquire(self, ctx: QueryContext,
                timeout_ms: int = 0) -> int:
        """Admit ``ctx``, returning the queue-wait in ns.  Raises
        :class:`QueryRejected` immediately when the wait queue is full,
        or after ``timeout_ms`` (0 = wait indefinitely); raises the
        token's exception if the query is cancelled while queued."""
        from spark_rapids_tpu import perfcounters as PC

        from spark_rapids_tpu.governor import context as _GOV

        tenant = getattr(ctx, "tenant", "") or ""
        t0 = time.perf_counter_ns()
        with self._cond:
            sched = SCHEDULER
            if (self._running < self.limit and not self._waiters
                    and (sched is None
                         or sched.admissible(tenant, self._running_by))):
                self._admit_locked(tenant, sched)
                PC.bump("queries_admitted")
                if sched is not None:
                    PC.bump("fair_share_admissions")
                return 0
            gov = _GOV.GOVERNOR
            depth = len(self._waiters)
            if depth >= self.max_queue:
                PC.bump("queries_rejected")
                raise QueryRejected(
                    f"admission queue full ({depth} queued, "
                    f"{self._running}/{self.limit} running; "
                    f"spark.rapids.tpu.admission.maxQueueDepth="
                    f"{self.max_queue})",
                    queue_depth=depth,
                    retry_after_ms=(gov.retry_after_ms(depth, self.limit)
                                    if gov is not None else None),
                    pressure_state=(gov.state if gov is not None else ""))
            if gov is not None:
                # overload governor (ISSUE 13): under RED, a query whose
                # deadline cannot survive predicted wall + predicted
                # queue wait is shed HERE — before it pins a queue slot
                # it can only convert into a deadline cascade.  ISSUE 19
                # makes the decision tenant-aware (the per-tenant running
                # counts ride along; a copy, so the governor never
                # touches controller state)
                retry_ms = gov.shed_admission(
                    ctx, self._running, self.limit, depth,
                    running_by=dict(self._running_by))
                if retry_ms is not None:
                    PC.bump("queries_shed")
                    PC.bump("queries_rejected")
                    raise QueryRejected(
                        f"{ctx.query_id}: shed under {gov.state} pressure "
                        f"({depth} queued, {self._running}/{self.limit} "
                        f"running): predicted wall + queue wait cannot "
                        f"meet the query deadline; retry after "
                        f"{retry_ms}ms",
                        queue_depth=depth,
                        retry_after_ms=retry_ms,
                        pressure_state=gov.state)
            ticket = _Ticket(tenant)
            self._waiters.append(ticket)
            deadline = (None if timeout_ms <= 0
                        else time.monotonic() + timeout_ms / 1000.0)
            try:
                while not (self._running < self.limit
                           and self._next_locked(SCHEDULER) is ticket):
                    ctx.token.check()
                    if deadline is not None and time.monotonic() >= deadline:
                        PC.bump("queries_rejected")
                        gov = _GOV.GOVERNOR
                        qd = len(self._waiters)
                        raise QueryRejected(
                            f"{ctx.query_id}: admission wait exceeded "
                            f"queueTimeoutMs={timeout_ms}",
                            queue_depth=qd,
                            retry_after_ms=(
                                gov.retry_after_ms(qd, self.limit)
                                if gov is not None else None),
                            pressure_state=(gov.state if gov is not None
                                            else ""))
                    self._cond.wait(_POLL_S)
                self._waiters.remove(ticket)
                sched = SCHEDULER
                self._admit_locked(tenant, sched)
                if sched is not None:
                    PC.bump("fair_share_admissions")
            except BaseException:
                try:
                    self._waiters.remove(ticket)
                except ValueError:
                    pass
                self._cond.notify_all()
                raise
            # the pick moved: the next waiter (or a free slot) may now
            # be eligible
            self._cond.notify_all()
        wait_ns = time.perf_counter_ns() - t0
        PC.bump("queries_admitted")
        PC.bump("admission_wait_ns", wait_ns)
        return wait_ns

    def release(self, tenant: str = "") -> None:
        with self._cond:
            self._running = max(0, self._running - 1)
            n = self._running_by.get(tenant, 0) - 1
            if n > 0:
                self._running_by[tenant] = n
            else:
                self._running_by.pop(tenant, None)
            self._cond.notify_all()


_lock = threading.Lock()
_controller: Optional[AdmissionController] = None
_controller_key: Optional[Tuple[int, int]] = None


def get_admission(limit: int, max_queue: int) -> AdmissionController:
    """Process-wide controller, rebuilt when the confs change (the
    get_semaphore pattern)."""
    global _controller, _controller_key
    with _lock:
        key = (int(limit), int(max_queue))
        if _controller is None or key != _controller_key:
            _controller = AdmissionController(limit, max_queue)
            _controller_key = key
        return _controller


def peek_admission() -> Optional[AdmissionController]:
    """The controller if it exists — the telemetry sampler must read
    queue depth without CREATING a controller on an idle process."""
    return _controller


def reset_admission() -> None:
    global _controller, _controller_key
    with _lock:
        _controller = None
        _controller_key = None
