"""Cooperative OOM retry — the resilience backbone.

Reference analog: RmmRapidsRetryIterator.withRetry / withRetryNoSplit /
splitSpillableInHalfByRows + the jni RmmSpark / SparkResourceAdaptor state
machine (SURVEY.md §2.3, §5.3): per-batch work runs inside a retry block; a
failed allocation surfaces as GpuRetryOOM (roll back, spill, retry) or
GpuSplitAndRetryOOM (roll back, split the input in half, retry each half).
Tests force these via RmmSpark.forceRetryOOM / forceSplitAndRetryOOM.

TPU adaptation: XLA signals device OOM with RESOURCE_EXHAUSTED runtime
errors, which we translate into the same two exceptions; the spill
framework's ensure_room() failing is the cooperative (pre-allocation)
signal.  The injection hooks match the reference's test API.
"""
from __future__ import annotations

import threading
from typing import Callable, Iterator, List, Optional, TypeVar, Union

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.memory.spill import SpillableColumnarBatch

X = TypeVar("X")


def _bump(key: str) -> None:
    """Deferred-import perfcounters bump (house style: this module
    defers framework imports to call time)."""
    from spark_rapids_tpu import perfcounters as PC

    PC.bump(key)


class TpuRetryOOM(RuntimeError):
    """Roll back and retry the block (after the framework spills)."""


class TpuSplitAndRetryOOM(RuntimeError):
    """Roll back, split the input in half by rows, retry each half."""


class _InjectState(threading.local):
    def __init__(self):
        self.retry_count = 0
        self.split_count = 0


_inject = _InjectState()


def force_retry_oom(count: int = 1) -> None:
    """Test hook (reference: RmmSpark.forceRetryOOM)."""
    _inject.retry_count = count


def force_split_and_retry_oom(count: int = 1) -> None:
    """Test hook (reference: RmmSpark.forceSplitAndRetryOOM)."""
    _inject.split_count = count


def _check_injection() -> None:
    if _inject.retry_count > 0:
        _inject.retry_count -= 1
        raise TpuRetryOOM("injected")
    if _inject.split_count > 0:
        _inject.split_count -= 1
        raise TpuSplitAndRetryOOM("injected")


def _is_device_oom(exc: BaseException) -> bool:
    """RESOURCE_EXHAUSTED anywhere in the __cause__/__context__ chain.

    Framework layers wrap jaxlib's XlaRuntimeError (``raise X from e``),
    so sniffing only ``repr(exc)`` misclassified wrapped OOMs as
    deterministic failures; resilience/classify.py walks the chain and
    matches XLA status codes."""
    from spark_rapids_tpu.resilience.classify import is_device_oom

    return is_device_oom(exc)


def _preempt_instead_of_split() -> bool:
    """Overload-governor consult (ISSUE 13 satellite): under RED, an
    OOM that would split the batch first requests a pause-and-spill
    preemption pass — the pool drains from the NEWEST-admitted query's
    working set before this query halves its own batch (halving under
    transient co-tenant pressure permanently degrades this query's
    launch efficiency for someone else's spike).  Tried at most once
    per batch (the caller's flag); the counters ``oom_retry_preempts``
    / ``oom_retry_splits`` distinguish the two outcomes."""
    from spark_rapids_tpu.governor import context as _GOV

    gov = _GOV.GOVERNOR
    if gov is None or gov.maybe_update() != "RED":
        return False
    from spark_rapids_tpu.lifecycle.context import current

    ctx = current()
    return gov.preempt_for_oom(
        exclude_qid=ctx.query_id if ctx is not None else None)


def split_in_half_by_rows(
        spillable: SpillableColumnarBatch) -> List[SpillableColumnarBatch]:
    """Reference analog: splitSpillableInHalfByRows."""
    from spark_rapids_tpu.memory.spill import get_spill_framework

    batch = spillable.get_batch()
    n = batch.num_rows
    if n < 2:
        raise TpuSplitAndRetryOOM(
            f"cannot split batch of {n} rows any further")
    half = n // 2
    fw = get_spill_framework()
    first = fw.track(batch.slice_rows(0, half))
    second = fw.track(batch.slice_rows(half, n - half))
    spillable.close()
    return [first, second]


def with_retry(
        inputs: Union[SpillableColumnarBatch, List[SpillableColumnarBatch]],
        fn: Callable[[ColumnarBatch], X],
        max_attempts: int = 8,
        min_split_rows: int = 8,
        split: bool = True) -> Iterator[X]:
    """Run fn over each input batch with OOM retry and split-and-retry.

    `fn` must be re-runnable (CheckpointRestore contract: no side effects
    it cannot repeat).  Yields one result per (possibly split) input."""
    from spark_rapids_tpu.memory.spill import get_spill_framework

    from spark_rapids_tpu.lifecycle.context import check_cancel

    queue: List[SpillableColumnarBatch] = (
        [inputs] if isinstance(inputs, SpillableColumnarBatch) else
        list(inputs))
    fw = get_spill_framework()
    try:
        while queue:
            # cooperative cancellation (ISSUE 4): checked while every
            # handle is still queued, so the finally below closes them
            check_cancel()
            item = queue.pop(0)
            attempts = 0
            preempted = False
            while True:
                attempts += 1
                try:
                    _check_injection()
                    item.pin()
                    try:
                        result = fn(item.get_batch())
                    finally:
                        item.unpin()
                    item.close()
                    yield result
                    break
                except TpuRetryOOM:
                    if attempts >= max_attempts:
                        item.close()
                        raise
                    fw.spill_device_pressure()
                except TpuSplitAndRetryOOM:
                    # governor RED (ISSUE 13): one preemption pass
                    # before halving — retry at FULL size once the
                    # newest-admitted query's working set spills
                    if not preempted and attempts < max_attempts \
                            and _preempt_instead_of_split():
                        preempted = True
                        _bump("oom_retry_preempts")
                        continue
                    if not split or item.num_rows < max(min_split_rows, 2):
                        item.close()
                        raise
                    _bump("oom_retry_splits")
                    queue = split_in_half_by_rows(item) + queue
                    break
                except Exception as e:  # XLA RESOURCE_EXHAUSTED
                    if not _is_device_oom(e):
                        item.close()
                        raise
                    # preempt check BEFORE the spill: preempt_for_oom
                    # runs its own spill pass, so the preempt path must
                    # not pay two back-to-back handle-list sweeps in
                    # the middle of a pressure storm
                    if not preempted and attempts < max_attempts \
                            and _preempt_instead_of_split():
                        preempted = True
                        _bump("oom_retry_preempts")
                        continue
                    fw.spill_device_pressure()
                    if split and item.num_rows >= max(min_split_rows, 2):
                        _bump("oom_retry_splits")
                        queue = split_in_half_by_rows(item) + queue
                        break
                    if attempts >= max_attempts:
                        item.close()
                        raise
    finally:
        # a consumer that abandons the generator early (GeneratorExit) —
        # or any raise above — must not leak the still-queued spillable
        # handles; the in-flight item is always closed before its yield
        for q in queue:
            try:
                q.close()
            except Exception:
                pass


def with_retry_no_split(fn: Callable[[], X], max_attempts: int = 8) -> X:
    """Reference analog: withRetryNoSplit — retry a block (spilling between
    attempts) without an input to split."""
    from spark_rapids_tpu.memory.spill import get_spill_framework

    from spark_rapids_tpu.lifecycle.context import check_cancel

    fw = get_spill_framework()
    attempts = 0
    while True:
        attempts += 1
        try:
            check_cancel()
            _check_injection()
            return fn()
        except TpuRetryOOM:
            if attempts >= max_attempts:
                raise
            fw.spill_device_pressure()
        except TpuSplitAndRetryOOM:
            raise
        except Exception as e:
            if not _is_device_oom(e) or attempts >= max_attempts:
                raise
            fw.spill_device_pressure()
