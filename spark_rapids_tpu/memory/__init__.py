"""Memory & execution runtime (SURVEY.md §2.3).

Reference analogs: GpuDeviceManager (RMM pool init), GpuSemaphore (task
admission), the spill framework (SpillableColumnarBatch, device->host->disk
stores), and RmmRapidsRetryIterator (cooperative OOM retry / split-and-retry).

TPU adaptation: XLA owns physical HBM, so the arena is a *logical* budget —
every live batch is registered with the spill framework and accounted
against the pool derived from the chip's memory stats; pressure beyond the
budget spills least-recently-used batches host-ward and, cooperatively,
raises TpuRetryOOM / TpuSplitAndRetryOOM for the retry framework to unwind
(mirroring RmmSpark's allocation callbacks without cudaMalloc semantics).
"""
from spark_rapids_tpu.memory.device_manager import (
    TpuDeviceManager,
    get_device_manager,
)
from spark_rapids_tpu.memory.retry import (
    TpuRetryOOM,
    TpuSplitAndRetryOOM,
    force_retry_oom,
    force_split_and_retry_oom,
    split_in_half_by_rows,
    with_retry,
    with_retry_no_split,
)
from spark_rapids_tpu.memory.semaphore import (
    SemaphoreTimeout,
    TpuSemaphore,
    get_semaphore,
)
from spark_rapids_tpu.memory.spill import (
    SpillableColumnarBatch,
    SpillCorruption,
    SpillFramework,
    get_spill_framework,
)

__all__ = [
    "TpuDeviceManager", "get_device_manager",
    "TpuRetryOOM", "TpuSplitAndRetryOOM", "force_retry_oom",
    "force_split_and_retry_oom", "split_in_half_by_rows", "with_retry",
    "with_retry_no_split",
    "SemaphoreTimeout", "TpuSemaphore", "get_semaphore",
    "SpillableColumnarBatch", "SpillCorruption", "SpillFramework",
    "get_spill_framework",
]
