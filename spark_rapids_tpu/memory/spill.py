"""Spill framework — spillable batches with device -> host -> disk tiers.

Reference analog: spill/SpillFramework.scala + SpillableColumnarBatch (and
the older RapidsBufferCatalog / RapidsDeviceMemoryStore / RapidsHostMemoryStore
/ RapidsDiskStore family) in SURVEY.md §2.3: batches an operator is not
actively computing on are registered as spillable handles; under memory
pressure the framework moves the least-recently-used ones down-tier and
materializes them back on demand.

TPU adaptation: XLA owns physical HBM, so the device tier is accounted
logically — a handle's batch contributes its padded nbytes to the pool while
device-resident.  Spilling device->host is a jax.device_get into pinned-ish
numpy arrays; host->disk is an .npz file under ``spark.rapids.memory.spillDir``.
Materializing uploads back (which may in turn spill other handles).
"""
from __future__ import annotations

import os
import tempfile
import threading
from typing import Dict, List, Optional

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.accounting import context as _ACCT
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.config import (
    HOST_SPILL_STORAGE_SIZE,
    MEM_DEBUG,
    SPILL_DIR,
    TpuConf,
)

STATE_DEVICE = "DEVICE"
STATE_HOST = "HOST"
STATE_DISK = "DISK"


class SpillCorruption(RuntimeError):
    """A disk-tier unspill read back bytes whose CRC32 does not match
    what was written (bit rot / torn write / corrupted spill dir).
    Deterministic by classification: re-reading re-derives the same
    corruption, so the fault domain falls the stage back to the CPU
    oracle instead of retrying."""


def _crc_host_cols(host_cols: List[Dict[str, np.ndarray]]) -> int:
    """CRC32 of a host-tier column set, independent of dict ordering
    (write and read build their entries in different key orders)."""
    import zlib

    crc = 0
    for i, entry in enumerate(host_cols):
        for k in sorted(entry):
            v = entry[k]
            crc = zlib.crc32(f"{i}:{k}".encode(), crc)
            crc = zlib.crc32(np.ascontiguousarray(v).tobytes(), crc)
    return crc


class SpillableColumnarBatch:
    """A batch handle that can migrate between HBM, host RAM, and disk.

    Reference analog: SpillableColumnarBatch /
    SpillableColumnarBatchHandle."""

    def __init__(self, batch: ColumnarBatch, framework: "SpillFramework",
                 persistent: bool = False):
        self._framework = framework
        self._batch: Optional[ColumnarBatch] = batch
        self._host: Optional[List[Dict[str, np.ndarray]]] = None
        self._disk_path: Optional[str] = None
        self._disk_crc: Optional[int] = None
        self.schema = batch.schema
        self.num_rows = batch.num_rows
        self.device_bytes = batch.nbytes()
        self.state = STATE_DEVICE
        self.pinned = 0          # >0 while an operator computes on it
        self.lru_tick = 0
        self.closed = False
        # lifecycle bookkeeping (ISSUE 4): which query tracked this
        # handle (query-end cleanup closes its leftovers) and whether it
        # intentionally outlives the query (df.cache() handles)
        self.persistent = persistent
        from spark_rapids_tpu.lifecycle.context import current

        ctx = current()
        self.owner_qid = ctx.query_id if ctx is not None else None
        framework._register(self)

    # -- public API ------------------------------------------------------
    def get_batch(self) -> ColumnarBatch:
        """Materialize on device (unspilling if needed) and bump LRU."""
        with self._framework._lock:
            self._framework._touch_locked(self)
            if self.state == STATE_DEVICE:
                return self._batch
        # needs unspill: make room first (outside our own pin)
        self._framework.ensure_room(self.device_bytes, exclude=self)
        with self._framework._lock:
            if self.state == STATE_DISK:
                self._disk_to_host_locked()
            if self.state == STATE_HOST:
                self._host_to_device_locked()
            return self._batch

    def pin(self) -> "SpillableColumnarBatch":
        with self._framework._lock:
            self.pinned += 1
        return self

    def unpin(self) -> None:
        with self._framework._lock:
            self.pinned = max(0, self.pinned - 1)

    def close(self) -> None:
        with self._framework._lock:
            if self.closed:
                return
            self.closed = True
            self._framework._unregister_locked(self)
            self._batch = None
            self._host = None
            if self._disk_path and os.path.exists(self._disk_path):
                try:
                    os.unlink(self._disk_path)
                except OSError:
                    pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    # -- tier moves (framework lock held) --------------------------------
    def _device_to_host_locked(self) -> int:
        import jax

        assert self.state == STATE_DEVICE
        host_cols = []
        for c in self._batch.columns:
            entry = {"validity": np.asarray(jax.device_get(c.validity))}
            if c.is_string:
                entry["chars"] = np.asarray(jax.device_get(c.chars))
                entry["lengths"] = np.asarray(jax.device_get(c.lengths))
            else:
                entry["data"] = np.asarray(jax.device_get(c.data))
            host_cols.append(entry)
        self._host = host_cols
        self._batch = None
        self.state = STATE_HOST
        return self.device_bytes

    def _host_to_device_locked(self) -> None:
        import jax.numpy as jnp

        assert self.state == STATE_HOST
        cols = []
        for f, entry in zip(self.schema.fields, self._host):
            if "chars" in entry:
                cols.append(DeviceColumn(
                    f.dataType, jnp.asarray(entry["validity"]),
                    chars=jnp.asarray(entry["chars"]),
                    lengths=jnp.asarray(entry["lengths"])))
            else:
                cols.append(DeviceColumn(
                    f.dataType, jnp.asarray(entry["validity"]),
                    data=jnp.asarray(entry["data"])))
        self._batch = ColumnarBatch(cols, self.num_rows, self.schema)
        self._host = None
        self.state = STATE_DEVICE
        fw = self._framework
        fw._device_used += self.device_bytes
        fw._device_used_peak = max(fw._device_used_peak, fw._device_used)
        if _ACCT.LEDGERS is not None:
            # restore re-charges device residency AND bills the up-tier
            # traffic (ISSUE 18)
            _ACCT.LEDGERS.charge_device(self.owner_qid, self.device_bytes,
                                        self.persistent)
            _ACCT.LEDGERS.charge_spill(self.owner_qid, "restore",
                                       self.device_bytes)

    def host_bytes(self) -> int:
        if self._host is None:
            return 0
        return sum(a.nbytes for e in self._host for a in e.values())

    def _host_to_disk_locked(self) -> int:
        assert self.state == STATE_HOST
        nbytes = self.host_bytes()
        arrays = {}
        for i, entry in enumerate(self._host):
            for k, v in entry.items():
                arrays[f"c{i}_{k}"] = v
        # integrity checksum (ISSUE 4 satellite): remember what the
        # bytes looked like going down; unspill verifies before trusting
        self._disk_crc = _crc_host_cols(self._host)
        fd, path = tempfile.mkstemp(suffix=".spill.npz",
                                    dir=self._framework.spill_dir)
        os.close(fd)
        np.savez(path, **arrays)
        self._disk_path = path
        self._host = None
        self.state = STATE_DISK
        return nbytes

    def _disk_to_host_locked(self) -> None:
        assert self.state == STATE_DISK
        try:
            loaded = np.load(self._disk_path)
            host_cols: List[Dict[str, np.ndarray]] = []
            for i in range(len(self.schema.fields)):
                entry = {}
                for k in ("validity", "data", "chars", "lengths"):
                    key = f"c{i}_{k}"
                    if key in loaded:
                        entry[k] = loaded[key]
                host_cols.append(entry)
        except Exception as e:
            # the zip container itself rejected the bytes (BadZipFile /
            # zlib error from a flipped byte): same corruption class
            raise SpillCorruption(
                f"disk unspill of {self._disk_path} failed to decode: "
                f"{type(e).__name__}: {e}") from e
        if self._disk_crc is not None:
            got = _crc_host_cols(host_cols)
            if got != self._disk_crc:
                raise SpillCorruption(
                    f"disk unspill CRC mismatch for {self._disk_path}: "
                    f"wrote {self._disk_crc:#010x}, read {got:#010x}")
        self._host = host_cols
        try:
            os.unlink(self._disk_path)
        except OSError:
            pass
        self._disk_path = None
        self._disk_crc = None
        self.state = STATE_HOST


class SpillFramework:
    """Tracks spillable handles and enforces the HBM pool budget."""

    def __init__(self, pool_bytes: int, host_limit: int,
                 spill_dir: Optional[str], debug: bool = False):
        self.pool_bytes = pool_bytes
        self.host_limit = host_limit
        self.spill_dir = spill_dir
        if self.spill_dir:
            os.makedirs(self.spill_dir, exist_ok=True)
        self.debug = debug
        self._lock = threading.RLock()
        self._handles: List[SpillableColumnarBatch] = []
        self._device_used = 0
        self._device_used_peak = 0
        self._tick = 0
        # metrics (GpuTaskMetrics analog)
        self.spill_to_host_count = 0
        self.spill_to_disk_count = 0
        self.spill_to_host_bytes = 0
        self.spill_to_disk_bytes = 0

    # -- registration ----------------------------------------------------
    def _register(self, h: SpillableColumnarBatch) -> None:
        # make room BEFORE admitting the new batch (ISSUE 10): residency
        # then never exceeds the pool bound while the budget is meetable
        # (a single batch larger than the whole pool still admits — the
        # caller's retry block owns that case), which is what the
        # out-of-core pins assert via device_used_peak
        self.ensure_room(h.device_bytes, exclude=h)
        with self._lock:
            self._touch_locked(h)
            self._handles.append(h)
            self._device_used += h.device_bytes
            self._device_used_peak = max(self._device_used_peak,
                                         self._device_used)
            if _ACCT.LEDGERS is not None:
                _ACCT.LEDGERS.charge_device(h.owner_qid, h.device_bytes,
                                            h.persistent)
            if self.debug:
                # handle-leak tracking (the cuDF refcount-debug analog,
                # SURVEY.md §5.2): remember where each live handle came
                # from so leak_report() can name the allocation site
                import traceback

                h._alloc_stack = "".join(traceback.format_stack(limit=8))

    def leak_report(self, include_persistent: bool = False) -> List[str]:
        """Live (unclosed) handles with their allocation sites.

        Reference analog: ai.rapids.refcount.debug leak logs (SURVEY.md
        §5.2).  Enable with spark.rapids.memory.debug=true; an empty list
        after a query completes means every spillable handle was
        released.  Handles marked ``persistent`` (df.cache() batches,
        which intentionally outlive their query) are excluded unless
        ``include_persistent``."""
        with self._lock:
            out = []
            for h in self._handles:
                if h.persistent and not include_persistent:
                    continue
                site = getattr(h, "_alloc_stack", "<enable "
                               "spark.rapids.memory.debug for stacks>")
                owner = f" owner={h.owner_qid}" if h.owner_qid else ""
                out.append(
                    f"LEAK: {h.state} handle {h.device_bytes}B{owner}"
                    f"\n{site}")
            return out

    def close_owned_by(self, query_id: str) -> int:
        """Query-end cleanup (ISSUE 4): close every non-persistent handle
        the given query tracked and never closed (a mid-batch unwind
        leaves these behind); returns how many were closed."""
        with self._lock:
            victims = [h for h in self._handles
                       if h.owner_qid == query_id and not h.persistent]
        for h in victims:
            try:
                h.close()
            except Exception:
                pass
        return len(victims)

    def close_all(self, include_persistent: bool = True) -> int:
        """Close every live handle (leak recovery / session shutdown)."""
        with self._lock:
            victims = [h for h in self._handles
                       if include_persistent or not h.persistent]
        for h in victims:
            try:
                h.close()
            except Exception:
                pass
        return len(victims)

    def _unregister_locked(self, h: SpillableColumnarBatch) -> None:
        if h.state == STATE_DEVICE:
            self._device_used -= h.device_bytes
            if _ACCT.LEDGERS is not None:
                _ACCT.LEDGERS.release_device(h.owner_qid, h.device_bytes,
                                             h.persistent)
        if h in self._handles:
            self._handles.remove(h)

    def _touch_locked(self, h: SpillableColumnarBatch) -> None:
        self._tick += 1
        h.lru_tick = self._tick

    def track(self, batch: ColumnarBatch,
              persistent: bool = False) -> SpillableColumnarBatch:
        return SpillableColumnarBatch(batch, self, persistent=persistent)

    # -- pressure --------------------------------------------------------
    @property
    def device_used(self) -> int:
        return self._device_used

    @property
    def device_used_peak(self) -> int:
        """High-water mark of tracked device residency — the number the
        out-of-core pins compare against pool_bytes (register makes room
        BEFORE admitting, so the peak only exceeds the pool when a
        single batch is larger than the whole pool)."""
        return self._device_used_peak

    def ensure_room(self, nbytes: int,
                    exclude: Optional[SpillableColumnarBatch] = None) -> bool:
        """Spill LRU device handles until `nbytes` more fit in the pool.

        Returns False when the budget cannot be met (caller's retry block
        turns that into TpuRetryOOM)."""
        while True:
            with self._lock:
                if self._device_used + nbytes <= self.pool_bytes:
                    return True
                victims = sorted(
                    (h for h in self._handles
                     if h.state == STATE_DEVICE and h.pinned == 0
                     and h is not exclude),
                    key=lambda h: h.lru_tick)
                if not victims:
                    return False
                v = victims[0]
                freed = v._device_to_host_locked()
                self._device_used -= freed
                self.spill_to_host_count += 1
                self.spill_to_host_bytes += freed
                if _ACCT.LEDGERS is not None:
                    # the bill releases device residency AND records the
                    # down-tier traffic against the handle's OWNER (who
                    # held the memory), not whoever triggered pressure
                    _ACCT.LEDGERS.release_device(v.owner_qid, freed,
                                                 v.persistent)
                    _ACCT.LEDGERS.charge_spill(v.owner_qid, "host", freed)
                if self.debug:
                    print(f"[spill] device->host {freed >> 10}KiB "
                          f"rows={v.num_rows} used={self._device_used >> 20}MiB")
                self._host_pressure_locked()

    def _host_pressure_locked(self) -> None:
        host_used = sum(h.host_bytes() for h in self._handles
                        if h.state == STATE_HOST)
        if host_used <= self.host_limit:
            return
        if self.spill_dir is None:
            self.spill_dir = tempfile.mkdtemp(prefix="srt_spill_")
        for h in sorted((h for h in self._handles if h.state == STATE_HOST),
                        key=lambda h: h.lru_tick):
            if host_used <= self.host_limit:
                break
            n = h._host_to_disk_locked()
            host_used -= n
            self.spill_to_disk_count += 1
            self.spill_to_disk_bytes += n
            if _ACCT.LEDGERS is not None:
                _ACCT.LEDGERS.charge_spill(h.owner_qid, "disk", n)

    def spill_device_pressure(self) -> int:
        """Spill everything unpinned (the RetryOOM 'roll back' release)."""
        spilled = 0
        with self._lock:
            for h in sorted((h for h in self._handles
                             if h.state == STATE_DEVICE and h.pinned == 0),
                            key=lambda h: h.lru_tick):
                freed = h._device_to_host_locked()
                self._device_used -= freed
                self.spill_to_host_count += 1
                self.spill_to_host_bytes += freed
                spilled += freed
                if _ACCT.LEDGERS is not None:
                    _ACCT.LEDGERS.release_device(h.owner_qid, freed,
                                                 h.persistent)
                    _ACCT.LEDGERS.charge_spill(h.owner_qid, "host", freed)
            self._host_pressure_locked()
        return spilled

    def metrics(self) -> Dict[str, int]:
        return {
            "spillToHostCount": self.spill_to_host_count,
            "spillToDiskCount": self.spill_to_disk_count,
            "spillToHostBytes": self.spill_to_host_bytes,
            "spillToDiskBytes": self.spill_to_disk_bytes,
            "deviceUsedBytes": self._device_used,
            "deviceUsedPeakBytes": self._device_used_peak,
        }


_lock = threading.Lock()
_framework: Optional[SpillFramework] = None


def get_spill_framework(tpu_conf: Optional[TpuConf] = None) -> SpillFramework:
    global _framework
    with _lock:
        if _framework is None or tpu_conf is not None:
            from spark_rapids_tpu.memory.device_manager import get_device_manager

            c = tpu_conf or TpuConf()
            dm = get_device_manager(tpu_conf)
            _framework = SpillFramework(
                pool_bytes=dm.pool_bytes,
                host_limit=c.get(HOST_SPILL_STORAGE_SIZE),
                spill_dir=c.get(SPILL_DIR),
                debug=c.get(MEM_DEBUG))
        return _framework


def peek_spill_framework() -> Optional[SpillFramework]:
    """The singleton if it exists — cleanup/leak paths must never CREATE
    one (get_spill_framework would build a device manager)."""
    return _framework


def reset_spill_framework() -> None:
    global _framework
    with _lock:
        _framework = None
