"""TpuSemaphore — bounds how many tasks hold the device concurrently.

Reference analog: GpuSemaphore (SURVEY.md §2.3):
``spark.rapids.sql.concurrentGpuTasks`` permits gate device access so
oversubscribed Spark tasks don't OOM the device together; host-side work
(file fetch/decode threads) deliberately runs *outside* the semaphore.

Here a "task" is the thread driving a partition's iterator chain.  Permits
are reentrant per thread (a task that already holds one passes through),
matching acquireIfNecessary semantics.

Concurrent-query hardening (ISSUE 4):

* **Priority-aware**: waiters are granted in (priority, arrival) order,
  where priority defaults to the admission sequence of the current
  QueryContext — a query admitted EARLIER (already running, already
  holding device memory) outranks a newly admitted one, so the running
  query drains and releases instead of both convoying on a half-held
  working set (the reference's GpuSemaphore priority, which uses "has
  the task held the semaphore before" for the same reason).
* **Cancellable**: waiters poll in short slices and observe the current
  query's CancelToken, so a deadline/cancel aborts a blocked acquire
  within ~50ms.
* **Typed timeout**: an exhausted ``timeout`` raises
  :class:`SemaphoreTimeout` (a TimeoutError subtype, classified
  TRANSIENT by resilience/classify.py) with the permit deterministically
  NOT held; ``release_if_necessary`` stays safe to call from ``finally``
  after a failed acquire.
* **Lock ordering**: acquiring the semaphore while holding the spill
  framework's lock is a deadlock recipe (a spilling thread would wait on
  a permit held by a thread waiting to spill) and raises immediately —
  the ordering is semaphore BEFORE spill locks, always.
"""
from __future__ import annotations

import bisect
import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

_NO_PRIORITY = 1 << 62
_POLL_S = 0.05


class SemaphoreTimeout(TimeoutError):
    """TpuSemaphore.acquire_if_necessary ran out of time; the permit is
    NOT held.  Classified transient: by the time the fault domain's
    backoff retries, the convoy may have drained."""


class TpuSemaphore:
    def __init__(self, permits: int):
        self.permits = permits
        self._available = permits
        self._cond = threading.Condition()
        self._holders: Dict[int, int] = {}   # thread id -> depth
        self._waiters: List[Tuple[int, int]] = []   # sorted (priority, seq)
        self._seq = itertools.count()
        self.total_wait_ns = 0               # semaphoreWaitTime metric

    def _check_lock_order(self) -> None:
        from spark_rapids_tpu.memory import spill as _spill

        fw = _spill.peek_spill_framework()
        if fw is not None:
            owned = getattr(fw._lock, "_is_owned", None)
            if owned is not None and owned():
                raise RuntimeError(
                    "lock-order violation: TpuSemaphore.acquire_if_"
                    "necessary while holding the SpillFramework lock "
                    "(ordering is semaphore -> spill; the reverse "
                    "deadlocks concurrent OOM-spill paths)")

    def acquire_if_necessary(self, timeout: Optional[float] = None,
                             priority: Optional[int] = None) -> None:
        tid = threading.get_ident()
        token = None
        if priority is None:
            from spark_rapids_tpu.lifecycle.context import current

            ctx = current()
            if ctx is not None:
                priority = ctx.admission_seq
                token = ctx.token
            else:
                priority = _NO_PRIORITY
        else:
            from spark_rapids_tpu.lifecycle.context import current_token

            token = current_token()
        with self._cond:
            if self._holders.get(tid, 0) > 0:
                self._holders[tid] += 1
                return
            self._check_lock_order()
            t0 = time.perf_counter_ns()
            deadline = None if timeout is None else t0 + int(timeout * 1e9)
            ticket = (priority, next(self._seq))
            bisect.insort(self._waiters, ticket)
            try:
                while self._available <= 0 or self._waiters[0] != ticket:
                    if token is not None:
                        token.check()
                    now = time.perf_counter_ns()
                    if deadline is not None and now >= deadline:
                        raise SemaphoreTimeout(
                            f"TpuSemaphore acquire timed out after "
                            f"{timeout:.3f}s ({self.permits} permits, "
                            f"{len(self._holders)} holders)")
                    if deadline is None:
                        wait_s = _POLL_S if token is not None else None
                    else:
                        left = (deadline - now) / 1e9
                        wait_s = min(_POLL_S, left) if token is not None \
                            else left
                    self._cond.wait(wait_s)
                self._available -= 1
                self._holders[tid] = 1
            finally:
                self._waiters.remove(ticket)
                self.total_wait_ns += time.perf_counter_ns() - t0
                # waiter-set or availability changed either way; let the
                # new head re-evaluate
                self._cond.notify_all()

    def release_if_necessary(self) -> None:
        """Safe from ``finally`` even after a FAILED acquire: a thread
        holding no permit returns without touching the count."""
        tid = threading.get_ident()
        with self._cond:
            depth = self._holders.get(tid, 0)
            if depth == 0:
                return
            if depth > 1:
                self._holders[tid] = depth - 1
                return
            del self._holders[tid]
            self._available += 1
            self._cond.notify_all()

    def force_release_current_thread(self) -> int:
        """Drop ALL depth the current thread holds (query cleanup after a
        mid-batch unwind); returns the depth released."""
        tid = threading.get_ident()
        with self._cond:
            depth = self._holders.pop(tid, 0)
            if depth:
                self._available += 1
                self._cond.notify_all()
            return depth

    def held_by_current_thread(self) -> bool:
        return self._holders.get(threading.get_ident(), 0) > 0

    def leak_report(self) -> List[str]:
        """Permit-accounting anomalies: held permits (leaked by a thread
        that never released) or a corrupted available count."""
        with self._cond:
            out = [f"LEAK: semaphore permit held by thread {tid} "
                   f"(depth {d})" for tid, d in self._holders.items()]
            if self._available + len(self._holders) != self.permits:
                out.append(
                    f"LEAK: semaphore accounting off — available="
                    f"{self._available} holders={len(self._holders)} "
                    f"permits={self.permits}")
            return out

    class _Scope:
        def __init__(self, sem, timeout=None, priority=None):
            self.sem = sem
            self.timeout = timeout
            self.priority = priority

        def __enter__(self):
            self.sem.acquire_if_necessary(self.timeout, self.priority)
            return self.sem

        def __exit__(self, *a):
            self.sem.release_if_necessary()

    def scope(self, timeout: Optional[float] = None,
              priority: Optional[int] = None) -> "_Scope":
        return TpuSemaphore._Scope(self, timeout, priority)


_lock = threading.Lock()
_semaphore: Optional[TpuSemaphore] = None


def get_semaphore(permits: Optional[int] = None) -> TpuSemaphore:
    global _semaphore
    with _lock:
        if _semaphore is None or (permits is not None
                                  and _semaphore.permits != permits):
            _semaphore = TpuSemaphore(permits if permits is not None else 2)
        return _semaphore


def peek_semaphore() -> Optional[TpuSemaphore]:
    """The singleton if it exists — cleanup/leak paths must never CREATE
    one."""
    return _semaphore


def reset_semaphore() -> None:
    global _semaphore
    with _lock:
        _semaphore = None
