"""TpuSemaphore — bounds how many tasks hold the device concurrently.

Reference analog: GpuSemaphore (SURVEY.md §2.3):
``spark.rapids.sql.concurrentGpuTasks`` permits gate device access so
oversubscribed Spark tasks don't OOM the device together; host-side work
(file fetch/decode threads) deliberately runs *outside* the semaphore.

Here a "task" is the thread driving a partition's iterator chain.  Permits
are reentrant per thread (a task that already holds one passes through),
matching acquireIfNecessary semantics.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class TpuSemaphore:
    def __init__(self, permits: int):
        self.permits = permits
        self._available = permits
        self._cond = threading.Condition()
        self._holders: Dict[int, int] = {}   # thread id -> depth
        self.total_wait_ns = 0               # semaphoreWaitTime metric

    def acquire_if_necessary(self, timeout: Optional[float] = None) -> None:
        tid = threading.get_ident()
        with self._cond:
            if self._holders.get(tid, 0) > 0:
                self._holders[tid] += 1
                return
            t0 = time.perf_counter_ns()
            while self._available <= 0:
                if not self._cond.wait(timeout):
                    raise TimeoutError("TpuSemaphore acquire timed out")
            self.total_wait_ns += time.perf_counter_ns() - t0
            self._available -= 1
            self._holders[tid] = 1

    def release_if_necessary(self) -> None:
        tid = threading.get_ident()
        with self._cond:
            depth = self._holders.get(tid, 0)
            if depth == 0:
                return
            if depth > 1:
                self._holders[tid] = depth - 1
                return
            del self._holders[tid]
            self._available += 1
            self._cond.notify()

    def held_by_current_thread(self) -> bool:
        return self._holders.get(threading.get_ident(), 0) > 0

    class _Scope:
        def __init__(self, sem):
            self.sem = sem

        def __enter__(self):
            self.sem.acquire_if_necessary()
            return self.sem

        def __exit__(self, *a):
            self.sem.release_if_necessary()

    def scope(self) -> "_Scope":
        return TpuSemaphore._Scope(self)


_lock = threading.Lock()
_semaphore: Optional[TpuSemaphore] = None


def get_semaphore(permits: Optional[int] = None) -> TpuSemaphore:
    global _semaphore
    with _lock:
        if _semaphore is None or (permits is not None
                                  and _semaphore.permits != permits):
            _semaphore = TpuSemaphore(permits if permits is not None else 2)
        return _semaphore


def reset_semaphore() -> None:
    global _semaphore
    with _lock:
        _semaphore = None
