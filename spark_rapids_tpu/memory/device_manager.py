"""TpuDeviceManager — pool sizing and device init.

Reference analog: GpuDeviceManager.initializeGpuAndMemory / initializeRmm
(SURVEY.md §2.3): picks the device, sizes the RMM pool from
``spark.rapids.memory.gpu.allocFraction`` minus a reserve for non-pool
allocations.  Here the "pool" is the logical HBM budget the spill framework
enforces; the reserve mirrors the reference's headroom for framework
temporaries (there: CUDA context/cuDF scratch; here: XLA scratch and the
compiled programs' workspaces).
"""
from __future__ import annotations

import threading
from typing import Optional

from spark_rapids_tpu.config import (
    HBM_POOL_FRACTION,
    HBM_RESERVE,
    TpuConf,
    conf,
)

TEST_DEVICE_MEMORY = conf("spark.rapids.tpu.test.deviceMemoryBytes").doc(
    "Test override for the physical device memory size the pool is computed "
    "from (the XLA CPU backend reports no memory stats).").internal(
).bytes_conf(0)


def _physical_hbm_bytes() -> Optional[int]:
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:
        pass
    return None


class TpuDeviceManager:
    """Computes and holds the HBM pool budget (thread-safe singleton)."""

    def __init__(self, tpu_conf: Optional[TpuConf] = None):
        c = tpu_conf or TpuConf()
        override = c.get(TEST_DEVICE_MEMORY)
        physical = override or _physical_hbm_bytes() or (16 << 30)
        reserve = c.get(HBM_RESERVE)
        frac = c.get(HBM_POOL_FRACTION)
        self.physical_bytes = physical
        self.pool_bytes = max(int(physical * frac) - reserve, 64 << 20) \
            if not override else override
        self.reserve_bytes = reserve

    def describe(self) -> str:
        return (f"TpuDeviceManager pool={self.pool_bytes >> 20}MiB "
                f"physical={self.physical_bytes >> 20}MiB "
                f"reserve={self.reserve_bytes >> 20}MiB")


_lock = threading.Lock()
_manager: Optional[TpuDeviceManager] = None


def get_device_manager(tpu_conf: Optional[TpuConf] = None) -> TpuDeviceManager:
    global _manager
    with _lock:
        if _manager is None or tpu_conf is not None:
            _manager = TpuDeviceManager(tpu_conf)
        return _manager


def reset_device_manager() -> None:
    global _manager
    with _lock:
        _manager = None
