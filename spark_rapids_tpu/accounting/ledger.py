"""Per-query resource bills (ISSUE 18 tentpole).

Every HBM registration / spill / release in ``memory/spill.py`` charges
the owning query's ledger: device bytes charged/released, the per-query
device high-water mark, device-byte-seconds (the integral of tracked
device residency over time — the number a per-tenant quota would
meter), and spill traffic per tier (device->host, host->disk, and
restore traffic back up).  ``accounting.record_bill`` joins the ledger
with the diagnostics recorder's per-query counter deltas at collect end;
``settle`` retires the bill at lifecycle exit after query cleanup closed
the query's leftover handles.

Invariant discipline (the PR 3 attribution pin, applied to bytes): every
charge site bumps a global ``acct_*`` perf counter AND the owning bill
by the same amount, so the sum of per-bill values across live + settled
bills equals the global counter ``since()`` deltas exactly
(tests/test_accounting.py pins it).  Charges with no lifecycle context
land in the ``(unowned)`` bucket so the sums still balance.

Lock discipline: charge sites call in under the spill framework's lock;
``_lock`` here is a LEAF (nothing is called while holding it except
dict/arithmetic), and the paired perf-counter bumps happen outside it
(order: fw._lock -> ledger._lock, fw._lock -> PC._LOCK — no cycles).
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from spark_rapids_tpu import perfcounters as PC
from spark_rapids_tpu.accounting import context as CTX

UNOWNED = "(unowned)"

# tier name -> the paired global counter (the exact-sum invariant's
# other half; keys must exist in perfcounters.COUNTERS)
_TIER_COUNTER = {
    "host": "acct_spill_bytes_host",
    "disk": "acct_spill_bytes_disk",
    "restore": "acct_bytes_restored",
}


class Bill:
    """One query's accumulating resource bill."""

    __slots__ = ("owner", "charged", "released", "now", "peak",
                 "persistent_now", "byte_seconds", "spill", "partitions",
                 "started_t_ns", "last_t_ns", "settled", "residual")

    def __init__(self, owner: str):
        self.owner = owner
        self.charged = 0          # device bytes ever charged
        self.released = 0         # device bytes ever released
        self.now = 0              # device bytes currently held
        self.peak = 0             # per-query device high-water mark
        self.persistent_now = 0   # the df.cache() share of `now`
        self.byte_seconds = 0.0   # integral of `now` over wall time
        self.spill: Dict[str, int] = {
            "host_bytes": 0, "host_count": 0,
            "disk_bytes": 0, "disk_count": 0,
            "restore_bytes": 0, "restore_count": 0,
        }
        # pid -> {"spill_bytes", "restore_bytes"} — the draining
        # partition that DROVE the traffic (ISSUE 18 satellite)
        self.partitions: Dict[int, Dict[str, int]] = {}
        self.started_t_ns = time.monotonic_ns()
        self.last_t_ns = self.started_t_ns
        self.settled = False
        self.residual = 0

    def _integrate_locked(self) -> None:
        t = time.monotonic_ns()
        if self.now > 0 and t > self.last_t_ns:
            self.byte_seconds += self.now * (t - self.last_t_ns) / 1e9
        self.last_t_ns = t

    def snapshot(self) -> Dict[str, Any]:
        return {
            "owner": self.owner,
            "device_bytes_charged": self.charged,
            "device_bytes_released": self.released,
            "device_bytes_now": self.now,
            "device_peak_bytes": self.peak,
            "persistent_bytes": self.persistent_now,
            "residual_bytes": self.now - self.persistent_now,
            "device_byte_seconds": round(self.byte_seconds, 6),
            "spill": dict(self.spill),
            "partitions": {p: dict(d)
                           for p, d in self.partitions.items()},
        }


class LedgerRegistry:
    """The process-global bill table: live bills keyed by lifecycle
    query id, plus a bounded ring of settled bills."""

    def __init__(self, retained_bills: int = 64):
        self._lock = threading.Lock()
        self._bills: Dict[str, Bill] = {}
        self._finished: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._retained = max(int(retained_bills), 1)
        # settled bills whose residual was nonzero (charged bytes never
        # released, persistent excluded) — the conftest leak gate reads
        # and clears these, mirroring the spillable leak gate
        self._residuals: Dict[str, int] = {}

    # -- charge API (memory/spill.py charge sites) ----------------------
    def _bill_locked(self, qid: Optional[str]) -> Bill:
        key = qid if qid is not None else UNOWNED
        b = self._bills.get(key)
        if b is None:
            # a settled query's stragglers must not resurrect a live
            # bill — fold them into the unowned bucket so the global
            # sums still balance
            if key in self._finished:
                b = self._bills.get(UNOWNED)
                if b is None:
                    b = self._bills[UNOWNED] = Bill(UNOWNED)
                return b
            b = self._bills[key] = Bill(key)
        return b

    def charge_device(self, qid: Optional[str], nbytes: int,
                      persistent: bool = False) -> None:
        n = int(nbytes)
        with self._lock:
            key = qid if qid is not None else UNOWNED
            fin = self._finished.get(key) \
                if key not in self._bills else None
            if fin is not None:
                # late charge against an already-settled bill (ISSUE 19:
                # a serving result fragment is inserted after its
                # producing query's lifecycle exited — the owner still
                # pays): mirror of the late-release path below
                fin["device_bytes_charged"] += n
                fin["device_bytes_now"] += n
                if persistent:
                    fin["persistent_bytes"] += n
                fin["residual_bytes"] = fin["device_bytes_now"] \
                    - fin["persistent_bytes"]
                if fin["residual_bytes"]:
                    self._residuals[key] = fin["residual_bytes"]
                elif key in self._residuals:
                    del self._residuals[key]
            else:
                b = self._bill_locked(qid)
                b._integrate_locked()
                b.charged += n
                b.now += n
                if b.now > b.peak:
                    b.peak = b.now
                if persistent:
                    b.persistent_now += n
        PC.bump("acct_device_bytes_charged", n)

    def release_device(self, qid: Optional[str], nbytes: int,
                       persistent: bool = False) -> None:
        n = int(nbytes)
        with self._lock:
            key = qid if qid is not None else UNOWNED
            fin = self._finished.get(key) \
                if key not in self._bills else None
            if fin is not None:
                # late release for an already-settled bill (a persistent
                # cache handle closed after its query): keep the settled
                # record — and the residual gate — truthful
                fin["device_bytes_released"] += n
                fin["device_bytes_now"] -= n
                if persistent:
                    fin["persistent_bytes"] -= n
                fin["residual_bytes"] = fin["device_bytes_now"] \
                    - fin["persistent_bytes"]
                if key in self._residuals:
                    if fin["residual_bytes"]:
                        self._residuals[key] = fin["residual_bytes"]
                    else:
                        del self._residuals[key]
            else:
                b = self._bill_locked(qid)
                b._integrate_locked()
                b.released += n
                b.now -= n
                if persistent:
                    b.persistent_now -= n
        PC.bump("acct_device_bytes_released", n)

    def charge_spill(self, qid: Optional[str], tier: str,
                     nbytes: int) -> None:
        """One spill/restore movement: ``tier`` is ``host``
        (device->host), ``disk`` (host->disk), or ``restore``
        (back up-tier).  Tagged with the draining partition id when the
        exchange drain set the ``PARTITION`` stamp."""
        n = int(nbytes)
        pid = CTX.PARTITION.get()
        with self._lock:
            b = self._bill_locked(qid)
            b.spill[f"{tier}_bytes"] += n
            b.spill[f"{tier}_count"] += 1
            if pid >= 0:
                part = b.partitions.get(pid)
                if part is None:
                    part = b.partitions[pid] = {"spill_bytes": 0,
                                                "restore_bytes": 0}
                part["restore_bytes" if tier == "restore"
                     else "spill_bytes"] += n
        PC.bump(_TIER_COUNTER[tier], n)

    # -- read/lifecycle surfaces ----------------------------------------
    def snapshot(self, qid: Optional[str]) -> Optional[Dict[str, Any]]:
        """The query's live bill as a dict (byte-seconds integrated up
        to now), or its settled record, or None."""
        key = qid if qid is not None else UNOWNED
        with self._lock:
            b = self._bills.get(key)
            if b is not None:
                b._integrate_locked()
                return b.snapshot()
            fin = self._finished.get(key)
            return dict(fin) if fin is not None else None

    def snapshot_all(self) -> List[Dict[str, Any]]:
        """Every live AND settled bill (the invariant-sum surface)."""
        with self._lock:
            out = []
            for b in self._bills.values():
                b._integrate_locked()
                out.append(b.snapshot())
            out.extend(dict(f) for f in self._finished.values())
            return out

    def settle(self, qid: str) -> Optional[Dict[str, Any]]:
        """Retire the query's bill at lifecycle exit (after
        ``close_owned_by`` swept its leftover handles).  A nonzero
        residual — charged device bytes never released, persistent
        df.cache() handles excluded — is recorded for the leak gate."""
        with self._lock:
            b = self._bills.pop(qid, None)
            if b is None:
                return None
            b._integrate_locked()
            b.settled = True
            b.residual = b.now - b.persistent_now
            snap = b.snapshot()
            snap["settled"] = True
            self._finished[qid] = snap
            while len(self._finished) > self._retained:
                old_qid, old = self._finished.popitem(last=False)
                # an evicted bill keeps its residual visible: bounded
                # retention must not silently forgive a leak
                if old["residual_bytes"]:
                    self._residuals.setdefault(old_qid,
                                               old["residual_bytes"])
            if b.residual:
                self._residuals[qid] = b.residual
        PC.bump_unattributed("bills_settled")
        return snap

    def last_settled(self) -> Optional[Dict[str, Any]]:
        """The most recently settled bill (bench.py's per-run columns)."""
        with self._lock:
            if not self._finished:
                return None
            return dict(next(reversed(self._finished.values())))

    # -- leak gate (lifecycle.leak_report_all / conftest) ---------------
    def leak_report(self) -> List[str]:
        with self._lock:
            return [f"LEAK: resource bill {qid} residual {res}B "
                    "(charged device bytes never released; persistent "
                    "handles excluded)"
                    for qid, res in self._residuals.items()]

    def reset_residuals(self) -> None:
        with self._lock:
            self._residuals.clear()
