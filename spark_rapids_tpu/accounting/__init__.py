"""Per-query resource accounting + the live regression sentinel
(ISSUE 18).

Reference analog: the reference plugin meters per-task GPU time and
semaphore wait (GpuTaskMetrics, SURVEY §5.5) but never aggregates a
query-attributable resource record; Theseus (arXiv:2508.05029) argues
accelerated SQL platforms win or lose at the resource-scheduling layer,
and a scheduler needs exactly this substrate: "which query is holding
the HBM" (the bill) and "did this plan signature just get slower" (the
sentinel).  The ROADMAP's multi-tenant serving tier (per-tenant quotas,
tenant-aware shed/preempt) and adaptive execution (observed-vs-predicted
feedback) both sit on it.

Layout:
  context.py  — the ambient LEDGERS slot + the PARTITION drain stamp
  ledger.py   — LedgerRegistry / Bill (charged by memory/spill.py)
  sentinel.py — per-signature baseline comparison + the delta table

Wiring:
  * ``TpuSession.__init__`` calls :func:`maybe_configure` — the first
    session with ``spark.rapids.tpu.accounting.enabled=true`` installs
    the process ledger registry.
  * ``memory/spill.py`` charge sites bill every HBM registration /
    spill / release (one ambient ``context.LEDGERS`` check each —
    disabled: ZERO accounting calls, cProfile-pinned).
  * ``diagnostics.query_scope``'s finish hook calls
    :func:`record_bill` — the bill joins the recorder's counter deltas,
    progress background wall, and federated worker bytes; lands as a
    ``resource_bill`` event + telemetry gauges; the sentinel runs.
  * ``lifecycle._cleanup_query`` settles the bill after the query's
    leftover handles were swept; a nonzero residual feeds the conftest
    leak gate.

Every entry point swallows its own failures — accounting must never
fail a query.
"""
from __future__ import annotations

import sys
import threading
from typing import Any, Dict, Optional

from spark_rapids_tpu.accounting import context as CTX
from spark_rapids_tpu.accounting.ledger import UNOWNED, LedgerRegistry

_LOCK = threading.Lock()

# the counter-delta slice joined into the resource_bill event (the
# dimensions the ISSUE names: transfer volume, launches, syncs, compile
# wall, plus the acct_* mirror keys the invariant test reconciles)
BILL_COUNTER_KEYS = (
    "bytes_h2d", "bytes_d2h", "programs_launched", "host_syncs",
    "compile_wall_ns", "aot_compile_wall_ns", "launch_wall_ns",
    "compile_cache_hits", "compile_cache_misses",
    "acct_device_bytes_charged", "acct_device_bytes_released",
    "acct_spill_bytes_host", "acct_spill_bytes_disk",
    "acct_bytes_restored",
)

# partitions listed in the resource_bill event, largest traffic first
# (a 4096-partition exchange must not bloat every event)
MAX_EVENT_PARTITIONS = 8


def maybe_configure(conf) -> Optional[LedgerRegistry]:
    """Idempotent process-global start (TpuSession.__init__): the FIRST
    enabling conf installs the ledger registry; later sessions reuse
    it.  None when the conf disables accounting."""
    from spark_rapids_tpu.config import (
        ACCOUNTING_ENABLED,
        ACCOUNTING_RETAINED_BILLS,
    )

    if not conf.get(ACCOUNTING_ENABLED):
        return None
    with _LOCK:
        if CTX.LEDGERS is None:
            CTX.LEDGERS = LedgerRegistry(
                int(conf.get(ACCOUNTING_RETAINED_BILLS)))
        return CTX.LEDGERS


def get_registry() -> Optional[LedgerRegistry]:
    return CTX.LEDGERS


def shutdown() -> None:
    """Clear the ledger slot (tests / process teardown); the next
    enabling TpuSession rebuilds."""
    with _LOCK:
        CTX.LEDGERS = None


def last_bill() -> Optional[Dict[str, Any]]:
    """The most recently settled bill (bench.py's per-run columns)."""
    reg = CTX.LEDGERS
    return reg.last_settled() if reg is not None else None


def _empty_bill(owner: str) -> Dict[str, Any]:
    from spark_rapids_tpu.accounting.ledger import Bill

    return Bill(owner).snapshot()


def plan_signature_of(diag) -> str:
    """The recorder's plan signature — ``path:name`` joined in plan
    order, the same identity ``QueryProfile.plan_signature`` derives
    from the event-log header (so offline tooling matches sentinel
    baselines to history pages)."""
    return "|".join(f"{p}:{diag.ops[p].name}"
                    for p in diag._op_order if p != "")


def record_bill(diag, conf) -> None:
    """query_scope finish hook (after ``finish()`` closed the window,
    before the sinks flush): join the query's ledger with the
    recorder's counter deltas + progress background wall + federated
    worker bytes, emit the ``resource_bill`` event and telemetry
    gauges, then run the regression sentinel."""
    try:
        reg = CTX.LEDGERS
        if reg is None:
            return
        from spark_rapids_tpu.lifecycle.context import current

        ctx = current()
        qid = ctx.query_id if ctx is not None else None
        bill = reg.snapshot(qid) or _empty_bill(qid or UNOWNED)
        sig = plan_signature_of(diag)
        with diag._lock:
            events = list(diag.events)
        background_wall = 0
        worker_bytes: Dict[str, int] = {}
        for e in events:
            ev = e.get("ev")
            if ev == "progress":
                background_wall = sum(
                    int(d.get("wall_ns", 0))
                    for d in (e.get("background") or {}).values())
            elif ev == "worker_telemetry":
                # last payload per worker wins — store occupancy is a
                # level, not a delta
                worker_bytes[str(e.get("worker_id", "?"))] = \
                    int(e.get("bytes", 0))
        if not worker_bytes:
            worker_bytes = _federated_worker_bytes()
        counters = {k: int(diag.total.get(k, 0))
                    for k in BILL_COUNTER_KEYS}
        parts = sorted(
            bill.get("partitions", {}).items(),
            key=lambda kv: kv[1].get("spill_bytes", 0)
            + kv[1].get("restore_bytes", 0),
            reverse=True)[:MAX_EVENT_PARTITIONS]
        diag.record_resource_bill(
            query_id=qid or diag.query_id, signature=sig,
            wall_ns=diag.wall_ns,
            device_peak_bytes=bill["device_peak_bytes"],
            device_byte_seconds=bill["device_byte_seconds"],
            device_bytes_charged=bill["device_bytes_charged"],
            device_bytes_released=bill["device_bytes_released"],
            residual_bytes=bill["residual_bytes"],
            persistent_bytes=bill["persistent_bytes"],
            spill=dict(bill["spill"]),
            partitions={str(p): dict(d) for p, d in parts},
            background_wall_ns=background_wall,
            worker_bytes=worker_bytes,
            counters=counters)
        _record_gauges(bill)
        _run_sentinel(diag, conf, qid or diag.query_id, sig, bill)
    except Exception as e:   # accounting must never fail a query
        print(f"spark_rapids_tpu.accounting: bill recording failed: {e}",
              file=sys.stderr)


def _record_gauges(bill: Dict[str, Any]) -> None:
    """Per-query bill gauges on the always-on registry (ISSUE 7
    surface): HBM pressure per query is visible beside latency/SLOs."""
    from spark_rapids_tpu.telemetry import context as TEL

    hub = TEL.HUB
    if hub is None:
        return
    reg = hub.registry
    spill = bill.get("spill") or {}
    reg.record("bill_device_peak_bytes",
               float(bill["device_peak_bytes"]))
    reg.record("bill_device_byte_seconds",
               float(bill["device_byte_seconds"]))
    reg.record("bill_spilled_bytes",
               float(spill.get("host_bytes", 0)
                     + spill.get("disk_bytes", 0)))


def _federated_worker_bytes() -> Dict[str, int]:
    """Live federated store bytes when the query recorded no
    worker_telemetry events (heartbeats landed between queries).  The
    coordinator is peeked via sys.modules — the in-process path makes
    zero calls into distributed modules (same rule as the worker-span
    merge)."""
    dist_mod = sys.modules.get("spark_rapids_tpu.distributed")
    coord = getattr(dist_mod, "_coordinator", None) \
        if dist_mod is not None else None
    if coord is None:
        return {}
    try:
        return coord.federated_store_bytes()
    # tpulint: disable=cancel-swallow (observability isolation: a dead
    # coordinator must not fail bill recording)
    except Exception:
        return {}


def _run_sentinel(diag, conf, qid: str, sig: str,
                  bill: Dict[str, Any]) -> None:
    """Compare this query against its signature baseline; flag at most
    ONE regression (counter + flight event + diagnostics event + a
    post-mortem bundle carrying the bill, the violated baseline, and
    the per-operator delta table), and fold clean ok-status
    observations into the store."""
    from spark_rapids_tpu.config import (
        ACCOUNTING_SENTINEL_ENABLED,
        ACCOUNTING_SENTINEL_MIN_SAMPLES,
        ACCOUNTING_SENTINEL_MIN_WALL_EXCESS_MS,
        ACCOUNTING_SENTINEL_WALL_RATIO,
        ACCOUNTING_SENTINEL_Z,
        PROFILE_DIR,
        PROFILE_EWMA_ALPHA,
    )

    if not conf.get(ACCOUNTING_SENTINEL_ENABLED) or not sig:
        return
    prof_dir = conf.get(PROFILE_DIR)
    if not prof_dir:
        return   # baselines live in the calibration store (docs)
    from spark_rapids_tpu.accounting.sentinel import (
        evaluate,
        op_self_walls,
        regressed_operator,
        signature_observation,
    )
    from spark_rapids_tpu.profiling.store import CalibrationStore

    alpha = float(conf.get(PROFILE_EWMA_ALPHA))
    store = CalibrationStore.load_cached(prof_dir, alpha=alpha)
    baseline = store.signature(sig)
    obs = signature_observation(diag, bill)
    ops_obs = op_self_walls(diag)
    finding = evaluate(
        baseline, obs,
        min_samples=int(conf.get(ACCOUNTING_SENTINEL_MIN_SAMPLES)),
        wall_ratio=float(conf.get(ACCOUNTING_SENTINEL_WALL_RATIO)),
        z_threshold=float(conf.get(ACCOUNTING_SENTINEL_Z)),
        min_wall_excess_ns=float(conf.get(
            ACCOUNTING_SENTINEL_MIN_WALL_EXCESS_MS)) * 1e6)
    if finding is not None:
        from spark_rapids_tpu import perfcounters as PC

        # UNATTRIBUTED: the hook runs after its own recorder closed; a
        # plain bump would land in a concurrent query's window
        PC.bump_unattributed("perf_regressions_flagged")
        op_path, op_name, table = regressed_operator(baseline, ops_obs)
        detail = (f"{finding['dimension']}: observed "
                  f"{finding['observed']:.0f} vs baseline "
                  f"{finding['baseline']:.0f} "
                  f"(ratio {finding['ratio']:.2f}, z {finding['z']:.1f});"
                  f" worst operator {op_path}:{op_name}")
        diag.record_regression(
            query_id=qid, signature=sig,
            dimension=finding["dimension"],
            observed=finding["observed"], baseline=finding["baseline"],
            ratio=finding["ratio"], z=finding["z"],
            op_path=op_path, op_name=op_name, detail=detail)
        from spark_rapids_tpu.telemetry import context as TEL

        hub = TEL.HUB
        if hub is not None:
            hub.record_event("regression", query_id=qid, signature=sig,
                             dimension=finding["dimension"],
                             ratio=finding["ratio"])
            hub.postmortem(
                "perf_regression", query_id=qid, detail=detail,
                extra={"bill": bill, "baseline": baseline,
                       "op_deltas": table[:12]})
        return
    if diag.status != "ok":
        return   # truncated queries must not poison the baselines
    wstore = CalibrationStore(prof_dir, alpha=alpha)
    wstore.observe_signature(sig, obs, ops_obs)
    wstore.save()


__all__ = [
    "BILL_COUNTER_KEYS", "LedgerRegistry", "UNOWNED", "get_registry",
    "last_bill", "maybe_configure", "plan_signature_of", "record_bill",
    "shutdown",
]
