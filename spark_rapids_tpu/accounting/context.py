"""The ambient accounting slots (ISSUE 18).

Hot paths import ONLY this module: the charge sites in memory/spill.py
and the partition stamps in shuffle/partition_queues.py read one module
attribute (``LEDGERS``) per event — with accounting disabled the slot is
None and they make ZERO calls into the accounting package
(tests/test_accounting.py pins it with cProfile, the same methodology as
the diagnostics / telemetry / progress disabled-path pins).

``PARTITION`` is the draining-partition stamp (ISSUE 18 satellite): the
spill-backed exchange sets it around per-partition appends and drains so
spill/restore traffic a partition DRIVES is attributable to that
partition in the owning query's bill, localizing out-of-core pressure.
"""
from __future__ import annotations

import contextvars
from typing import Optional

# the process LedgerRegistry while accounting is enabled, else None —
# the one ambient check every charge site makes
LEDGERS = None  # type: Optional["object"]

# reduce-partition id currently driving spill/restore traffic (-1: none)
PARTITION: contextvars.ContextVar[int] = contextvars.ContextVar(
    "srt_acct_partition", default=-1)
