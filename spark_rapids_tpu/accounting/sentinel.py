"""The live regression sentinel (ISSUE 18 tentpole, part 2).

Production serving traffic is dominated by REPEATED plan signatures
(Presto+GPU, arXiv:2606.24647), so per-signature baselines make
slowdowns machine-detectable: at each collect exit the sentinel compares
the query's bill + wall against the calibration store's per-plan-
signature EWMAs (wall, host syncs, spill bytes, compile-cache hit rate)
and flags excursions past the conf'd ratio/z thresholds — a live fleet
notices its own slowdowns without a human running
``profile_report --diff``.

Discipline against false positives and baseline poisoning:

* a dimension flags only when BOTH the ratio gate and an absolute
  excess floor trip (wall additionally requires the z-score gate, with
  the deviation EWMA floored at 5% of the mean so a near-constant
  baseline cannot make trivial jitter look like many sigmas);
* at most ONE regression is flagged per query — the worst dimension;
* a FLAGGED observation is NOT folded into the baseline (folding the
  regression would teach the store the slowdown is normal), and only
  ``status == "ok"`` queries fold at all (same rule as the PR 8
  operator calibration).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

# absolute excess floors: below these a ratio excursion is noise, not a
# regression (a 2-sync query tripling to 6 syncs is not an incident)
SYNC_EXCESS_FLOOR = 16
SPILL_EXCESS_FLOOR = 1 << 20          # 1 MiB
CACHE_HIT_DROP_FLOOR = 0.5            # absolute hit-rate drop
# the deviation-EWMA floor as a fraction of the mean (the z denominator
# can never collapse below 5% of the baseline wall)
WALL_STD_FLOOR_FRAC = 0.05

# the EWMA'd per-signature dimensions (stored under the calibration
# store's "signatures" section)
SIGNATURE_KEYS = ("wall_ns", "host_syncs", "spill_bytes",
                  "cache_hit_rate")


def signature_observation(diag, bill: Dict[str, Any]) -> Dict[str, Any]:
    """One query's sentinel-dimension observation, harvested from the
    finished recorder's global-delta window and its resource bill."""
    total = diag.total or {}
    hits = int(total.get("compile_cache_hits", 0))
    misses = int(total.get("compile_cache_misses", 0))
    spill = bill.get("spill") or {}
    return {
        "wall_ns": float(diag.wall_ns),
        "host_syncs": float(total.get("host_syncs", 0)),
        "spill_bytes": float(spill.get("host_bytes", 0)
                             + spill.get("disk_bytes", 0)),
        "cache_hit_rate": (hits / (hits + misses)
                           if (hits + misses) else 1.0),
    }


def op_self_walls(diag) -> Dict[str, int]:
    """Per-operator self-wall observation keyed ``path:name`` — the
    delta table a flagged regression's post-mortem names the regressed
    operator from."""
    out: Dict[str, int] = {}
    child_wall: Dict[str, int] = {}
    for path, st in diag.ops.items():
        dot = path.rfind(".")
        if dot > 0:
            parent = path[:dot]
            child_wall[parent] = child_wall.get(parent, 0) + st.wall_ns
    for path, st in diag.ops.items():
        if path == "":
            continue
        out[f"{path}:{st.name}"] = max(
            st.wall_ns - child_wall.get(path, 0), 0)
    return out


def evaluate(baseline: Optional[Dict[str, Any]],
             obs: Dict[str, Any],
             min_samples: int,
             wall_ratio: float,
             z_threshold: float,
             min_wall_excess_ns: float) -> Optional[Dict[str, Any]]:
    """Compare one observation against its signature baseline; the
    worst offending dimension as a finding dict, or None.  Pure
    function — tests drive the thresholds directly."""
    if baseline is None or int(baseline.get("n", 0)) < int(min_samples):
        return None
    ew = baseline.get("ewma") or {}
    findings: List[Tuple[float, Dict[str, Any]]] = []

    mean = float(ew.get("wall_ns", 0.0))
    w = float(obs.get("wall_ns", 0.0))
    if mean > 0 and w > mean * wall_ratio \
            and (w - mean) >= float(min_wall_excess_ns):
        std = max(float(baseline.get("wall_dev_ns", 0.0)),
                  mean * WALL_STD_FLOOR_FRAC, 1.0)
        z = (w - mean) / std
        if z >= z_threshold:
            findings.append((w / mean, {
                "dimension": "wall_ns", "observed": w,
                "baseline": mean, "ratio": w / mean, "z": z}))

    for dim, floor in (("host_syncs", SYNC_EXCESS_FLOOR),
                       ("spill_bytes", SPILL_EXCESS_FLOOR)):
        mean = float(ew.get(dim, 0.0))
        v = float(obs.get(dim, 0.0))
        if v > mean * wall_ratio and (v - mean) >= floor:
            ratio = v / mean if mean > 0 else float("inf")
            findings.append((min(ratio, 1e9), {
                "dimension": dim, "observed": v, "baseline": mean,
                "ratio": round(min(ratio, 1e9), 3), "z": 0.0}))

    mean = float(ew.get("cache_hit_rate", 1.0))
    v = float(obs.get("cache_hit_rate", 1.0))
    if (mean - v) >= CACHE_HIT_DROP_FLOOR:
        findings.append((1.0 + (mean - v), {
            "dimension": "cache_hit_rate", "observed": v,
            "baseline": mean, "ratio": round(mean - v, 3), "z": 0.0}))

    if not findings:
        return None
    findings.sort(key=lambda f: f[0], reverse=True)
    return findings[0][1]


def regressed_operator(baseline: Optional[Dict[str, Any]],
                       ops_obs: Dict[str, int]
                       ) -> Tuple[str, str, List[Dict[str, Any]]]:
    """(op_path, op_name, per-operator delta table) — the operator whose
    self-wall grew most over its baseline EWMA, the post-mortem's
    primary suspect.  With no baseline ops the largest observed
    self-wall stands in."""
    base_ops = (baseline or {}).get("ops") or {}
    table: List[Dict[str, Any]] = []
    for key, wall in ops_obs.items():
        base = float(base_ops.get(key, 0.0))
        path, _, name = key.partition(":")
        table.append({"path": path, "name": name,
                      "self_wall_ns": int(wall),
                      "baseline_self_wall_ns": int(base),
                      "delta_ns": int(wall - base)})
    table.sort(key=lambda r: r["delta_ns"], reverse=True)
    if not table:
        return "", "", table
    top = table[0]
    return top["path"], top["name"], table
