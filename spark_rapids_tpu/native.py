"""ctypes bindings for the native host kernels (native/host_kernels.cpp).

Reference analog: the reference runtime's C++ host components
(spark-rapids-jni Kudo serializer / string kernels, SURVEY.md §2.10);
python↔native goes through ctypes because pybind11 is not in the image.

The library is compiled on first use with g++ (cached next to the source);
every entry point has a pure-Python fallback so a missing toolchain only
costs speed, never correctness.  ``python -m spark_rapids_tpu.native``
rebuilds and self-tests.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_HERE, "native", "host_kernels.cpp")
_SO = os.path.join(_HERE, "native", "host_kernels.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", _SO, _SRC],
            check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def get_lib():
    """The loaded library, or None (fallbacks used)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SRC):
            return None
        # rebuild keyed on a source HASH (mtimes are not preserved by git
        # checkouts, so a stale binary could silently shadow newer source)
        import hashlib

        with open(_SRC, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()
        stamp = _SO + ".hash"
        current = None
        if os.path.exists(stamp):
            with open(stamp) as f:
                current = f.read().strip()
        if not os.path.exists(_SO) or current != digest:
            if not _build():
                return None
            with open(stamp, "w") as f:
                f.write(digest)
        try:
            lib = ctypes.CDLL(_SO)
            u8p = ctypes.POINTER(ctypes.c_uint8)
            i32p = ctypes.POINTER(ctypes.c_int32)
            i64p = ctypes.POINTER(ctypes.c_int64)
            lib.ragged_to_padded.argtypes = [u8p, i64p, ctypes.c_int64,
                                             ctypes.c_int64, u8p]
            lib.padded_to_ragged.argtypes = [u8p, i32p, ctypes.c_int64,
                                             ctypes.c_int64, u8p, i64p]
            lib.get_json_object_padded.argtypes = [
                u8p, i32p, u8p, ctypes.c_int64, ctypes.c_int64,
                u8p, ctypes.c_int64, u8p, i32p, u8p]
        except Exception:
            # stale/incompatible .so: fall back to the python paths
            return None
        _lib = lib
        return _lib


def _p(arr, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def ragged_to_padded(buf: np.ndarray, offsets: np.ndarray,
                     width: int) -> np.ndarray:
    """Arrow string (chars buffer, int64 offsets) -> (rows, width) uint8."""
    rows = len(offsets) - 1
    out = np.zeros((rows, max(width, 1)), np.uint8)
    lib = get_lib()
    if lib is not None and rows:
        buf = np.ascontiguousarray(buf)
        offsets = np.ascontiguousarray(offsets, np.int64)
        lib.ragged_to_padded(_p(buf, ctypes.c_uint8),
                             _p(offsets, ctypes.c_int64),
                             rows, out.shape[1],
                             _p(out, ctypes.c_uint8))
        return out
    for i in range(rows):
        s, e = offsets[i], offsets[i + 1]
        ln = min(e - s, out.shape[1])
        if ln > 0:
            out[i, :ln] = buf[s: s + ln]
    return out


def padded_to_ragged(chars: np.ndarray, lengths: np.ndarray):
    """(rows, width) uint8 + lengths -> (packed bytes, int64 offsets)."""
    rows, width = chars.shape
    lens = np.minimum(lengths.astype(np.int64), width)
    total = int(lens.sum())
    out = np.empty(total, np.uint8)
    offsets = np.empty(rows + 1, np.int64)
    lib = get_lib()
    if lib is not None and rows:
        chars = np.ascontiguousarray(chars)
        l32 = np.ascontiguousarray(lengths, np.int32)
        lib.padded_to_ragged(_p(chars, ctypes.c_uint8),
                             _p(l32, ctypes.c_int32), rows, width,
                             _p(out, ctypes.c_uint8),
                             _p(offsets, ctypes.c_int64))
        return out, offsets
    pos = 0
    offsets[0] = 0
    for i in range(rows):
        ln = int(lens[i])
        if ln:
            out[pos: pos + ln] = chars[i, :ln]
            pos += ln
        offsets[i + 1] = pos
    return out, offsets


def _serialize_json_steps(steps) -> np.ndarray:
    """[key|index] steps -> the C kernel's tag/u32/bytes blob."""
    import struct

    blob = bytearray()
    for s in steps:
        if isinstance(s, str):
            b = s.encode("utf-8")
            blob += b"k" + struct.pack("<I", len(b)) + b
        else:
            blob += b"i" + struct.pack("<I", int(s))
    return np.frombuffer(bytes(blob), np.uint8) if blob else np.zeros(
        0, np.uint8)


def get_json_object_padded(chars: np.ndarray, lengths: np.ndarray,
                           validity: np.ndarray, steps):
    """Evaluate one JSON path over a padded char matrix.

    Returns (out_chars, out_lengths, out_valid); invalid/unmatched rows are
    null.  Native C++ engine when available, else the Python engine in
    spark_rapids_tpu/jsonpath.py (the semantic spec both must match)."""
    rows, width = chars.shape
    out_chars = np.zeros((rows, width), np.uint8)
    out_lens = np.zeros(rows, np.int32)
    out_valid = np.zeros(rows, np.bool_)
    lib = get_lib()
    if lib is not None and rows:
        blob = np.ascontiguousarray(_serialize_json_steps(steps))
        chars_c = np.ascontiguousarray(chars)
        lens_c = np.ascontiguousarray(lengths, np.int32)
        valid_c = np.ascontiguousarray(validity, np.uint8)
        lib.get_json_object_padded(
            _p(chars_c, ctypes.c_uint8), _p(lens_c, ctypes.c_int32),
            _p(valid_c, ctypes.c_uint8), rows, width,
            _p(blob, ctypes.c_uint8), len(blob),
            _p(out_chars, ctypes.c_uint8), _p(out_lens, ctypes.c_int32),
            _p(out_valid.view(np.uint8), ctypes.c_uint8))
        return out_chars, out_lens, out_valid
    from spark_rapids_tpu.jsonpath import get_json_object_bytes

    for i in range(rows):
        if not validity[i]:
            continue
        doc = bytes(chars[i, :lengths[i]])
        res = get_json_object_bytes(doc, list(steps))
        if res is None:
            continue
        res = res[:width]
        out_chars[i, :len(res)] = np.frombuffer(res, np.uint8)
        out_lens[i] = len(res)
        out_valid[i] = True
    return out_chars, out_lens, out_valid


def _selftest():
    import time

    strs = [b"hello", b"", b"a" * 37, b"xy"] * 50000
    offs = np.zeros(len(strs) + 1, np.int64)
    np.cumsum([len(s) for s in strs], out=offs[1:])
    buf = np.frombuffer(b"".join(strs), np.uint8)
    width = 64
    t0 = time.perf_counter()
    out = ragged_to_padded(buf, offs, width)
    t_native = time.perf_counter() - t0
    for i in (0, 1, 2, 3):
        assert bytes(out[i, : len(strs[i])]) == strs[i]
        assert not out[i, len(strs[i]):].any()
    lengths = (offs[1:] - offs[:-1]).astype(np.int32)
    packed, offs2 = padded_to_ragged(out, lengths)
    assert bytes(packed[: len(strs[0])]) == strs[0]
    assert np.array_equal(offs, offs2)
    mode = "native" if get_lib() is not None else "python fallback"
    print(f"host_kernels self-test OK ({mode}; "
          f"{len(strs)} rows in {t_native * 1000:.1f}ms)")


if __name__ == "__main__":
    _selftest()


def snappy_uncompress(data: bytes, usize: int) -> bytes:
    """Raw snappy block decompression (parquet's default codec).

    Native C++ when built; a pure-python twin otherwise — the format is
    a simple LZ77 variant (varint length + literal/copy tags)."""
    lib = get_lib()
    if lib is not None:
        inp = np.frombuffer(data, np.uint8)
        out = np.zeros(max(usize, 1), np.uint8)
        fn = lib.snappy_uncompress
        fn.restype = ctypes.c_int64
        n = fn(_p(np.ascontiguousarray(inp), ctypes.c_uint8),
               len(inp), _p(out, ctypes.c_uint8),
               ctypes.c_int64(len(out)))
        if n < 0:
            raise ValueError("malformed snappy block")
        return out[:n].tobytes()
    return _snappy_uncompress_py(data, usize)


def _snappy_uncompress_py(data: bytes, usize: int) -> bytes:
    ip = 0
    ulen = 0
    shift = 0
    n = len(data)
    while ip < n:
        b = data[ip]
        ip += 1
        ulen |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
        if shift > 35:
            raise ValueError("malformed snappy varint")
    out = bytearray()
    while ip < n:
        tag = data[ip]
        ip += 1
        typ = tag & 3
        if typ == 0:
            ln = (tag >> 2) + 1
            if ln > 60:
                nb = ln - 60
                ln = int.from_bytes(data[ip: ip + nb], "little") + 1
                ip += nb
            out += data[ip: ip + ln]
            ip += ln
            continue
        if typ == 1:
            ln = ((tag >> 2) & 0x7) + 4
            offset = ((tag >> 5) << 8) | data[ip]
            ip += 1
        elif typ == 2:
            ln = (tag >> 2) + 1
            offset = int.from_bytes(data[ip: ip + 2], "little")
            ip += 2
        else:
            ln = (tag >> 2) + 1
            offset = int.from_bytes(data[ip: ip + 4], "little")
            ip += 4
        if offset <= 0 or offset > len(out):
            raise ValueError("malformed snappy copy")
        for _ in range(ln):
            out.append(out[-offset])
    if len(out) != ulen:
        raise ValueError("snappy length mismatch")
    return bytes(out)




def snappy_compress(data: bytes) -> bytes:
    """Raw snappy block compression — the decompressor's twin (device
    parquet ENCODE path).  Greedy hash-table LZ77; any stream it emits
    round-trips through snappy_uncompress (and google/snappy)."""
    lib = get_lib()
    if lib is not None and hasattr(lib, "snappy_compress"):
        inp = np.frombuffer(data, np.uint8)
        cap = len(data) + len(data) // 6 + 32
        out = np.zeros(cap, np.uint8)
        fn = lib.snappy_compress
        fn.restype = ctypes.c_int64
        n = fn(_p(np.ascontiguousarray(inp), ctypes.c_uint8),
               len(inp), _p(out, ctypes.c_uint8), ctypes.c_int64(cap))
        if n < 0:
            raise ValueError("snappy compress overflow")
        return out[:n].tobytes()
    return _snappy_compress_py(data)


def _snappy_compress_py(data: bytes) -> bytes:
    out = bytearray()
    u = len(data)
    while True:
        b = u & 0x7F
        u >>= 7
        out.append(b | 0x80 if u else b)
        if not u:
            break

    def emit_literal(frm, ln):
        while ln > 0:
            chunk = min(ln, (1 << 24) - 1)
            if chunk <= 60:
                out.append((chunk - 1) << 2)
            else:
                nb = 1 if chunk < (1 << 8) else (2 if chunk < (1 << 16)
                                                 else 3)
                out.append((59 + nb) << 2)
                out.extend(int(chunk - 1).to_bytes(nb, "little"))
            out.extend(data[frm: frm + chunk])
            frm += chunk
            ln -= chunk

    def emit_copy(off, ln):
        while ln >= 4:
            chunk = min(ln, 64)
            if 0 < ln - chunk < 4:
                chunk = ln - 4
            if off < 2048 and 4 <= chunk <= 11:
                out.append(1 | ((chunk - 4) << 2) | ((off >> 8) << 5))
                out.append(off & 0xFF)
            elif off < (1 << 16):
                out.append(2 | ((chunk - 1) << 2))
                out.extend(int(off).to_bytes(2, "little"))
            else:
                out.append(3 | ((chunk - 1) << 2))
                out.extend(int(off).to_bytes(4, "little"))
            ln -= chunk

    table = {}
    n = len(data)
    ip = 0
    lit = 0
    while ip + 4 <= n:
        key = data[ip: ip + 4]
        cand = table.get(key, -1)
        table[key] = ip
        if cand >= 0 and ip - cand < (1 << 16):
            if ip > lit:
                emit_literal(lit, ip - lit)
            ln = 4
            while ip + ln < n and data[cand + ln] == data[ip + ln]:
                ln += 1
            emit_copy(ip - cand, ln)
            ip += ln
            lit = ip
        else:
            ip += 1
    if n > lit:
        emit_literal(lit, n - lit)
    return bytes(out)


def plain_byte_array_lens(buf: bytes, n: int) -> np.ndarray:
    """PLAIN BYTE_ARRAY page -> int32 lengths (C walk; python twin)."""
    lens = np.zeros(max(n, 1), np.int32)
    lib = get_lib()
    if lib is not None and n:
        inp = np.frombuffer(buf, np.uint8)
        fn = lib.plain_byte_array_lens
        fn.restype = ctypes.c_int64
        total = fn(_p(np.ascontiguousarray(inp), ctypes.c_uint8),
                   ctypes.c_int64(len(inp)), ctypes.c_int64(n),
                   _p(lens, ctypes.c_int32))
        if total < 0:
            raise ValueError("malformed PLAIN byte_array page")
        return lens[:n]
    pos = 0
    for i in range(n):
        ln = int.from_bytes(buf[pos: pos + 4], "little")
        pos += 4
        if pos + ln > len(buf):
            raise ValueError("malformed PLAIN byte_array page")
        lens[i] = ln
        pos += ln
    return lens[:n]
