"""netchaos — in-process TCP chaos proxy for the distributed tier.

ISSUE 20: the harness that makes gray-failure handling *pinnable*.  A
:class:`NetChaosProxy` sits between the coordinator's data-plane
sockets and one worker's data listener, forwarding byte streams while
injecting network weather per (worker, direction) from a seeded spec:

  * ``delay``      — fixed extra latency per TKD1 frame (the straggler
                     shape: everything arrives, late),
  * ``throttle``   — bandwidth cap in bytes/s (congested link),
  * ``drop_after`` — forward N bytes then silently swallow the rest of
                     the stream (gray partition: the peer never learns),
  * ``half_open``  — one trigger stalls BOTH directions of the
                     connection (the classic half-open TCP session: the
                     peer waits out its socket timeout),
  * ``dup_frame``  — re-emit whole frames with probability p (exercises
                     the store's per-seq idempotence and the client's
                     reply-desync recovery),
  * ``reorder``    — swap adjacent frames with probability p,
  * ``reset``      — hard RST (SO_LINGER 0) after N bytes mid-stream.

Frame-aware kinds (delay / dup_frame / reorder) parse the ``TKD1``
framing so injections land on message boundaries; byte-level kinds
(throttle / drop_after / half_open / reset) act on raw chunks.  All
randomness flows from the spec's seed, so a sweep failure replays.

The proxy is deliberately ignorant of the protocol's *meaning*: it can
only delay, duplicate, damage, or destroy bytes — exactly what a real
network can do — so every test assertion downstream of it is about the
resilience machinery (hedges, DEGRADED demotion, idempotent stores,
CRC surfacing corruption structurally), never about luck.

Wiring: ``interpose(coord, worker_id, spec)`` rewires the registered
worker's host/port to the proxy and evicts the pooled data connection;
``proxy.set_spec``/``proxy.clear`` swap the weather live (a lifted
delay is how the promotion path gets exercised); control-plane
heartbeats do NOT pass through the proxy — a gray data plane with a
healthy control plane is precisely the failure mode under test.
"""
from __future__ import annotations

import random
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.distributed.protocol import MAGIC

_HDR = struct.Struct("<4sII")

# injection kinds accepted by make_injection / ChaosSpec
KINDS = ("delay", "throttle", "drop_after", "half_open", "dup_frame",
         "reorder", "reset")
# directions: client(coordinator) -> worker, worker -> client
DIRECTIONS = ("c2w", "w2c")


class _ResetSignal(Exception):
    """Internal: the injection wants a hard RST now."""


def _split_frames(buf: bytes) -> Tuple[List[bytes], bytes]:
    """Split a byte buffer into complete TKD1 frames + the remainder.
    A non-TKD1 prefix (never produced by this protocol, but the proxy
    must not wedge on it) is passed through as one pseudo-frame."""
    frames: List[bytes] = []
    while len(buf) >= _HDR.size:
        magic, plen, _crc = _HDR.unpack_from(buf, 0)
        if magic != MAGIC:
            frames.append(buf)
            return frames, b""
        total = _HDR.size + plen
        if len(buf) < total:
            break
        frames.append(buf[:total])
        buf = buf[total:]
    return frames, buf


class _Injection:
    """One direction's stateful injection.  ``feed(data)`` returns the
    bytes to forward now (possibly sleeping to shape time) or raises
    :class:`_ResetSignal`; ``stalled`` on the shared conn state swallows
    everything once a half-open trigger fired."""

    def __init__(self, kind: str, rng: random.Random, *, delay_s=0.05,
                 bytes_per_s=1 << 20, after_bytes=4096, p=0.25,
                 min_bytes=0):
        if kind not in KINDS:
            raise ValueError(f"unknown injection kind {kind!r}")
        self.kind = kind
        self.rng = rng
        self.delay_s = float(delay_s)
        self.bytes_per_s = max(float(bytes_per_s), 1.0)
        self.after_bytes = int(after_bytes)
        self.p = float(p)
        # delay only frames at least this large: tiny acks pass while
        # data-carrying replies crawl — a congested bulk path under a
        # healthy RPC path, the shape that keeps a straggler's latency
        # estimate honest on small ops while its fetches blow deadlines
        self.min_bytes = int(min_bytes)
        self._buf = b""
        self._seen = 0
        self._held: Optional[bytes] = None   # reorder's parked frame

    def feed(self, data: bytes, state: Dict) -> bytes:
        self._seen += len(data)
        k = self.kind
        if k == "delay":
            frames, self._buf = _split_frames(self._buf + data)
            out = []
            for f in frames:
                if len(f) >= self.min_bytes:
                    time.sleep(self.delay_s)
                out.append(f)
            return b"".join(out)
        if k == "throttle":
            time.sleep(len(data) / self.bytes_per_s)
            return data
        if k == "drop_after":
            if self._seen > self.after_bytes:
                over = self._seen - self.after_bytes
                return data[:max(len(data) - over, 0)]
            return data
        if k == "half_open":
            if self._seen > self.after_bytes:
                state["stalled"] = True
            if state.get("stalled"):
                over = self._seen - self.after_bytes
                return data[:max(len(data) - over, 0)]
            return data
        if k == "dup_frame":
            frames, self._buf = _split_frames(self._buf + data)
            out = []
            for f in frames:
                out.append(f)
                if self.rng.random() < self.p:
                    out.append(f)
            return b"".join(out)
        if k == "reorder":
            frames, self._buf = _split_frames(self._buf + data)
            out = []
            for f in frames:
                if self._held is not None:
                    if self.rng.random() < self.p:
                        out.append(f)
                        out.append(self._held)
                    else:
                        out.append(self._held)
                        out.append(f)
                    self._held = None
                elif self.rng.random() < self.p:
                    self._held = f
                else:
                    out.append(f)
            return b"".join(out)
        if k == "reset":
            if self._seen > self.after_bytes:
                raise _ResetSignal()
            return data
        return data

    def flush(self) -> bytes:
        """End-of-stream: forward anything a frame-aware kind parked."""
        out = self._buf
        self._buf = b""
        if self._held is not None:
            out = self._held + out
            self._held = None
        return out


class ChaosSpec:
    """Seeded per-(worker, direction) injection plan.  ``injections``
    maps a direction (``"c2w"``/``"w2c"``) to ``(kind, params)``; a
    missing direction forwards untouched.  Each accepted connection
    spawns FRESH stateful injections from a connection-local RNG child
    of the seed, so runs replay byte-for-byte."""

    def __init__(self, seed: int,
                 injections: Optional[Dict[str, Tuple[str, Dict]]] = None):
        self.seed = int(seed)
        self.injections = dict(injections or {})
        for d in self.injections:
            if d not in DIRECTIONS:
                raise ValueError(f"unknown direction {d!r}")

    def spawn(self, conn_idx: int) -> Dict[str, Optional[_Injection]]:
        out: Dict[str, Optional[_Injection]] = {}
        for d in DIRECTIONS:
            spec = self.injections.get(d)
            if spec is None:
                out[d] = None
            else:
                kind, params = spec
                rng = random.Random(
                    (self.seed * 1_000_003 + conn_idx * 7919
                     + DIRECTIONS.index(d)) & 0x7FFFFFFF)
                out[d] = _Injection(kind, rng, **params)
        return out


def _rst_close(sock: socket.socket) -> None:
    """Close with RST (SO_LINGER 0) — a mid-stream reset, not FIN."""
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class NetChaosProxy:
    """One worker's chaos interposer: listens on an ephemeral loopback
    port, forwards every accepted connection to ``(target_host,
    target_port)`` through the current :class:`ChaosSpec`.  The spec is
    swappable live (``set_spec``/``clear``) so a harness can lift the
    weather and watch the DEGRADED worker earn promotion back."""

    def __init__(self, target_host: str, target_port: int,
                 spec: Optional[ChaosSpec] = None, name: str = ""):
        self.target = (target_host, int(target_port))
        self.name = name or f"{target_host}:{target_port}"
        self._spec = spec
        self._spec_lock = threading.Lock()
        self._conn_idx = 0
        self._stop = threading.Event()
        self._socks: List[socket.socket] = []
        self._socks_lock = threading.Lock()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(64)
        self.port = self._listener.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"srt-netchaos-{self.name}")
        self._accept_thread.start()

    # -- spec management -------------------------------------------------
    def set_spec(self, spec: Optional[ChaosSpec]) -> None:
        """Swap the injection plan; applies to NEW connections (the
        coordinator's always-evict-on-error pooling dials fresh ones),
        and existing pumps pick it up per chunk for the stall flag."""
        with self._spec_lock:
            self._spec = spec

    def clear(self) -> None:
        self.set_spec(None)

    # -- forwarding ------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                src, _addr = self._listener.accept()
            except OSError:
                return
            try:
                dst = socket.create_connection(self.target, timeout=10.0)
                dst.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                src.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                # worker gone: the client sees EOF, exactly what a dead
                # backend looks like
                try:
                    src.close()
                except OSError:
                    pass
                continue
            with self._spec_lock:
                spec = self._spec
                idx = self._conn_idx
                self._conn_idx += 1
            inj = spec.spawn(idx) if spec is not None \
                else {d: None for d in DIRECTIONS}
            with self._socks_lock:
                self._socks += [src, dst]
            state: Dict = {}
            for a, b, d in ((src, dst, "c2w"), (dst, src, "w2c")):
                threading.Thread(
                    target=self._pump, args=(a, b, inj[d], state),
                    daemon=True,
                    name=f"srt-netchaos-{self.name}-{d}").start()

    def _pump(self, src: socket.socket, dst: socket.socket,
              inj: Optional[_Injection], state: Dict) -> None:
        try:
            while not self._stop.is_set():
                data = src.recv(1 << 16)
                if not data:
                    break
                if inj is None:
                    # a half-open trigger in the opposite direction
                    # stalls the whole connection — keep draining the
                    # sender (so it never learns) but forward nothing
                    if not state.get("stalled"):
                        dst.sendall(data)
                    continue
                out = inj.feed(data, state)
                if out:
                    dst.sendall(out)
            if inj is not None and not state.get("stalled"):
                tail = inj.flush()
                if tail:
                    dst.sendall(tail)
        except _ResetSignal:
            _rst_close(src)
            _rst_close(dst)
            return
        except OSError:
            pass
        for s in (src, dst):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    def close(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._socks_lock:
            socks, self._socks = self._socks, []
        for s in socks:
            try:
                s.close()
            except OSError:
                pass


def interpose(coord, worker_id: str,
              spec: Optional[ChaosSpec] = None) -> NetChaosProxy:
    """Rewire one registered worker's data plane through a fresh chaos
    proxy: the coordinator's next op (and its liveness probes) dial the
    proxy instead of the worker.  Heartbeats ride the worker's OWN
    control connection and stay untouched — gray data plane, healthy
    control plane.  Returns the proxy (caller owns ``close()``)."""
    with coord._lock:
        w = coord._workers[worker_id]
        proxy = NetChaosProxy(w.host, w.data_port, spec, name=worker_id)
        w.host, w.data_port = "127.0.0.1", proxy.port
        stale = coord._conns.pop(worker_id, None)
    if stale is not None:
        try:
            stale.close()
        except OSError:
            pass
    return proxy
