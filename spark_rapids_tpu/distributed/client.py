"""DistributedExchange — the producer/consumer driver of one exchange
over the worker tier, with lineage retry.

Contract (the fault-tolerance core of the cross-host tier):

  * every partition slice is CRC-framed ONCE (``exec/ici.ici_host_frame``,
    the PR 4 ``TKU2`` block) and lands in TWO places: the placed worker
    (``Coordinator.put_block``) and the producer-side spill-backed
    partition queue (``shuffle/partition_queues.py``) — the durable
    lineage copy;
  * the producer RETAINS its copy until the consuming stage COMMITS the
    partition (one ``release_partition`` per fully-drained pid), so a
    worker lost at any point before commit is recoverable;
  * a loss (heartbeat silence or dead socket) re-places the dead
    worker's partitions on survivors; this client claims the re-drive
    queue at every produce/consume step and re-pushes the retained
    blocks to the new owners — ``partitions_replayed`` counts each
    re-driven partition;
  * the consumer verifies completeness by SEQUENCE SET (a worker that
    restarted empty under the same id returns fewer blocks than the
    producer shipped) and re-drives instead of returning short data;
    corrupted blocks surface as deterministic ``ShuffleCorruption`` at
    deserialize time — never silent wrong rows.

``redriveMaxAttempts`` bounds how many times one partition may be
re-driven (repeated losses), after which :class:`WorkerLost` escapes to
the operator fault domain — classified WORKER_LOST, which falls back to
the CPU oracle without indicting the operator's breaker key.

Hedged fetches (ISSUE 20, docs/distributed.md): because the producer
retains every framed slice until commit, the lineage queue IS a free
replica of every un-committed partition.  A paged fetch that blows the
owner's soft deadline (``Coordinator.soft_deadline_s`` — softDeadline
Factor x the worker's p95 latency EWMA) therefore hedges against
``queues.peek_blobs`` instead of waiting out the straggler:
first-complete-wins, the remote's eventual reply is discarded, and any
duplicate a re-drive later ships is dropped by the worker store's
per-seq idempotence.  ``fetch_hedges`` counts launches, ``hedges_won``
counts lineage wins; on a healthy fleet both stay 0 (pinned by the
bench rung4_dist A/B at <= 2% overhead).
"""
from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional

from spark_rapids_tpu import perfcounters as PC
from spark_rapids_tpu.distributed.protocol import WorkerLost

# test hook (chaos/kill-timing): called as (exch, pid, seq) after every
# successfully shipped block; assigned only by tests/harnesses
TEST_SHIP_HOOK = None

# fetch page size: one reduce partition streams back in ~this many
# bytes per wire frame, so a partition far larger than the frame cap
# (or the worker's memory) never materializes whole on the worker
FETCH_PAGE_BYTES = 8 << 20


class DistributedExchange:
    """One exchange's view of the worker tier (driver side)."""

    def __init__(self, coordinator, exch_id: int, n_parts: int,
                 schema, codec: Optional[str], queues,
                 est_bytes: Optional[int] = None,
                 redrive_max_attempts: int = 4):
        self.coord = coordinator
        self.exch_id = exch_id
        self.n_parts = n_parts
        self.schema = schema
        self.codec = codec
        self.queues = queues          # SpillBackedPartitionQueues
        self.redrive_max_attempts = max(int(redrive_max_attempts), 1)
        self._counts: Dict[int, int] = {}
        self._redriven: Dict[int, int] = {}
        self._lock = threading.Lock()
        self.placement = coordinator.place(exch_id, n_parts, est_bytes)

    def block_counts(self) -> Dict[int, int]:
        """Per-partition shipped-block counts (sequences are contiguous
        from 0, so count == the completeness bar a consumer — or a
        recovery lease, ISSUE 16 — checks against)."""
        with self._lock:
            return dict(self._counts)

    # -- produce ---------------------------------------------------------
    def add_slice(self, pid: int, batch) -> None:
        """Frame one partition slice, retain it in the lineage queue,
        and ship it to the placed worker."""
        if batch is None or batch.num_rows == 0:
            return
        from spark_rapids_tpu.exec.ici import ici_host_frame

        blob = ici_host_frame(batch, codec=self.codec)
        with self._lock:
            seq = self._counts.get(pid, 0)
            self._counts[pid] = seq + 1
        self.queues.append_framed(pid, blob)
        self._drain_redrives()
        self._ship(pid, seq, blob)

    def _ship(self, pid: int, seq: int, blob: bytes) -> None:
        while True:
            try:
                self.coord.put_block(self.exch_id, pid, seq, blob)
                if TEST_SHIP_HOOK is not None:
                    TEST_SHIP_HOOK(self.exch_id, pid, seq)
                return
            except WorkerLost:
                # the owner died mid-put: the coordinator already
                # declared the loss and re-placed its partitions; claim
                # the re-drive queue (which re-pushes every retained
                # block of the affected pids, including this one's
                # earlier seqs) and re-send this block to the new owner
                self._bump_redrive_budget(pid)
                self._drain_redrives(include=pid)

    def _bump_redrive_budget(self, pid: int) -> None:
        with self._lock:
            used = self._redriven.get(pid, 0) + 1
            self._redriven[pid] = used
        if used > self.redrive_max_attempts:
            raise WorkerLost(
                str(self.placement.get(pid, "?")),
                f"partition {pid} exceeded {self.redrive_max_attempts} "
                f"re-drive attempts")

    def _drain_redrives(self, include: Optional[int] = None) -> None:
        """Claim and replay every partition a loss re-placed.  Replays
        the FULL retained block list of each claimed pid to its new
        owner (worker stores are idempotent per seq, so overlap with
        already-landed blocks is harmless).  A REPLACEMENT owner dying
        mid-replay folds its re-placed pids back into this pass and
        restarts the current pid from sequence 0 — blocks already
        pushed in the aborted attempt went to the dead owner."""
        pending = self.coord.claim_redrives(self.exch_id)
        if include is not None:
            pending.add(include)
        while pending:
            pid = min(pending)
            pending.discard(pid)
            blobs = self.queues.peek_blobs(pid)
            if not blobs:
                # nothing retained: never produced, or the consuming
                # stage already committed this partition — either way
                # there is nothing left to protect
                continue
            seq = 0
            while seq < len(blobs):
                try:
                    # redrive-flagged: the worker counts the replay
                    # (store_redrive_puts) and records a `redrive_put`
                    # span, so recovery traffic is visible cluster-wide
                    self.coord.put_block(self.exch_id, pid, seq,
                                         blobs[seq], redrive=True)
                    seq += 1
                except WorkerLost:
                    # the replacement died too: budget-check, fold ITS
                    # re-placed pids into this pass, and restart this
                    # pid's replay against the next owner
                    self._bump_redrive_budget(pid)
                    pending |= self.coord.claim_redrives(self.exch_id)
                    pending.discard(pid)
                    seq = 0
            # counted only once the partition's blocks all LANDED on the
            # new owner — a replay that died against every survivor must
            # not satisfy "recovered" pins via the CPU-oracle fallback
            PC.bump("partitions_replayed")
            self._diag_redrive(pid, len(blobs))

    def _diag_redrive(self, pid: int, n_blocks: int) -> None:
        from spark_rapids_tpu.diagnostics import context as _DIAG

        rec = _DIAG.RECORDER
        if rec is not None:
            rec.distributed(
                "partition_replayed",
                str(self.placement.get(pid, "?")),
                f"pid={pid} blocks={n_blocks}", 0, 0)

    # -- consume ---------------------------------------------------------
    def read_partition_chunks(self, pid: int,
                              target_bytes: int = 0) -> Iterator:
        """Drain one reduce partition from its owning worker as device
        batches of ~``target_bytes``, STREAMING page by page — the
        driver's working set is one decode group, never the whole
        partition (the same residency discipline the lineage buffer
        keeps on the produce side).  Commits (releases the lineage
        copy) only after the full partition deserialized."""
        from spark_rapids_tpu.lifecycle.context import check_cancel
        from spark_rapids_tpu.shuffle.serializer import deserialize_concat

        expected = self._counts.get(pid, 0)
        if expected == 0:
            self.queues.release_partition(pid)
            return
        self._ensure_remote_complete(pid, expected)
        # the owner holds exactly sequences 0..expected-1 (producer
        # seqs are contiguous and the store dedups), so pages stream
        # out in ascending order with no gaps possible.  A WorkerLost
        # AFTER the first yield propagates — rows already delivered
        # downstream cannot be retracted, so the fault domain's
        # whole-query fallback takes over (mid-stream loss before any
        # yield re-enters the completeness loop via the caller retry).
        group: List[bytes] = []
        group_bytes = 0
        next_seq = 0
        while next_seq < expected:
            check_cancel()
            seqs, blobs, _n = self._fetch_page(pid, next_seq)
            if not seqs:
                raise WorkerLost(
                    str(self.placement.get(pid, "?")),
                    f"partition {pid} truncated mid-stream "
                    f"(at seq {next_seq}/{expected})")
            for s, blob in zip(seqs, blobs):
                next_seq = s + 1
                if group and target_bytes \
                        and group_bytes + len(blob) > target_bytes:
                    yield deserialize_concat(group, self.schema,
                                             codec=self.codec)
                    check_cancel()
                    group, group_bytes = [], 0
                group.append(blob)
                group_bytes += len(blob)
        if group:
            yield deserialize_concat(group, self.schema,
                                     codec=self.codec)
        # success against this owner: a probed (previously quarantined)
        # worker earns its breaker entry back
        self.coord.note_worker_ok(self.coord.owner_of(self.exch_id, pid))
        # the consuming stage committed this partition: lineage copy
        # released (a later loss can no longer need it)
        self.queues.release_partition(pid)

    def _fetch_page(self, pid: int, next_seq: int):
        """One page of the partition (sequences above ``next_seq - 1``)
        from its owning worker, HEDGED (ISSUE 20): the remote fetch
        runs on a side thread racing the owner's soft deadline; blowing
        it launches a hedge against the producer-side lineage buffer —
        which retains every framed slice until commit, so it can serve
        the whole remainder locally.  First-complete-wins: a hedge win
        abandons the straggler's in-flight reply (its wall still feeds
        the worker's latency EWMA when it lands) and counts the miss
        toward the owner's DEGRADED demotion."""
        def remote():
            return self.coord.fetch_blocks(
                self.exch_id, pid, after_seq=next_seq - 1,
                max_bytes=FETCH_PAGE_BYTES)

        deadline = None
        owner = None
        if getattr(self.coord, "hedge_enabled", False):
            try:
                owner = self.coord.owner_of(self.exch_id, pid)
                deadline = self.coord.soft_deadline_s(owner)
            except KeyError:
                pass
        if deadline is None:
            return remote()
        box: Dict[str, object] = {}
        done = threading.Event()

        def run():
            try:
                box["out"] = remote()
            except BaseException as e:
                box["err"] = e
            finally:
                done.set()

        t = threading.Thread(target=run, daemon=True,
                             name="srt-dist-hedge-fetch")
        t.start()
        if not done.wait(deadline):
            PC.bump("fetch_hedges")
            self.coord.note_soft_deadline_miss(owner)
            blobs = self.queues.peek_blobs(pid)
            if len(blobs) > next_seq:
                # the lineage copy holds the remainder (it always does
                # before commit): serve it and discard whatever the
                # straggler eventually answers — byte-identical by
                # construction, these ARE the shipped frames
                PC.bump("hedges_won")
                return (list(range(next_seq, len(blobs))),
                        blobs[next_seq:], len(blobs))
            # lineage already committed/empty (cannot happen before the
            # final release, but never hang on it): take the remote
            done.wait()
        err = box.get("err")
        if err is not None:
            raise err
        return box["out"]

    def _ensure_remote_complete(self, pid: int, expected: int) -> None:
        """Re-drive until the owner's store holds the full partition
        (``n_total == expected`` — producer sequences are contiguous
        and the store dedups, so the count IS the completeness check),
        WITHOUT materializing any data; bounded by
        ``redriveMaxAttempts``."""
        while True:
            self._drain_redrives()
            try:
                _seqs, _blobs, n_total = self.coord.fetch_blocks(
                    self.exch_id, pid, after_seq=-1, max_bytes=1)
            except WorkerLost:
                self._bump_redrive_budget(pid)
                self._drain_redrives(include=pid)
                continue
            if n_total >= expected:
                return
            # short read: the worker restarted empty (or missed blocks)
            # under the same id — re-drive the producer's retained copy
            self._bump_redrive_budget(pid)
            self.coord.mark_redrive(self.exch_id, pid)

    # -- cleanup ---------------------------------------------------------
    def close(self) -> None:
        """Error-unwind/commit cleanup: drop the lineage queues and the
        remote copies (idempotent; the shuffle-manager unregister path
        broadcasts the release too)."""
        self.queues.close()
        try:
            self.coord.release_exchange(self.exch_id)
        except WorkerLost:
            pass
