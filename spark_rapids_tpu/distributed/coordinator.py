"""Coordinator — membership, heartbeat liveness, placement, loss
recovery bookkeeping for the cross-host tier.

Reference analog: the driver-side shuffle coordination the reference
delegates to Spark's MapOutputTracker + the RapidsShuffleHeartbeat
endpoint (SURVEY.md §2.7); Theseus (arXiv:2508.05029) centralizes
exactly this: a lightweight control plane that PLACES data movement and
survives executor churn.  The coordinator owns:

  * **membership** — workers join (HELLO over the control listener) and
    leave (GOODBYE / dead socket) between queries; every join warms from
    the shared persistent stores on the worker side and bumps
    ``workers_joined``.
  * **liveness** — each worker heartbeats every
    ``spark.rapids.tpu.distributed.heartbeatMs``; the monitor thread
    counts late workers (``worker_heartbeat_misses``) and declares one
    LOST past ``workerLostMs`` (or instantly on a dead socket reported
    by the block layer).  A loss bumps ``worker_lost``, records a
    per-worker circuit-breaker entry (key ``("DistributedWorker",
    worker_id)``) so a flapping worker that rejoins is QUARANTINED until
    the breaker TTL re-probe, emits the ``distributed`` diagnostics
    event, and dumps a flight-recorder post-mortem bundle carrying the
    placement table and the re-drive plan.
  * **placement** — ``place()`` spreads one exchange's reduce partitions
    over placeable workers, least-loaded first, weighted by each
    worker's advertised memory (fed by ``exec/partition_sizing.py``
    estimates on the exchange side).
  * **re-drive bookkeeping** — a loss re-places the dead worker's
    partitions on survivors and queues them for re-drive; the exchange
    client claims the queue and re-pushes the retained producer-side
    blocks (lineage retry), bumping ``partitions_replayed``.

  * **gray failure** (ISSUE 20, docs/distributed.md) — the full state
    machine is ALIVE <-> DEGRADED -> LOST: every data-plane op walls
    into a per-worker p95-biased latency EWMA (refined by heartbeat-
    federated worker service times); a worker past ``slowFactor``x the
    fleet median, or stacking consecutive soft-deadline misses, is
    DEGRADED — demoted in capacity-weighted placement, its pending
    partitions speculatively re-driven onto healthy survivors
    (``speculative_redrives``), quarantine breaker untouched — and
    promoted back after ``promoteAfterOks`` within-deadline
    observations.  ``soft_deadline_s()`` is what the client's hedged
    fetch path races against.

The coordinator never holds partition DATA — blocks flow producer ->
worker -> consumer; losing the coordinator process loses the query but
never corrupts one (every data block is CRC-framed end to end).
"""
from __future__ import annotations

import json
import socket
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

from spark_rapids_tpu import perfcounters as PC
from spark_rapids_tpu.distributed import protocol as P
from spark_rapids_tpu.distributed.protocol import WorkerDegraded, WorkerLost

ALIVE = "ALIVE"
QUARANTINED = "QUARANTINED"
LOST = "LOST"
LEFT = "LEFT"
# gray failure (ISSUE 20): slow, not dead — demoted in placement, its
# pending partitions speculated onto healthy survivors, promotable back
# to ALIVE on sustained recovery.  ALIVE <-> DEGRADED -> LOST.
DEGRADED = "DEGRADED"

# the per-worker circuit-breaker key family: first element mirrors the
# (operator-class, fingerprint) shape the breaker registry indexes by
BREAKER_OP = "DistributedWorker"


def _full_jitter_sleep(attempt: int, base_s: float = 0.02,
                       cap_s: float = 0.2, sleep=time.sleep,
                       rand=None) -> float:
    """Full-jitter backoff for the distributed retry path (ISSUE 20
    audit): sleep uniform(0, min(base * 2^(attempt-1), cap)) — a
    coordinated fleet retrying a hiccuping worker must not re-arrive in
    lockstep the way the old fixed ``0.02 * attempt`` schedule did.
    Returns the slept duration so the regression test can pin the
    distribution without patching time."""
    import random as _random

    cap = min(base_s * (2 ** max(attempt - 1, 0)), cap_s)
    delay = (rand if rand is not None else _random.random)() * cap
    sleep(delay)
    return delay


class WorkerInfo:
    __slots__ = ("worker_id", "host", "data_port", "pid", "mem_bytes",
                 "state", "last_hb", "joined_at", "control",
                 "hb_missed", "probe_failed", "warmed_entries",
                 "counters", "store_stats", "mirror", "mirror_last_n",
                 "clock_offset_s", "held", "lat_ewma_s", "lat_samples",
                 "miss_streak", "ok_streak", "slow_ticks",
                 "degraded_since")

    def __init__(self, worker_id: str, host: str, data_port: int,
                 pid: int, mem_bytes: int, control: socket.socket,
                 warmed_entries: int = 0, mirror_capacity: int = 512):
        self.worker_id = worker_id
        self.host = host
        self.data_port = data_port
        self.pid = pid
        self.mem_bytes = max(int(mem_bytes), 1)
        self.state = ALIVE
        self.last_hb = time.monotonic()
        self.joined_at = time.monotonic()
        self.control = control
        self.hb_missed = False
        self.probe_failed = False
        self.warmed_entries = warmed_entries
        # federated telemetry (ISSUE 15): the worker's latest
        # heartbeat-reported counter snapshot + store stats, the mirror
        # of its diagnostics ring (what a SIGKILLed worker's post-mortem
        # contains), the ring sequence already folded (heartbeat deltas
        # and full `dump` pulls both dedup on it), and the
        # handshake-estimated clock offset (driver wall - worker wall;
        # min over samples, so one slow frame cannot skew it)
        self.counters: Dict[str, int] = {}
        self.store_stats: Dict[str, int] = {}
        self.mirror: deque = deque(maxlen=max(int(mirror_capacity), 1))
        self.mirror_last_n = 0
        self.clock_offset_s: Optional[float] = None
        # crash recovery (ISSUE 16): the (wire_exch, pid, n_blocks,
        # max_seq) inventory a re-attaching worker enumerated in its
        # HELLO — what a reborn coordinator rebuilds the placement map
        # from when adopting a journaled stage lease
        self.held: List[Tuple[int, int, int, int]] = []
        # gray-failure bookkeeping (ISSUE 20): a p95-biased latency
        # EWMA over this worker's data-plane op walls (driver-observed,
        # refined by the heartbeat-federated worker-side service time),
        # consecutive soft-deadline miss / within-deadline streaks,
        # monitor ticks spent past slowFactor x the fleet median, and
        # when the worker entered DEGRADED (None while healthy)
        self.lat_ewma_s: Optional[float] = None
        self.lat_samples = 0
        self.miss_streak = 0
        self.ok_streak = 0
        self.slow_ticks = 0
        self.degraded_since: Optional[float] = None


class Coordinator:
    """One per process; built lazily by the first distributed exchange
    (or explicitly by tests/harnesses via ``get_coordinator``)."""

    def __init__(self, conf=None):
        from spark_rapids_tpu.config import (
            DISTRIBUTED_DEGRADE_AFTER_MISSES,
            DISTRIBUTED_HEARTBEAT_MS,
            DISTRIBUTED_HEDGE_ENABLED,
            DISTRIBUTED_LOSS_BREAKER_THRESHOLD,
            DISTRIBUTED_OP_TIMEOUT_MS,
            DISTRIBUTED_PROMOTE_AFTER_OKS,
            DISTRIBUTED_PUT_RETRIES,
            DISTRIBUTED_SLOW_FACTOR,
            DISTRIBUTED_SOFT_DEADLINE_FACTOR,
            DISTRIBUTED_SOFT_DEADLINE_MIN_MS,
            DISTRIBUTED_TELEMETRY_RING,
            DISTRIBUTED_TRACE_ENABLED,
            DISTRIBUTED_WORKER_LOST_MS,
            RESILIENCE_BREAKER_TTL_SEC,
            get_conf,
        )

        c = conf if conf is not None else get_conf()
        self.heartbeat_s = max(
            int(c.get(DISTRIBUTED_HEARTBEAT_MS)), 10) / 1000.0
        self.lost_s = max(int(c.get(DISTRIBUTED_WORKER_LOST_MS)),
                          int(c.get(DISTRIBUTED_HEARTBEAT_MS))) / 1000.0
        self.op_timeout_s = max(
            int(c.get(DISTRIBUTED_OP_TIMEOUT_MS)), 100) / 1000.0
        self.put_retries = int(c.get(DISTRIBUTED_PUT_RETRIES))
        self.breaker_threshold = int(
            c.get(DISTRIBUTED_LOSS_BREAKER_THRESHOLD))
        self.breaker_ttl_s = float(c.get(RESILIENCE_BREAKER_TTL_SEC))
        self.trace_enabled = bool(c.get(DISTRIBUTED_TRACE_ENABLED))
        self.telemetry_ring = int(c.get(DISTRIBUTED_TELEMETRY_RING))
        # gray-failure resilience (ISSUE 20)
        self.hedge_enabled = bool(c.get(DISTRIBUTED_HEDGE_ENABLED))
        self.soft_factor = max(
            float(c.get(DISTRIBUTED_SOFT_DEADLINE_FACTOR)), 1.0)
        self.soft_min_s = max(
            int(c.get(DISTRIBUTED_SOFT_DEADLINE_MIN_MS)), 1) / 1000.0
        self.slow_factor = max(
            float(c.get(DISTRIBUTED_SLOW_FACTOR)), 1.0)
        self.degrade_after = max(
            int(c.get(DISTRIBUTED_DEGRADE_AFTER_MISSES)), 1)
        self.promote_after = max(
            int(c.get(DISTRIBUTED_PROMOTE_AFTER_OKS)), 1)

        self._lock = threading.Lock()
        self._workers: Dict[str, WorkerInfo] = {}
        # wire ids: the identifier used in put/fetch/release headers is
        # minted HERE, never reused for the coordinator's lifetime.
        # Shuffle-manager ids are process-unique themselves (the
        # module-level counter in shuffle/manager.py), so for manager
        # callers this is defense in depth; it is load-bearing for
        # DIRECT place() callers (tests, tools) whose raw exchange ids
        # can repeat — a stale worker-store entry under a colliding
        # (exch, pid) key would satisfy the consumer's completeness
        # check with WRONG (CRC-valid) rows
        import itertools as _it

        self._wire_ids = _it.count(1)
        self._wire_of: Dict[int, int] = {}
        # (exch, pid) -> worker_id
        self._placement: Dict[Tuple[int, int], str] = {}
        # shipped-block bookkeeping for the leak gate: (exch, pid) ->
        # blocks currently held remotely
        self._holdings: Dict[Tuple[int, int], int] = {}
        # pids a loss re-placed, awaiting producer re-drive
        self._redrives: Dict[int, Set[int]] = {}
        # gray failure (ISSUE 20): workers speculation moved an
        # exchange's partitions AWAY from.  Unlike a LOST worker, a
        # DEGRADED one still runs — release_exchange must broadcast to
        # these former owners too, or their store copies outlive the
        # query
        self._former_owners: Dict[int, Set[str]] = {}
        # put-receipt reconciliation (ISSUE 15): blocks this coordinator
        # shipped vs blocks workers REPORT having received (heartbeat
        # counters: store_puts + store_put_dedups).  A rejoin resets a
        # worker's counters, so the superseded incarnation's last report
        # retires into _acked_retired.  gauges() surfaces the difference
        # as `dist_blocks_unacked` — nonzero past heartbeat lag means
        # frames the CRC can't flag because they never arrived at all
        # (or a dead worker's unreported tail, exactly what re-drive
        # re-ships).
        self._shipped_blocks = 0
        self._acked_retired = 0
        # data-plane connections (shared by put/fetch/release), one per
        # worker, serialized by a per-worker lock
        self._conns: Dict[str, socket.socket] = {}
        self._conn_locks: Dict[str, threading.Lock] = {}
        self._stop = threading.Event()

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(32)
        self.port = self._listener.getsockname()[1]
        # crash recovery (ISSUE 16): publish this incarnation's control
        # endpoint under the recovery root so workers that outlived a
        # dead driver re-dial the successor (atomic tmp+rename; workers
        # poll the file during their bounded re-attach window)
        from spark_rapids_tpu.config import RECOVERY_ENABLED

        if bool(c.get(RECOVERY_ENABLED)):
            from spark_rapids_tpu.lifecycle import journal as _journal

            try:
                _journal.write_endpoint(_journal.resolve_root(c),
                                        "127.0.0.1", self.port)
            # tpulint: disable=cancel-swallow (durability isolation: an
            # unwritable endpoint file degrades re-attach, never the
            # coordinator itself)
            except Exception:
                pass
        self._threads: List[threading.Thread] = []
        for target, name in ((self._accept_loop, "accept"),
                             (self._monitor_loop, "monitor")):
            t = threading.Thread(target=target, daemon=True,
                                 name=f"srt-dist-coord-{name}")
            t.start()
            self._threads.append(t)

    # -- membership ------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self._listener.accept()
            except OSError:
                if self._stop.is_set():
                    return
                # transient accept failure (EMFILE during a heavy
                # shuffle, interrupted syscall): keep serving joins —
                # a dead accept loop would silently disable elastic
                # membership for the rest of the process
                time.sleep(self.heartbeat_s)
                continue
            conn.settimeout(self.lost_s * 2 + 1.0)
            t = threading.Thread(
                target=self._control_conn, args=(conn, addr[0]),
                daemon=True, name="srt-dist-coord-control")
            t.start()

    def _control_conn(self, conn: socket.socket, host: str) -> None:
        """One worker's control connection: HELLO, then heartbeats until
        EOF/error (= dead socket)."""
        wid = None
        try:
            header, _ = P.recv_msg(conn)
            if header.get("op") != "hello":
                P.send_msg(conn, {"error": "expected hello"})
                return
            wid = str(header["worker_id"])
            self._admit(wid, host, header, conn)
            P.send_msg(conn, {"op": "welcome", "worker_id": wid})
            while not self._stop.is_set():
                msg, _ = P.recv_msg(conn)
                op = msg.get("op")
                if op == "heartbeat":
                    self._heartbeat(wid, msg)
                elif op == "goodbye":
                    self._leave(wid)
                    return
        except (OSError, ConnectionError, P.ProtocolCorruption):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            if wid is not None and not self._stop.is_set():
                # EOF without goodbye: dead socket — LOST, unless this
                # connection was already superseded by a rejoin, the
                # worker left cleanly, or the coordinator itself is
                # shutting down (a teardown must not bleed stray loss
                # declarations into whatever runs next)
                with self._lock:
                    w = self._workers.get(wid)
                    stale = w is None or w.control is not conn \
                        or w.state in (LOST, LEFT)
                if not stale:
                    self.declare_lost(wid, "control socket closed")

    def _admit(self, wid: str, host: str, header: Dict,
               conn: socket.socket) -> None:
        from spark_rapids_tpu.resilience.breaker import get_breaker

        info = WorkerInfo(wid, host, int(header["data_port"]),
                          int(header.get("pid", 0)),
                          int(header.get("mem_bytes", 1 << 20)), conn,
                          int(header.get("warmed_entries", 0)),
                          mirror_capacity=self.telemetry_ring)
        if "t_wall" in header:
            # clock-offset handshake: driver receipt wall minus worker
            # send wall.  Overestimates by the one-way frame latency;
            # heartbeats refine it (min over samples, see _fold below)
            info.clock_offset_s = time.time() - float(header["t_wall"])
        inventory = header.get("held") or []
        if inventory:
            # recovery re-HELLO (ISSUE 16): the worker outlived a dead
            # driver and is re-attaching with its held partitions.  Its
            # prior incarnation's control socket died WITH the driver,
            # so any ("DistributedWorker", id) breaker entry that loss
            # left behind is about the crash, not about this worker —
            # clear it outright; quarantining the one process that
            # still holds the checkpointed blocks would turn a
            # resumable query into a full re-execution
            info.held = [(int(e), int(p), int(n), int(mx))
                         for e, p, n, mx in inventory]
            get_breaker().clear_key((BREAKER_OP, wid))
        # flapping-worker quarantine: a worker id whose loss history
        # holds the breaker OPEN joins QUARANTINED (heartbeats, but is
        # never placed) until the TTL re-probe admits it again
        held = get_breaker().consult((BREAKER_OP, wid),
                                     self.breaker_ttl_s)
        if held is not None:
            info.state = QUARANTINED
        with self._lock:
            if info.held:
                # cross-incarnation wire-id safety: this coordinator's
                # counter restarted at 1, but the re-attached worker's
                # store still keys blocks by the DEAD incarnation's wire
                # ids — minting a colliding id would let stale
                # (CRC-valid!) blocks satisfy a new exchange's
                # completeness check with wrong rows.  Reseed past the
                # inventory's max before any place() can run.
                import itertools as _it

                nxt = next(self._wire_ids)
                top = max(e for e, _p, _n, _mx in info.held) + 1
                self._wire_ids = _it.count(max(nxt, top))
            old = self._workers.get(wid)
            if old is not None and old.counters:
                # the superseded incarnation's put receipts retire into
                # the running total — the rejoined process restarts its
                # counters at zero
                self._acked_retired += (
                    int(old.counters.get("store_puts", 0))
                    + int(old.counters.get("store_put_dedups", 0)))
            self._workers[wid] = info
            self._conn_locks.setdefault(wid, threading.Lock())
            # a rejoin supersedes the old connection; drop any stale
            # data conn so the next op dials the new port
            stale_conn = self._conns.pop(wid, None)
        if old is not None and old.control is not conn:
            try:
                old.control.close()
            except OSError:
                pass
        if stale_conn is not None:
            try:
                stale_conn.close()
            except OSError:
                pass
        PC.bump("workers_joined")
        self._diag_event("worker_joined" if info.state == ALIVE
                         else "worker_quarantined", wid,
                         f"mem={info.mem_bytes} state={info.state}")
        self._flight_event("worker_joined", worker_id=wid,
                           state=info.state)

    def _heartbeat(self, wid: str, msg: Optional[Dict] = None) -> None:
        tel = None
        with self._lock:
            w = self._workers.get(wid)
            if w is not None:
                w.last_hb = time.monotonic()
                w.hb_missed = False
                w.probe_failed = False
                # a quarantined worker re-probes via consult() in
                # placeable_workers(); heartbeats alone never un-lose a
                # LOST worker (it must rejoin with a fresh HELLO)
                if msg is not None:
                    tel = self._fold_telemetry_locked(w, msg)
        if tel is not None:
            # one ambient check: a recorded query sees the federation
            # arrive as `worker_telemetry` diagnostics events
            from spark_rapids_tpu.diagnostics import context as _DIAG

            rec = _DIAG.RECORDER
            if rec is not None:
                rec.worker_telemetry(wid, tel["blocks"], tel["bytes"],
                                     tel["mem_used"], tel["counters"])

    def _fold_telemetry_locked(self, w: WorkerInfo,
                               msg: Dict) -> Optional[Dict]:
        """Fold one heartbeat/dump payload into the worker's federated
        state (caller holds self._lock).  Returns the summary for the
        diagnostics event, or None when the payload carried no
        telemetry (an old-protocol worker)."""
        counters = msg.get("counters")
        if counters is None and "ring" not in msg:
            return None
        if isinstance(counters, dict):
            new = {k: int(v) for k, v in counters.items()}
            # federated latency refinement (ISSUE 20): the heartbeat-
            # piggybacked service-time counters contribute one mean-
            # per-op sample per fold to the worker's p95 EWMA — a
            # thrashing spill disk shows up here even when the driver
            # sent it no ops this interval.  Deltas against the prior
            # snapshot; a rejoin resets worker counters, which the
            # negative-delta guard skips.
            d_wall = (new.get("put_wall_ns", 0)
                      + new.get("fetch_wall_ns", 0)
                      - int(w.counters.get("put_wall_ns", 0))
                      - int(w.counters.get("fetch_wall_ns", 0)))
            d_ops = (new.get("store_puts", 0)
                     + new.get("store_put_dedups", 0)
                     + new.get("store_fetches", 0)
                     - int(w.counters.get("store_puts", 0))
                     - int(w.counters.get("store_put_dedups", 0))
                     - int(w.counters.get("store_fetches", 0)))
            if d_ops > 0 and d_wall >= 0:
                self._note_sample_locked(w, (d_wall / d_ops) / 1e9)
            w.counters = new
        w.store_stats = {k: int(msg[k]) for k in
                         ("blocks", "bytes", "mem_used", "spilled_blocks",
                          "partitions") if k in msg}
        for e in msg.get("ring") or ():
            n = int(e.get("n", 0))
            if n > w.mirror_last_n:
                w.mirror.append(e)
                w.mirror_last_n = n
        if "t_wall" in msg:
            off = time.time() - float(msg["t_wall"])
            if w.clock_offset_s is None or off < w.clock_offset_s:
                w.clock_offset_s = off
        return {"blocks": int(msg.get("blocks", 0)),
                "bytes": int(msg.get("bytes", 0)),
                "mem_used": int(msg.get("mem_used", 0)),
                "counters": dict(w.counters)}

    def _leave(self, wid: str) -> None:
        with self._lock:
            w = self._workers.get(wid)
            if w is None:
                return
            w.state = LEFT
            conn = self._conns.pop(wid, None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
        self._diag_event("worker_left", wid, "")
        self._flight_event("worker_left", worker_id=wid)

    # -- liveness --------------------------------------------------------
    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            now = time.monotonic()
            late: List[str] = []
            lost: List[str] = []
            degraded: List[str] = []
            with self._lock:
                for wid, w in self._workers.items():
                    if w.state not in (ALIVE, QUARANTINED, DEGRADED):
                        continue
                    age = now - w.last_hb
                    if age > self.lost_s:
                        lost.append(wid)
                    elif age > self.heartbeat_s * 2 and not w.hb_missed:
                        w.hb_missed = True
                        late.append(wid)
                    if w.state == DEGRADED and wid not in lost:
                        degraded.append(wid)
            for wid in late:
                PC.bump("worker_heartbeat_misses")
            self._scan_stragglers()
            for wid in degraded:
                # a DEGRADED worker may carry no traffic (speculation
                # moved its partitions), so promotion cannot wait for
                # served ops — a timed data-port ping per scan keeps its
                # latency EWMA fed and banks the recovery streak
                t0 = time.monotonic()
                alive, _refused = self._probe_alive(wid)
                if alive:
                    self.note_op_latency(wid, time.monotonic() - t0)
            for wid in lost:
                # heartbeat silence alone is ambiguous on a BUSY driver:
                # a long GIL hold (XLA compile) starves the reader
                # threads, so frames sit unread while the worker is
                # fine.  An active data-port probe disambiguates — a
                # live worker answers, a SIGSTOPped one times out, a
                # SIGKILLed one refuses — and a TIMED-OUT probe must
                # fail twice in a row before declaring (one slow answer
                # under load is not a death certificate; a refused
                # connection is).
                alive, refused = self._probe_alive(wid)
                if alive:
                    self._heartbeat(wid)
                    continue
                with self._lock:
                    w = self._workers.get(wid)
                    first_failure = w is not None and not w.probe_failed
                    if w is not None:
                        w.probe_failed = True
                if first_failure and not refused:
                    continue      # re-probe next scan before declaring
                self.declare_lost(
                    wid, f"no heartbeat for {self.lost_s * 1000:.0f}ms "
                         f"and data-port probe failed")

    def _probe_alive(self, wid: str) -> Tuple[bool, bool]:
        """One ping against the worker's data listener (fresh
        connection; the pooled conn may be mid-operation).  Returns
        (alive, connection_refused) — refusal means the process is
        gone and needs no second opinion."""
        with self._lock:
            w = self._workers.get(wid)
            if w is None or w.state in (LOST, LEFT):
                return False, True
            host, port = w.host, w.data_port
        try:
            s = P.connect(host, port, self.op_timeout_s)
            try:
                rep, _ = P.request(s, {"op": "ping"})
                return bool(rep.get("ok")), False
            finally:
                s.close()
        except ConnectionRefusedError:
            return False, True
        except (OSError, ConnectionError, RuntimeError,
                P.ProtocolCorruption):
            return False, False

    def declare_lost(self, wid: str, reason: str) -> bool:
        """Idempotent LOST declaration: quarantine the id, re-place its
        partitions on survivors, queue them for re-drive, and emit the
        post-mortem bundle.  True when this call performed the
        declaration."""
        from spark_rapids_tpu.resilience.breaker import get_breaker

        with self._lock:
            w = self._workers.get(wid)
            if w is None or w.state in (LOST, LEFT):
                return False
            w.state = LOST
            control, conn = w.control, self._conns.pop(wid, None)
            owned = [k for k, owner in self._placement.items()
                     if owner == wid]
        for s in (control, conn):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        # re-place + queue re-drives FIRST: once the LOST state is
        # visible (state was flipped under the lock above) an observer
        # acting on it must find the re-drive plan already queued — the
        # breaker hook below can spend tens of ms building a post-mortem
        # bundle, and recovery must not wait on observability
        replaced = self._replace_owner(owned)
        PC.bump("worker_lost")
        get_breaker().record_failure((BREAKER_OP, wid),
                                     self.breaker_threshold,
                                     reason=f"worker lost: {reason}")
        plan = [{"exch": e, "pid": p, "to": to}
                for (e, p), to in sorted(replaced.items())]
        self._diag_event("worker_lost", wid,
                         f"{reason}; re-placing {len(plan)} partitions")
        self._flight_event("worker_lost", worker_id=wid, reason=reason,
                           replaced=len(plan))
        self._postmortem(wid, reason, plan)
        return True

    # -- gray failure (ISSUE 20) ----------------------------------------
    def _note_sample_locked(self, w: WorkerInfo, wall_s: float) -> None:
        """Fold one op wall into the worker's p95-biased latency EWMA
        (caller holds self._lock): overshoots pull the estimate up fast,
        undershoots bleed off slowly, so the estimate rides near the
        tail of the distribution rather than its mean."""
        if w.lat_ewma_s is None:
            w.lat_ewma_s = wall_s
        else:
            a = 0.5 if wall_s > w.lat_ewma_s else 0.05
            w.lat_ewma_s += a * (wall_s - w.lat_ewma_s)
        w.lat_samples += 1

    def soft_deadline_s(self, wid: str) -> Optional[float]:
        """The worker's current per-op soft deadline:
        max(softDeadlineMinMs, softDeadlineFactor x its p95 latency
        EWMA); the floor alone before any samples.  None when hedging
        is off — the caller then never hedges or counts misses."""
        if not self.hedge_enabled:
            return None
        with self._lock:
            w = self._workers.get(wid)
            ewma = None if w is None else w.lat_ewma_s
        if ewma is None:
            return self.soft_min_s
        return max(self.soft_min_s, self.soft_factor * ewma)

    def note_op_latency(self, wid: str, wall_s: float) -> None:
        """One completed data-plane op wall against one worker: feed
        the EWMA, judge it against the soft deadline derived from the
        PRIOR estimate (an op must not raise its own bar), and step the
        degrade/promote streaks."""
        degrade_evidence = None
        promote = False
        with self._lock:
            w = self._workers.get(wid)
            if w is None or w.state in (LOST, LEFT):
                return
            prior = w.lat_ewma_s
            self._note_sample_locked(w, wall_s)
            if prior is None:
                return
            deadline = max(self.soft_min_s, self.soft_factor * prior)
            if wall_s > deadline:
                w.miss_streak += 1
                w.ok_streak = 0
                if w.state == ALIVE \
                        and w.miss_streak >= self.degrade_after:
                    degrade_evidence = (
                        f"{w.miss_streak} consecutive soft-deadline "
                        f"misses (last {wall_s * 1e3:.1f}ms > "
                        f"{deadline * 1e3:.1f}ms)")
            else:
                w.ok_streak += 1
                w.miss_streak = 0
                promote = (w.state == DEGRADED
                           and w.ok_streak >= self.promote_after
                           and self._recovered_locked(w))
        if degrade_evidence is not None:
            self.declare_degraded(wid, degrade_evidence)
        elif promote:
            self._promote(wid)

    def note_soft_deadline_miss(self, wid: str) -> None:
        """A caller (the hedged fetch path) watched an op blow its soft
        deadline while still in flight — count the miss now; the op's
        eventual wall will feed the EWMA when it lands."""
        evidence = None
        with self._lock:
            w = self._workers.get(wid)
            if w is None or w.state in (LOST, LEFT):
                return
            w.miss_streak += 1
            w.ok_streak = 0
            if w.state == ALIVE and w.miss_streak >= self.degrade_after:
                evidence = (f"{w.miss_streak} consecutive soft-deadline "
                            f"misses (hedged fetches)")
        if evidence is not None:
            self.declare_degraded(wid, evidence)

    def _recovered_locked(self, w: WorkerInfo) -> bool:
        """Caller holds self._lock: is this worker's EWMA back under
        slowFactor x the healthy fleet's median?  Vacuously true with
        no healthy peers to compare against."""
        peers = [x.lat_ewma_s for x in self._workers.values()
                 if x.state == ALIVE and x.lat_ewma_s is not None]
        if not peers or w.lat_ewma_s is None:
            return True
        med = sorted(peers)[len(peers) // 2]
        return med <= 0 or w.lat_ewma_s <= self.slow_factor * med

    def _scan_stragglers(self) -> None:
        """One monitor tick of the fleet-median rule: an ALIVE worker
        whose EWMA sits past slowFactor x the fleet median for
        degradeAfterMisses consecutive scans is DEGRADED — the
        persistent-outlier complement to the per-op miss streak."""
        victims: List[Tuple[str, float, float]] = []
        with self._lock:
            sam = [w.lat_ewma_s for w in self._workers.values()
                   if w.state in (ALIVE, DEGRADED)
                   and w.lat_ewma_s is not None and w.lat_samples >= 3]
            if len(sam) >= 2:
                med = sorted(sam)[len(sam) // 2]
                for wid, w in self._workers.items():
                    if w.state != ALIVE or w.lat_ewma_s is None \
                            or w.lat_samples < 3:
                        continue
                    if med > 0 and w.lat_ewma_s > self.slow_factor * med:
                        w.slow_ticks += 1
                        if w.slow_ticks >= self.degrade_after:
                            victims.append((wid, w.lat_ewma_s, med))
                    else:
                        w.slow_ticks = 0
        for wid, ewma, med in victims:
            self.declare_degraded(
                wid, f"latency EWMA {ewma * 1e3:.1f}ms persistently > "
                     f"slowFactor({self.slow_factor:g}) x fleet median "
                     f"{med * 1e3:.1f}ms")

    def declare_degraded(self, wid: str, evidence: str) -> bool:
        """Demote one ALIVE worker to DEGRADED: speculate its pending
        partitions onto healthy survivors (lineage contract, same as
        loss recovery) WITHOUT declaring it LOST and WITHOUT the
        quarantine breaker — a slow worker is not a dead one.  It keeps
        heartbeating, keeps serving what it still owns, takes demoted
        placement weight, and promotes back on sustained recovery.
        True when this call performed the demotion."""
        with self._lock:
            w = self._workers.get(wid)
            if w is None or w.state != ALIVE:
                return False
            w.state = DEGRADED
            w.degraded_since = time.monotonic()
            w.ok_streak = 0
            w.slow_ticks = 0
            owned = [k for k, owner in self._placement.items()
                     if owner == wid]
            healthy = any(x.state == ALIVE
                          for x in self._workers.values())
        PC.bump("workers_degraded")
        replaced: Dict[Tuple[int, int], str] = {}
        if owned and healthy:
            # speculation re-uses the loss re-placement machinery (the
            # client re-drives from its retained producer-side queues;
            # the worker store's per-seq idempotence discards any
            # duplicate the in-flight originals already landed) — but
            # only when a healthy survivor exists; with none, the
            # partitions stay where they are (slow beats stranded)
            replaced = self._replace_owner(owned)
            if replaced:
                with self._lock:
                    for (e, _p) in replaced:
                        self._former_owners.setdefault(e, set()).add(wid)
                PC.bump("speculative_redrives", len(replaced))
        plan = [{"exch": e, "pid": p, "to": to}
                for (e, p), to in sorted(replaced.items())]
        self._diag_event(
            "worker_degraded", wid,
            f"{evidence}; speculating {len(plan)} pending partitions")
        self._flight_event("worker_degraded", worker_id=wid,
                           evidence=evidence, speculated=len(plan))
        self._postmortem(wid, evidence, plan, kind="worker_degraded")
        return True

    def _promote(self, wid: str) -> None:
        """DEGRADED -> ALIVE on sustained recovery (the note_op_latency
        streaks banked promoteAfterOks within-deadline observations and
        the EWMA is back under the fleet bar)."""
        with self._lock:
            w = self._workers.get(wid)
            if w is None or w.state != DEGRADED:
                return
            w.state = ALIVE
            since = w.degraded_since
            w.degraded_since = None
            w.miss_streak = 0
            w.slow_ticks = 0
        dur = (time.monotonic() - since) if since is not None else 0.0
        self._diag_event("worker_promoted", wid,
                         f"recovered after {dur * 1e3:.0f}ms degraded")
        self._flight_event("worker_promoted", worker_id=wid,
                           degraded_s=round(dur, 3))

    def fleet_pressure(self) -> float:
        """Fleet tail-latency pressure in [0, 1] for the governor
        (peek-only): the DEGRADED fraction of the fleet, or — when at
        least two workers carry latency estimates — how far the worst
        EWMA sits past slowFactor x the median, whichever is worse."""
        with self._lock:
            states = [w.state for w in self._workers.values()
                      if w.state in (ALIVE, DEGRADED)]
            sam = [w.lat_ewma_s for w in self._workers.values()
                   if w.state in (ALIVE, DEGRADED)
                   and w.lat_ewma_s is not None and w.lat_samples >= 3]
        if not states:
            return 0.0
        p = states.count(DEGRADED) / len(states)
        if len(sam) >= 2:
            med = sorted(sam)[len(sam) // 2]
            if med > 0:
                ratio = max(sam) / med
                p = max(p, (ratio - self.slow_factor) / self.slow_factor)
        return max(0.0, min(p, 1.0))

    def _replace_owner(
            self, keys: List[Tuple[int, int]]
    ) -> Dict[Tuple[int, int], str]:
        """Re-place the given (exch, pid) keys on surviving placeable
        workers and queue them for re-drive.  Keys with no survivor stay
        mapped to the dead worker — the client's re-drive attempt will
        raise WorkerLost and the fault domain falls back."""
        survivors = self.placeable_workers()
        out: Dict[Tuple[int, int], str] = {}
        if not survivors:
            with self._lock:
                for e, p in keys:
                    self._redrives.setdefault(e, set()).add(p)
            return out
        with self._lock:
            # re-verify under the lock: a CONCURRENT loss may have
            # flipped a snapshot survivor to LOST between the
            # placeable scan above and here — assigning to it would
            # strand these keys on a dead worker (its own declare_lost
            # already snapshotted its owned keys and will not re-run)
            live = [w for w in survivors if w.state == ALIVE]
            if not live:
                # last resort: a DEGRADED survivor is slow, not dead —
                # landing the keys on it beats stranding them
                live = [w for w in survivors if w.state == DEGRADED]
            if not live:
                for e, p in keys:
                    self._redrives.setdefault(e, set()).add(p)
                return out
            loads: Dict[str, float] = {w.worker_id: 0.0 for w in live}
            for k, owner in self._placement.items():
                if owner in loads:
                    loads[owner] += self._holdings.get(k, 0)
            by_id = {w.worker_id: w for w in live}
            for e, p in sorted(keys):
                wid = min(loads, key=lambda i: (loads[i] / by_id[i]
                                                .mem_bytes, i))
                self._placement[(e, p)] = wid
                self._holdings.pop((e, p), None)
                loads[wid] += 1
                self._redrives.setdefault(e, set()).add(p)
                out[(e, p)] = wid
        return out

    # -- placement -------------------------------------------------------
    def placeable_workers(self) -> List[WorkerInfo]:
        """ALIVE workers, DEGRADED ones (demoted — place() divides
        their capacity weight by slowFactor; a slow worker still beats
        no worker), plus QUARANTINED ones whose breaker TTL expired
        (the consult admits the re-probe, flipping them placeable)."""
        from spark_rapids_tpu.resilience.breaker import get_breaker

        out = []
        with self._lock:
            candidates = list(self._workers.values())
        for w in candidates:
            if w.state in (ALIVE, DEGRADED):
                out.append(w)
            elif w.state == QUARANTINED:
                if get_breaker().consult((BREAKER_OP, w.worker_id),
                                         self.breaker_ttl_s) is None:
                    with self._lock:
                        if w.state == QUARANTINED:
                            w.state = ALIVE
                            out.append(w)
                    self._diag_event("worker_probed", w.worker_id,
                                     "quarantine TTL expired")
        return out

    def live_worker_count(self) -> int:
        with self._lock:
            return sum(1 for w in self._workers.values()
                       if w.state == ALIVE)

    def worker_state(self, wid: str) -> Optional[str]:
        with self._lock:
            w = self._workers.get(wid)
            return w.state if w is not None else None

    def redrive_backlog(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._redrives.values())

    def wait_for_workers(self, n: int, timeout_s: float = 15.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.live_worker_count() >= n:
                return True
            time.sleep(0.02)
        return self.live_worker_count() >= n

    def place(self, exch: int, n_parts: int,
              est_bytes: Optional[int] = None) -> Dict[int, str]:
        """Spread one exchange's reduce partitions over placeable
        workers, least-loaded-by-capacity first (``est_bytes`` comes
        from the partition-sizing estimate when the planner had one)."""
        workers = self.placeable_workers()
        if not workers:
            raise WorkerLost("<none>", "no placeable workers")
        per_pid = (est_bytes / n_parts) if est_bytes else 1.0
        loads = {w.worker_id: 0.0 for w in workers}
        # capacity-weighted with DEGRADED demotion (ISSUE 20): a
        # straggler's advertised memory counts at 1/slowFactor, so it
        # receives proportionally fewer partitions while demoted but is
        # never starved outright
        cap = {w.worker_id: (w.mem_bytes / self.slow_factor
                             if w.state == DEGRADED else
                             float(w.mem_bytes))
               for w in workers}
        out: Dict[int, str] = {}
        with self._lock:
            self._wire_of.setdefault(exch, next(self._wire_ids))
            for pid in range(n_parts):
                wid = min(loads,
                          key=lambda i: (loads[i] / cap[i], i))
                loads[wid] += per_pid
                out[pid] = wid
                self._placement[(exch, pid)] = wid
        return out

    def _wire(self, exch: int) -> int:
        """The never-reused wire identifier for one exchange (falls
        back to the raw id for ops against unplaced exchanges)."""
        with self._lock:
            return self._wire_of.get(exch, exch)

    def owner_of(self, exch: int, pid: int) -> str:
        with self._lock:
            wid = self._placement.get((exch, pid))
        if wid is None:
            raise KeyError(f"partition ({exch}, {pid}) is not placed")
        return wid

    def placement_of(self, exch: int) -> Dict[int, str]:
        with self._lock:
            return {p: w for (e, p), w in self._placement.items()
                    if e == exch}

    def wire_of(self, exch: int) -> int:
        """Public wire-id accessor (ISSUE 16): the identifier a stage
        lease journals — the one that survives a driver restart,
        because worker stores key blocks under it."""
        return self._wire(exch)

    def worker_inventory(self) -> Dict[str, List[Tuple[int, int, int,
                                                       int]]]:
        """Every live worker's re-HELLO-enumerated holdings:
        worker_id -> [(wire_exch, pid, n_blocks, max_seq), ...].  Empty
        lists for workers that joined fresh — the lease-adoption check
        in lifecycle/journal.py matches journaled block counts against
        this."""
        with self._lock:
            return {wid: list(w.held)
                    for wid, w in self._workers.items()
                    if w.state == ALIVE}

    def adopt_exchange(self, wire: int, placement: Dict[int, str],
                       counts: Optional[Dict[int, int]] = None) -> None:
        """Rebuild one journaled exchange's placement from re-attached
        workers' inventories (ISSUE 16).  The exchange registers under
        its ORIGINAL wire id (that is the key the worker stores hold),
        holdings are restored so the leak gate and gauges track the
        adopted blocks, and the wire-id counter reseeds past it so a
        fresh place() can never mint a colliding id."""
        import itertools as _it

        with self._lock:
            self._wire_of[wire] = wire
            for pid, wid in placement.items():
                self._placement[(wire, pid)] = wid
                if counts:
                    self._holdings[(wire, pid)] = int(
                        counts.get(pid, 0))
            nxt = next(self._wire_ids)
            self._wire_ids = _it.count(max(nxt, wire + 1))
        self._diag_event("exchange_adopted", "-",
                         f"wire={wire} n_parts={len(placement)}")

    def release_orphan_holdings(self, keep: Set[int]) -> int:
        """Release every re-HELLO-held wire id that is neither in
        ``keep`` (still-adoptable journaled leases) nor currently placed
        (an adoption mid-serve) — blocks a dead incarnation shipped but
        never lease-committed must not outlive its journal (ISSUE 16:
        the zero-stranded-partitions pin).  Returns wires released."""
        with self._lock:
            placed = set(self._wire_of.values())
            victims: Dict[str, Set[int]] = {}
            for wid, w in self._workers.items():
                if w.state != ALIVE or not w.held:
                    continue
                drop = {e for (e, _p, _n, _mx) in w.held
                        if e not in keep and e not in placed}
                if drop:
                    victims[wid] = drop
                    w.held = [h for h in w.held if h[0] not in drop]
        n = 0
        for wid, wires in sorted(victims.items()):
            for wire in sorted(wires):
                try:
                    self._request(wid, {"op": "release", "exch": wire},
                                  cancellable=False)
                    n += 1
                except (WorkerLost, RuntimeError, OSError):
                    # a dead/slow worker's store dies with its process
                    pass
            self._diag_event("orphans_released", wid,
                             f"wires={sorted(wires)}")
        return n

    def claim_redrives(self, exch: int) -> Set[int]:
        """Atomically take (and clear) the exchange's pending re-drive
        pids — the producer-side client re-pushes them from its spilled
        partition queues."""
        with self._lock:
            return self._redrives.pop(exch, set())

    def mark_redrive(self, exch: int, pid: int) -> None:
        """Queue one partition for re-drive (the consumer found a
        worker's copy incomplete — e.g. it restarted empty)."""
        with self._lock:
            self._redrives.setdefault(exch, set()).add(pid)

    # -- data plane ------------------------------------------------------
    def _data_conn_locked_args(self, wid: str):
        with self._lock:
            w = self._workers.get(wid)
            if w is None or w.state in (LOST, LEFT):
                raise WorkerLost(wid, f"state={'?' if w is None else w.state}")
            lock = self._conn_locks.setdefault(wid, threading.Lock())
            return w, lock

    def _request(self, wid: str, header: Dict, blobs=(),
                 cancellable: bool = True) -> Tuple[Dict, List[bytes]]:
        """One data-plane request to one worker, with bounded transient
        retry (connection refused/reset/timeout may heal); exhausted
        retries or a LOST/unknown worker raise :class:`WorkerLost` after
        declaring the loss.  ``cancellable=False`` is the CLEANUP
        contract: a release broadcast for a cancelled query must still
        reach the workers (remote copies must never outlive the query),
        so it does not observe the tripped CancelToken."""
        from spark_rapids_tpu.lifecycle.context import check_cancel
        from spark_rapids_tpu.resilience.classify import (
            TRANSIENT,
            classify_failure,
        )

        attempt = 0
        while True:
            if cancellable:
                check_cancel()
            w, lock = self._data_conn_locked_args(wid)
            t0 = time.monotonic()
            try:
                with lock:
                    conn = self._conns.get(wid)
                    if conn is None:
                        conn = P.connect(w.host, w.data_port,
                                         self.op_timeout_s)
                        with self._lock:
                            self._conns[wid] = conn
                    try:
                        out = P.request(conn, header, blobs)
                    except (OSError, ConnectionError):
                        # one reconnect-and-retry inside the same
                        # attempt: the pooled conn may simply be stale
                        with self._lock:
                            if self._conns.get(wid) is conn:
                                del self._conns[wid]
                        try:
                            conn.close()
                        except OSError:
                            pass
                        conn = P.connect(w.host, w.data_port,
                                         self.op_timeout_s)
                        with self._lock:
                            self._conns[wid] = conn
                        out = P.request(conn, header, blobs)
                # per-op latency feed (ISSUE 20): every served data-
                # plane op walls into the worker's p95 EWMA and steps
                # the degrade/promote streaks
                self.note_op_latency(wid, time.monotonic() - t0)
                return out
            except (OSError, ConnectionError, socket.timeout,
                    P.RemoteOpError, P.ProtocolCorruption) as e:
                # ALWAYS evict the pooled conn: a corrupted frame in
                # particular leaves the TCP stream mid-frame
                # desynchronized — reusing it would fail every later op
                # against this worker with bad-magic noise
                with self._lock:
                    if self._conns.get(wid) is not None:
                        try:
                            self._conns.pop(wid).close()
                        except OSError:
                            pass
                attempt += 1
                # RemoteOpError: the worker answered but could not
                # serve (ENOSPC on its spill dir, a racing release) —
                # treat like a dead socket: declare + re-place, never
                # let it escape as DETERMINISTIC and indict the
                # query's operator breaker.  ProtocolCorruption retries
                # on a FRESH connection (frame desync heals with the
                # socket; persistent corruption becomes a loss).
                retryable = isinstance(e, P.ProtocolCorruption) \
                    or (not isinstance(e, P.RemoteOpError)
                        and classify_failure(e) == TRANSIENT)
                if retryable and attempt <= self.put_retries:
                    _full_jitter_sleep(attempt)
                    continue
                with self._lock:
                    ww = self._workers.get(wid)
                    is_degraded = ww is not None \
                        and ww.state == DEGRADED
                if is_degraded:
                    # a DEGRADED worker that cannot serve this op is
                    # still heartbeating — speculate whatever it still
                    # owns (demoted placement may have landed keys on
                    # it after the demotion) and surface the typed
                    # degradation (the caller re-drives) without a loss
                    # declaration or the quarantine breaker
                    with self._lock:
                        owned = [k for k, o in self._placement.items()
                                 if o == wid]
                        healthy = any(x.state == ALIVE for x in
                                      self._workers.values())
                    if owned and healthy:
                        moved = self._replace_owner(owned)
                        if moved:
                            with self._lock:
                                for (e2, _p2) in moved:
                                    self._former_owners.setdefault(
                                        e2, set()).add(wid)
                            PC.bump("speculative_redrives", len(moved))
                    raise WorkerDegraded(
                        wid, f"{type(e).__name__}: {e}") from e
                self.declare_lost(wid, f"{type(e).__name__}: {e}")
                raise WorkerLost(wid, f"{type(e).__name__}: {e}") from e

    def _trace_fields(self) -> Dict:
        """The trace/span stamp for one outgoing data-plane header
        (ISSUE 15): the active query's trace id (minted at lifecycle
        collect start) and the diagnostics current-operator path.  Empty
        when tracing is off or no lifecycle-managed query is active —
        the worker then records counters but no attributed spans."""
        if not self.trace_enabled:
            return {}
        from spark_rapids_tpu.lifecycle.context import current

        ctx = current()
        if ctx is None:
            return {}
        fields = {"trace": getattr(ctx, "trace_id", "") or ctx.query_id}
        from spark_rapids_tpu.diagnostics import context as _DIAG

        span = _DIAG.CURRENT_OP.get() if _DIAG.RECORDER is not None \
            else None
        if span:
            fields["span"] = span
        return fields

    def _ensure_live_owner(self, exch: int, pid: int) -> str:
        """The partition's owner, re-placed first if a concurrent loss
        left it mapped to a dead worker (the dead worker's own
        declare_lost snapshotted its keys BEFORE this one landed there,
        so nobody else will heal it).  The re-placement queues the pid
        for re-drive like any other loss."""
        wid = self.owner_of(exch, pid)
        with self._lock:
            w = self._workers.get(wid)
            dead = w is None or w.state in (LOST, LEFT)
        if dead:
            replaced = self._replace_owner([(exch, pid)])
            wid = replaced.get((exch, pid))
            if wid is None:
                raise WorkerLost(
                    "<none>", f"partition ({exch}, {pid}) owner dead "
                              f"and no placeable survivors")
        return wid

    def put_block(self, exch: int, pid: int, seq: int,
                  blob: bytes, redrive: bool = False) -> str:
        """Ship one block to the partition's current owner; returns the
        owner id (raises WorkerLost when the owner died and retries
        were exhausted — the caller re-drives after re-placement).
        ``redrive=True`` marks a lineage replay so the worker's
        `store_redrive_puts` counter (and its `redrive_put` span kind)
        makes recovery traffic countable on the worker side."""
        wid = self._ensure_live_owner(exch, pid)
        header = {"op": "put", "exch": self._wire(exch),
                  "pid": pid, "seq": seq, **self._trace_fields()}
        if redrive:
            header["redrive"] = 1
        self._request(wid, header, [blob])
        with self._lock:
            # distinct-block count, not send count: replays re-send
            # sequences the worker's idempotent store deduplicates, and
            # inflated holdings would skew re-placement load weighting
            self._holdings[(exch, pid)] = max(
                self._holdings.get((exch, pid), 0), seq + 1)
            self._shipped_blocks += 1
        PC.bump("dist_blocks_shipped")
        PC.bump("dist_block_bytes", len(blob))
        return wid

    def fetch_blocks(self, exch: int, pid: int, after_seq: int = -1,
                     max_bytes: int = 0
                     ) -> Tuple[List[int], List[bytes], int]:
        """One PAGE of a partition from its owner (sequences above
        ``after_seq``, ~``max_bytes`` per page) — a reduce partition
        far larger than one wire frame streams out page by page
        instead of materializing whole on the worker.  Returns (seqs,
        blobs, the worker's total block count for the partition)."""
        wid = self._ensure_live_owner(exch, pid)
        rep, blobs = self._request(
            wid, {"op": "fetch", "exch": self._wire(exch), "pid": pid,
                  "after_seq": after_seq, "max_bytes": max_bytes,
                  **self._trace_fields()})
        return ([int(s) for s in rep.get("seqs", [])], blobs,
                int(rep.get("n_total", len(blobs))))

    def worker_stats(self, wid: str) -> Dict:
        rep, _ = self._request(wid, {"op": "stats"})
        return rep

    # -- federated telemetry (ISSUE 15) ---------------------------------
    def dump_worker(self, wid: str) -> Optional[Dict]:
        """Pull one LIVE worker's full telemetry via the DUMP control
        op and fold it into the mirror.  Runs on a FRESH connection
        with no loss-declaration side effects (observability must never
        kill membership — a slow dump is just a None).  Returns the
        folded view (counters + full mirror ring + clock offset) or
        None when the worker is gone/slow."""
        with self._lock:
            w = self._workers.get(wid)
            if w is None or w.state in (LOST, LEFT):
                return None
            host, port = w.host, w.data_port
        try:
            s = P.connect(host, port, self.op_timeout_s)
            try:
                rep, _ = P.request(s, {"op": "dump",
                                       **self._trace_fields()})
            finally:
                s.close()
        except (OSError, ConnectionError, RuntimeError,
                P.ProtocolCorruption):
            return None
        with self._lock:
            w = self._workers.get(wid)
            if w is None:
                return None
            self._fold_telemetry_locked(w, rep)
            view = self._worker_view_locked(w)
        PC.bump("dist_worker_dumps")
        return view

    def _worker_view_locked(self, w: WorkerInfo,
                            trace_id: Optional[str] = None) -> Dict:
        ring = [e for e in w.mirror
                if not trace_id or e.get("trace") == trace_id]
        return {"worker_id": w.worker_id, "state": w.state,
                "pid": w.pid, "clock_offset_s": w.clock_offset_s,
                "counters": dict(w.counters),
                "store_stats": dict(w.store_stats),
                "ring": ring}

    def collect_trace(self, trace_id: Optional[str] = None,
                      pull_live: bool = False) -> List[Dict]:
        """Every worker's federated telemetry view, ring filtered to
        ``trace_id`` when given.  ``pull_live`` first DUMPs each ALIVE
        worker so the view includes spans newer than the last heartbeat
        (the query-end merge uses this; LOST workers contribute their
        last-shipped mirror — the whole point of the piggyback)."""
        if pull_live:
            with self._lock:
                live = [w.worker_id for w in self._workers.values()
                        if w.state in (ALIVE, DEGRADED)]
            for wid in live:
                self.dump_worker(wid)
        out = []
        with self._lock:
            for w in self._workers.values():
                view = self._worker_view_locked(w, trace_id)
                if view["ring"] or view["counters"]:
                    out.append(view)
        return out

    def worker_telemetry(self) -> Dict[str, Dict]:
        """Per-worker federated counter snapshots for the sampler fold
        (peek-only: latest heartbeat-reported values, no network)."""
        with self._lock:
            return {w.worker_id: {"state": w.state,
                                  "counters": dict(w.counters),
                                  "store_stats": dict(w.store_stats),
                                  "clock_offset_s": w.clock_offset_s,
                                  "lat_ewma_ms": (w.lat_ewma_s or 0.0)
                                  * 1000.0}
                    for w in self._workers.values() if w.counters}

    def federated_store_bytes(self) -> Dict[str, int]:
        """Last-heartbeat store bytes per worker (peek-only) — the
        resource bill's worker-side bytes when a query's window caught
        no worker_telemetry events (ISSUE 18)."""
        with self._lock:
            return {w.worker_id: int(w.store_stats.get("bytes", 0))
                    for w in self._workers.values() if w.store_stats}

    def postmortem_worker(self, wid: str, detail: str = "") -> Optional[Dict]:
        """On-demand merged post-mortem (the DUMP-op twin of the
        worker-loss bundle): pull the worker's ring + counters and dump
        a flight-recorder bundle naming it.  Returns the bundle or None
        (telemetry off / worker gone with an empty mirror)."""
        from spark_rapids_tpu.telemetry import context as TEL

        hub = TEL.HUB
        if hub is None:
            return None
        view = self.dump_worker(wid)
        if view is None:
            with self._lock:
                w = self._workers.get(wid)
                view = self._worker_view_locked(w) if w is not None \
                    else None
        if view is None:
            return None
        try:
            return hub.postmortem(
                "worker_dump", detail=detail or wid, force=True,
                extra={"worker_id": wid, "worker_diagnostics": view,
                       "trace_ids": sorted(
                           {e.get("trace") for e in view["ring"]
                            if e.get("trace")})})
        # tpulint: disable=cancel-swallow (telemetry isolation: a dump
        # failure must never break the caller)
        except Exception:
            return None

    def note_worker_ok(self, wid: str) -> None:
        """A probed (previously quarantined) worker served successfully:
        close its breaker entry so future joins are clean."""
        from spark_rapids_tpu.resilience.breaker import get_breaker

        get_breaker().record_success((BREAKER_OP, wid))

    # -- release / leak accounting --------------------------------------
    def release_exchange(self, exch: int) -> None:
        """Drop one exchange everywhere: placement, holdings, pending
        re-drives, and a best-effort release broadcast to every worker
        that held any of its partitions (the query committed or died —
        remote copies must not outlive it)."""
        with self._lock:
            owners = {w for (e, _), w in self._placement.items()
                      if e == exch}
            # speculation moved partitions off still-running DEGRADED
            # workers — their store copies need the release broadcast
            # too (a LOST former owner just fails the request quietly)
            owners |= self._former_owners.pop(exch, set())
            for k in [k for k in self._placement if k[0] == exch]:
                del self._placement[k]
                self._holdings.pop(k, None)
            self._redrives.pop(exch, None)
            wire = self._wire_of.pop(exch, exch)
        for wid in sorted(owners):
            try:
                self._request(wid, {"op": "release", "exch": wire,
                                    **self._trace_fields()},
                              cancellable=False)
            except (WorkerLost, RuntimeError, OSError):
                # a dead/slow worker cannot hold up query cleanup; its
                # store dies with its process
                pass

    def release_all(self) -> None:
        with self._lock:
            exchanges = {e for (e, _) in self._placement}
        for e in sorted(exchanges):
            self.release_exchange(e)

    def leak_report(self) -> List[str]:
        """One line per exchange still placed remotely — wired into
        ``lifecycle.leak_report_all`` so the conftest gate fails the
        owning test on a leftover remote partition."""
        with self._lock:
            by_exch: Dict[int, int] = {}
            for (e, _p), w in self._placement.items():
                by_exch[e] = by_exch.get(e, 0) + 1
            return [
                f"LEAK: distributed exchange {e} still placed "
                f"({n} partitions on remote workers)"
                for e, n in sorted(by_exch.items())]

    # -- observability ---------------------------------------------------
    def _diag_event(self, kind: str, wid: str, detail: str) -> None:
        from spark_rapids_tpu.diagnostics import context as _DIAG

        rec = _DIAG.RECORDER
        if rec is not None:
            with self._lock:
                n_workers = sum(1 for w in self._workers.values()
                                if w.state == ALIVE)
                n_parts = len(self._placement)
            rec.distributed(kind, wid, detail, n_workers, n_parts)

    def _flight_event(self, kind: str, **fields) -> None:
        from spark_rapids_tpu.telemetry import context as TEL

        hub = TEL.HUB
        if hub is not None:
            try:
                hub.record_event(kind, **fields)
            # tpulint: disable=cancel-swallow (telemetry isolation: a
            # hub failure must never break membership handling)
            except Exception:
                pass

    def _postmortem(self, wid: str, reason: str, plan: List[Dict],
                    kind: str = "worker_lost") -> None:
        """The worker-loss flight-recorder bundle: the driver's view
        (placement table + re-drive plan + membership) MERGED with the
        lost worker's last-shipped diagnostics ring + counter snapshot
        (ISSUE 15) — a SIGKILLed process cannot answer a DUMP, so what
        its heartbeats already piggybacked is the post-mortem.  ISSUE
        20 reuses the bundle with ``kind="worker_degraded"``: same
        evidence shape, the worker merely stays a member."""
        from spark_rapids_tpu.telemetry import context as TEL

        hub = TEL.HUB
        if hub is None:
            return
        with self._lock:
            placement = [
                {"exch": e, "pid": p, "worker": w,
                 "blocks": self._holdings.get((e, p), 0)}
                for (e, p), w in sorted(self._placement.items())]
            members = [{"worker_id": w.worker_id, "state": w.state,
                        "host": w.host, "data_port": w.data_port,
                        "pid": w.pid}
                       for w in self._workers.values()]
            lost = self._workers.get(wid)
            diagnostics = self._worker_view_locked(lost) \
                if lost is not None else None
        trace_ids = sorted({e.get("trace")
                            for e in (diagnostics or {}).get("ring", [])
                            if e.get("trace")})
        try:
            hub.postmortem(
                kind, detail=f"{wid}: {reason}", force=True,
                extra={"worker_id": wid,
                       "placement_table": placement,
                       "redrive_plan": plan,
                       "membership": members,
                       "worker_diagnostics": diagnostics,
                       "trace_ids": trace_ids})
        # tpulint: disable=cancel-swallow (telemetry isolation: a dump
        # failure must never break loss recovery)
        except Exception:
            pass

    def gauges(self) -> Dict[str, float]:
        """Sampler hook (peek-only): live worker count, re-placement
        backlog, and the put-receipt drift (ISSUE 15)."""
        with self._lock:
            live = sum(1 for w in self._workers.values()
                       if w.state == ALIVE)
            quarantined = sum(1 for w in self._workers.values()
                              if w.state == QUARANTINED)
            degraded = sum(1 for w in self._workers.values()
                           if w.state == DEGRADED)
            backlog = sum(len(v) for v in self._redrives.values())
            acked = self._acked_retired + sum(
                int(w.counters.get("store_puts", 0))
                + int(w.counters.get("store_put_dedups", 0))
                for w in self._workers.values())
            unacked = max(self._shipped_blocks - acked, 0)
            lat = [w.lat_ewma_s for w in self._workers.values()
                   if w.state in (ALIVE, DEGRADED)
                   and w.lat_ewma_s is not None]
        return {"dist_workers_live": float(live),
                "dist_workers_quarantined": float(quarantined),
                # gray failure (ISSUE 20): current straggler count and
                # the fleet's worst per-worker p95 latency EWMA — the
                # tail the governor's fleet pressure component watches
                "dist_workers_degraded": float(degraded),
                "dist_fleet_lat_p95_ms": (max(lat) * 1000.0
                                          if lat else 0.0),
                "dist_replacement_backlog": float(backlog),
                # shipped-but-never-reported blocks: transiently nonzero
                # within one heartbeat of shipping; persistently nonzero
                # means silent frame loss (or a dead worker's unreported
                # tail — cross-check worker_lost)
                "dist_blocks_unacked": float(unacked)}

    def describe(self) -> str:
        with self._lock:
            states = {w.worker_id: w.state
                      for w in self._workers.values()}
        return json.dumps({"port": self.port, "workers": states})

    def shutdown(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            socks = list(self._conns.values()) + [
                w.control for w in self._workers.values()
                if w.control is not None]
            self._conns.clear()
            # membership ends with the coordinator: mark everyone LEFT
            # so in-flight reader/monitor threads waking on the closed
            # sockets below cannot declare stray losses (bumping
            # counters and dumping bundles into whatever runs next)
            for w in self._workers.values():
                if w.state in (ALIVE, QUARANTINED, DEGRADED):
                    w.state = LEFT
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
