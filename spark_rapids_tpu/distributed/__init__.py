"""Cross-host fault-tolerant execution tier (ISSUE 14).

The first true multi-process tier of the engine: worker processes own
durable exchange partitions, a lightweight in-driver coordinator places
them (``exec/partition_sizing.py`` estimates feed the weighting), blocks
cross hosts as PR 4 CRC-framed ``TKU2`` blocks over the ``TKD1`` control
protocol, and the spill-backed partition queues from
``shuffle/partition_queues.py`` double as the producer-side LINEAGE
buffer — every shipped block is retained until the consuming stage
commits its partition, so a SIGKILLed worker is recovered by re-placing
its partitions on survivors and re-driving the retained blocks.

Modules:

  protocol.py    — TKD1 control framing + the WorkerLost taxonomy
  worker.py      — the worker process (store, heartbeats, data server)
  coordinator.py — membership / liveness / placement / re-drive plan
  client.py      — DistributedExchange (produce, consume, lineage retry)

Robustness state machine (docs/distributed.md has the full picture):

    JOINED --heartbeats--> ALIVE --workerLostMs silence / dead socket-->
    LOST --(rejoin, breaker OPEN)--> QUARANTINED --TTL re-probe--> ALIVE

The singleton accessors below mirror the shuffle-manager pattern:
cleanup paths only ever *peek* (a leak sweep must never build a
coordinator), and ``reset_coordinator`` tears the listener down for
test isolation.
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
from typing import List, Optional

from spark_rapids_tpu.distributed.coordinator import Coordinator
from spark_rapids_tpu.distributed.protocol import (  # noqa: F401
    ProtocolCorruption,
    WorkerLost,
)

_lock = threading.Lock()
_coordinator: Optional[Coordinator] = None


def get_coordinator(conf=None) -> Coordinator:
    """The process coordinator, built on first use (the harness/test or
    the first distributed exchange)."""
    global _coordinator
    with _lock:
        if _coordinator is None:
            _coordinator = Coordinator(conf)
        return _coordinator


def peek_coordinator() -> Optional[Coordinator]:
    """The singleton if it exists — cleanup/leak paths must never
    CREATE one."""
    return _coordinator


def reset_coordinator() -> None:
    global _coordinator
    with _lock:
        c, _coordinator = _coordinator, None
    if c is not None:
        c.shutdown()


def spawn_local_worker(coordinator: Coordinator, worker_id: str,
                       mem_bytes: int = 64 << 20,
                       heartbeat_ms: Optional[int] = None,
                       spill_dir: Optional[str] = None,
                       warm_compile_dir: Optional[str] = None,
                       op_timeout_ms: Optional[int] = None,
                       telemetry_ring: Optional[int] = None,
                       extra_env: Optional[dict] = None,
                       reattach_ms: Optional[int] = None,
                       endpoint_file: Optional[str] = None
                       ) -> subprocess.Popen:
    """Launch one worker PROCESS against the given coordinator (tests,
    the chaos sweep, and bench all spawn through here).  The child runs
    on the CPU backend regardless of the parent's platform — workers
    hold serialized blocks, not device state.  ``reattach_ms`` +
    ``endpoint_file`` arm crash recovery (ISSUE 16): the worker
    survives THIS driver's death and re-dials whatever endpoint the
    successor publishes."""
    hb = heartbeat_ms if heartbeat_ms is not None \
        else int(coordinator.heartbeat_s * 1000)
    ot = op_timeout_ms if op_timeout_ms is not None \
        else int(coordinator.op_timeout_s * 1000)
    ring = telemetry_ring if telemetry_ring is not None \
        else getattr(coordinator, "telemetry_ring", 512)
    cmd = [sys.executable, "-m", "spark_rapids_tpu.distributed.worker",
           "--coordinator", f"127.0.0.1:{coordinator.port}",
           "--worker-id", worker_id,
           "--mem-bytes", str(int(mem_bytes)),
           "--heartbeat-ms", str(hb),
           "--op-timeout-ms", str(ot),
           "--telemetry-ring", str(int(ring))]
    if spill_dir:
        cmd += ["--spill-dir", spill_dir]
    if warm_compile_dir:
        cmd += ["--warm-compile-dir", warm_compile_dir]
    if reattach_ms:
        cmd += ["--reattach-ms", str(int(reattach_ms))]
    if endpoint_file:
        cmd += ["--endpoint-file", endpoint_file]
    env = dict(os.environ)
    # unconditional: workers hold serialized blocks, not device state,
    # and on a real TPU host an inherited JAX_PLATFORMS=tpu would have
    # N worker processes contending with the driver for the one-client
    # TPU runtime (extra_env can still override for tests)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env or {})
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(cmd, env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)


def leak_report() -> List[str]:
    """Remote-partition leak lines (lifecycle.leak_report_all hook)."""
    c = peek_coordinator()
    return c.leak_report() if c is not None else []
