"""Wire protocol for the cross-host tier — CRC-framed control messages.

Reference analog: the reference's shuffle transport frames blocks with
metadata over UCX/netty (SURVEY.md §2.7, RapidsShuffleClient/Server);
Theseus (arXiv:2508.05029) keeps its control plane tiny next to a
disciplined data plane.  Here the CONTROL plane is this module — small
JSON headers in a ``TKD1`` frame with the same CRC32 stance as the PR 4
``TKU2`` batch serializer — while the DATA plane payloads riding behind
a header are the ``TKU2`` blocks themselves (``exec/ici.ici_host_frame``
output), so a flipped bit anywhere between producer and consumer
surfaces as a deterministic corruption error, never silent wrong rows.

Frame layout (little-endian):

    TKD1 | u32 payload_len | u32 crc32(payload) | payload
    payload = u32 header_len | header_json | blob_0 | blob_1 | ...

with the header carrying ``blobs`` (the list of blob sizes) when binary
payloads follow.  One frame is one message; sockets carry a sequence of
frames.  Failure taxonomy (consumed by ``resilience/classify.py``):

  * :class:`ProtocolCorruption` — CRC/magic/length mismatch; re-reading
    re-derives it, so DETERMINISTIC.
  * ``ConnectionError`` / ``BrokenPipeError`` / ``socket.timeout`` —
    raised by the socket layer itself; TRANSIENT for the block layer
    (a retry may heal a hiccup).
  * :class:`WorkerLost` — the block layer exhausted its transient
    budget against one worker (or the coordinator declared it dead);
    classifies as the WORKER_LOST class, which triggers partition
    re-placement + re-drive rather than per-batch backoff.

Trace propagation (ISSUE 15, docs/cluster_observability.md): every
data-plane header MAY carry two optional fields the driver stamps when
``spark.rapids.tpu.distributed.traceEnabled`` is on —

  * ``trace`` — the originating query's cluster-wide trace id (minted
    by ``lifecycle.context.mint_trace_id`` at collect start and echoed
    in the query's diagnostics event-log header), and
  * ``span``  — the driver-side operator path ("0.1") current when the
    frame was sent (the diagnostics contextvar).

Workers copy both into their local diagnostics ring, so worker-side
work attributes to exactly one collect across processes; a header
without them is valid (tracing off / non-query tooling) and records
counters only.  ``redrive: 1`` on a put marks a lineage replay.

This module is deliberately dependency-light (stdlib only) so worker
processes can import it before paying for the full engine import.
"""
from __future__ import annotations

import itertools
import json
import socket
import struct
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

MAGIC = b"TKD1"
_HDR = struct.Struct("<4sII")
_U32 = struct.Struct("<I")

# one control frame is small; a data frame carries TKU2 blobs that are
# themselves bounded by the exchange batch-size goal — this cap only
# guards against a corrupted length word allocating gigabytes
MAX_FRAME_BYTES = 1 << 30

# process-unique request correlation ids (``rid``): uniqueness is all
# the desync check needs, and a global counter avoids per-socket state
# (socket.socket carries __slots__; GIL makes next() atomic)
_RID = itertools.count(1)


class ProtocolCorruption(RuntimeError):
    """Bad magic / length / CRC on a control frame — deterministic (the
    same bytes re-derive the same corruption)."""


class ProtocolDesync(ConnectionError):
    """The reply frame read off the socket answers a DIFFERENT request
    than the one just sent (its echoed ``rid`` mismatches).  A network
    that duplicates or reorders frames (netchaos ``dup_frame`` /
    ``reorder``, a misbehaving middlebox) leaves a stale reply in the
    stream; every frame after it would be off-by-one forever, so the
    only safe move is to abandon the connection.  A ``ConnectionError``
    subclass: TRANSIENT, and the caller's retry dials a fresh pooled
    connection whose request/reply cursor starts clean."""


class RemoteOpError(RuntimeError):
    """The worker ANSWERED but reported the operation failed (e.g.
    ENOSPC writing a spill file).  The transport is fine but that
    worker cannot serve — the coordinator treats it like a dead socket:
    declare the loss and re-place, never indict the query's operator."""


class WorkerLost(ConnectionError):
    """A worker is gone for good as far as this operation is concerned:
    transient retries against it were exhausted, or the coordinator
    declared it LOST.  Classified as the WORKER_LOST failure class —
    the distributed layer answers with re-placement + re-drive from the
    producer-side spilled partition queues, not with backoff."""

    def __init__(self, worker_id: str, detail: str = ""):
        super().__init__(
            f"worker {worker_id} lost" + (f": {detail}" if detail else ""))
        self.worker_id = worker_id


class WorkerDegraded(WorkerLost):
    """A worker is *slow*, not dead (ISSUE 20, gray failure): its ops
    keep blowing the soft deadline or its latency EWMA sits past
    slowFactor x the fleet median, and an op against it exhausted the
    transient budget.  Classified as the WORKER_DEGRADED class — never
    DETERMINISTIC, never the quarantine breaker: the caller re-drives
    the affected partitions onto the healthy survivors the coordinator
    already speculated them to, and the worker stays a member
    (DEGRADED, promotable back on sustained recovery)."""

    def __init__(self, worker_id: str, detail: str = ""):
        ConnectionError.__init__(
            self,
            f"worker {worker_id} degraded"
            + (f": {detail}" if detail else ""))
        self.worker_id = worker_id


def encode_msg(header: Dict, blobs: Sequence[bytes] = ()) -> bytes:
    """One wire frame for ``header`` (+ optional binary payloads)."""
    if blobs:
        header = dict(header)
        header["blobs"] = [len(b) for b in blobs]
    hj = json.dumps(header, separators=(",", ":")).encode("utf-8")
    payload = b"".join([_U32.pack(len(hj)), hj, *blobs])
    return _HDR.pack(MAGIC, len(payload), zlib.crc32(payload)) + payload


def decode_payload(payload: bytes) -> Tuple[Dict, List[bytes]]:
    if len(payload) < 4:
        raise ProtocolCorruption("truncated payload")
    (hlen,) = _U32.unpack_from(payload, 0)
    if 4 + hlen > len(payload):
        raise ProtocolCorruption("header length past payload end")
    try:
        header = json.loads(payload[4:4 + hlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolCorruption(f"undecodable header: {e}") from e
    blobs: List[bytes] = []
    off = 4 + hlen
    for size in header.get("blobs", []):
        if off + size > len(payload):
            raise ProtocolCorruption("blob length past payload end")
        blobs.append(payload[off:off + size])
        off += size
    return header, blobs


def recv_exactly(sock: socket.socket, n: int) -> bytes:
    """Read exactly n bytes or raise ConnectionError on EOF (a peer
    vanishing mid-frame is a connection failure, not corruption)."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise ConnectionError(
                f"peer closed mid-frame ({got}/{n} bytes)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def send_msg(sock: socket.socket, header: Dict,
             blobs: Sequence[bytes] = ()) -> None:
    sock.sendall(encode_msg(header, blobs))


def recv_msg(sock: socket.socket) -> Tuple[Dict, List[bytes]]:
    """One frame off the socket (honors the socket's timeout)."""
    raw = recv_exactly(sock, _HDR.size)
    magic, plen, crc = _HDR.unpack(raw)
    if magic != MAGIC:
        raise ProtocolCorruption(f"bad magic {magic!r}")
    if plen > MAX_FRAME_BYTES:
        raise ProtocolCorruption(f"frame length {plen} exceeds cap")
    payload = recv_exactly(sock, plen)
    if zlib.crc32(payload) != crc:
        raise ProtocolCorruption("control-frame CRC mismatch")
    return decode_payload(payload)


def request(sock: socket.socket, header: Dict,
            blobs: Sequence[bytes] = ()) -> Tuple[Dict, List[bytes]]:
    """Send one message and read one reply; a reply carrying ``error``
    raises :class:`RemoteOpError` (the remote failed the op, the
    transport itself is fine).

    Every request carries a process-unique correlation id (``rid``)
    that the worker echoes into its reply; a mismatch means the stream
    holds a duplicated or reordered frame and raises
    :class:`ProtocolDesync` BEFORE the error field is consulted (a
    stale error reply must not be attributed to this op)."""
    rid = next(_RID)
    header = dict(header)
    header["rid"] = rid
    send_msg(sock, header, blobs)
    rep, rblobs = recv_msg(sock)
    got = rep.get("rid")
    if got != rid:
        raise ProtocolDesync(
            f"reply rid {got!r} answers a different request than "
            f"{rid} — duplicated/reordered frame in the stream")
    if rep.get("error"):
        raise RemoteOpError(f"remote error: {rep['error']}")
    return rep, rblobs


def connect(host: str, port: int, timeout_s: float) -> socket.socket:
    s = socket.create_connection((host, port), timeout=timeout_s)
    s.settimeout(timeout_s)
    try:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass
    return s


def parse_endpoint(ep: str) -> Tuple[str, int]:
    host, _, port = ep.rpartition(":")
    return (host or "127.0.0.1"), int(port)
