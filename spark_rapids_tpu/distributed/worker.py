"""Worker process — one durable exchange-buffer node of the cross-host
tier.

Reference analog: a RapidsShuffleServer executor holding its shuffle
blocks for peer fetches (SURVEY.md §2.7), reduced to the role the
coordinator places on it: own a set of reduce partitions, keep their
CRC-framed (``TKU2``) blocks durably (bounded memory, overflowing to a
spill directory — the netty shuffle-file analog), serve fetches, and
heartbeat so the coordinator can tell a live worker from a dead one.

A worker is deliberately almost stateless: everything it holds can be
re-driven from the producer-side spilled partition queues (lineage
retry), so SIGKILLing one loses no query.  Protocol (over the data
listener; the control socket to the coordinator carries only HELLO +
heartbeats):

  put     {exch, pid, seq}+blob -> {ok}     store one partition block
  fetch   {exch, pid} -> {seqs}+blobs       every block of one partition
  release {exch} -> {ok}                    drop one exchange's blocks
  stats   {} -> {blocks, bytes, ...}        introspection
  dump    {} -> {counters, ring, ...}       full telemetry pull (ISSUE 15)
  ping    {} -> {ok}

Cluster observability (ISSUE 15, docs/cluster_observability.md): every
data-plane op bumps WORKER-LOCAL counters (:data:`WORKER_COUNTER_KEYS` —
plain dict, no engine import: worker processes must stay light) and,
when the header carries ``trace``/``span`` fields (the driver stamps the
query's trace id + current-operator span id on every frame), records a
span event into a bounded worker-local diagnostics ring.  Heartbeats
piggyback the cumulative counter snapshot + the ring entries recorded
since the previous heartbeat + ``t_wall`` (the clock-offset handshake),
so the coordinator's mirror holds a SIGKILLed worker's last-shipped
telemetry; the ``dump`` op pulls the full live ring on demand.

Run as a process:

    python -m spark_rapids_tpu.distributed.worker \
        --coordinator 127.0.0.1:<port> [--worker-id w0] \
        [--mem-bytes 67108864] [--heartbeat-ms 200] \
        [--spill-dir DIR] [--warm-compile-dir DIR]

On join the worker warms what can be warmed from shared persistent
stores: ``--warm-compile-dir`` points the process-wide persistent XLA
compile cache (``spark.rapids.tpu.compile.cacheDir``) at the shared
directory, so programs any peer already compiled load instead of
recompiling (elastic membership without cold-compile storms).
"""
from __future__ import annotations

import argparse
import os
import shutil
import socket
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.distributed import protocol as P

# the worker-local counter vocabulary (docs/cluster_observability.md —
# the doc-drift rule pins every key documented).  Deliberately NOT
# perfcounters.COUNTERS: these live in the WORKER process, which must
# import nothing heavier than stdlib + protocol, and they cross to the
# driver only as heartbeat-piggybacked snapshots the coordinator folds
# into per-worker labeled registry series.
WORKER_COUNTER_KEYS = (
    "store_puts",            # blocks landed (idempotent dedups excluded)
    "store_put_bytes",       # bytes landed
    "store_put_dedups",      # idempotent re-sends dropped (seq existed)
    "store_redrive_puts",    # puts flagged as lineage re-drives
    "store_fetches",         # fetch pages served
    "store_blocks_served",   # blocks returned across fetch pages
    "store_bytes_served",    # bytes returned across fetch pages
    "store_overflow_blocks",  # puts that overflowed memory to disk
    "store_overflow_bytes",  # bytes written to the spill directory
    "put_wall_ns",           # wall inside put handling
    "fetch_wall_ns",         # wall inside fetch handling (page walls)
)


class WorkerTelemetry:
    """Worker-local counters + bounded diagnostics span ring.

    The ring holds one event per traced data-plane op:
    ``{"n": ring-seq, "kind": put|redrive_put|spill|fetch|release,
    "trace": query trace id, "span": driver operator path, "exch",
    "pid", "seq": block seq (-1 when n/a), "bytes", "ts_wall":
    time.time() at op start, "dur_ns"}``.  ``n`` is monotonic per
    worker incarnation so heartbeat deltas and full ``dump`` pulls
    deduplicate on the coordinator's mirror."""

    def __init__(self, ring_capacity: int = 512):
        self._lock = threading.Lock()
        self.ring_capacity = max(int(ring_capacity), 0)
        self.counters: Dict[str, int] = {k: 0 for k in WORKER_COUNTER_KEYS}
        self._ring: deque = deque(maxlen=self.ring_capacity or 1)
        self._seq = 0
        self._last_shipped = 0     # ring seq already heartbeat-shipped

    def bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] = self.counters.get(key, 0) + n

    def span(self, kind: str, trace: str, span: str, exch: int,
             pid: int, seq: int, nbytes: int, ts_wall: float,
             dur_ns: int) -> None:
        if self.ring_capacity <= 0:
            return
        with self._lock:
            self._seq += 1
            self._ring.append({
                "n": self._seq, "kind": kind, "trace": trace,
                "span": span, "exch": int(exch), "pid": int(pid),
                "seq": int(seq), "bytes": int(nbytes),
                "ts_wall": round(float(ts_wall), 6),
                "dur_ns": int(dur_ns)})

    def counters_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.counters)

    def ring_snapshot(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def drain_unshipped(self) -> List[Dict]:
        """Ring entries recorded since the previous heartbeat (the
        piggyback payload) — the mirror dedups on ``n`` anyway, so a
        lost heartbeat only costs the window the ring itself rotated
        out."""
        with self._lock:
            out = [e for e in self._ring if e["n"] > self._last_shipped]
            if out:
                self._last_shipped = out[-1]["n"]
            return out


class PartitionStore:
    """Blocks keyed (exchange, pid) -> ordered (seq, blob) entries, with
    bounded memory residency; over-budget blocks land as files in the
    spill dir (one file per block — blocks are already CRC-framed, so
    disk rot surfaces at deserialize time as ShuffleCorruption)."""

    def __init__(self, mem_bytes: int, spill_dir: Optional[str] = None,
                 telemetry: Optional[WorkerTelemetry] = None):
        self.mem_bytes = max(int(mem_bytes), 0)
        self.telemetry = telemetry
        self._spill_dir = spill_dir
        self._made_spill_dir = spill_dir is None
        self._lock = threading.Lock()
        # (exch, pid) -> {seq: ("mem"|"disk", blob|path)} — keyed by
        # sequence so the idempotent-put dedup is O(1), not a linear
        # scan per block on a thousands-of-blocks partition
        self._parts: Dict[Tuple[int, int],
                          Dict[int, Tuple[str, object]]] = {}
        self._mem_used = 0
        self.blocks = 0
        self.bytes = 0
        self.spilled_blocks = 0

    def _spill_path(self, exch: int, pid: int, seq: int) -> str:
        if self._spill_dir is None:
            # pid-stamped (not mkdtemp-random): a SIGKILLed worker —
            # the central scenario of this tier — cannot clean up after
            # itself, so the name must let reap_stale_spill_dirs()
            # identify dead owners' leftovers later
            self._spill_dir = os.path.join(
                tempfile.gettempdir(), f"srt_dist_worker_{os.getpid()}")
        os.makedirs(self._spill_dir, exist_ok=True)
        return os.path.join(self._spill_dir,
                            f"part_{exch}_{pid}_{seq}.blk")

    def put(self, exch: int, pid: int, seq: int, blob: bytes) -> str:
        """Store one block; returns where it landed — ``"mem"``,
        ``"disk"`` (memory budget overflowed to the spill dir), or
        ``"dup"`` (idempotent re-drive: the block already landed)."""
        tel = self.telemetry
        with self._lock:
            entries = self._parts.setdefault((exch, pid), {})
            if seq in entries:
                if tel is not None:
                    tel.bump("store_put_dedups")
                return "dup"
            if self._mem_used + len(blob) <= self.mem_bytes:
                entries[seq] = ("mem", blob)
                self._mem_used += len(blob)
                kind = "mem"
            else:
                path = self._spill_path(exch, pid, seq)
                with open(path, "wb") as f:
                    f.write(blob)
                entries[seq] = ("disk", path)
                self.spilled_blocks += 1
                kind = "disk"
            self.blocks += 1
            self.bytes += len(blob)
        if tel is not None:
            tel.bump("store_puts")
            tel.bump("store_put_bytes", len(blob))
            if kind == "disk":
                tel.bump("store_overflow_blocks")
                tel.bump("store_overflow_bytes", len(blob))
        return kind

    def fetch(self, exch: int, pid: int, after_seq: int = -1,
              max_bytes: int = 0) -> Tuple[List[int], List[bytes], int]:
        """One PAGE of a partition's blocks: sequences above
        ``after_seq``, up to ~``max_bytes`` (0 = everything; at least
        one block always returns).  Paging keeps a huge reduce
        partition out of any single wire frame and off this process's
        heap — spilled blocks load lazily per page.  Returns (seqs,
        blobs, total block count for the partition)."""
        with self._lock:
            part = self._parts.get((exch, pid), {})
            n_total = len(part)
            entries = sorted((s, kv) for s, kv in part.items()
                             if s > after_seq)
        seqs: List[int] = []
        blobs: List[bytes] = []
        total = 0
        for seq, (kind, x) in entries:
            if kind == "mem":
                blob = x
            else:
                with open(x, "rb") as f:
                    blob = f.read()
            if blobs and max_bytes and total + len(blob) > max_bytes:
                break
            seqs.append(seq)
            blobs.append(blob)
            total += len(blob)
        tel = self.telemetry
        if tel is not None:
            tel.bump("store_fetches")
            tel.bump("store_blocks_served", len(seqs))
            tel.bump("store_bytes_served", total)
        return seqs, blobs, n_total

    def release(self, exch: int) -> int:
        with self._lock:
            victims = [k for k in self._parts if k[0] == exch]
            dropped = 0
            for k in victims:
                for kind, x in self._parts.pop(k).values():
                    dropped += 1
                    self.blocks -= 1
                    if kind == "mem":
                        self._mem_used -= len(x)
                        self.bytes -= len(x)
                    else:
                        try:
                            self.bytes -= os.path.getsize(x)
                            os.unlink(x)
                        except OSError:
                            pass
            return dropped

    def inventory(self) -> List[Tuple[int, int, int, int]]:
        """Every held partition as (exch, pid, n_blocks, max_seq) —
        what a recovery re-HELLO enumerates so a reborn coordinator can
        rebuild its placement map from surviving workers (ISSUE 16)."""
        with self._lock:
            return [(e, p, len(d), max(d) if d else -1)
                    for (e, p), d in sorted(self._parts.items())]

    def stats(self) -> Dict:
        with self._lock:
            return {"blocks": self.blocks, "bytes": self.bytes,
                    "mem_used": self._mem_used,
                    "mem_bytes": self.mem_bytes,
                    "spilled_blocks": self.spilled_blocks,
                    "partitions": len(self._parts)}

    def close(self) -> None:
        with self._lock:
            self._parts.clear()
            self._mem_used = 0
        if self._made_spill_dir and self._spill_dir:
            shutil.rmtree(self._spill_dir, ignore_errors=True)


def reap_stale_spill_dirs() -> int:
    """Remove ``srt_dist_worker_<pid>`` spill dirs whose owning process
    is gone — SIGKILLed workers cannot clean up after themselves, so
    every STARTING worker sweeps the graveyard (best-effort; foreign
    dirs that refuse to die are left alone).  Returns dirs removed."""
    reaped = 0
    tmp = tempfile.gettempdir()
    try:
        names = os.listdir(tmp)
    except OSError:
        return 0
    for name in names:
        if not name.startswith("srt_dist_worker_"):
            continue
        pid_s = name[len("srt_dist_worker_"):]
        if not pid_s.isdigit() or int(pid_s) == os.getpid():
            continue
        try:
            os.kill(int(pid_s), 0)
            continue              # owner still alive
        except ProcessLookupError:
            pass
        except OSError:
            continue              # e.g. EPERM: someone else's pid space
        shutil.rmtree(os.path.join(tmp, name), ignore_errors=True)
        reaped += 1
    return reaped


def _warm_caches(compile_dir: Optional[str]) -> int:
    """Elastic-join cache warming: point the persistent XLA compile
    cache at the shared store so this worker reuses every executable a
    peer already built.  Returns how many cached entries were visible
    at join (0 when warming is off/empty); never raises — a missing
    store must not fail the join."""
    if not compile_dir:
        return 0
    try:
        import jax

        from spark_rapids_tpu.compilecache import ensure_atomic_cache_put

        # N workers + the driver write this SHARED directory; stock
        # jax publishes entries non-atomically (see the helper)
        ensure_atomic_cache_put()
        jax.config.update("jax_compilation_cache_dir", compile_dir)
        return len([f for f in os.listdir(compile_dir)
                    if not f.startswith(".")]) if os.path.isdir(
                        compile_dir) else 0
    except Exception:
        return 0


class WorkerServer:
    """The in-process server object (the CLI main() instantiates one;
    tests drive it directly for protocol-level coverage)."""

    def __init__(self, coordinator: Optional[Tuple[str, int]],
                 worker_id: str,
                 mem_bytes: int = 64 << 20, heartbeat_ms: int = 200,
                 spill_dir: Optional[str] = None,
                 warm_compile_dir: Optional[str] = None,
                 op_timeout_ms: int = 4000,
                 telemetry_ring: int = 512,
                 reattach_ms: int = 0,
                 endpoint_file: Optional[str] = None):
        self.coordinator = coordinator
        self.worker_id = worker_id
        self.heartbeat_s = max(heartbeat_ms, 10) / 1000.0
        self.op_timeout_s = max(op_timeout_ms, 100) / 1000.0
        # crash recovery (ISSUE 16): with a re-attach window the worker
        # OUTLIVES a dead driver — heartbeat loss enters a bounded
        # re-dial loop against the endpoint file the successor
        # coordinator publishes, re-HELLOing with the held-partition
        # inventory.  0 (default) keeps the pre-recovery behavior:
        # membership ends when the control socket dies.
        self.reattach_ms = max(int(reattach_ms), 0)
        self.endpoint_file = endpoint_file
        if spill_dir is None:
            reap_stale_spill_dirs()
        self.telemetry = WorkerTelemetry(telemetry_ring)
        self.store = PartitionStore(mem_bytes, spill_dir,
                                    telemetry=self.telemetry)
        self.warmed_entries = _warm_caches(warm_compile_dir)
        self.mem_bytes = mem_bytes
        self._stop = threading.Event()
        self._reattaching = threading.Event()
        self._listener: Optional[socket.socket] = None
        self._control: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self.data_port: Optional[int] = None

    # -- lifecycle -------------------------------------------------------
    def _resolve_endpoint(self) -> Optional[Tuple[str, int]]:
        """The coordinator endpoint to dial: the endpoint file (re-read
        every attempt — a reborn coordinator publishes a NEW port) when
        configured, else the fixed --coordinator address."""
        if self.endpoint_file:
            try:
                with open(self.endpoint_file) as f:
                    host, port = f.read().strip().rsplit(":", 1)
                return host, int(port)
            except (OSError, ValueError):
                pass
        return self.coordinator

    def _join(self, endpoint: Tuple[str, int],
              reattach: bool) -> socket.socket:
        """Dial + HELLO + welcome on one control socket.  A recovery
        re-HELLO (``reattach``) enumerates the held-partition inventory
        so the coordinator can rebuild placement for journaled stage
        leases."""
        c = P.connect(endpoint[0], endpoint[1], self.op_timeout_s)
        try:
            P.send_msg(c, {
                "op": "hello", "worker_id": self.worker_id,
                "data_port": self.data_port, "pid": os.getpid(),
                "mem_bytes": self.mem_bytes,
                "warmed_entries": self.warmed_entries,
                "reattach": bool(reattach),
                "held": (self.store.inventory() if reattach else []),
                # clock-offset handshake (ISSUE 15): the coordinator
                # estimates offset = its receipt wall-clock minus this,
                # so worker ring timestamps align onto the driver
                # timeline
                "t_wall": time.time()})
            rep, _ = P.recv_msg(c)
            if rep.get("op") != "welcome":
                raise ConnectionError(f"unexpected join reply: {rep}")
        except BaseException:
            try:
                c.close()
            except OSError:
                pass
            raise
        return c

    def start(self) -> None:
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(32)
        self.data_port = self._listener.getsockname()[1]
        endpoint = self._resolve_endpoint()
        if endpoint is None and self.endpoint_file:
            # endpoint-file mode may race the coordinator's startup:
            # wait briefly for the file to appear
            deadline = time.monotonic() + self.op_timeout_s * 4
            while endpoint is None and time.monotonic() < deadline:
                time.sleep(0.05)
                endpoint = self._resolve_endpoint()
        if endpoint is None:
            raise ConnectionError("no coordinator endpoint (neither "
                                  "--coordinator nor a readable "
                                  "endpoint file)")
        self._control = self._join(endpoint, reattach=False)
        for target, name in ((self._serve_loop, "accept"),
                             (self._heartbeat_loop, "heartbeat")):
            t = threading.Thread(
                target=target, daemon=True,
                name=f"srt-dist-worker-{self.worker_id}-{name}")
            t.start()
            self._threads.append(t)

    def stop(self, goodbye: bool = True) -> None:
        self._stop.set()
        if goodbye and self._control is not None:
            try:
                P.send_msg(self._control, {"op": "goodbye",
                                           "worker_id": self.worker_id})
            except OSError:
                pass
        for s in (self._control, self._listener):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._control = self._listener = None
        self.store.close()

    def run_forever(self) -> None:
        """Block until the control socket dies (coordinator gone or it
        evicted us) or stop() is called — the CLI process's main loop.
        A re-attach in progress (ISSUE 16) is NOT a dead control: the
        process must stay up through the bounded re-dial window, or the
        held partitions die with it."""
        while not self._stop.wait(self.heartbeat_s):
            if self._control is None and not self._reattaching.is_set():
                break

    # -- heartbeats ------------------------------------------------------
    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            c = self._control
            if c is None:
                return
            try:
                # telemetry piggyback (ISSUE 15): cumulative counter
                # snapshot + ring entries since the last beat + t_wall —
                # the coordinator's per-worker mirror is what survives
                # this process being SIGKILLed
                P.send_msg(c, {"op": "heartbeat",
                               "worker_id": self.worker_id,
                               "counters":
                                   self.telemetry.counters_snapshot(),
                               "ring": self.telemetry.drain_unshipped(),
                               "t_wall": time.time(),
                               **self.store.stats()})
            except OSError:
                # the coordinator hung up: a LOST declaration closed
                # our socket, or the coordinator itself died.  With a
                # re-attach window (ISSUE 16) the DRIVER dying is
                # survivable — keep the held partitions and re-dial the
                # successor; only an exhausted window ends membership
                if self._try_reattach():
                    continue
                self._stop.set()
                self._control = None
                return

    def _try_reattach(self) -> bool:
        """Bounded re-attach loop (ISSUE 16): re-resolve the endpoint
        (the successor coordinator publishes a NEW port in the endpoint
        file), re-HELLO with the held-partition inventory, and resume
        heartbeating on success.  False when the window is 0 (recovery
        off), stop() raced, or the deadline exhausted — the caller then
        falls back to the pre-recovery death path."""
        if self.reattach_ms <= 0 or self._stop.is_set():
            return False
        self._reattaching.set()
        try:
            old, self._control = self._control, None
            if old is not None:
                try:
                    old.close()
                except OSError:
                    pass
            deadline = time.monotonic() + self.reattach_ms / 1000.0
            while not self._stop.is_set() \
                    and time.monotonic() < deadline:
                endpoint = self._resolve_endpoint()
                if endpoint is not None:
                    try:
                        self._control = self._join(endpoint,
                                                   reattach=True)
                        return True
                    except (OSError, ConnectionError,
                            P.ProtocolCorruption):
                        pass
                if self._stop.wait(min(self.heartbeat_s, 0.2)):
                    return False
            return False
        finally:
            self._reattaching.clear()

    # -- data plane ------------------------------------------------------
    def _serve_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.settimeout(self.op_timeout_s * 4)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True,
                                 name=f"srt-dist-data-{self.worker_id}")
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                try:
                    header, blobs = P.recv_msg(conn)
                except (OSError, ConnectionError):
                    return
                try:
                    reply, rblobs = self._handle(header, blobs)
                except P.ProtocolCorruption as e:
                    reply, rblobs = {"error": f"corrupt: {e}"}, []
                except Exception as e:   # a bad op must not kill the conn
                    reply, rblobs = {
                        "error": f"{type(e).__name__}: {e}"}, []
                # echo the request's correlation id so the client can
                # detect duplicated/reordered reply frames (ISSUE 20,
                # protocol.ProtocolDesync)
                if "rid" in header:
                    reply.setdefault("rid", header["rid"])
                try:
                    P.send_msg(conn, reply, rblobs)
                except OSError:
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, h: Dict, blobs: List[bytes]) -> Tuple[Dict, list]:
        op = h.get("op")
        trace = str(h.get("trace", "") or "")
        span = str(h.get("span", "") or "")
        tel = self.telemetry
        if op == "put":
            t_wall = time.time()
            t0 = time.perf_counter_ns()
            blob = blobs[0] if blobs else b""
            redrive = bool(h.get("redrive"))
            landed = self.store.put(int(h["exch"]), int(h["pid"]),
                                    int(h["seq"]), blob)
            dur = time.perf_counter_ns() - t0
            tel.bump("put_wall_ns", dur)
            if redrive and landed != "dup":
                tel.bump("store_redrive_puts")
            # untraced frames (tracing off, non-query tooling) record
            # counters only — a span without a trace id could never be
            # attributed and would just rotate attributed history out
            # of the bounded ring
            if trace and landed != "dup":
                kind = ("redrive_put" if redrive
                        else "spill" if landed == "disk" else "put")
                tel.span(kind, trace, span, int(h["exch"]),
                         int(h["pid"]), int(h["seq"]), len(blob),
                         t_wall, dur)
            return {"ok": True}, []
        if op == "fetch":
            t_wall = time.time()
            t0 = time.perf_counter_ns()
            seqs, out, n_total = self.store.fetch(
                int(h["exch"]), int(h["pid"]),
                after_seq=int(h.get("after_seq", -1)),
                max_bytes=int(h.get("max_bytes", 0)))
            dur = time.perf_counter_ns() - t0
            tel.bump("fetch_wall_ns", dur)
            if trace and seqs:
                tel.span("fetch", trace, span, int(h["exch"]),
                         int(h["pid"]), seqs[-1],
                         sum(len(b) for b in out), t_wall, dur)
            return {"ok": True, "seqs": seqs, "n_total": n_total}, out
        if op == "release":
            t_wall = time.time()
            t0 = time.perf_counter_ns()
            dropped = self.store.release(int(h["exch"]))
            if trace and dropped:
                tel.span("release", trace, span, int(h["exch"]), -1, -1,
                         0, t_wall, time.perf_counter_ns() - t0)
            return {"ok": True, "dropped": dropped}, []
        if op == "stats":
            return {"ok": True, **self.store.stats()}, []
        if op == "dump":
            # the on-demand telemetry pull (ISSUE 15): full ring +
            # counter snapshot + clock sample, same shape as the
            # heartbeat piggyback so the coordinator mirror folds both
            return {"ok": True, "worker_id": self.worker_id,
                    "pid": os.getpid(),
                    "counters": tel.counters_snapshot(),
                    "ring": tel.ring_snapshot(),
                    "t_wall": time.time(),
                    **self.store.stats()}, []
        if op == "ping":
            return {"ok": True, "worker_id": self.worker_id}, []
        return {"error": f"unknown op {op!r}"}, []


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--coordinator", default=None,
                    help="host:port of the coordinator's listener "
                         "(or use --endpoint-file)")
    ap.add_argument("--endpoint-file", default=None,
                    help="path of the coordinator.endpoint file under "
                         "the recovery root — re-read on every "
                         "(re-)attach so a reborn coordinator's new "
                         "port is found (ISSUE 16)")
    ap.add_argument("--reattach-ms", type=int, default=0,
                    help="on heartbeat loss, re-dial the coordinator "
                         "for up to this many ms instead of exiting "
                         "(0: exit immediately — pre-recovery "
                         "behavior)")
    ap.add_argument("--worker-id",
                    default=f"w-{os.getpid()}")
    ap.add_argument("--mem-bytes", type=int, default=64 << 20)
    ap.add_argument("--heartbeat-ms", type=int, default=200)
    ap.add_argument("--op-timeout-ms", type=int, default=4000)
    ap.add_argument("--spill-dir", default=None)
    ap.add_argument("--warm-compile-dir", default=None)
    ap.add_argument("--telemetry-ring", type=int, default=512,
                    help="worker-local diagnostics ring capacity "
                         "(0 disables span recording; counters still "
                         "federate over heartbeats)")
    args = ap.parse_args(argv)
    if not args.coordinator and not args.endpoint_file:
        ap.error("one of --coordinator / --endpoint-file is required")

    srv = WorkerServer(
        (P.parse_endpoint(args.coordinator)
         if args.coordinator else None), args.worker_id,
        mem_bytes=args.mem_bytes, heartbeat_ms=args.heartbeat_ms,
        spill_dir=args.spill_dir, warm_compile_dir=args.warm_compile_dir,
        op_timeout_ms=args.op_timeout_ms,
        telemetry_ring=args.telemetry_ring,
        reattach_ms=args.reattach_ms,
        endpoint_file=args.endpoint_file)
    try:
        srv.start()
    except OSError as e:
        print(f"worker {args.worker_id}: cannot join: {e}",
              file=sys.stderr)
        return 1
    try:
        srv.run_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
