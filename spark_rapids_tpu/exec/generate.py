"""TpuGenerateExec (explode/posexplode) + TpuExpandExec +
TpuBroadcastNestedLoopJoinExec.

Reference analogs (SURVEY.md §2.4): GpuGenerateExec.scala,
GpuExpandExec.scala, GpuBroadcastNestedLoopJoinExec.

TPU designs:
  * explode: the same two-index gather-map expansion the joins use — output
    row j maps to (source row, element) via searchsorted over the prefix
    sum of per-row element counts; one jitted program, one host sync for
    the total (output capacity bucket).
  * expand: one projected batch per projection set, concatenated on device.
  * BNLJ: chunked cartesian expansion with the condition fused in; SEMI /
    ANTI / LEFT OUTER reduce a per-left-row match flag across right chunks.
"""
from __future__ import annotations

from typing import Iterator, List, Optional

import jax
from spark_rapids_tpu.perfcounters import sync_get, tpu_jit
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import (
    DEFAULT_ROW_BUCKETS,
    DeviceColumn,
    round_up_bucket,
)
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.expr.base import EvalContext, Expression
from spark_rapids_tpu.ops.filterops import compact_columns, gather_columns
from spark_rapids_tpu.plan.nodes import JoinType


class TpuGenerateExec(TpuExec):
    def __init__(self, gen_expr: Expression, child: TpuExec,
                 position: bool, outer: bool, output_schema: T.StructType,
                 ansi: bool = False):
        super().__init__([child])
        self.gen_expr = gen_expr
        self.position = position
        self.outer = outer
        self._output = output_schema
        self.ansi = ansi
        self._jits = {}

    @property
    def output(self):
        return self._output

    def describe(self):
        kind = "posexplode" if self.position else "explode"
        return f"TpuGenerate {kind}({self.gen_expr.sql_string()})"

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        for batch in self.children[0].execute_columnar():
            with self.metrics["opTime"].timed():
                out = self._generate(batch)
            if out is not None:
                yield self._count_output(out)

    def _counts(self, batch: ColumnarBatch):
        def fn(cols, num_rows):
            b = ColumnarBatch(list(cols), num_rows, batch.schema)
            ctx = EvalContext(b, ansi=self.ansi)
            arr = self.gen_expr.eval_tpu(ctx)
            eff = jnp.where(arr.validity, arr.lengths, 0)
            if self.outer:
                eff = jnp.maximum(eff, 1)
            eff = jnp.where(b.row_mask, eff, 0)
            return eff, jnp.sum(eff.astype(jnp.int64))

        if "counts" not in self._jits:
            self._jits["counts"] = tpu_jit(fn)
        return self._jits["counts"](tuple(batch.columns),
                                    jnp.int32(batch.num_rows))

    def _generate(self, batch: ColumnarBatch) -> Optional[ColumnarBatch]:
        eff, total = self._counts(batch)
        total = int(total)
        if total == 0:
            return None
        out_cap = round_up_bucket(total, DEFAULT_ROW_BUCKETS)

        def fn(cols, eff, num_rows, total):
            b = ColumnarBatch(list(cols), num_rows, batch.schema)
            ctx = EvalContext(b, ansi=self.ansi)
            arr = self.gen_expr.eval_tpu(ctx)
            from spark_rapids_tpu.exec.join import _slots_to_probe_rows

            offsets = jnp.cumsum(eff.astype(jnp.int64))
            excl = offsets - eff.astype(jnp.int64)
            j = jnp.arange(out_cap, dtype=jnp.int64)
            src = _slots_to_probe_rows(excl, eff, out_cap)
            k = (j - excl[src]).astype(jnp.int32)
            row_valid = j < total
            out_cols = gather_columns(src, row_valid, b.columns)
            ew = max(arr.ewidth, 1)
            ksafe = jnp.clip(k, 0, ew - 1)
            if arr.is_string_array:
                elem_chars = arr.chars[src, ksafe]       # (out_cap, w)
                elem_lens = arr.data[src, ksafe]
                elem = None
            else:
                elem = arr.data[src, ksafe] if arr.ewidth else jnp.zeros(
                    out_cap, arr.data.dtype)
            ev = arr.elem_valid[src, ksafe] if arr.ewidth else jnp.zeros(
                out_cap, jnp.bool_)
            # outer rows synthesized for empty/null arrays have k==0 but no
            # real element (and a NULL pos, matching Spark posexplode_outer)
            in_arr = (k < arr.lengths[src]) & arr.validity[src]
            if self.position:
                out_cols.append(DeviceColumn(
                    T.INT, row_valid & in_arr, data=k))
            if arr.is_string_array:
                out_cols.append(DeviceColumn(
                    self._output.fields[-1].dataType,
                    row_valid & ev & in_arr, chars=elem_chars,
                    lengths=elem_lens.astype(jnp.int32)))
            else:
                out_cols.append(DeviceColumn(
                    self._output.fields[-1].dataType,
                    row_valid & ev & in_arr, data=elem))
            return tuple(out_cols)

        key = ("gen", out_cap)
        if key not in self._jits:
            self._jits[key] = tpu_jit(fn)
        cols = self._jits[key](tuple(batch.columns), eff,
                               jnp.int32(batch.num_rows), jnp.int64(total))
        return ColumnarBatch(list(cols), total, self._output)


def _stack_sel(arrs, p, i):
    """Select across P stacked per-projection arrays: ``out[j] =
    arrs[p[j]][i[j], ...]``.  Trailing dims pad to the common max
    (string char widths differ per projection)."""
    tails = {a.shape[1:] for a in arrs}
    if len(tails) > 1:
        rank = len(arrs[0].shape) - 1
        maxs = tuple(max(a.shape[1 + d] for a in arrs)
                     for d in range(rank))
        arrs = [jnp.pad(a, [(0, 0)] + [(0, m - s) for m, s
                                       in zip(maxs, a.shape[1:])])
                for a in arrs]
    if len(arrs) == 1:
        return arrs[0][i]
    return jnp.stack(arrs)[p, i]


def _select_variant(vcols, p, i, row_valid):
    """One output DeviceColumn from P per-projection variants: row j
    takes projection p[j]'s row i[j] — the device-side concatenation of
    expand's projected batches (recursing into struct children)."""
    c0 = vcols[0]
    validity = _stack_sel([v.validity for v in vcols], p, i) & row_valid
    if c0.is_struct:
        kids = tuple(
            _select_variant([v.children[k] for v in vcols], p, i,
                            row_valid)
            for k in range(len(c0.children)))
        return DeviceColumn(c0.dtype, validity, children=kids)

    def pick(attr):
        vals = [getattr(v, attr) for v in vcols]
        if any(x is None for x in vals):
            return None
        return _stack_sel(vals, p, i)

    return DeviceColumn(c0.dtype, validity, data=pick("data"),
                        chars=pick("chars"), lengths=pick("lengths"),
                        elem_valid=pick("elem_valid"))


class TpuExpandExec(TpuExec):
    def __init__(self, projections: List[List[Expression]], child: TpuExec,
                 output_schema: T.StructType, ansi: bool = False):
        super().__init__([child])
        self.projections = projections
        self._output = output_schema
        self.ansi = ansi
        self._jit = None

    @property
    def output(self):
        return self._output

    def describe(self):
        return f"TpuExpand [{len(self.projections)} projections]"

    def fusion_segment(self):
        """Whole-plan fusion slice (exec/fusion.py): ALL projections in
        one traced program, device-concatenated — output row j takes
        projection ``j // n``'s input row ``j % n``, so P launches and
        P batches per input become one launch and one batch.  The ANSI
        message aux travels with the fused executable as registry aux
        (the manifest's fusable-with-rewrite rewrite for Expand)."""
        from spark_rapids_tpu.compilecache.keys import exprs_fp, schema_fp
        from spark_rapids_tpu.exec.fusion import PipelineSegment

        projections = self.projections
        ansi = self.ansi
        out_schema = self._output
        P = len(projections)
        efp = exprs_fp([e for proj in projections for e in proj])

        def make(in_schema):
            msgs: List[str] = []

            def fn(cols, num_rows):
                b = ColumnarBatch(list(cols), num_rows, in_schema)
                cap = b.capacity
                out_cap = round_up_bucket(max(P * cap, 1),
                                          DEFAULT_ROW_BUCKETS)
                variants, flags, acc = [], [], []
                for proj in projections:
                    ctx = EvalContext(b, ansi=ansi)
                    variants.append([e.eval_tpu(ctx) for e in proj])
                    flags.extend(jnp.any(f) for f, _ in ctx.error_flags)
                    acc.extend(m for _, m in ctx.error_flags)
                # tpulint: disable=trace-closure-state (deliberate
                # trace-time aux: travels WITH the fused executable)
                msgs.clear()
                # tpulint: disable=trace-closure-state (same aux store)
                msgs.extend(acc)
                n = num_rows.astype(jnp.int64)
                nsafe = jnp.maximum(n, 1)
                j = jnp.arange(out_cap, dtype=jnp.int64)
                p = jnp.clip(j // nsafe, 0, P - 1).astype(jnp.int32)
                i = jnp.clip(j % nsafe, 0, cap - 1).astype(jnp.int32)
                row_valid = j < (P * n)
                out_cols = [
                    _select_variant([v[k] for v in variants], p, i,
                                    row_valid)
                    for k in range(len(out_schema.fields))]
                return (tuple(out_cols), (P * n).astype(jnp.int32),
                        tuple(flags))

            return fn, msgs

        return PipelineSegment(
            name=self.describe(),
            fp=None if efp is None else (
                "expand", efp, P, schema_fp(out_schema), bool(ansi)),
            make=make,
            out_schema=out_schema,
            count_map=lambda n: P * n,
            programs_unfused=P)

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        for batch in self.children[0].execute_columnar():
            with self.metrics["opTime"].timed():
                for proj_idx in range(len(self.projections)):
                    out = self._one(batch, proj_idx)
                    yield self._count_output(out)

    def _one(self, batch: ColumnarBatch, proj_idx: int) -> ColumnarBatch:
        msgs = []

        def fn(cols, num_rows):
            b = ColumnarBatch(list(cols), num_rows, batch.schema)
            ctx = EvalContext(b, ansi=self.ansi)
            out = tuple(e.eval_tpu(ctx) for e in self.projections[proj_idx])
            # tpulint: disable=trace-closure-state (deliberate trace-time
            # aux: the msgs list is cached WITH the jit in self._jit)
            msgs.clear()
            # tpulint: disable=trace-closure-state (same aux store)
            msgs.extend(m for _, m in ctx.error_flags)
            return out, tuple(jnp.any(f) for f, _ in ctx.error_flags)

        key = ("expand", proj_idx)
        if self._jit is None:
            self._jit = {}
        if key not in self._jit:
            self._jit[key] = (tpu_jit(fn), msgs)
        jitted, msgs = self._jit[key]
        cols, flags = jitted(tuple(batch.columns),
                             jnp.int32(batch.num_rows))
        from spark_rapids_tpu.expr.base import SparkArithmeticException

        # all error flags in ONE logical round trip — a per-flag bool()
        # was a device sync per flag per batch (trace-split-sync)
        host_flags = sync_get(tuple(flags)) if flags else ()
        for f, m in zip(host_flags, list(msgs)):
            if f:
                raise SparkArithmeticException(m)
        return ColumnarBatch(list(cols), batch.num_rows, self._output)


class TpuBroadcastNestedLoopJoinExec(TpuExec):
    """Non-equi join: condition over the cartesian expansion, chunked so a
    left-chunk x right product stays within one capacity bucket."""

    MAX_PRODUCT = 1 << 20

    def __init__(self, left: TpuExec, right: TpuExec, join_type: JoinType,
                 condition: Optional[Expression],
                 output_schema: T.StructType, ansi: bool = False):
        super().__init__([left, right])
        self.join_type = join_type
        self.condition = condition
        self._output = output_schema
        self.ansi = ansi
        self._jits = {}

    @property
    def output(self):
        return self._output

    def describe(self):
        c = self.condition.sql_string() if self.condition is not None else ""
        return f"TpuBroadcastNestedLoopJoin {self.join_type.value} [{c}]"

    def _cached(self, key, fn):
        if key not in self._jits:
            self._jits[key] = tpu_jit(fn)
        return self._jits[key]

    def _match_key_parts(self, lb, rbatch, key):
        """Registry key for the match program, or None (private entry)
        when the condition is unfingerprintable."""
        from spark_rapids_tpu.compilecache.keys import (
            conf_fp,
            exprs_fp,
            schema_fp,
        )

        cfp = exprs_fp([self.condition]
                       if self.condition is not None else [])
        if cfp is None:
            return None
        return ("bnlj", cfp, self.join_type.value, bool(self.ansi),
                schema_fp(lb.schema), schema_fp(rbatch.schema), key,
                conf_fp())

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        right_batches = list(self.children[1].execute_columnar())
        if right_batches:
            rbatch = (right_batches[0] if len(right_batches) == 1
                      else ColumnarBatch.concat(right_batches))
        else:
            from spark_rapids_tpu.columnar.batch import empty_batch

            rbatch = empty_batch(self.children[1].output)
        nright = rbatch.num_rows
        jt = self.join_type
        pair_schema = T.StructType(
            list(self.children[0].output.fields)
            + [T.StructField(f.name, f.dataType, True)
               for f in rbatch.schema.fields])
        chunk = max(1, self.MAX_PRODUCT // max(nright, 1))
        for lb in self.children[0].execute_columnar():
            start = 0
            while start < lb.num_rows or (lb.num_rows == 0 and start == 0):
                piece = lb.slice_rows(start, min(chunk, lb.num_rows - start)) \
                    if lb.num_rows else lb
                start += chunk
                out = self._join_chunk(piece, rbatch, nright, jt, pair_schema)
                if out is not None and out.num_rows > 0:
                    yield self._count_output(out)
                if lb.num_rows == 0:
                    break

    def _join_chunk(self, lb: ColumnarBatch, rbatch: ColumnarBatch,
                    nright: int, jt: JoinType, pair_schema):
        flag_msgs_store = flag_msgs = []
        nl = lb.num_rows
        if jt in (JoinType.INNER, JoinType.CROSS):
            if nl * nright == 0:
                return None
        out_cap = round_up_bucket(max(nl * max(nright, 1), 1),
                                  DEFAULT_ROW_BUCKETS)
        # locals only: a registry-shared closure over ``self``/``lb``
        # would pin the exec subtree and the left batch's device buffers
        # for as long as the entry lives
        condition, ansi, l_cap = self.condition, self.ansi, lb.capacity

        def match_fn(lcols, rcols, n_l, n_r):
            """(matched pairs flags + per-left any-match) on the expansion."""
            j = jnp.arange(out_cap, dtype=jnp.int64)
            nr = jnp.maximum(n_r, 1)
            li = (j // nr).astype(jnp.int32)
            ri = (j % nr).astype(jnp.int32)
            pair_ok = j < n_l * n_r
            lo = gather_columns(li, pair_ok, list(lcols))
            ro = gather_columns(ri, pair_ok, list(rcols))
            pb = ColumnarBatch(list(lo) + list(ro),
                               (n_l * n_r).astype(jnp.int32), pair_schema)
            flags = ()
            if condition is not None:
                ctx = EvalContext(pb, ansi=ansi)
                pred = condition.eval_tpu(ctx)
                ok = pred.data & pred.validity & pair_ok
                flags = tuple(jnp.any(f) for f, _ in ctx.error_flags)
                # tpulint: disable=trace-closure-state (deliberate
                # trace-time aux: travels WITH the executable as the
                # registry entry's aux)
                flag_msgs.clear()
                # tpulint: disable=trace-closure-state (same aux store)
                flag_msgs.extend(m for _, m in ctx.error_flags)
            else:
                ok = pair_ok
            li_safe = jnp.where(pair_ok, li, 0).astype(jnp.int32)
            li_safe = jnp.clip(li_safe, 0, l_cap - 1)
            any_match = jax.ops.segment_max(
                jnp.where(ok, 1, 0), li_safe,
                num_segments=l_cap) > 0
            return tuple(lo), tuple(ro), ok, any_match, flags

        key = ("match", out_cap, lb.capacity)
        if key not in self._jits:
            # the match program routes through the compile-cache registry
            # with the trace-time flag-message aux traveling WITH the
            # executable (entry.aux) — the manifest's fusable-with-
            # rewrite rewrite for BroadcastNestedLoopJoin; an
            # unfingerprintable condition keys None (instance-private
            # entry, correct just not shared)
            from spark_rapids_tpu.compilecache.registry import (
                cached_program,
            )

            entry = cached_program(
                self._match_key_parts(lb, rbatch, key),
                lambda: (tpu_jit(match_fn), flag_msgs_store),
                label=f"bnlj:{self.describe()[:44]}")
            self._jits[key] = (entry.jitted, entry.aux)
        mf, flag_msgs = self._jits[key]
        lo, ro, ok, any_match, flags = mf(
            tuple(lb.columns), tuple(rbatch.columns),
            jnp.int64(nl), jnp.int64(nright))
        from spark_rapids_tpu.expr.base import SparkArithmeticException

        # all condition error flags in ONE logical round trip — a
        # per-flag bool() was a device sync per flag per chunk
        # (trace-split-sync)
        host_flags = sync_get(tuple(flags)) if flags else ()
        for f, m in zip(host_flags, list(flag_msgs)):
            if f:
                raise SparkArithmeticException(m)
        if jt in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI):
            def compact_fn(cols, flags, num_rows):
                b = ColumnarBatch(list(cols), num_rows, lb.schema)
                keep = flags if jt == JoinType.LEFT_SEMI else ~flags
                keep = keep & b.row_mask
                out, cnt = compact_columns(keep, b.columns)
                return tuple(out), cnt

            cf = self._cached(("semi", jt.value, lb.capacity), compact_fn)
            cols, cnt = cf(tuple(lb.columns), any_match,
                           jnp.int32(lb.num_rows))
            n = int(cnt)
            return ColumnarBatch(list(cols), n, self._output) if n else None
        # INNER / CROSS / LEFT_OUTER: compact matched pairs; LEFT_OUTER
        # appends unmatched left rows with null right side
        def pairs_fn(lo, ro, ok):
            cols = list(lo) + list(ro)
            out, cnt = compact_columns(ok, cols)
            return tuple(out), cnt

        pf = self._cached(("pairs", out_cap), pairs_fn)
        pcols, pcnt = pf(lo, ro, ok)
        n_pairs = int(pcnt)
        parts = []
        if n_pairs:
            parts.append(ColumnarBatch(list(pcols), n_pairs, self._output))
        if jt == JoinType.LEFT_OUTER:
            def unmatched_fn(cols, flags, num_rows):
                b = ColumnarBatch(list(cols), num_rows, lb.schema)
                keep = ~flags & b.row_mask
                out, cnt = compact_columns(keep, b.columns)
                return tuple(out), cnt

            uf = self._cached(("um", lb.capacity), unmatched_fn)
            ucols, ucnt = uf(tuple(lb.columns), any_match,
                             jnp.int32(lb.num_rows))
            n_um = int(ucnt)
            if n_um:
                cap = lb.capacity
                rfields = rbatch.schema.fields
                null_right = []
                for f in rfields:
                    if isinstance(f.dataType, T.StringType):
                        null_right.append(DeviceColumn(
                            f.dataType, jnp.zeros(cap, jnp.bool_),
                            chars=jnp.zeros((cap, 8), jnp.uint8),
                            lengths=jnp.zeros(cap, jnp.int32)))
                    else:
                        shape = ((cap, 2) if isinstance(f.dataType,
                                                        T.DecimalType)
                                 and f.dataType.is_128 else (cap,))
                        null_right.append(DeviceColumn(
                            f.dataType, jnp.zeros(cap, jnp.bool_),
                            data=jnp.zeros(shape,
                                           T.storage_dtype(f.dataType))))
                parts.append(ColumnarBatch(
                    list(ucols) + null_right, n_um, self._output))
        if not parts:
            return None
        return parts[0] if len(parts) == 1 else ColumnarBatch.concat(parts)
