"""TpuCoalesceBatchesExec — batch concatenation to a size goal.

Reference analog: GpuCoalesceBatches / CoalesceGoal / RequireSingleBatch +
GpuShuffleCoalesceExec (SURVEY.md §2.3): small batches are concatenated up to
``spark.rapids.sql.batchSizeBytes`` before expensive operators.  On TPU this
additionally *re-buckets* row capacity and string widths so downstream ops
compile against fewer shapes.
"""
from __future__ import annotations

from typing import Iterator, List, Optional

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import TpuExec


class CoalesceGoal:
    def __init__(self, target_bytes: Optional[int] = None,
                 require_single: bool = False):
        self.target_bytes = target_bytes or (1 << 30)
        self.require_single = require_single

    @staticmethod
    def require_single_batch() -> "CoalesceGoal":
        return CoalesceGoal(require_single=True)


class TpuCoalesceBatchesExec(TpuExec):
    EXTRA_METRICS = {"concatTime": "MODERATE"}

    def __init__(self, goal: CoalesceGoal, child: TpuExec):
        super().__init__([child])
        self.goal = goal

    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        g = "RequireSingleBatch" if self.goal.require_single else \
            f"TargetSize({self.goal.target_bytes})"
        return f"TpuCoalesceBatches {g}"

    def _aot_one_flush(self) -> bool:
        """Plan-time guess: with a production-scale byte goal the whole
        input coalesces into one flush; a deliberately tiny goal (tests,
        re-bucketing configs) means one flush per input batch."""
        return self.goal.require_single \
            or self.goal.target_bytes >= (32 << 20)

    def aot_output_rows(self):
        """Shape estimate: one batch of the total row count under the
        one-flush guess, else the child's batching passes through.  A
        wrong guess only costs one speculative background compile;
        correctness never depends on it."""
        rows = self.aot_input_rows()
        if rows is None:
            return None
        return [sum(rows)] if self._aot_one_flush() else rows

    def aot_emits_single_batch(self):
        # claim a single output batch only when the flush heuristic says
        # so (or the input is single anyway): downstream single-batch
        # fused programs are only warmed when they will actually dispatch
        return (self._aot_one_flush()
                and self.aot_input_rows() is not None) \
            or self.aot_child_single_batch()

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        """Pending batches are held *spillable* while more input streams in
        (reference: the coalesce iterator's batches are
        SpillableColumnarBatch), and the concat runs in a retry block."""
        from spark_rapids_tpu.memory.spill import get_spill_framework

        fw = get_spill_framework()
        pending: List = []   # SpillableColumnarBatch
        pending_bytes = 0
        with self.metric("concatTime").timed():
            for b in self.children[0].execute_columnar():
                nb = b.nbytes()
                if (pending and not self.goal.require_single
                        and pending_bytes + nb > self.goal.target_bytes):
                    yield self._flush(pending)
                    pending, pending_bytes = [], 0
                pending.append(fw.track(b))
                pending_bytes += nb
        if pending:
            yield self._flush(pending)

    def _flush(self, pending: List) -> ColumnarBatch:
        from spark_rapids_tpu.memory.retry import with_retry_no_split

        def concat():
            for s in pending:
                s.pin()
            try:
                batches = [s.get_batch() for s in pending]
                return (batches[0] if len(batches) == 1
                        else ColumnarBatch.concat(batches))
            finally:
                for s in pending:
                    s.unpin()

        out = with_retry_no_split(concat)
        for s in pending:
            s.close()
        return self._count_output(out)
