"""TpuSortExec / TpuTopNExec.

Reference analog: GpuSortExec + GpuOutOfCoreSortIterator + GpuTopN
(SURVEY.md §2.4).  In-core path: one lax.sort over packed key words per shape
bucket.  Out-of-core path (big inputs): each input batch is sorted in-core,
sorted runs are kept spillable, and an N-way merge re-sorts run heads in
memory-bounded windows — see mem/spill.py integration (round 1 keeps runs
device-resident; spill hooks land with the memory runtime).
"""
from __future__ import annotations

from typing import Iterator, List, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.expr.base import EvalContext, Expression
from spark_rapids_tpu.ops.sortkeys import SortSpec, sort_permutation


def _gather_batch(batch: ColumnarBatch, perm, num_rows,
                  schema) -> ColumnarBatch:
    cols = []
    for c in batch.columns:
        if c.is_string:
            cols.append(DeviceColumn(c.dtype, c.validity[perm],
                                     chars=c.chars[perm],
                                     lengths=c.lengths[perm]))
        else:
            cols.append(DeviceColumn(c.dtype, c.validity[perm],
                                     data=c.data[perm]))
    return ColumnarBatch(cols, num_rows, schema)


class TpuSortExec(TpuExec):
    def __init__(self, orders: List[Tuple[Expression, SortSpec]],
                 is_global: bool, child: TpuExec, ansi: bool = False):
        super().__init__([child])
        self.orders = orders
        self.is_global = is_global
        self.ansi = ansi

    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        o = ", ".join(f"{e.sql_string()} {'ASC' if s.ascending else 'DESC'}"
                      for e, s in self.orders)
        return f"TpuSort [{o}]"

    def _sort_fn(self, schema):
        if getattr(self, "_jitted", None) is not None:
            return self._jitted
        orders = self.orders
        ansi = self.ansi

        def fn(cols, num_rows):
            batch = ColumnarBatch(list(cols), num_rows, schema)
            ctx = EvalContext(batch, ansi=ansi)
            key_cols = [e.eval_tpu(ctx) for e, _ in orders]
            specs = [s for _, s in orders]
            perm = sort_permutation(key_cols, specs, batch.row_mask)
            out = _gather_batch(batch, perm, num_rows, schema)
            return tuple(out.columns)

        self._jitted = jax.jit(fn)
        return self._jitted

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.memory.retry import with_retry_no_split
        from spark_rapids_tpu.memory.spill import get_spill_framework

        fw = get_spill_framework()
        spillables = [fw.track(b)
                      for b in self.children[0].execute_columnar()]
        if not spillables:
            return
        with self.metric("sortTime").timed():
            def run():
                for s in spillables:
                    s.pin()
                try:
                    batches = [s.get_batch() for s in spillables]
                    batch = (batches[0] if len(batches) == 1
                             else ColumnarBatch.concat(batches))
                    fn = self._sort_fn(batch.schema)
                    cols = fn(tuple(batch.columns), jnp.int32(batch.num_rows))
                    return ColumnarBatch(list(cols), batch.num_rows,
                                         batch.schema)
                finally:
                    for s in spillables:
                        s.unpin()

            out = with_retry_no_split(run)
            for s in spillables:
                s.close()
        yield self._count_output(out)


class TpuTopNExec(TpuExec):
    """sort + limit fused: keeps only n rows per batch then merges.

    Reference analog: GpuTopN in limit.scala — sort each batch, slice to n,
    concat + re-sort + slice; avoids materializing the full sort."""

    def __init__(self, n: int, orders: List[Tuple[Expression, SortSpec]],
                 child: TpuExec, ansi: bool = False):
        super().__init__([child])
        self.n = n
        self.orders = orders
        self.ansi = ansi

    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        return f"TpuTopN {self.n}"

    def execute_columnar(self):
        sorter = TpuSortExec(self.orders, True, self.children[0], self.ansi)
        pending: List[ColumnarBatch] = []
        for b in self.children[0].execute_columnar():
            fn = sorter._sort_fn(b.schema)
            cols = fn(tuple(b.columns), jnp.int32(b.num_rows))
            sb = ColumnarBatch(list(cols), b.num_rows, b.schema)
            pending.append(sb.slice_rows(0, min(self.n, sb.num_rows)))
            if len(pending) > 8:
                pending = [self._merge(pending, sorter)]
        if not pending:
            return
        out = self._merge(pending, sorter)
        yield self._count_output(out)

    def _merge(self, batches, sorter):
        merged = (batches[0] if len(batches) == 1
                  else ColumnarBatch.concat(batches))
        fn = sorter._sort_fn(merged.schema)
        cols = fn(tuple(merged.columns), jnp.int32(merged.num_rows))
        sb = ColumnarBatch(list(cols), merged.num_rows, merged.schema)
        return sb.slice_rows(0, min(self.n, sb.num_rows))
