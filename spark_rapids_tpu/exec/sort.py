"""TpuSortExec / TpuTopNExec.

Reference analog: GpuSortExec + GpuOutOfCoreSortIterator + GpuTopN
(SURVEY.md §2.4).  In-core path: one lax.sort over packed key words per shape
bucket.  Out-of-core path (big inputs): each input batch is sorted in-core,
sorted runs are kept spillable, and an N-way merge re-sorts run heads in
memory-bounded windows — see mem/spill.py integration (round 1 keeps runs
device-resident; spill hooks land with the memory runtime).
"""
from __future__ import annotations

from typing import Iterator, List, Tuple

import jax
from spark_rapids_tpu.perfcounters import tpu_jit
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.expr.base import EvalContext, Expression
from spark_rapids_tpu.ops.sortkeys import SortSpec, sort_permutation


def _gather_batch(batch: ColumnarBatch, perm, num_rows,
                  schema) -> ColumnarBatch:
    cols = []
    for c in batch.columns:
        if c.is_string:
            cols.append(DeviceColumn(c.dtype, c.validity[perm],
                                     chars=c.chars[perm],
                                     lengths=c.lengths[perm]))
        else:
            cols.append(DeviceColumn(c.dtype, c.validity[perm],
                                     data=c.data[perm]))
    return ColumnarBatch(cols, num_rows, schema)


class TpuSortExec(TpuExec):
    # declared up front with reference levels (GpuSortExec metrics)
    EXTRA_METRICS = {"sortTime": "MODERATE"}

    def __init__(self, orders: List[Tuple[Expression, SortSpec]],
                 is_global: bool, child: TpuExec, ansi: bool = False,
                 ooc_bytes: int = 1 << 30, ooc_chunk_rows: int = 1024):
        super().__init__([child])
        self.orders = orders
        self.is_global = is_global
        self.ansi = ansi
        # out-of-core threshold + merge window chunk (GpuOutOfCoreSortIterator
        # analog: inputs beyond the goal sort as spillable runs + k-way merge)
        self.ooc_bytes = ooc_bytes
        self.ooc_chunk_rows = ooc_chunk_rows

    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        o = ", ".join(f"{e.sql_string()} {'ASC' if s.ascending else 'DESC'}"
                      for e, s in self.orders)
        return f"TpuSort [{o}]"

    def _sort_program(self, schema):
        """(registry key parts, factory) — shared by the runtime path and
        the plan-time AOT enumeration."""
        from spark_rapids_tpu.compilecache.keys import (
            conf_fp,
            exprs_fp,
            schema_fp,
        )

        orders = self.orders
        ansi = self.ansi
        okeys = exprs_fp([e for e, _ in orders])
        key_parts = None if okeys is None else (
            "sort", schema_fp(schema), okeys,
            tuple((s.ascending, s.nulls_first) for _, s in orders),
            bool(ansi), conf_fp())

        def factory():
            def fn(cols, num_rows):
                batch = ColumnarBatch(list(cols), num_rows, schema)
                ctx = EvalContext(batch, ansi=ansi)
                key_cols = [e.eval_tpu(ctx) for e, _ in orders]
                specs = [s for _, s in orders]
                perm = sort_permutation(key_cols, specs, batch.row_mask)
                out = _gather_batch(batch, perm, num_rows, schema)
                return tuple(out.columns)

            return tpu_jit(fn), None

        return key_parts, factory

    def _sort_fn(self, schema):
        if getattr(self, "_jitted", None) is not None:
            return self._jitted
        from spark_rapids_tpu.compilecache.registry import cached_program

        key_parts, factory = self._sort_program(schema)
        self._jitted = cached_program(key_parts, factory,
                                      label=self.describe()).jitted
        return self._jitted

    def aot_output_rows(self):
        # global sort concatenates the whole input into one batch
        rows = self.aot_input_rows()
        return None if rows is None else [sum(rows)]

    def aot_output_caps(self):
        caps = super().aot_output_caps()
        return caps if caps is not None else self.aot_input_concat_caps()

    def aot_emits_single_batch(self):
        return True

    def aot_programs(self):
        from spark_rapids_tpu.compilecache.aot import (
            AotProgram,
            dummy_batch_args,
        )

        caps = self.aot_input_concat_caps()
        if not caps:
            return []
        schema = self.children[0].output
        key_parts, factory = self._sort_program(schema)

        def args_factory():
            return [dummy_batch_args(schema, c) for c in caps]

        return [AotProgram(key_parts, factory, args_factory,
                           f"sort:{self.describe()[:48]}")]

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.memory.retry import with_retry_no_split
        from spark_rapids_tpu.memory.spill import get_spill_framework

        fw = get_spill_framework()
        spillables = []
        total_bytes = 0
        for b in self.children[0].execute_columnar():
            total_bytes += b.nbytes()
            spillables.append(fw.track(b))
        if not spillables:
            return
        if len(spillables) > 1 and total_bytes > self.ooc_bytes:
            yield from self._execute_out_of_core(spillables, fw)
            return
        with self.metric("sortTime").timed():
            def run():
                for s in spillables:
                    s.pin()
                try:
                    batches = [s.get_batch() for s in spillables]
                    batch = (batches[0] if len(batches) == 1
                             else ColumnarBatch.concat(batches))
                    fn = self._sort_fn(batch.schema)
                    cols = fn(tuple(batch.columns), jnp.int32(batch.num_rows))
                    return ColumnarBatch(list(cols), batch.num_rows,
                                         batch.schema)
                finally:
                    for s in spillables:
                        s.unpin()

            out = with_retry_no_split(run)
            for s in spillables:
                s.close()
        yield self._count_output(out)

    # -- out-of-core: sorted runs + k-way windowed merge -----------------
    def _execute_out_of_core(self, spillables, fw) -> Iterator[ColumnarBatch]:
        """GpuOutOfCoreSortIterator analog: sort each batch into a spillable
        run, then merge fixed-size chunk windows of all runs; rows are safe
        to emit once their key is <= the smallest last-loaded key of any
        non-exhausted run.  Peak device memory ~ one run + k * chunk."""
        from spark_rapids_tpu.memory.retry import with_retry_no_split

        schema = self.children[0].output
        C = self.ooc_chunk_rows
        sort_one = self._sort_fn(schema)

        runs = []            # (spillable sorted run, row count)
        with self.metric("sortTime").timed():
            for s in spillables:
                def mk(s=s):
                    s.pin()
                    try:
                        b = s.get_batch()
                        cols = sort_one(tuple(b.columns),
                                        jnp.int32(b.num_rows))
                        return ColumnarBatch(list(cols), b.num_rows, schema)
                    finally:
                        s.unpin()
                sorted_b = with_retry_no_split(mk)
                runs.append([fw.track(sorted_b), sorted_b.num_rows, 0])
                s.close()
        yield from self._merge_runs(runs, schema)

    def _merge_runs(self, runs, schema) -> Iterator[ColumnarBatch]:
        """Memory-bounded k-way merge of sorted spillable runs — shared by
        the single-chip out-of-core sort and the per-device emit of the
        distributed ICI sort (exec/ici.py)."""
        from spark_rapids_tpu.lifecycle.context import check_cancel

        C = self.ooc_chunk_rows
        k = len(runs)
        merge = self._merge_window_fn(schema, k)
        while any(off < n for _, n, off in runs):
            # cooperative cancellation per merge window: the k-way merge
            # can loop for many windows between yields
            check_cancel()
            chunks = []
            metas = []   # (nvalid, exhausted)
            for s, n, off in runs:
                remaining = n - off
                take = min(C, max(remaining, 0))
                if take > 0:
                    s.pin()
                    try:
                        full = s.get_batch()
                        chunk = full.slice_rows(off, C)
                    finally:
                        s.unpin()
                    # capacity C even when fewer rows remain
                    chunk = ColumnarBatch(
                        [c.slice_to(C) for c in chunk.columns], take, schema)
                else:
                    from spark_rapids_tpu.columnar.batch import empty_batch

                    chunk = empty_batch(schema, capacity=C)
                chunks.append(chunk)
                metas.append((take, remaining <= C))
            nvalid = jnp.asarray([m[0] for m in metas], jnp.int32)
            exhausted = jnp.asarray([m[1] for m in metas], jnp.bool_)
            with self.metric("sortTime").timed():
                out_cols, emit_cnt, consumed = merge(
                    tuple(tuple(c.columns) for c in chunks), nvalid,
                    exhausted)
                emit = int(emit_cnt)
                consumed_np = [int(x) for x in consumed]
            for i, used in enumerate(consumed_np):
                runs[i][2] += used
            if emit:
                yield self._count_output(
                    ColumnarBatch(list(out_cols), emit, schema))
        for s, _, _ in runs:
            s.close()

    def _merge_window_fn(self, schema, k: int):
        orders = self.orders
        ansi = self.ansi

        def keys_of(batch):
            ctx = EvalContext(batch, ansi=ansi)
            key_cols = [e.eval_tpu(ctx) for e, _ in orders]
            specs = [s for _, s in orders]
            from spark_rapids_tpu.ops.sortkeys import pack_sort_keys

            return pack_sort_keys(key_cols, specs, batch.row_mask)

        def le_bound(words, bound):
            """per row: key <= bound (lexicographic over packed words)."""
            lt = jnp.zeros(words[0].shape, jnp.bool_)
            eq = jnp.ones(words[0].shape, jnp.bool_)
            for w, b in zip(words, bound):
                lt = lt | (eq & (w < b))
                eq = eq & (w == b)
            return lt | eq

        def cat_columns(batches, C, k):
            """Static-shape concat of k C-capacity chunk batches."""
            out = []
            for ci in range(len(batches[0].columns)):
                cs = [b.columns[ci] for b in batches]
                validity = jnp.concatenate([c.validity for c in cs])
                if cs[0].is_string:
                    w = max(c.width for c in cs)
                    chars = jnp.concatenate([
                        jnp.pad(c.chars, ((0, 0), (0, w - c.width)))
                        for c in cs])
                    lengths = jnp.concatenate([c.lengths for c in cs])
                    out.append(DeviceColumn(cs[0].dtype, validity,
                                            chars=chars, lengths=lengths))
                else:
                    out.append(DeviceColumn(
                        cs[0].dtype, validity,
                        data=jnp.concatenate([c.data for c in cs])))
            return out

        def fn(chunk_cols, nvalid, exhausted):
            C = chunk_cols[0][0].capacity if chunk_cols else 0
            # normalize string widths across chunks: pack_sort_keys emits one
            # word per 8 chars, so differing widths would misalign the
            # word-by-word bound comparisons
            ncols = len(chunk_cols[0])
            widths = [max(cs[ci].width for cs in chunk_cols)
                      for ci in range(ncols)]
            from spark_rapids_tpu.expr.predicates import _pad_to

            norm = []
            for cs in chunk_cols:
                row = []
                for ci, c in enumerate(cs):
                    if c.is_string and c.width < widths[ci]:
                        row.append(DeviceColumn(
                            c.dtype, c.validity,
                            chars=_pad_to(c.chars, widths[ci]),
                            lengths=c.lengths))
                    else:
                        row.append(c)
                norm.append(row)
            chunk_cols = norm
            batches = [ColumnarBatch(list(cs), nvalid[i], schema)
                       for i, cs in enumerate(chunk_cols)]
            all_words = []
            bounds = []       # last valid key of each non-exhausted chunk
            big = jnp.int64(9223372036854775807)
            for i, b in enumerate(batches):
                mask = jnp.arange(C) < nvalid[i]
                words = keys_of(b)
                all_words.append((words, mask))
                last = jnp.clip(nvalid[i] - 1, 0, C - 1)
                # exhausted or empty runs impose no bound
                no_bound = exhausted[i] | (nvalid[i] == 0)
                bounds.append([jnp.where(no_bound, big, w[last])
                               for w in words])
            bound = bounds[0]
            for cand in bounds[1:]:
                lt = jnp.zeros((), jnp.bool_)
                eq = jnp.ones((), jnp.bool_)
                for a, c in zip(bound, cand):
                    lt = lt | (eq & (c < a))
                    eq = eq & (c == a)
                bound = [jnp.where(lt, c, a) for a, c in zip(bound, cand)]
            # consumed per chunk + total window sort
            consumed = []
            for words, mask in all_words:
                ok = le_bound(words, bound) & mask
                consumed.append(jnp.sum(ok.astype(jnp.int32)))
            mcols = cat_columns(batches, C, k)
            mmask = jnp.concatenate(
                [jnp.arange(C) < nvalid[i] for i in range(k)])
            merged = ColumnarBatch(mcols, C * k, schema)
            ctx = EvalContext(merged, ansi=ansi)
            key_cols = [e.eval_tpu(ctx) for e, _ in orders]
            specs = [s for _, s in orders]
            perm = sort_permutation(key_cols, specs, mmask)
            out = _gather_batch(merged, perm, C * k, schema)
            from spark_rapids_tpu.ops.sortkeys import pack_sort_keys

            mwords = [w[perm]
                      for w in pack_sort_keys(key_cols, specs, mmask)]
            emit = jnp.sum((le_bound(mwords, bound)
                            & mmask[perm]).astype(jnp.int32))
            return tuple(out.columns), emit, jnp.stack(consumed)

        return tpu_jit(fn)


class TpuTopNExec(TpuExec):
    """sort + limit fused: keeps only n rows per batch then merges.

    Reference analog: GpuTopN in limit.scala — sort each batch, slice to n,
    concat + re-sort + slice; avoids materializing the full sort."""

    def __init__(self, n: int, orders: List[Tuple[Expression, SortSpec]],
                 child: TpuExec, ansi: bool = False):
        super().__init__([child])
        self.n = n
        self.orders = orders
        self.ansi = ansi

    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        return f"TpuTopN {self.n}"

    def execute_columnar(self):
        sorter = TpuSortExec(self.orders, True, self.children[0], self.ansi)
        pending: List[ColumnarBatch] = []
        for b in self.children[0].execute_columnar():
            fn = sorter._sort_fn(b.schema)
            cols = fn(tuple(b.columns), jnp.int32(b.num_rows))
            sb = ColumnarBatch(list(cols), b.num_rows, b.schema)
            pending.append(sb.slice_rows(0, min(self.n, sb.num_rows)))
            if len(pending) > 8:
                pending = [self._merge(pending, sorter)]
        if not pending:
            return
        out = self._merge(pending, sorter)
        yield self._count_output(out)

    def _merge(self, batches, sorter):
        merged = (batches[0] if len(batches) == 1
                  else ColumnarBatch.concat(batches))
        fn = sorter._sort_fn(merged.schema)
        cols = fn(tuple(merged.columns), jnp.int32(merged.num_rows))
        sb = ColumnarBatch(list(cols), merged.num_rows, merged.schema)
        return sb.slice_rows(0, min(self.n, sb.num_rows))
