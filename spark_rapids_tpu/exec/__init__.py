from spark_rapids_tpu.exec.base import TpuExec, TpuMetric  # noqa: F401
from spark_rapids_tpu.exec.basic import (  # noqa: F401
    TpuFilterExec,
    TpuInMemoryTableScanExec,
    TpuLocalTableScanExec,
    TpuProjectExec,
    TpuRangeExec,
    TpuStageExec,
    TpuUnionExec,
)
from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec  # noqa: F401
from spark_rapids_tpu.exec.sort import TpuSortExec, TpuTopNExec  # noqa: F401
from spark_rapids_tpu.exec.join import (  # noqa: F401
    TpuBroadcastHashJoinExec,
    TpuShuffledSymmetricHashJoinExec,
)
from spark_rapids_tpu.exec.limit import (  # noqa: F401
    TpuGlobalLimitExec,
    TpuLocalLimitExec,
)
from spark_rapids_tpu.exec.window import TpuWindowExec  # noqa: F401
from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec  # noqa: F401
from spark_rapids_tpu.exec.coalesce import TpuCoalesceBatchesExec  # noqa: F401
from spark_rapids_tpu.exec.transitions import (  # noqa: F401
    TpuColumnarToRowExec,
    TpuRowToColumnarExec,
)
