"""ICI mesh execution — whole plan stages as one SPMD collective program.

Reference analog: the reference's distributed execution is Spark tasks
pulling shuffle blocks peer-to-peer over UCX (SURVEY.md §2.7/§5.8,
RapidsShuffleClient/Server).  TPU-first replacement: the stage pair

    HashAggregate(FINAL) <- [Coalesce] <- ShuffleExchange <-
    HashAggregate(PARTIAL, fused scan ops)

compiles to ONE shard_map program over the device mesh:

    per device:  local partial _agg_fn (the unchanged single-chip program)
              -> spark murmur3 partition ids over the group keys
              -> all-to-all of every partial-buffer column over ICI
              -> local final _agg_fn on the received buffer rows

The per-device program IS the single-chip code path — shard_map only wires
the collectives around it (the "same program, sharded data" SPMD design the
scaling-book recipe prescribes).  Global (no-key) aggregates skip the
all-to-all: partial buffers are all-gathered and every device finalizes the
replicated merge (one row; replication is free).

The Spark-async vs SPMD-collective impedance mismatch (SURVEY.md §7 hard
part #1) is resolved by epoching: an exchange is already a full barrier in
Spark semantics, so executing it as one collective step loses no generality.

Current quota layout: the all-to-all reserves local-cap slots per peer
(received capacity = global cap).  jax.lax.ragged_all_to_all is the planned
upgrade for skewed partitions.
"""
from __future__ import annotations

from typing import Iterator, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.exec.base import TpuExec

try:  # jax>=0.8
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore


class TpuIciShuffleAggExec(TpuExec):
    """Fused distributed aggregation stage over a jax Mesh."""

    def __init__(self, partial, final, mesh, axis: str = "dp"):
        super().__init__(list(partial.children))
        self.partial = partial
        self.final = final
        self.mesh = mesh
        self.axis = axis
        self._program = None

    @property
    def output(self):
        return self.final.output

    def describe(self):
        n = self.mesh.devices.size
        return (f"TpuIciShuffleAgg[{n}dev] partial=({self.partial.describe()})"
                f" final=({self.final.describe()})")

    # ------------------------------------------------------------------
    def _build_program(self):
        axis = self.axis
        n_dev = int(self.mesh.devices.size)
        partial = self.partial
        final = self.final
        grouped = bool(final.grouping)
        nkeys = len(partial.grouping)

        def per_device(cols, num_rows):
            from spark_rapids_tpu.parallel.mesh import ici_all_to_all_columns

            local_cap = cols[0].capacity
            idx = jax.lax.axis_index(axis)
            nloc = jnp.clip(num_rows - idx.astype(jnp.int32) * local_cap,
                            0, local_cap)
            pcols, ng = partial._agg_fn(cols, nloc)
            pcols = list(pcols)
            grows = jnp.arange(pcols[0].capacity) < ng
            if grouped:
                from spark_rapids_tpu.ops.hashing import spark_partition_ids

                tgt = spark_partition_ids(pcols[:nkeys], n_dev)
                rcols, rok = ici_all_to_all_columns(pcols, grows, tgt,
                                                    n_dev, axis)
                fcols, fng = final._agg_fn(
                    tuple(rcols), jnp.int32(rcols[0].capacity), row_valid=rok)
            else:
                gathered = []
                for c in pcols:
                    validity = jax.lax.all_gather(c.validity, axis, tiled=True)
                    if c.is_string:
                        gathered.append(DeviceColumn(
                            c.dtype, validity,
                            chars=jax.lax.all_gather(c.chars, axis, tiled=True),
                            lengths=jax.lax.all_gather(c.lengths, axis,
                                                       tiled=True)))
                    else:
                        gathered.append(DeviceColumn(
                            c.dtype, validity,
                            data=jax.lax.all_gather(c.data, axis, tiled=True)))
                rok = jax.lax.all_gather(grows, axis, tiled=True)
                fcols, fng = final._agg_fn(
                    tuple(gathered), jnp.int32(gathered[0].capacity),
                    row_valid=rok)
            return tuple(fcols), fng.reshape(1)

        out_spec = P(axis) if grouped else P()
        return shard_map(
            per_device, mesh=self.mesh,
            in_specs=(P(axis), P()),
            out_specs=(out_spec, out_spec),
            check_vma=False)

    # ------------------------------------------------------------------
    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        n_dev = int(self.mesh.devices.size)
        batches = list(self.children[0].execute_columnar())
        if not batches:
            batches = [None]
        with self.metrics["opTime"].timed():
            batch = (ColumnarBatch.concat(batches)
                     if batches[0] is not None and len(batches) > 1
                     else batches[0])
            if batch is None or batch.num_rows == 0:
                yield from self._empty_input()
                return
            cap = batch.capacity
            if cap % n_dev or cap < n_dev:
                batch = ColumnarBatch(
                    [c.slice_to(-(-cap // n_dev) * n_dev)
                     for c in batch.columns], batch.num_rows, batch.schema)
            sharded = self._shard_batch(batch)
            if self._program is None:
                self._program = self._build_program()
            fcols, fng = self._program(tuple(sharded),
                                       jnp.int32(batch.num_rows))
            fng_np = np.asarray(fng)          # one host sync
        out_schema = self.final.output
        if not self.final.grouping:
            yield self._count_output(
                ColumnarBatch([c.gather(jnp.arange(1)) for c in fcols],
                              1, out_schema))
            return
        per_dev_cap = fcols[0].capacity // n_dev
        for d in range(n_dev):
            ng = int(fng_np[d])
            if ng == 0:
                continue
            lo = d * per_dev_cap
            cols = [
                DeviceColumn(c.dtype,
                             c.validity[lo: lo + per_dev_cap],
                             data=None if c.data is None
                             else c.data[lo: lo + per_dev_cap],
                             chars=None if c.chars is None
                             else c.chars[lo: lo + per_dev_cap],
                             lengths=None if c.lengths is None
                             else c.lengths[lo: lo + per_dev_cap])
                for c in fcols]
            yield self._count_output(
                ColumnarBatch(cols, ng, out_schema))

    def _shard_batch(self, batch: ColumnarBatch) -> List[DeviceColumn]:
        """Row-shard every column array over the mesh axis."""
        def put(arr):
            if arr is None:
                return None
            spec = P(self.axis) if arr.ndim >= 1 else P()
            return jax.device_put(arr, NamedSharding(self.mesh, spec))

        return [DeviceColumn(c.dtype, put(c.validity), data=put(c.data),
                             chars=put(c.chars), lengths=put(c.lengths),
                             elem_valid=put(c.elem_valid))
                for c in batch.columns]

    def _empty_input(self):
        """Empty scan: reproduce the single-chip chain's semantics — the
        partial emits its initial buffer row (global agg) which the final
        merges and finalizes; grouped aggregates emit nothing."""
        from spark_rapids_tpu.columnar.batch import empty_batch

        if self.final.grouping:
            yield self._count_output(empty_batch(self.final.output))
            return
        pb = self.partial._global_agg_empty()
        merged = self.final._merge_batch(pb)
        yield self._count_output(self.final._finalize(merged))
