"""ICI mesh execution — whole plan stages as one SPMD collective program.

Reference analog: the reference's distributed execution is Spark tasks
pulling shuffle blocks peer-to-peer over UCX (SURVEY.md §2.7/§5.8,
RapidsShuffleClient/Server).  TPU-first replacement: the stage pair

    HashAggregate(FINAL) <- [Coalesce] <- ShuffleExchange <-
    HashAggregate(PARTIAL, fused scan ops)

compiles to ONE shard_map program over the device mesh:

    per device:  local partial _agg_fn (the unchanged single-chip program)
              -> spark murmur3 partition ids over the group keys
              -> all-to-all of every partial-buffer column over ICI
              -> local final _agg_fn on the received buffer rows

The per-device program IS the single-chip code path — shard_map only wires
the collectives around it (the "same program, sharded data" SPMD design the
scaling-book recipe prescribes).  Global (no-key) aggregates skip the
all-to-all: partial buffers are all-gathered and every device finalizes the
replicated merge (one row; replication is free).

The Spark-async vs SPMD-collective impedance mismatch (SURVEY.md §7 hard
part #1) is resolved by epoching: an exchange is already a full barrier in
Spark semantics, so executing it as one collective step loses no generality.

Current quota layout: the all-to-all reserves local-cap slots per peer
(received capacity = global cap).  jax.lax.ragged_all_to_all is the planned
upgrade for skewed partitions.
"""
from __future__ import annotations

import time
from typing import Iterator, List, Optional

import jax
from spark_rapids_tpu import perfcounters as PC
from spark_rapids_tpu.perfcounters import tpu_jit
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.exec.base import TpuExec

from spark_rapids_tpu.parallel.compat import shard_map


# ---------------------------------------------------------------------------
# ICI shuffle accounting + the host boundary (ISSUE 10)
# ---------------------------------------------------------------------------

def _ici_account(stage: str, n_dev: int, rows: int, nbytes: int,
                 dur_ns: int) -> None:
    """Per-collective-epoch accounting shared by every ICI stage exec:
    the ``ici_*`` counters (epochs / rows / bytes exchanged device-to-
    device, wall inside the collective program) and the per-query
    ``ici_shuffle`` diagnostics event.  The exchanged bytes never cross
    the host — the zero-host-bytes pin in tests/test_multichip.py holds
    the all-device path to that."""
    PC.bump("ici_epochs")
    PC.bump("ici_rows_exchanged", int(rows))
    PC.bump("ici_bytes_moved", int(nbytes))
    PC.bump("ici_shuffle_ns", int(dur_ns))
    from spark_rapids_tpu.diagnostics import context as _DIAG

    rec = _DIAG.RECORDER
    if rec is not None:
        rec.ici_shuffle(stage, n_dev, int(rows), int(nbytes), int(dur_ns))


def ici_host_frame(batch: ColumnarBatch,
                   codec: Optional[str] = None) -> bytes:
    """Frame an ICI/exchange batch crossing the HOST boundary as one
    CRC32-checked wire block (the PR 4 ``TKU2`` serializer): a flipped
    bit anywhere between write and read surfaces as a deterministic
    :class:`ShuffleCorruption` instead of silent wrong rows.  The
    spill-backed exchange queues frame every over-budget slice through
    here; device-to-device collective traffic never does."""
    from spark_rapids_tpu.shuffle.serializer import serialize_batch

    return serialize_batch(batch, codec=codec)


def ici_host_unframe(blob: bytes, schema,
                     codec: Optional[str] = None) -> ColumnarBatch:
    """Verify + decode one host-boundary block (raises
    ShuffleCorruption on CRC/codec rejection)."""
    from spark_rapids_tpu.shuffle.serializer import deserialize_concat

    return deserialize_concat([blob], schema, codec=codec)


def _pad_chars(chars, w):
    if chars.shape[-1] == w:
        return chars
    pad = [(0, 0)] * (chars.ndim - 1) + [(0, w - chars.shape[-1])]
    return jnp.pad(chars, pad)


def _concat_cols(a: DeviceColumn, b: DeviceColumn) -> DeviceColumn:
    """Row-concat two buffer-form device columns (flat or string)."""
    validity = jnp.concatenate([a.validity, b.validity])
    if a.is_string:
        w = max(a.width, b.width)
        return DeviceColumn(
            a.dtype, validity,
            chars=jnp.concatenate([_pad_chars(a.chars, w),
                                   _pad_chars(b.chars, w)]),
            lengths=jnp.concatenate([a.lengths, b.lengths]))
    return DeviceColumn(a.dtype, validity,
                        data=jnp.concatenate([a.data, b.data]))


def _epoch_batches(it, epoch_bytes: int):
    """Group a batch iterator into ~epoch_bytes concats (skipping empty
    batches) — the shared epoch bucketing of every ICI stage exec."""
    pending, size = [], 0
    for b in it:
        if b.num_rows == 0:
            continue
        pending.append(b)
        size += b.nbytes()
        if size >= epoch_bytes:
            yield (pending[0] if len(pending) == 1
                   else ColumnarBatch.concat(pending))
            pending, size = [], 0
    if pending:
        yield (pending[0] if len(pending) == 1
               else ColumnarBatch.concat(pending))


def _slice_cols(cols, cap):
    return tuple(
        DeviceColumn(c.dtype, c.validity[:cap],
                     data=None if c.data is None else c.data[:cap],
                     chars=None if c.chars is None else c.chars[:cap],
                     lengths=None if c.lengths is None else c.lengths[:cap])
        for c in cols)


def _map_col_arrays(c: DeviceColumn, f) -> DeviceColumn:
    """Rebuild a DeviceColumn with ``f`` applied to every row-major array
    (validity/data/chars/lengths/elem_valid, recursing into struct
    children) — the one place column-layout completeness lives for the
    mesh helpers below."""
    return DeviceColumn(
        c.dtype, f(c.validity),
        data=None if c.data is None else f(c.data),
        chars=None if c.chars is None else f(c.chars),
        lengths=None if c.lengths is None else f(c.lengths),
        elem_valid=None if c.elem_valid is None else f(c.elem_valid),
        children=None if c.children is None
        else tuple(_map_col_arrays(k, f) for k in c.children))


def _fit_cols(cols, cap):
    """Slice or zero-pad columns to exactly ``cap`` rows."""
    def fit(arr):
        n = arr.shape[0]
        if cap <= n:
            return arr[:cap]
        return jnp.pad(arr, [(0, cap - n)] + [(0, 0)] * (arr.ndim - 1))

    return tuple(_map_col_arrays(c, fit) for c in cols)


def _rebucket_sharded(cols, per_dev_cap: int, tgt_cap: int, n_dev: int,
                      mesh, axis: str):
    """Re-bucket device-sharded prefix-compacted columns from per_dev_cap
    to tgt_cap rows per device (the agg accumulator's resize, shared by
    the window/repartition stages)."""
    def rs(arr):
        shp = arr.shape
        a = arr.reshape((n_dev, per_dev_cap) + shp[1:])
        if tgt_cap <= per_dev_cap:
            a = a[:, :tgt_cap]
        else:
            a = jnp.pad(a, [(0, 0), (0, tgt_cap - per_dev_cap)]
                        + [(0, 0)] * (arr.ndim - 1))
        out = a.reshape((n_dev * tgt_cap,) + shp[1:])
        return jax.device_put(out, NamedSharding(mesh, P(axis)))

    return [_map_col_arrays(c, rs) for c in cols]


def _ceil_to_mesh(batch: ColumnarBatch, n_dev: int) -> ColumnarBatch:
    """Pad a batch's capacity up to a multiple of the device count."""
    cap = batch.capacity
    if cap % n_dev or cap < n_dev:
        return ColumnarBatch(
            [c.slice_to(-(-cap // n_dev) * n_dev) for c in batch.columns],
            batch.num_rows, batch.schema)
    return batch


def _shard_cols(batch: ColumnarBatch, mesh, axis: str):
    """Row-shard every column array of a batch over the mesh axis."""
    def put(arr):
        return jax.device_put(arr, NamedSharding(mesh, P(axis)))

    return [_map_col_arrays(c, put) for c in batch.columns]


class TpuIciShuffleAggExec(TpuExec):
    """Fused distributed aggregation stage over a jax Mesh.

    Epoch-streamed (VERDICT r2 missing #1 / weak #2): the child's batches
    flow through the collective program in bounded epochs —

        per epoch, per device:
          local partial agg -> all-to-all by key hash -> MERGE the received
          partial buffers into the device-resident accumulator (the
          unfinalized buffer form, bounded by distinct keys per device)

    and one finalize program runs after the last epoch.  Per-device peak
    memory is one epoch shard + the accumulator: the merge runs at full
    concat capacity (never truncating), then the accumulator re-buckets to
    the smallest pow2 per-device capacity that holds every device's
    groups."""

    def __init__(self, partial, final, mesh, axis: str = "dp",
                 epoch_bytes: int = 1 << 28):
        super().__init__(list(partial.children))
        self.partial = partial
        self.final = final
        self.mesh = mesh
        self.axis = axis
        self.epoch_bytes = epoch_bytes
        self._programs = {}
        self._finalize_p = None

    @property
    def output(self):
        return self.final.output

    def describe(self):
        n = self.mesh.devices.size
        return (f"TpuIciShuffleAgg[{n}dev] partial=({self.partial.describe()})"
                f" final=({self.final.describe()})")

    # ------------------------------------------------------------------
    def _build_epoch_program(self, first: bool, acc_cap_local: int = 0):
        """One epoch: partial -> all-to-all -> merge into the accumulator.

        ``first`` epochs have no accumulator input; later epochs concat
        the accumulator's buffer rows with the received partials before
        the merge.  Returns per-device (acc buffer cols, group count)."""
        axis = self.axis
        n_dev = int(self.mesh.devices.size)
        partial = self.partial
        final = self.final
        grouped = bool(final.grouping)
        nkeys = len(partial.grouping)

        def per_device(cols, num_rows, *acc):
            from spark_rapids_tpu.parallel.mesh import ici_all_to_all_columns

            local_cap = cols[0].capacity
            idx = jax.lax.axis_index(axis)
            nloc = jnp.clip(num_rows - idx.astype(jnp.int32) * local_cap,
                            0, local_cap)
            pcols, ng = partial._agg_fn(cols, nloc)
            pcols = list(pcols)
            grows = jnp.arange(pcols[0].capacity) < ng
            if grouped:
                from spark_rapids_tpu.ops.hashing import spark_partition_ids

                tgt = spark_partition_ids(pcols[:nkeys], n_dev)
                rcols, rok = ici_all_to_all_columns(pcols, grows, tgt,
                                                    n_dev, axis)
            else:
                rcols = []
                for c in pcols:
                    validity = jax.lax.all_gather(c.validity, axis,
                                                  tiled=True)
                    if c.is_string:
                        rcols.append(DeviceColumn(
                            c.dtype, validity,
                            chars=jax.lax.all_gather(c.chars, axis,
                                                     tiled=True),
                            lengths=jax.lax.all_gather(c.lengths, axis,
                                                       tiled=True)))
                    else:
                        rcols.append(DeviceColumn(
                            c.dtype, validity,
                            data=jax.lax.all_gather(c.data, axis,
                                                    tiled=True)))
                rok = jax.lax.all_gather(grows, axis, tiled=True)
            if not first:
                acc_cols, acc_ng = acc
                acc_ok = (jnp.arange(acc_cap_local, dtype=jnp.int32)
                          < acc_ng[0])
                rcols = [_concat_cols(a, r)
                         for a, r in zip(acc_cols, rcols)]
                rok = jnp.concatenate([acc_ok, rok])
            mcols, mng = final._merge_fn(
                tuple(rcols), jnp.int32(rcols[0].capacity), row_valid=rok)
            if not grouped:
                mng = jnp.int32(1)
            return tuple(mcols), mng.astype(jnp.int32).reshape(1)

        out_spec = P(axis) if grouped else P()
        in_specs = (P(axis), P()) + (() if first else (out_spec, out_spec))
        return shard_map(
            per_device, mesh=self.mesh,
            in_specs=in_specs,
            out_specs=(out_spec, out_spec),
            check_vma=False)

    def _build_finalize_program(self, acc_cap_local: int):
        axis = self.axis
        final = self.final
        grouped = bool(final.grouping)

        def per_device(acc_cols, acc_ng):
            acc_ok = (jnp.arange(acc_cap_local, dtype=jnp.int32)
                      < acc_ng[0])
            fcols, fng = final._agg_fn(
                acc_cols, jnp.int32(acc_cap_local), row_valid=acc_ok)
            return tuple(fcols), fng.astype(jnp.int32).reshape(1)

        out_spec = P(axis) if grouped else P()
        return shard_map(
            per_device, mesh=self.mesh,
            in_specs=(out_spec, out_spec),
            out_specs=(out_spec, out_spec),
            check_vma=False)

    # ------------------------------------------------------------------
    def _epochs(self, it) -> Iterator[ColumnarBatch]:
        return _epoch_batches(it, self.epoch_bytes)

    def _resize_acc(self, mcols, mcl: int, tgt_cap: int, n_dev: int):
        """Re-bucket the accumulator to tgt_cap rows per device.

        Merged groups are compacted to each device's block prefix, so the
        per-device resize is a reshape+slice/pad of the sharded arrays;
        the result is re-laid-out row-sharded over the mesh axis."""
        grouped = bool(self.final.grouping)

        def rs(arr):
            if arr is None:
                return None
            if not grouped:
                out = (arr[:tgt_cap] if tgt_cap <= arr.shape[0]
                       else jnp.pad(arr, [(0, tgt_cap - arr.shape[0])]
                                    + [(0, 0)] * (arr.ndim - 1)))
                return out
            shp = arr.shape
            a = arr.reshape((n_dev, mcl) + shp[1:])
            if tgt_cap <= mcl:
                a = a[:, :tgt_cap]
            else:
                a = jnp.pad(a, [(0, 0), (0, tgt_cap - mcl)]
                            + [(0, 0)] * (arr.ndim - 1))
            out = a.reshape((n_dev * tgt_cap,) + shp[1:])
            return jax.device_put(
                out, NamedSharding(self.mesh, P(self.axis)))

        return [DeviceColumn(c.dtype, rs(c.validity), data=rs(c.data),
                             chars=rs(c.chars), lengths=rs(c.lengths))
                for c in mcols]

    def _run_epoch(self, batch: ColumnarBatch, acc, acc_ng_arr, n_dev):
        """Run one epoch; re-bucket the merged accumulator to the smallest
        pow2 per-device capacity holding every device's groups (the merge
        runs at full concat capacity, so nothing is ever truncated)."""
        cap = batch.capacity
        if cap % n_dev or cap < n_dev:
            batch = ColumnarBatch(
                [c.slice_to(-(-cap // n_dev) * n_dev)
                 for c in batch.columns], batch.num_rows, batch.schema)
        sharded = self._shard_batch(batch)
        first = acc is None
        grouped = bool(self.final.grouping)
        acc_cap_local = (0 if first
                         else acc[0].capacity // (n_dev if grouped else 1))
        key = (batch.capacity, first, acc_cap_local)
        if key not in self._programs:
            self._programs[key] = self._build_epoch_program(
                first, acc_cap_local)
        args = (tuple(sharded), jnp.int32(batch.num_rows))
        if not first:
            args = args + (tuple(acc), acc_ng_arr)
        t0 = time.perf_counter_ns()
        mcols, mng = self._programs[key](*args)
        mng_np = np.asarray(mng)            # one host sync per epoch
        _ici_account(self.node_name, n_dev, int(mng_np.sum()),
                     batch.nbytes(), time.perf_counter_ns() - t0)
        mcl = mcols[0].capacity // (n_dev if grouped else 1)
        need = max(int(mng_np.max()), 1)
        tgt_cap = 1 << (need - 1).bit_length()
        if tgt_cap != mcl:
            return self._resize_acc(mcols, mcl, tgt_cap, n_dev), mng
        return list(mcols), mng

    # ------------------------------------------------------------------
    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        n_dev = int(self.mesh.devices.size)
        acc = None
        acc_ng = None
        saw_rows = False
        with self.metrics["opTime"].timed():
            for epoch in self._epochs(self.children[0].execute_columnar()):
                if epoch.num_rows == 0:
                    continue
                saw_rows = True
                acc, acc_ng = self._run_epoch(epoch, acc, acc_ng, n_dev)
            if not saw_rows:
                yield from self._empty_input()
                return
            acc_cap_local = acc[0].capacity // (
                n_dev if self.final.grouping else 1)
            fkey = acc_cap_local
            if self._finalize_p is None or self._finalize_p[0] != fkey:
                self._finalize_p = (fkey,
                                    self._build_finalize_program(fkey))
            fcols, fng = self._finalize_p[1](tuple(acc), acc_ng)
            fng_np = np.asarray(fng)          # one host sync
        out_schema = self.final.output
        if not self.final.grouping:
            yield self._count_output(
                ColumnarBatch([c.gather(jnp.arange(1)) for c in fcols],
                              1, out_schema))
            return
        per_dev_cap = fcols[0].capacity // n_dev
        for d in range(n_dev):
            ng = int(fng_np[d])
            if ng == 0:
                continue
            lo = d * per_dev_cap
            cols = [
                DeviceColumn(c.dtype,
                             c.validity[lo: lo + per_dev_cap],
                             data=None if c.data is None
                             else c.data[lo: lo + per_dev_cap],
                             chars=None if c.chars is None
                             else c.chars[lo: lo + per_dev_cap],
                             lengths=None if c.lengths is None
                             else c.lengths[lo: lo + per_dev_cap])
                for c in fcols]
            yield self._count_output(
                ColumnarBatch(cols, ng, out_schema))

    def _shard_batch(self, batch: ColumnarBatch) -> List[DeviceColumn]:
        """Row-shard every column array over the mesh axis."""
        def put(arr):
            if arr is None:
                return None
            spec = P(self.axis) if arr.ndim >= 1 else P()
            return jax.device_put(arr, NamedSharding(self.mesh, spec))

        return [DeviceColumn(c.dtype, put(c.validity), data=put(c.data),
                             chars=put(c.chars), lengths=put(c.lengths),
                             elem_valid=put(c.elem_valid))
                for c in batch.columns]

    def _empty_input(self):
        """Empty scan: reproduce the single-chip chain's semantics — the
        partial emits its initial buffer row (global agg) which the final
        merges and finalizes; grouped aggregates emit nothing."""
        from spark_rapids_tpu.columnar.batch import empty_batch

        if self.final.grouping:
            yield self._count_output(empty_batch(self.final.output))
            return
        pb = self.partial._global_agg_empty()
        merged = self.final._merge_batch(pb)
        yield self._count_output(self.final._finalize(merged))


class TpuIciShuffleJoinExec(TpuExec):
    """Distributed shuffled equi-join over the mesh — the UCX-shuffle
    join's TPU-native replacement (SURVEY.md §5.8 mode 2, VERDICT r1 #3's
    "and the shuffled join").

    Two SPMD steps (mirroring the agg exec's epoch design):

      1. COLLECTIVE program: both inputs row-shard over the mesh; each
         device computes murmur3 partition ids of its join keys and
         all-to-alls both sides over ICI (null-keyed rows stay put), then
         sorts its received build keys and probes counts — returning the
         received shards + gather-plan arrays, all still device-sharded.
      2. LOCAL program: with the per-device pair counts synced once to the
         host (the static output capacity), a collective-free shard_map
         materializes each device's join output via the same searchsorted
         gather maps the single-chip join uses.

    Supported (VERDICT r3 Next #3): INNER (incl. residual conditions,
    filtered in the materialization program) / LEFT_OUTER / LEFT_SEMI /
    LEFT_ANTI / RIGHT_OUTER (mirror-swapped to LEFT_OUTER, columns
    reordered on emit — the single-chip _execute_right_outer design) /
    FULL_OUTER (LEFT_OUTER streaming + device-resident matched-build mask
    + one unmatched-build tail program after the last epoch).
    """

    # AQE skew-split count (OptimizeSkewedJoin analog)
    EXTRA_METRICS = {"skewSplits": "DEBUG"}

    def __init__(self, join, left_inner, right_inner, mesh,
                 axis: str = "dp", epoch_bytes: int = 1 << 28):
        from spark_rapids_tpu.plan.nodes import JoinType

        self._orig_output = join.output
        self._mirror_nl = None
        if join.join_type == JoinType.RIGHT_OUTER:
            from spark_rapids_tpu.exec.join import (
                TpuShuffledSymmetricHashJoinExec,
            )

            swapped_schema = T.StructType(
                list(right_inner.output.fields)
                + [T.StructField(f.name, f.dataType, True)
                   for f in left_inner.output.fields])
            join = TpuShuffledSymmetricHashJoinExec(
                right_inner, left_inner, join.right_keys, join.left_keys,
                JoinType.LEFT_OUTER, join.condition, swapped_schema,
                join.ansi)
            left_inner, right_inner = right_inner, left_inner
            self._mirror_nl = len(left_inner.output.fields)
        super().__init__([left_inner, right_inner])
        self.join = join            # TpuShuffledSymmetricHashJoinExec
        self.mesh = mesh
        self.axis = axis
        self.epoch_bytes = epoch_bytes
        self._pbuild = None
        self._pprobe = {}
        self._p2 = {}
        self._ptail = None

    @property
    def output(self):
        return self._orig_output

    def describe(self):
        n = self.mesh.devices.size
        jt = ("right_outer(mirrored)" if self._mirror_nl is not None
              else self.join.join_type.value)
        return (f"TpuIciShuffleJoin[{n}dev] "
                f"{jt} "
                f"[{self.join.describe()}]")

    # ------------------------------------------------------------------
    def _keys_and_valid(self, cols, schema, keys, nloc, ansi):
        from spark_rapids_tpu.exec.join import _key_words_of
        from spark_rapids_tpu.expr.base import EvalContext

        cap = cols[0].capacity
        b = ColumnarBatch(list(cols), nloc, schema)
        ctx = EvalContext(b, ansi=ansi)
        key_cols = [k.eval_tpu(ctx) for k in keys]
        rows = jnp.arange(cap) < nloc
        kvalid = rows
        for kc in key_cols:
            kvalid = kvalid & kc.validity
        return key_cols, rows, kvalid

    def _build_pbuild(self, r_schema):
        """One-time collective: all-to-all the BUILD side by key hash and
        sort each device's received keys.  The returned arrays stay
        device-resident across every probe epoch."""
        axis = self.axis
        n_dev = int(self.mesh.devices.size)
        join = self.join

        def per_device(rcols, r_rows):
            from spark_rapids_tpu.exec.join import _key_words_of
            from spark_rapids_tpu.ops.hashing import spark_partition_ids
            from spark_rapids_tpu.parallel.mesh import ici_all_to_all_columns

            idx = jax.lax.axis_index(axis)
            rcap = rcols[0].capacity
            nloc_r = jnp.clip(r_rows - idx.astype(jnp.int32) * rcap, 0, rcap)
            rkeys, rrows, rkvalid = self._keys_and_valid(
                rcols, r_schema, join.right_keys, nloc_r, join.ansi)
            tgt_r = jnp.where(
                rkvalid,
                spark_partition_ids(rkeys, n_dev),
                idx.astype(jnp.int32))  # null-keyed rows stay local
            rr, rr_ok = ici_all_to_all_columns(list(rcols), rrows, tgt_r,
                                               n_dev, axis)
            bkeys, _, bkvalid = self._keys_and_valid(
                rr, r_schema, join.right_keys,
                jnp.int32(rr[0].capacity), join.ansi)
            bkvalid = bkvalid & rr_ok
            bwords = _key_words_of(bkeys)
            inv = (~bkvalid).astype(jnp.int64)
            iota = jnp.arange(rr[0].capacity, dtype=jnp.int32)
            srt = jax.lax.sort(tuple([inv] + bwords + [iota]),
                               num_keys=1 + len(bwords), is_stable=True)
            swords = list(srt[1:-1])
            row_index = srt[-1]
            n_valid = jnp.sum(bkvalid.astype(jnp.int32))
            return (tuple(rr), tuple(swords), row_index,
                    n_valid.reshape(1), rr_ok)

        return shard_map(
            per_device, mesh=self.mesh,
            in_specs=(P(axis), P()),
            out_specs=(P(axis), P(axis), P(axis), P(axis), P(axis)),
            check_vma=False)

    def _build_pprobe(self, l_schema):
        """Per probe epoch: all-to-all the epoch's PROBE rows and count
        matches against the resident sorted build keys.  FULL OUTER also
        ORs covered build positions (sorted space, diff-array) into the
        device-resident matched accumulator."""
        from spark_rapids_tpu.plan.nodes import JoinType

        axis = self.axis
        n_dev = int(self.mesh.devices.size)
        join = self.join
        full = join.join_type == JoinType.FULL_OUTER

        def per_device(lcols, l_rows, swords, n_valid, *acc):
            from spark_rapids_tpu.exec.join import (
                _key_words_of,
                _multiword_searchsorted,
            )
            from spark_rapids_tpu.ops.hashing import spark_partition_ids
            from spark_rapids_tpu.parallel.mesh import ici_all_to_all_columns

            idx = jax.lax.axis_index(axis)
            lcap = lcols[0].capacity
            nloc_l = jnp.clip(l_rows - idx.astype(jnp.int32) * lcap, 0, lcap)
            lkeys, lrows, lkvalid = self._keys_and_valid(
                lcols, l_schema, join.left_keys, nloc_l, join.ansi)
            tgt_l = jnp.where(
                lkvalid,
                spark_partition_ids(lkeys, n_dev),
                idx.astype(jnp.int32))
            rl, rl_ok = ici_all_to_all_columns(list(lcols), lrows, tgt_l,
                                               n_dev, axis)
            pkeys, _, pkvalid = self._keys_and_valid(
                rl, l_schema, join.left_keys,
                jnp.int32(rl[0].capacity), join.ansi)
            pkvalid = pkvalid & rl_ok
            qwords = _key_words_of(pkeys)
            lo = _multiword_searchsorted(list(swords), n_valid[0], qwords,
                                         "left")
            hi = _multiword_searchsorted(list(swords), n_valid[0], qwords,
                                         "right")
            counts = jnp.where(pkvalid, hi - lo, 0)
            total = jnp.sum(counts.astype(jnp.int64))
            unmatched = rl_ok & (counts == 0)
            n_unmatched = jnp.sum(unmatched.astype(jnp.int64))
            out = (tuple(rl), lo, counts, unmatched, rl_ok,
                   jnp.stack([total, n_unmatched]).reshape(1, 2))
            if full:
                bcap = swords[0].shape[0]
                diff = jnp.zeros(bcap + 1, jnp.int32)
                has = counts > 0
                start = jnp.where(has, lo, bcap)
                end = jnp.where(has, lo + counts, bcap)
                diff = diff.at[start].add(1, mode="drop")
                diff = diff.at[end].add(-1, mode="drop")
                covered_sorted = jnp.cumsum(diff[:-1]) > 0
                out = out + (acc[0] | covered_sorted,)
            return out

        return shard_map(
            per_device, mesh=self.mesh,
            in_specs=(P(axis), P(), P(axis), P(axis))
            + ((P(axis),) if full else ()),
            out_specs=(P(axis), P(axis), P(axis), P(axis), P(axis),
                       P(axis)) + ((P(axis),) if full else ()),
            check_vma=False)

    def _build_p2(self, out_cap, l_schema, r_schema, n_l):
        """Collective-free per-device materialization."""
        axis = self.axis
        join = self.join
        jt = join.join_type
        from spark_rapids_tpu.plan.nodes import JoinType

        def per_device(flat, row_index, lo, counts, unmatched, rl_ok,
                       totals):
            from spark_rapids_tpu.ops.filterops import (
                compact_columns,
                gather_columns,
            )

            lcols = list(flat[:n_l])
            rcols = list(flat[n_l:])
            total = totals[0, 0]
            n_um = totals[0, 1]
            if jt in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI):
                keep = ((counts == 0) if jt == JoinType.LEFT_ANTI
                        else (counts > 0)) & rl_ok
                out, cnt = compact_columns(keep, lcols)
                return tuple(out), cnt.astype(jnp.int64).reshape(1)
            from spark_rapids_tpu.exec.join import _slots_to_probe_rows

            n = counts.shape[0]
            offsets = jnp.cumsum(counts.astype(jnp.int64))
            excl = offsets - counts.astype(jnp.int64)
            j = jnp.arange(out_cap, dtype=jnp.int64)
            probe_row = _slots_to_probe_rows(excl, counts, out_cap)
            k = j - excl[probe_row]
            build_pos = lo[probe_row].astype(jnp.int64) + k
            bcap = row_index.shape[0]
            build_row = row_index[jnp.clip(build_pos, 0,
                                           bcap - 1).astype(jnp.int32)]
            in_pairs = j < total
            with_um = jt in (JoinType.LEFT_OUTER, JoinType.FULL_OUTER)
            probe_idx = jnp.where(in_pairs, probe_row, 0)
            out_rows = total + (n_um if with_um else 0)
            if with_um:
                um_pos = jnp.cumsum(unmatched.astype(jnp.int64)) - 1
                um_slot = total + um_pos
                scatter_to = jnp.where(unmatched, um_slot,
                                       out_cap).astype(jnp.int64)
                probe_idx_full = jnp.zeros(out_cap, jnp.int32).at[
                    jnp.clip(scatter_to, 0, out_cap)].set(
                    jnp.arange(n, dtype=jnp.int32), mode="drop")
                probe_idx = jnp.where(in_pairs, probe_row, probe_idx_full)
            row_valid = j < out_rows
            out_l = gather_columns(probe_idx, row_valid, lcols)
            out_r = gather_columns(
                jnp.where(in_pairs, build_row, 0), row_valid & in_pairs,
                rcols)
            if join.condition is not None and jt == JoinType.INNER:
                # residual condition: evaluate over the materialized
                # pairs and compact (single-chip _apply_condition, fused
                # into this program)
                from spark_rapids_tpu.expr.base import EvalContext

                b = ColumnarBatch(list(out_l) + list(out_r), out_rows,
                                  join.output)
                ctx = EvalContext(b, ansi=join.ansi)
                pred = join.condition.eval_tpu(ctx)
                keep = pred.data & pred.validity & row_valid
                out, cnt = compact_columns(keep, list(out_l) + list(out_r))
                return tuple(out), cnt.astype(jnp.int64).reshape(1)
            return (tuple(out_l + out_r),
                    out_rows.astype(jnp.int64).reshape(1))

        return shard_map(
            per_device, mesh=self.mesh,
            in_specs=(P(axis),) * 7,
            out_specs=(P(axis), P(axis)),
            check_vma=False)

    def _build_ptail(self, bcap_local: int):
        """FULL OUTER tail: per device, compact the build rows never
        covered by any probe epoch (single-chip _unmatched_build_tail,
        device-resident)."""
        axis = self.axis

        def per_device(rr, row_index, matched_sorted, rok):
            from spark_rapids_tpu.ops.filterops import compact_columns

            matched_orig = jnp.zeros(bcap_local, jnp.bool_).at[
                row_index].set(matched_sorted, mode="drop")
            keep = rok & ~matched_orig
            out, cnt = compact_columns(keep, list(rr))
            return tuple(out), cnt.astype(jnp.int64).reshape(1)

        return shard_map(
            per_device, mesh=self.mesh,
            in_specs=(P(axis),) * 4,
            out_specs=(P(axis), P(axis)),
            check_vma=False)

    def _null_cols(self, fields, cap: int):
        """All-null columns for the unmatched side of an outer emit."""
        cols = []
        for f in fields:
            if isinstance(f.dataType, T.StringType):
                cols.append(DeviceColumn(
                    f.dataType, jnp.zeros(cap, jnp.bool_),
                    chars=jnp.zeros((cap, 8), jnp.uint8),
                    lengths=jnp.zeros(cap, jnp.int32)))
            else:
                cols.append(DeviceColumn(
                    f.dataType, jnp.zeros(cap, jnp.bool_),
                    data=jnp.zeros(cap, T.storage_dtype(f.dataType))))
        return cols

    # ------------------------------------------------------------------
    def _collect_side(self, child) -> ColumnarBatch:
        batches = list(child.execute_columnar())
        if not batches:
            from spark_rapids_tpu.columnar.batch import empty_batch

            return empty_batch(child.output)
        return (batches[0] if len(batches) == 1
                else ColumnarBatch.concat(batches))

    def _pad_for_mesh(self, batch: ColumnarBatch) -> ColumnarBatch:
        n_dev = int(self.mesh.devices.size)
        cap = batch.capacity
        if cap % n_dev or cap < n_dev:
            batch = ColumnarBatch(
                [c.slice_to(-(-cap // n_dev) * n_dev)
                 for c in batch.columns], batch.num_rows, batch.schema)
        return batch

    def _shard(self, batch: ColumnarBatch) -> List[DeviceColumn]:
        def put(arr):
            if arr is None:
                return None
            return jax.device_put(
                arr, NamedSharding(self.mesh, P(self.axis)))

        return [DeviceColumn(c.dtype, put(c.validity), data=put(c.data),
                             chars=put(c.chars), lengths=put(c.lengths),
                             elem_valid=put(c.elem_valid))
                for c in batch.columns]

    def _epochs(self, it) -> Iterator[ColumnarBatch]:
        return _epoch_batches(it, self.epoch_bytes)

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        """Build once, then stream the probe side through the mesh in
        epochs: per-device memory is the exchanged build side + one probe
        epoch (the reference's streamed-side iteration; build residency is
        hash-join's inherent requirement, sub-partitioning being its
        escape hatch on the single-chip path)."""
        from spark_rapids_tpu.plan.nodes import JoinType

        n_dev = int(self.mesh.devices.size)
        right = self._pad_for_mesh(self._collect_side(self.children[1]))
        l_schema = self.children[0].output
        r_schema = right.schema
        jt = self.join.join_type
        out_schema = self.join.output
        keep_cols = len(out_schema.fields)
        full = jt == JoinType.FULL_OUTER
        with self.metrics["opTime"].timed():
            rs = self._shard(right)
            if self._pbuild is None:
                self._pbuild = self._build_pbuild(r_schema)
            t0 = time.perf_counter_ns()
            rr, swords, row_index, n_valid, rr_ok = self._pbuild(
                tuple(rs), jnp.int32(right.num_rows))
            _ici_account(self.node_name, n_dev, right.num_rows,
                         right.nbytes(), time.perf_counter_ns() - t0)
        matched = None
        if full:
            matched = jax.device_put(
                jnp.zeros(swords[0].shape[0], jnp.bool_),
                NamedSharding(self.mesh, P(self.axis)))
        from spark_rapids_tpu.config import (SKEW_JOIN_ENABLED,
                                             SKEW_JOIN_FACTOR,
                                             SKEW_JOIN_MIN_ROWS, get_conf)

        conf = get_conf()
        skew_on = conf.get(SKEW_JOIN_ENABLED) and jt not in (
            JoinType.LEFT_SEMI, JoinType.LEFT_ANTI)
        skew_factor = conf.get(SKEW_JOIN_FACTOR)
        skew_min_rows = conf.get(SKEW_JOIN_MIN_ROWS)
        self.skew_splits = 0     # plan-visible evidence for tests/metrics

        # epochs are processed through an explicit stack so a skewed epoch
        # can SPLIT: when one device's matched total exceeds
        # skewedPartitionFactor x the device mean (AQE OptimizeSkewedJoin
        # analog, detected from the per-epoch totals the exec syncs
        # anyway), the epoch halves and re-routes — per-device output
        # capacity stays near the mean instead of the hot key's total
        pending: List[ColumnarBatch] = []

        def refill(epoch):
            pending.append(epoch)

        for epoch0 in self._epochs(self.children[0].execute_columnar()):
            refill(epoch0)
            while pending:
                epoch = pending.pop()
                with self.metrics["opTime"].timed():
                    epoch = self._pad_for_mesh(epoch)
                    ls = self._shard(epoch)
                    pkey = (epoch.capacity,)
                    if pkey not in self._pprobe:
                        self._pprobe[pkey] = self._build_pprobe(l_schema)
                    acc = (matched,) if full else ()
                    t0 = time.perf_counter_ns()
                    res = self._pprobe[pkey](tuple(ls),
                                             jnp.int32(epoch.num_rows),
                                             swords, n_valid, *acc)
                    _ici_account(self.node_name, n_dev, epoch.num_rows,
                                 epoch.nbytes(),
                                 time.perf_counter_ns() - t0)
                    (rl, lo, counts, unmatched, rl_ok, totals) = res[:6]
                    if full:
                        # OR-ing covered build rows is idempotent, so a
                        # skew re-run of the halves is safe
                        matched = res[6]
                    totals_np = np.asarray(totals)  # one host sync/epoch
                    per_dev_rows = totals_np[:, 0] + (
                        totals_np[:, 1]
                        if jt in (JoinType.LEFT_OUTER, JoinType.FULL_OUTER)
                        else 0)
                    if (skew_on
                            and epoch.num_rows > max(skew_min_rows, 1)
                            and per_dev_rows.max() > skew_factor
                            * max(per_dev_rows.mean(), 1.0)):
                        # split depth straight from the measured ratio
                        # (Spark AQE sizes splits from stats the same
                        # way) — a single hot key keeps max/mean
                        # constant under halving, so per-level
                        # re-probing would pay log2(n) wasted probes
                        import math as _math

                        ratio = per_dev_rows.max() / max(
                            per_dev_rows.mean(), 1.0)
                        k = max(1, _math.ceil(
                            _math.log2(ratio / skew_factor)) + 1)
                        parts = min(1 << k, 16, max(
                            epoch.num_rows // max(skew_min_rows, 1), 2))
                        step = -(-epoch.num_rows // parts)
                        self.skew_splits += 1
                        self.metric("skewSplits").add(1)
                        from spark_rapids_tpu.columnar.column import (
                            DEFAULT_ROW_BUCKETS,
                            round_up_bucket,
                        )

                        # bucketed capacities: sub-epochs land on the
                        # standard row-bucket ladder so the probe/p2
                        # programs compiled for those buckets are reused
                        # (arbitrary capacities would each compile fresh
                        # — minutes per program on the tunneled chip)
                        cap2 = round_up_bucket(max(step, 1),
                                               DEFAULT_ROW_BUCKETS)
                        for s0 in range(0, epoch.num_rows, step):
                            ln = min(step, epoch.num_rows - s0)
                            sub = epoch.slice_rows(s0, ln)
                            if sub.capacity != cap2:
                                sub = ColumnarBatch(
                                    [c.slice_to(cap2) for c in
                                     sub.columns], sub.num_rows,
                                    sub.schema)
                            pending.append(sub)
                        continue
                    flat = tuple(rl) + tuple(rr)
                    if jt in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI):
                        out_cap = rl[0].capacity // n_dev
                    else:
                        # pow2 ladder floored at the probe epoch's shard
                        # cap so repeated epochs reuse one program
                        out_cap = max(int(per_dev_rows.max()), 1,
                                      rl[0].capacity // n_dev)
                        out_cap = 1 << (out_cap - 1).bit_length()
                    key2 = (out_cap, epoch.capacity)
                    if key2 not in self._p2:
                        self._p2[key2] = self._build_p2(
                            out_cap, l_schema, r_schema, len(rl))
                    out_cols, out_rows = self._p2[key2](
                        flat, row_index, lo, counts, unmatched, rl_ok,
                        totals)
                    rows_np = np.asarray(out_rows)  # one host sync/epoch
                per_dev_cap = out_cols[0].capacity // n_dev
                for d in range(n_dev):
                    ng = int(rows_np[d])
                    if ng == 0:
                        continue
                    lo_i = d * per_dev_cap
                    cols = [c.gather(jnp.arange(lo_i, lo_i + per_dev_cap))
                            for c in out_cols[:keep_cols]]
                    yield self._emit(cols, ng)
        if full:
            with self.metrics["opTime"].timed():
                bcap_local = swords[0].shape[0] // n_dev
                if self._ptail is None:
                    self._ptail = self._build_ptail(bcap_local)
                tail_cols, tail_rows = self._ptail(rr, row_index, matched,
                                                   rr_ok)
                tail_np = np.asarray(tail_rows)  # one host sync
            n_l = len(l_schema.fields)
            per_dev_cap = tail_cols[0].capacity // n_dev
            for d in range(n_dev):
                ng = int(tail_np[d])
                if ng == 0:
                    continue
                lo_i = d * per_dev_cap
                bcols = [c.gather(jnp.arange(lo_i, lo_i + per_dev_cap))
                         for c in tail_cols]
                lcols = self._null_cols(out_schema.fields[:n_l],
                                        per_dev_cap)
                yield self._emit(lcols + list(bcols), ng)

    def _emit(self, cols, ng):
        """Emit one output batch, reordering mirrored RIGHT OUTER columns
        back to the original left-then-right order."""
        if self._mirror_nl is not None:
            nl = self._mirror_nl
            cols = cols[nl:] + cols[:nl]
        return self._count_output(
            ColumnarBatch(list(cols), ng, self._orig_output))


class TpuIciSortExec(TpuExec):
    """Distributed global sort over the mesh — the third ICI stage shape
    (VERDICT r2 missing #1): sampled global range bounds, range all-to-all
    exchange, per-device local sorts, ordered emit.

    Reference analog: GpuRangePartitioner (sample-based bounds) +
    GpuShuffleExchangeExec + per-partition GpuSortExec/
    GpuOutOfCoreSortIterator (SURVEY.md §2.4 Sort/Partitioning).

    Epoch-streamed: pass A spills the child's batches and samples their
    sort-key words host-side; global splitters are the sample quantiles
    (fixing r2 weak #3 — bounds are GLOBAL, not per-batch).  Pass B runs
    each epoch through one SPMD program (range-partition by splitter
    searchsorted, all-to-all over ICI, local sort of the received rows),
    emitting one sorted RUN per device per epoch.  Each device's runs then
    stream through the memory-bounded k-way merge the single-chip
    out-of-core sort uses, and devices emit in rank order — a globally
    ordered stream with per-device peak memory ~ one epoch shard + the
    merge windows."""

    SAMPLES_PER_EPOCH = 512

    def __init__(self, sort, mesh, axis: str = "dp",
                 epoch_bytes: int = 1 << 28):
        super().__init__(list(sort.children))
        self.sort = sort            # single-chip TpuSortExec (reused)
        self.orders = sort.orders
        self.mesh = mesh
        self.axis = axis
        self.epoch_bytes = epoch_bytes
        self._key_fns = {}
        self._part_programs = {}

    @property
    def output(self):
        return self.sort.output

    def describe(self):
        n = self.mesh.devices.size
        return f"TpuIciSort[{n}dev] [{self.sort.describe()}]"

    # -- key sampling (host-side, word space) ---------------------------
    def _key_fn(self, schema, cap):
        key = cap
        if key not in self._key_fns:
            orders = self.orders
            ansi = self.sort.ansi

            def fn(cols, num_rows):
                from spark_rapids_tpu.expr.base import EvalContext
                from spark_rapids_tpu.ops.sortkeys import pack_sort_keys

                batch = ColumnarBatch(list(cols), num_rows, schema)
                ctx = EvalContext(batch, ansi=ansi)
                key_cols = [e.eval_tpu(ctx) for e, _ in orders]
                specs = [s for _, s in orders]
                return tuple(pack_sort_keys(key_cols, specs,
                                            batch.row_mask))

            self._key_fns[key] = tpu_jit(fn)
        return self._key_fns[key]

    def _sample_words(self, batch: ColumnarBatch):
        n = batch.num_rows
        if n == 0:
            return None
        words = self._key_fn(batch.schema, batch.capacity)(
            tuple(batch.columns), jnp.int32(n))
        stride = max(n // self.SAMPLES_PER_EPOCH, 1)
        idx = np.arange(0, n, stride)
        return np.stack([np.asarray(w)[idx] for w in words])  # (nw, s)

    def _splitters(self, samples, n_dev):
        """(n_dev-1, nwords) int64 splitter matrix from pooled samples."""
        pooled = np.concatenate(samples, axis=1)  # (nw, total)
        nw, total = pooled.shape
        order = np.lexsort(pooled[::-1])
        q = [(total * (d + 1)) // n_dev for d in range(n_dev - 1)]
        picks = order[np.clip(q, 0, total - 1)]
        return pooled[:, picks].T.copy()          # (n_dev-1, nw)

    # -- partition + local-sort program ---------------------------------
    def _build_part_program(self, schema, nwords):
        axis = self.axis
        n_dev = int(self.mesh.devices.size)
        orders = self.orders
        ansi = self.sort.ansi

        def per_device(cols, num_rows, splitters):
            from spark_rapids_tpu.expr.base import EvalContext
            from spark_rapids_tpu.ops.sortkeys import (pack_sort_keys,
                                                       sort_permutation)
            from spark_rapids_tpu.parallel.mesh import (
                ici_all_to_all_columns)

            local_cap = cols[0].capacity
            idx = jax.lax.axis_index(axis)
            nloc = jnp.clip(num_rows - idx.astype(jnp.int32) * local_cap,
                            0, local_cap)
            rows = jnp.arange(local_cap) < nloc
            batch = ColumnarBatch(list(cols), nloc, schema)
            ctx = EvalContext(batch, ansi=ansi)
            key_cols = [e.eval_tpu(ctx) for e, _ in orders]
            specs = [s for _, s in orders]
            words = pack_sort_keys(key_cols, specs, rows)
            # target device = count of splitters <= key (lexicographic)
            tgt = jnp.zeros(local_cap, jnp.int32)
            for d in range(n_dev - 1):
                le = jnp.zeros(local_cap, jnp.bool_)
                eq = jnp.ones(local_cap, jnp.bool_)
                for wi, w in enumerate(words):
                    b = splitters[d, wi]
                    le = le | (eq & (b < w))
                    eq = eq & (b == w)
                tgt = tgt + (le | eq).astype(jnp.int32)
            rcols, rok = ici_all_to_all_columns(list(cols), rows, tgt,
                                                n_dev, axis)
            rbatch = ColumnarBatch(list(rcols), jnp.int32(rcols[0].capacity),
                                   schema)
            rctx = EvalContext(rbatch, ansi=ansi)
            rkeys = [e.eval_tpu(rctx) for e, _ in orders]
            perm = sort_permutation(rkeys, specs, rok)
            out = []
            for c in rcols:
                out.append(c.gather(perm))
            cnt = jnp.sum(rok.astype(jnp.int32))
            return tuple(out), cnt.reshape(1)

        return shard_map(
            per_device, mesh=self.mesh,
            in_specs=(P(axis), P(), P()),
            out_specs=(P(axis), P(axis)),
            check_vma=False)

    # -- execution ------------------------------------------------------
    def _spill_epochs(self, spillables):
        """Epoch bucketing over spill HANDLES (the sort retains its input
        as spillables for the second pass, unlike agg/join)."""
        pending, size = [], 0
        for s in spillables:
            pending.append(s)
            size += s.device_bytes
            if size >= self.epoch_bytes:
                yield pending
                pending, size = [], 0
        if pending:
            yield pending

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.memory.spill import get_spill_framework

        fw = get_spill_framework()
        n_dev = int(self.mesh.devices.size)
        schema = self.children[0].output
        spillables = []
        samples = []
        # pass A: spill + sample
        for b in self.children[0].execute_columnar():
            if b.num_rows == 0:
                continue
            sw = self._sample_words(b)
            if sw is not None:
                samples.append(sw)
            spillables.append(fw.track(b))
        if not spillables:
            return
        with self.metrics["opTime"].timed():
            splitters = jnp.asarray(self._splitters(samples, n_dev))
            runs = [[] for _ in range(n_dev)]
            for group in self._spill_epochs(spillables):
                for s in group:
                    s.pin()
                try:
                    batches = [s.get_batch() for s in group]
                    batch = (batches[0] if len(batches) == 1
                             else ColumnarBatch.concat(batches))
                finally:
                    for s in group:
                        s.unpin()
                for s in group:
                    s.close()
                cap = batch.capacity
                if cap % n_dev or cap < n_dev:
                    batch = ColumnarBatch(
                        [c.slice_to(-(-cap // n_dev) * n_dev)
                         for c in batch.columns], batch.num_rows, schema)
                sharded = self._shard(batch)
                pkey = (batch.capacity, splitters.shape[0])
                if pkey not in self._part_programs:
                    self._part_programs[pkey] = self._build_part_program(
                        schema, splitters.shape[1])
                t0 = time.perf_counter_ns()
                out_cols, cnts = self._part_programs[pkey](
                    tuple(sharded), jnp.int32(batch.num_rows), splitters)
                cnts_np = np.asarray(cnts)      # one host sync per epoch
                _ici_account(self.node_name, n_dev, int(cnts_np.sum()),
                             batch.nbytes(), time.perf_counter_ns() - t0)
                per_dev_cap = out_cols[0].capacity // n_dev
                for d in range(n_dev):
                    nrows = int(cnts_np[d])
                    if nrows == 0:
                        continue
                    lo = d * per_dev_cap
                    idxs = jnp.arange(lo, lo + per_dev_cap)
                    cols = [c.gather(idxs) for c in out_cols]
                    runs[d].append(
                        [fw.track(ColumnarBatch(cols, nrows, schema)),
                         nrows, 0])
        # ordered emit: device 0's runs first, then device 1, ...
        for d in range(n_dev):
            if not runs[d]:
                continue
            if len(runs[d]) == 1:
                s = runs[d][0][0]
                s.pin()
                try:
                    yield self._count_output(s.get_batch())
                finally:
                    s.unpin()
                s.close()
                continue
            yield from (self._count_output(b)
                        for b in self.sort._merge_runs(runs[d], schema))

    def _shard(self, batch: ColumnarBatch):
        def put(arr):
            if arr is None:
                return None
            return jax.device_put(
                arr, NamedSharding(self.mesh, P(self.axis)))

        return [DeviceColumn(c.dtype, put(c.validity), data=put(c.data),
                             chars=put(c.chars), lengths=put(c.lengths),
                             elem_valid=put(c.elem_valid))
                for c in batch.columns]


def _build_exchange_epoch_program(mesh, axis: str, tgt_of):
    """Shared SPMD exchange program for the window/repartition stages:
    local rows -> target device ids (``tgt_of``) -> all-to-all over ICI ->
    prefix compaction.  Returns per-device (received cols, count)."""
    n_dev = int(mesh.devices.size)

    def per_device(cols, num_rows):
        from spark_rapids_tpu.ops.filterops import compact_columns
        from spark_rapids_tpu.parallel.mesh import ici_all_to_all_columns

        local_cap = cols[0].capacity
        idx = jax.lax.axis_index(axis)
        nloc = jnp.clip(num_rows - idx.astype(jnp.int32) * local_cap,
                        0, local_cap)
        rows = jnp.arange(local_cap) < nloc
        tgt = tgt_of(cols, nloc, idx, local_cap)
        rcols, rok = ici_all_to_all_columns(list(cols), rows, tgt,
                                            n_dev, axis)
        out, cnt = compact_columns(rok, rcols)
        return tuple(out), cnt.astype(jnp.int32).reshape(1)

    return shard_map(
        per_device, mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=(P(axis), P(axis)),
        check_vma=False)


def _build_cross_slice_program(mesh, tgt_of):
    """Two-level (host x ici) exchange program: partition ids from
    ``tgt_of`` route hierarchically — intra-slice ICI hop to the local
    device index, then ONE hop per row across the host (DCN-analog)
    axis (parallel/crossslice.py's protocol, generalized to whole
    batches)."""
    n_host = int(mesh.shape["host"])
    n_ici = int(mesh.shape["ici"])

    def per_device(cols, num_rows):
        from spark_rapids_tpu.ops.filterops import compact_columns
        from spark_rapids_tpu.parallel.crossslice import (
            cross_slice_all_to_all_columns,
        )

        local_cap = cols[0].capacity
        hi = jax.lax.axis_index("host")
        ii = jax.lax.axis_index("ici")
        idx = (hi * n_ici + ii).astype(jnp.int32)
        nloc = jnp.clip(num_rows - idx * local_cap, 0, local_cap)
        rows = jnp.arange(local_cap) < nloc
        pid = tgt_of(cols, nloc, idx, local_cap)
        rcols, rok = cross_slice_all_to_all_columns(
            list(cols), rows, pid, n_host, n_ici)
        out, cnt = compact_columns(rok, list(rcols))
        return tuple(out), cnt.astype(jnp.int32).reshape(1)

    return shard_map(
        per_device, mesh=mesh,
        in_specs=(P(("host", "ici")), P()),
        out_specs=(P(("host", "ici")), P(("host", "ici"))),
        check_vma=False)


def mesh_exchange_schema_supported(schema) -> bool:
    """The generic exchange stages ride _concat_cols/_fit_cols, which
    handle flat and plain-string layouts; nested columns keep the host
    path (the rewrites check this before claiming a stage)."""
    return not any(
        isinstance(f.dataType, (T.ArrayType, T.MapType, T.StructType))
        for f in schema.fields)


class _IciExchangeStageBase(TpuExec):
    """Shared epoch driver for the exchange-shaped ICI stages (window /
    generic repartition): pad to the mesh, shard, run the exchange
    program, sync received counts, re-bucket the compacted block."""

    def __init__(self, children, mesh, axis: str, epoch_bytes: int):
        super().__init__(children)
        self.mesh = mesh
        self.axis = axis
        self.epoch_bytes = epoch_bytes
        self._pex = {}

    def _tgt_of(self):
        raise NotImplementedError

    def _build_program(self):
        """The per-capacity SPMD exchange program; subclasses with a
        different routing topology (cross-slice) override."""
        return _build_exchange_epoch_program(self.mesh, self.axis,
                                             self._tgt_of())

    def _run_exchange_epoch(self, epoch: ColumnarBatch):
        n_dev = int(self.mesh.devices.size)
        epoch = _ceil_to_mesh(epoch, n_dev)
        sharded = _shard_cols(epoch, self.mesh, self.axis)
        pkey = epoch.capacity
        if pkey not in self._pex:
            self._pex[pkey] = self._build_program()
        t0 = time.perf_counter_ns()
        rcols, cnts = self._pex[pkey](tuple(sharded),
                                      jnp.int32(epoch.num_rows))
        cnts_np = np.asarray(cnts).reshape(-1)  # one host sync per epoch
        _ici_account(self.node_name, n_dev, int(cnts_np.sum()),
                     epoch.nbytes(), time.perf_counter_ns() - t0)
        per_dev_cap = rcols[0].capacity // n_dev
        need = max(int(cnts_np.max()), 1)
        blk_cap = min(1 << (need - 1).bit_length(), per_dev_cap)
        block = (_rebucket_sharded(rcols, per_dev_cap, blk_cap, n_dev,
                                   self.mesh, self.axis)
                 if blk_cap != per_dev_cap else list(rcols))
        return block, blk_cap, cnts_np

    def _cnt_dev(self, cnts_np):
        return jax.device_put(
            np.asarray(cnts_np, np.int32).reshape(-1),
            NamedSharding(self.mesh, P(self.axis)))

    def _emit_per_device(self, cols, cnts_np, schema):
        n_dev = int(self.mesh.devices.size)
        per_dev_cap = cols[0].capacity // n_dev
        for d in range(n_dev):
            ng = int(cnts_np[d])
            if ng == 0:
                continue
            lo = d * per_dev_cap
            out = [c.gather(jnp.arange(lo, lo + per_dev_cap))
                   for c in cols]
            yield self._count_output(ColumnarBatch(out, ng, schema))


class TpuIciWindowExec(_IciExchangeStageBase):
    """Distributed partitioned window over the mesh — the fourth ICI stage
    shape (VERDICT r3 Next #2): hash all-to-all on the PARTITION BY keys
    co-locates every window partition on one device, then the unchanged
    single-chip window program (exec/window.TpuWindowExec._window_fn) runs
    per device inside shard_map.

    Reference analog: GpuWindowExec downstream of a hash-partitioned
    GpuShuffleExchangeExec (SURVEY.md §2.4 Window, §5.8): the reference
    relies on the exchange for partition co-location; on TPU the exchange
    IS the collective step of this exec.

    Epoch-streamed: each epoch runs one SPMD exchange program, the
    compacted block re-buckets to the smallest pow2 per-device capacity,
    and blocks fold into one device-resident accumulator; the window
    program runs once after the last epoch.  Programs: 1 exchange +
    [1 fold] per epoch + 1 window; one host sync per epoch."""

    def __init__(self, window, mesh, axis: str = "dp",
                 epoch_bytes: int = 1 << 28):
        super().__init__(list(window.children), mesh, axis, epoch_bytes)
        self.window = window            # single-chip TpuWindowExec (reused)
        self._pfold = {}
        self._pwin = {}

    @property
    def output(self):
        return self.window.output

    def describe(self):
        n = self.mesh.devices.size
        return f"TpuIciWindow[{n}dev] [{self.window.describe()}]"

    def _tgt_of(self):
        window = self.window
        n_dev = int(self.mesh.devices.size)
        schema = self.children[0].output

        def tgt(cols, nloc, idx, local_cap):
            from spark_rapids_tpu.expr.base import EvalContext
            from spark_rapids_tpu.ops.hashing import spark_partition_ids

            batch = ColumnarBatch(list(cols), nloc, schema)
            ctx = EvalContext(batch, ansi=window.ansi)
            pcols = [e.eval_tpu(ctx) for e in window.partition_by]
            return spark_partition_ids(pcols, n_dev)

        return tgt

    # ------------------------------------------------------------------
    def _build_fold_program(self, acc_cap: int, blk_cap: int, out_cap: int):
        """Concat the accumulator's and the new block's per-device valid
        prefixes into one prefix-compacted accumulator of out_cap rows."""
        axis = self.axis

        def per_device(acc_cols, acc_cnt, blk_cols, blk_cnt):
            from spark_rapids_tpu.ops.filterops import compact_columns

            rows_a = jnp.arange(acc_cap, dtype=jnp.int32) < acc_cnt[0]
            rows_b = jnp.arange(blk_cap, dtype=jnp.int32) < blk_cnt[0]
            cat = [_concat_cols(a, b)
                   for a, b in zip(acc_cols, blk_cols)]
            keep = jnp.concatenate([rows_a, rows_b])
            out, _cnt = compact_columns(keep, cat)
            return _fit_cols(out, out_cap)

        return shard_map(
            per_device, mesh=self.mesh,
            in_specs=(P(axis),) * 4,
            out_specs=P(axis),
            check_vma=False)

    def _build_window_program(self, acc_cap: int):
        axis = self.axis
        window = self.window

        def per_device(cols, cnt):
            return tuple(window._window_fn(tuple(cols), cnt[0]))

        return shard_map(
            per_device, mesh=self.mesh,
            in_specs=(P(axis), P(axis)),
            out_specs=P(axis),
            check_vma=False)

    # ------------------------------------------------------------------
    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        n_dev = int(self.mesh.devices.size)
        acc = None
        acc_cnts = None
        for epoch in _epoch_batches(self.children[0].execute_columnar(),
                                    self.epoch_bytes):
            # per-epoch timing only: the child's execution must not be
            # charged to this stage's opTime
            with self.metrics["opTime"].timed():
                block, blk_cap, cnts_np = self._run_exchange_epoch(epoch)
                if acc is None:
                    acc, acc_cnts = block, cnts_np
                    continue
                acc_cap = acc[0].capacity // n_dev
                tot = acc_cnts + cnts_np
                need = max(int(tot.max()), 1)
                out_cap = min(1 << (need - 1).bit_length(),
                              acc_cap + blk_cap)
                fkey = (acc_cap, blk_cap, out_cap)
                if fkey not in self._pfold:
                    self._pfold[fkey] = self._build_fold_program(
                        acc_cap, blk_cap, out_cap)
                acc = list(self._pfold[fkey](
                    tuple(acc), self._cnt_dev(acc_cnts),
                    tuple(block), self._cnt_dev(cnts_np)))
                acc_cnts = tot
        if acc is None:
            return
        with self.metrics["opTime"].timed():
            acc_cap = acc[0].capacity // n_dev
            if acc_cap not in self._pwin:
                self._pwin[acc_cap] = self._build_window_program(acc_cap)
            out_cols = self._pwin[acc_cap](tuple(acc),
                                           self._cnt_dev(acc_cnts))
        yield from self._emit_per_device(out_cols, acc_cnts,
                                         self.window.output)


class TpuIciRepartitionExec(_IciExchangeStageBase):
    """Generic mesh repartition — the fifth ICI stage shape (VERDICT r3
    Next #2): ANY hash/round-robin shuffle exchange lowers to one SPMD
    all-to-all program per epoch, so exchanges that no specialized ICI
    stage claims still execute on the mesh instead of the host loop.

    Reference analog: GpuShuffleExchangeExec + RapidsShuffleManager
    (SURVEY.md §2.7) — the generic exchange every plan shape rides.

    Per epoch: partition ids (murmur3 pmod for hash, cycling offset for
    round-robin) -> all-to-all -> compact -> re-bucket -> emit one batch
    per device.  Downstream single-chip operators consume the emitted
    batches exactly as they would the host shuffle's partitions."""

    def __init__(self, exchange, mesh, axis: str = "dp",
                 epoch_bytes: int = 1 << 28, cross_hosts: int = 0):
        self.cross_hosts = 0
        n_dev = int(mesh.devices.size)
        if cross_hosts > 1 and n_dev % cross_hosts == 0 \
                and n_dev // cross_hosts >= 1:
            # two-level (host x ici) routing: rebuild the SAME devices
            # as the hierarchical mesh; the outer axis models the
            # slice-to-slice fabric (parallel/crossslice.py)
            from spark_rapids_tpu.parallel.crossslice import make_mesh2

            mesh = make_mesh2(cross_hosts, n_dev // cross_hosts,
                              devices=list(mesh.devices.reshape(-1)))
            axis = ("host", "ici")
            self.cross_hosts = cross_hosts
        super().__init__(list(exchange.children), mesh, axis, epoch_bytes)
        self.exchange = exchange
        self.partitioning = exchange.partitioning

    def _build_program(self):
        if self.cross_hosts:
            return _build_cross_slice_program(self.mesh, self._tgt_of())
        return super()._build_program()

    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        n = self.mesh.devices.size
        lvl = (f" cross_slice={self.cross_hosts}x"
               f"{n // self.cross_hosts}" if self.cross_hosts else "")
        return (f"TpuIciRepartition[{n}dev{lvl}] "
                f"{self.partitioning.describe()}")

    def _tgt_of(self):
        from spark_rapids_tpu.plan.nodes import HashPartitioning

        part = self.partitioning
        n_dev = int(self.mesh.devices.size)
        schema = self.children[0].output
        ansi = getattr(self.exchange, "ansi", False)

        if isinstance(part, HashPartitioning):
            def tgt(cols, nloc, idx, local_cap):
                from spark_rapids_tpu.expr.base import EvalContext
                from spark_rapids_tpu.ops.hashing import spark_partition_ids

                batch = ColumnarBatch(list(cols), nloc, schema)
                ctx = EvalContext(batch, ansi=ansi)
                kcols = [e.eval_tpu(ctx) for e in part.keys]
                return spark_partition_ids(kcols, n_dev)
        else:
            def tgt(cols, nloc, idx, local_cap):
                return ((jnp.arange(local_cap, dtype=jnp.int32)
                         + idx.astype(jnp.int32)) % n_dev)

        return tgt

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        for epoch in _epoch_batches(self.children[0].execute_columnar(),
                                    self.epoch_bytes):
            with self.metrics["opTime"].timed():
                block, blk_cap, cnts_np = self._run_exchange_epoch(epoch)
            yield from self._emit_per_device(block, cnts_np, self.output)
