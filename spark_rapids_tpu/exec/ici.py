"""ICI mesh execution — whole plan stages as one SPMD collective program.

Reference analog: the reference's distributed execution is Spark tasks
pulling shuffle blocks peer-to-peer over UCX (SURVEY.md §2.7/§5.8,
RapidsShuffleClient/Server).  TPU-first replacement: the stage pair

    HashAggregate(FINAL) <- [Coalesce] <- ShuffleExchange <-
    HashAggregate(PARTIAL, fused scan ops)

compiles to ONE shard_map program over the device mesh:

    per device:  local partial _agg_fn (the unchanged single-chip program)
              -> spark murmur3 partition ids over the group keys
              -> all-to-all of every partial-buffer column over ICI
              -> local final _agg_fn on the received buffer rows

The per-device program IS the single-chip code path — shard_map only wires
the collectives around it (the "same program, sharded data" SPMD design the
scaling-book recipe prescribes).  Global (no-key) aggregates skip the
all-to-all: partial buffers are all-gathered and every device finalizes the
replicated merge (one row; replication is free).

The Spark-async vs SPMD-collective impedance mismatch (SURVEY.md §7 hard
part #1) is resolved by epoching: an exchange is already a full barrier in
Spark semantics, so executing it as one collective step loses no generality.

Current quota layout: the all-to-all reserves local-cap slots per peer
(received capacity = global cap).  jax.lax.ragged_all_to_all is the planned
upgrade for skewed partitions.
"""
from __future__ import annotations

from typing import Iterator, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.exec.base import TpuExec

try:  # jax>=0.8
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore


class TpuIciShuffleAggExec(TpuExec):
    """Fused distributed aggregation stage over a jax Mesh."""

    def __init__(self, partial, final, mesh, axis: str = "dp"):
        super().__init__(list(partial.children))
        self.partial = partial
        self.final = final
        self.mesh = mesh
        self.axis = axis
        self._program = None

    @property
    def output(self):
        return self.final.output

    def describe(self):
        n = self.mesh.devices.size
        return (f"TpuIciShuffleAgg[{n}dev] partial=({self.partial.describe()})"
                f" final=({self.final.describe()})")

    # ------------------------------------------------------------------
    def _build_program(self):
        axis = self.axis
        n_dev = int(self.mesh.devices.size)
        partial = self.partial
        final = self.final
        grouped = bool(final.grouping)
        nkeys = len(partial.grouping)

        def per_device(cols, num_rows):
            from spark_rapids_tpu.parallel.mesh import ici_all_to_all_columns

            local_cap = cols[0].capacity
            idx = jax.lax.axis_index(axis)
            nloc = jnp.clip(num_rows - idx.astype(jnp.int32) * local_cap,
                            0, local_cap)
            pcols, ng = partial._agg_fn(cols, nloc)
            pcols = list(pcols)
            grows = jnp.arange(pcols[0].capacity) < ng
            if grouped:
                from spark_rapids_tpu.ops.hashing import spark_partition_ids

                tgt = spark_partition_ids(pcols[:nkeys], n_dev)
                rcols, rok = ici_all_to_all_columns(pcols, grows, tgt,
                                                    n_dev, axis)
                fcols, fng = final._agg_fn(
                    tuple(rcols), jnp.int32(rcols[0].capacity), row_valid=rok)
            else:
                gathered = []
                for c in pcols:
                    validity = jax.lax.all_gather(c.validity, axis, tiled=True)
                    if c.is_string:
                        gathered.append(DeviceColumn(
                            c.dtype, validity,
                            chars=jax.lax.all_gather(c.chars, axis, tiled=True),
                            lengths=jax.lax.all_gather(c.lengths, axis,
                                                       tiled=True)))
                    else:
                        gathered.append(DeviceColumn(
                            c.dtype, validity,
                            data=jax.lax.all_gather(c.data, axis, tiled=True)))
                rok = jax.lax.all_gather(grows, axis, tiled=True)
                fcols, fng = final._agg_fn(
                    tuple(gathered), jnp.int32(gathered[0].capacity),
                    row_valid=rok)
            return tuple(fcols), fng.reshape(1)

        out_spec = P(axis) if grouped else P()
        return shard_map(
            per_device, mesh=self.mesh,
            in_specs=(P(axis), P()),
            out_specs=(out_spec, out_spec),
            check_vma=False)

    # ------------------------------------------------------------------
    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        n_dev = int(self.mesh.devices.size)
        batches = list(self.children[0].execute_columnar())
        if not batches:
            batches = [None]
        with self.metrics["opTime"].timed():
            batch = (ColumnarBatch.concat(batches)
                     if batches[0] is not None and len(batches) > 1
                     else batches[0])
            if batch is None or batch.num_rows == 0:
                yield from self._empty_input()
                return
            cap = batch.capacity
            if cap % n_dev or cap < n_dev:
                batch = ColumnarBatch(
                    [c.slice_to(-(-cap // n_dev) * n_dev)
                     for c in batch.columns], batch.num_rows, batch.schema)
            sharded = self._shard_batch(batch)
            if self._program is None:
                self._program = self._build_program()
            fcols, fng = self._program(tuple(sharded),
                                       jnp.int32(batch.num_rows))
            fng_np = np.asarray(fng)          # one host sync
        out_schema = self.final.output
        if not self.final.grouping:
            yield self._count_output(
                ColumnarBatch([c.gather(jnp.arange(1)) for c in fcols],
                              1, out_schema))
            return
        per_dev_cap = fcols[0].capacity // n_dev
        for d in range(n_dev):
            ng = int(fng_np[d])
            if ng == 0:
                continue
            lo = d * per_dev_cap
            cols = [
                DeviceColumn(c.dtype,
                             c.validity[lo: lo + per_dev_cap],
                             data=None if c.data is None
                             else c.data[lo: lo + per_dev_cap],
                             chars=None if c.chars is None
                             else c.chars[lo: lo + per_dev_cap],
                             lengths=None if c.lengths is None
                             else c.lengths[lo: lo + per_dev_cap])
                for c in fcols]
            yield self._count_output(
                ColumnarBatch(cols, ng, out_schema))

    def _shard_batch(self, batch: ColumnarBatch) -> List[DeviceColumn]:
        """Row-shard every column array over the mesh axis."""
        def put(arr):
            if arr is None:
                return None
            spec = P(self.axis) if arr.ndim >= 1 else P()
            return jax.device_put(arr, NamedSharding(self.mesh, spec))

        return [DeviceColumn(c.dtype, put(c.validity), data=put(c.data),
                             chars=put(c.chars), lengths=put(c.lengths),
                             elem_valid=put(c.elem_valid))
                for c in batch.columns]

    def _empty_input(self):
        """Empty scan: reproduce the single-chip chain's semantics — the
        partial emits its initial buffer row (global agg) which the final
        merges and finalizes; grouped aggregates emit nothing."""
        from spark_rapids_tpu.columnar.batch import empty_batch

        if self.final.grouping:
            yield self._count_output(empty_batch(self.final.output))
            return
        pb = self.partial._global_agg_empty()
        merged = self.final._merge_batch(pb)
        yield self._count_output(self.final._finalize(merged))


class TpuIciShuffleJoinExec(TpuExec):
    """Distributed shuffled equi-join over the mesh — the UCX-shuffle
    join's TPU-native replacement (SURVEY.md §5.8 mode 2, VERDICT r1 #3's
    "and the shuffled join").

    Two SPMD steps (mirroring the agg exec's epoch design):

      1. COLLECTIVE program: both inputs row-shard over the mesh; each
         device computes murmur3 partition ids of its join keys and
         all-to-alls both sides over ICI (null-keyed rows stay put), then
         sorts its received build keys and probes counts — returning the
         received shards + gather-plan arrays, all still device-sharded.
      2. LOCAL program: with the per-device pair counts synced once to the
         host (the static output capacity), a collective-free shard_map
         materializes each device's join output via the same searchsorted
         gather maps the single-chip join uses.

    Supported: INNER / LEFT_OUTER / LEFT_SEMI / LEFT_ANTI equi-joins
    without residual conditions; everything else keeps the single-chip
    exec.
    """

    def __init__(self, join, left_inner, right_inner, mesh,
                 axis: str = "dp"):
        super().__init__([left_inner, right_inner])
        self.join = join            # TpuShuffledSymmetricHashJoinExec
        self.mesh = mesh
        self.axis = axis
        self._p1 = None
        self._p2 = {}

    @property
    def output(self):
        return self.join.output

    def describe(self):
        n = self.mesh.devices.size
        return (f"TpuIciShuffleJoin[{n}dev] "
                f"{self.join.join_type.value} "
                f"[{self.join.describe()}]")

    # ------------------------------------------------------------------
    def _keys_and_valid(self, cols, schema, keys, nloc, ansi):
        from spark_rapids_tpu.exec.join import _key_words_of
        from spark_rapids_tpu.expr.base import EvalContext

        cap = cols[0].capacity
        b = ColumnarBatch(list(cols), nloc, schema)
        ctx = EvalContext(b, ansi=ansi)
        key_cols = [k.eval_tpu(ctx) for k in keys]
        rows = jnp.arange(cap) < nloc
        kvalid = rows
        for kc in key_cols:
            kvalid = kvalid & kc.validity
        return key_cols, rows, kvalid

    def _build_p1(self, l_schema, r_schema):
        axis = self.axis
        n_dev = int(self.mesh.devices.size)
        join = self.join

        def per_device(lcols, l_rows, rcols, r_rows):
            from spark_rapids_tpu.exec.join import (
                _key_words_of,
                _multiword_searchsorted,
            )
            from spark_rapids_tpu.ops.hashing import spark_partition_ids
            from spark_rapids_tpu.parallel.mesh import ici_all_to_all_columns

            idx = jax.lax.axis_index(axis)
            lcap = lcols[0].capacity
            rcap = rcols[0].capacity
            nloc_l = jnp.clip(l_rows - idx.astype(jnp.int32) * lcap, 0, lcap)
            nloc_r = jnp.clip(r_rows - idx.astype(jnp.int32) * rcap, 0, rcap)
            # ---- exchange left
            lkeys, lrows, lkvalid = self._keys_and_valid(
                lcols, l_schema, join.left_keys, nloc_l, join.ansi)
            tgt_l = jnp.where(
                lkvalid,
                spark_partition_ids(lkeys, n_dev),
                idx.astype(jnp.int32))  # null-keyed rows stay local
            rl, rl_ok = ici_all_to_all_columns(list(lcols), lrows, tgt_l,
                                               n_dev, axis)
            # ---- exchange right
            rkeys, rrows, rkvalid = self._keys_and_valid(
                rcols, r_schema, join.right_keys, nloc_r, join.ansi)
            tgt_r = jnp.where(
                rkvalid,
                spark_partition_ids(rkeys, n_dev),
                idx.astype(jnp.int32))
            rr, rr_ok = ici_all_to_all_columns(list(rcols), rrows, tgt_r,
                                               n_dev, axis)
            # ---- local build (received right)
            bkeys, _, bkvalid = self._keys_and_valid(
                rr, r_schema, join.right_keys,
                jnp.int32(rr[0].capacity), join.ansi)
            bkvalid = bkvalid & rr_ok
            bwords = _key_words_of(bkeys)
            inv = (~bkvalid).astype(jnp.int64)
            iota = jnp.arange(rr[0].capacity, dtype=jnp.int32)
            srt = jax.lax.sort(tuple([inv] + bwords + [iota]),
                               num_keys=1 + len(bwords), is_stable=True)
            swords = list(srt[1:-1])
            row_index = srt[-1]
            n_valid = jnp.sum(bkvalid.astype(jnp.int32))
            # ---- local probe (received left)
            pkeys, _, pkvalid = self._keys_and_valid(
                rl, l_schema, join.left_keys,
                jnp.int32(rl[0].capacity), join.ansi)
            pkvalid = pkvalid & rl_ok
            qwords = _key_words_of(pkeys)
            lo = _multiword_searchsorted(swords, n_valid, qwords, "left")
            hi = _multiword_searchsorted(swords, n_valid, qwords, "right")
            counts = jnp.where(pkvalid, hi - lo, 0)
            total = jnp.sum(counts.astype(jnp.int64))
            unmatched = rl_ok & (counts == 0)
            n_unmatched = jnp.sum(unmatched.astype(jnp.int64))
            flat = []
            for c in list(rl) + list(rr):
                flat.append(c)
            return (tuple(flat), tuple(swords), row_index, lo, counts,
                    unmatched, rl_ok,
                    jnp.stack([total, n_unmatched]).reshape(1, 2))

        return shard_map(
            per_device, mesh=self.mesh,
            in_specs=(P(axis), P(), P(axis), P()),
            out_specs=(P(axis), P(axis), P(axis), P(axis), P(axis),
                       P(axis), P(axis), P(axis)),
            check_vma=False)

    def _build_p2(self, out_cap, l_schema, r_schema, n_l):
        """Collective-free per-device materialization."""
        axis = self.axis
        join = self.join
        jt = join.join_type
        from spark_rapids_tpu.plan.nodes import JoinType

        def per_device(flat, row_index, lo, counts, unmatched, rl_ok,
                       totals):
            from spark_rapids_tpu.ops.filterops import (
                compact_columns,
                gather_columns,
            )

            lcols = list(flat[:n_l])
            rcols = list(flat[n_l:])
            total = totals[0, 0]
            n_um = totals[0, 1]
            if jt in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI):
                keep = ((counts == 0) if jt == JoinType.LEFT_ANTI
                        else (counts > 0)) & rl_ok
                out, cnt = compact_columns(keep, lcols)
                return tuple(out), cnt.astype(jnp.int64).reshape(1)
            n = counts.shape[0]
            offsets = jnp.cumsum(counts.astype(jnp.int64))
            excl = offsets - counts.astype(jnp.int64)
            j = jnp.arange(out_cap, dtype=jnp.int64)
            probe_row = jnp.searchsorted(offsets, j,
                                         side="right").astype(jnp.int32)
            probe_row = jnp.clip(probe_row, 0, n - 1)
            k = j - excl[probe_row]
            build_pos = lo[probe_row].astype(jnp.int64) + k
            bcap = row_index.shape[0]
            build_row = row_index[jnp.clip(build_pos, 0,
                                           bcap - 1).astype(jnp.int32)]
            in_pairs = j < total
            with_um = jt == JoinType.LEFT_OUTER
            probe_idx = jnp.where(in_pairs, probe_row, 0)
            out_rows = total + (n_um if with_um else 0)
            if with_um:
                um_pos = jnp.cumsum(unmatched.astype(jnp.int64)) - 1
                um_slot = total + um_pos
                scatter_to = jnp.where(unmatched, um_slot,
                                       out_cap).astype(jnp.int64)
                probe_idx_full = jnp.zeros(out_cap, jnp.int32).at[
                    jnp.clip(scatter_to, 0, out_cap)].set(
                    jnp.arange(n, dtype=jnp.int32), mode="drop")
                probe_idx = jnp.where(in_pairs, probe_row, probe_idx_full)
            row_valid = j < out_rows
            out_l = gather_columns(probe_idx, row_valid, lcols)
            out_r = gather_columns(
                jnp.where(in_pairs, build_row, 0), row_valid & in_pairs,
                rcols)
            return (tuple(out_l + out_r),
                    out_rows.astype(jnp.int64).reshape(1))

        return shard_map(
            per_device, mesh=self.mesh,
            in_specs=(P(axis),) * 7,
            out_specs=(P(axis), P(axis)),
            check_vma=False)

    # ------------------------------------------------------------------
    def _collect_side(self, child) -> ColumnarBatch:
        batches = list(child.execute_columnar())
        if not batches:
            from spark_rapids_tpu.columnar.batch import empty_batch

            return empty_batch(child.output)
        return (batches[0] if len(batches) == 1
                else ColumnarBatch.concat(batches))

    def _pad_for_mesh(self, batch: ColumnarBatch) -> ColumnarBatch:
        n_dev = int(self.mesh.devices.size)
        cap = batch.capacity
        if cap % n_dev or cap < n_dev:
            batch = ColumnarBatch(
                [c.slice_to(-(-cap // n_dev) * n_dev)
                 for c in batch.columns], batch.num_rows, batch.schema)
        return batch

    def _shard(self, batch: ColumnarBatch) -> List[DeviceColumn]:
        def put(arr):
            if arr is None:
                return None
            return jax.device_put(
                arr, NamedSharding(self.mesh, P(self.axis)))

        return [DeviceColumn(c.dtype, put(c.validity), data=put(c.data),
                             chars=put(c.chars), lengths=put(c.lengths),
                             elem_valid=put(c.elem_valid))
                for c in batch.columns]

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.plan.nodes import JoinType

        n_dev = int(self.mesh.devices.size)
        left = self._pad_for_mesh(self._collect_side(self.children[0]))
        right = self._pad_for_mesh(self._collect_side(self.children[1]))
        l_schema, r_schema = left.schema, right.schema
        with self.metrics["opTime"].timed():
            ls = self._shard(left)
            rs = self._shard(right)
            if self._p1 is None:
                self._p1 = self._build_p1(l_schema, r_schema)
            (flat, swords, row_index, lo, counts, unmatched, rl_ok,
             totals) = self._p1(tuple(ls), jnp.int32(left.num_rows),
                                tuple(rs), jnp.int32(right.num_rows))
            totals_np = np.asarray(totals)      # one host sync
            jt = self.join.join_type
            per_dev_rows = totals_np[:, 0] + (
                totals_np[:, 1] if jt == JoinType.LEFT_OUTER else 0)
            if jt in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI):
                out_cap = flat[0].capacity // n_dev
            else:
                out_cap = max(int(per_dev_rows.max()), 1)
                out_cap = 1 << (out_cap - 1).bit_length()
            key2 = out_cap
            if key2 not in self._p2:
                self._p2[key2] = self._build_p2(
                    out_cap, l_schema, r_schema, len(ls))
            out_cols, out_rows = self._p2[key2](
                flat, row_index, lo, counts, unmatched, rl_ok, totals)
            rows_np = np.asarray(out_rows)      # one host sync
        out_schema = self.join.output
        per_dev_cap = out_cols[0].capacity // n_dev
        keep_cols = len(out_schema.fields)
        for d in range(n_dev):
            ng = int(rows_np[d])
            if ng == 0:
                continue
            lo_i = d * per_dev_cap
            cols = [c.gather(jnp.arange(lo_i, lo_i + per_dev_cap))
                    for c in out_cols[:keep_cols]]
            yield self._count_output(
                ColumnarBatch(cols, ng, out_schema))
