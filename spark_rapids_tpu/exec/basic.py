"""Scan / Project / Filter / Union / Range + whole-stage fusion.

Reference analog: basicPhysicalOperators.scala (GpuProjectExec, GpuFilterExec,
GpuTieredProject, GpuUnionExec, GpuRangeExec).

The TPU-first centerpiece is ``TpuStageExec``: a chain of narrow operators
(project/filter) is traced ONCE into a single jitted function per shape
bucket — XLA fuses every expression, the filter's mask/compaction, and the
ANSI error-flag reductions into one executable.  This is strictly stronger
than the reference's cuDF AST fusion (which only fuses simple expression
trees); it is why `spark.rapids.tpu.wholeStageFusion.enabled` exists.

Filters keep the row count on device until the stage boundary, where one
host sync reads (count, error flags) back.
"""
from __future__ import annotations

from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import jax
from spark_rapids_tpu.perfcounters import sync_get, tpu_jit
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import DeviceColumn, HostColumn
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.expr.base import EvalContext, Expression, SparkArithmeticException


class _StageOp:
    """One narrow op inside a fused stage."""

    def apply(self, ctx: EvalContext, batch: ColumnarBatch) -> ColumnarBatch:
        raise NotImplementedError

    def apply_masked(self, ctx: EvalContext, batch: ColumnarBatch, mask):
        """Selection-vector mode: no compaction — filters only narrow the
        row mask.  Used when the stage is fused into a downstream aggregate
        (the TPU-first answer to compaction scatters: aggregates consume the
        mask directly, so filtered rows never move)."""
        raise NotImplementedError

    def out_schema(self, in_schema: T.StructType) -> T.StructType:
        raise NotImplementedError


class ProjectOp(_StageOp):
    def __init__(self, exprs: List[Expression]):
        self.exprs = exprs

    def apply(self, ctx, batch):
        ctx.batch = batch
        cols = [e.eval_tpu(ctx) for e in self.exprs]
        return ColumnarBatch(cols, batch.num_rows, self.out_schema(batch.schema))

    def apply_masked(self, ctx, batch, mask):
        return self.apply(ctx, batch), mask

    def out_schema(self, in_schema):
        return T.StructType([
            T.StructField(e.name, e.dataType, e.nullable) for e in self.exprs])


class FilterOp(_StageOp):
    def __init__(self, condition: Expression):
        self.condition = condition

    def _mask(self, ctx, batch, mask):
        ctx.batch = batch
        pred = self.condition.eval_tpu(ctx)
        return pred.data & pred.validity & mask

    def apply(self, ctx, batch):
        from spark_rapids_tpu.ops.filterops import compact_columns

        mask = self._mask(ctx, batch, batch.row_mask)
        cols, count = compact_columns(mask, batch.columns)
        return ColumnarBatch(cols, count, batch.schema)

    def apply_masked(self, ctx, batch, mask):
        return batch, self._mask(ctx, batch, mask)

    def out_schema(self, in_schema):
        return in_schema


class FilterProjectOp(_StageOp):
    """Filter immediately followed by Project, fused: projections evaluate on
    the *uncompacted* batch (vector lanes are free), then only the projected
    columns are compacted — halves scatter traffic vs compacting the full
    input.  Not used under ANSI (a removed row must not raise)."""

    def __init__(self, condition: Expression, exprs: List[Expression]):
        self.condition = condition
        self.exprs = exprs

    def apply(self, ctx, batch):
        from spark_rapids_tpu.ops.filterops import compact_columns

        ctx.batch = batch
        pred = self.condition.eval_tpu(ctx)
        mask = pred.data & pred.validity & batch.row_mask
        cols = [e.eval_tpu(ctx) for e in self.exprs]
        out, count = compact_columns(mask, cols)
        return ColumnarBatch(out, count, self.out_schema(batch.schema))

    def apply_masked(self, ctx, batch, mask):
        ctx.batch = batch
        pred = self.condition.eval_tpu(ctx)
        mask = pred.data & pred.validity & mask
        cols = [e.eval_tpu(ctx) for e in self.exprs]
        out = ColumnarBatch(cols, batch.num_rows,
                            self.out_schema(batch.schema))
        return out, mask

    def out_schema(self, in_schema):
        return T.StructType([
            T.StructField(e.name, e.dataType, e.nullable) for e in self.exprs])


def _fuse_filter_project(ops: List[_StageOp], ansi: bool) -> List[_StageOp]:
    if ansi:
        return ops
    out: List[_StageOp] = []
    i = 0
    while i < len(ops):
        if (i + 1 < len(ops) and isinstance(ops[i], FilterOp)
                and isinstance(ops[i + 1], ProjectOp)):
            out.append(FilterProjectOp(ops[i].condition, ops[i + 1].exprs))
            i += 2
        else:
            out.append(ops[i])
            i += 1
    return out


class TpuStageExec(TpuExec):
    """A fused chain of narrow ops over one child."""

    def __init__(self, ops: Sequence[_StageOp], child: TpuExec,
                 ansi: bool = False):
        super().__init__([child])
        self.ops = _fuse_filter_project(list(ops), ansi)
        self.ansi = ansi
        self._jitted = None
        self._offset_holder = [0]
        self._out_schema = child.output
        for op in self.ops:
            self._out_schema = op.out_schema(self._out_schema)

    @property
    def output(self):
        return self._out_schema

    def describe(self):
        names = "+".join(type(o).__name__.replace("Op", "") for o in self.ops)
        return f"TpuStageExec[{names}]"

    def _op_expressions(self) -> List[Expression]:
        out: List[Expression] = []
        for op in self.ops:
            out.extend(getattr(op, "exprs", []) or [])
            cond = getattr(op, "condition", None)
            if cond is not None:
                out.append(cond)
        return out

    def _has_host_kernels(self) -> bool:
        from spark_rapids_tpu.expr.base import contains_host_kernel

        return any(contains_host_kernel(e) for e in self._op_expressions())

    def _stage_fn(self, in_schema: T.StructType):
        """The traceable stage function + its ANSI message store (filled as
        a trace-time side effect, so it must travel WITH the executable)."""
        ops = self.ops
        ansi = self.ansi

        msgs_store: List[str] = []  # filled as a trace-time side effect

        offset_holder = self._offset_holder

        def fn(cols, num_rows):
            batch = ColumnarBatch(list(cols), num_rows, in_schema)
            # row_offset is only consumed by host-kernel expressions, which
            # force the EAGER path — under jit the closure value would be
            # baked at trace time, but jitted stages never contain them
            ctx = EvalContext(batch, ansi=ansi,
                              # tpulint: disable=trace-closure-state
                              # (eager-only read, per the comment above)
                              row_offset=offset_holder[0])
            for op in ops:
                batch = op.apply(ctx, batch)
            # tpulint: disable=trace-closure-state (deliberate trace-time
            # aux: the store travels WITH the executable as entry.aux)
            msgs_store.clear()
            # tpulint: disable=trace-closure-state (same aux store)
            msgs_store.extend(m for _, m in ctx.error_flags)
            flags = tuple(jnp.any(f) for f, _ in ctx.error_flags)
            return batch.columns, jnp.asarray(batch.num_rows), flags

        return fn, msgs_store

    def _program(self, in_schema: T.StructType):
        """(registry key parts, factory) — shared verbatim by the runtime
        build and the plan-time AOT enumeration so both land on the same
        registry entry."""
        from spark_rapids_tpu.compilecache.keys import (
            conf_fp,
            schema_fp,
            stage_ops_fp,
        )

        ops_fp = stage_ops_fp(self.ops)
        key_parts = None if ops_fp is None else (
            "stage", schema_fp(in_schema), ops_fp, bool(self.ansi),
            conf_fp())

        def factory():
            fn, msgs = self._stage_fn(in_schema)
            return tpu_jit(fn), msgs

        return key_parts, factory

    def _build(self, in_schema: T.StructType):
        # host-kernel expressions (JSON, digests, ... — jax.pure_callback)
        # cannot live inside a compiled TPU program (the PJRT plugin has no
        # host-callback channel); the stage runs op-by-op eagerly instead —
        # callbacks execute directly and the jnp ops still dispatch to the
        # device.  CPU/test backends jit as usual.
        if self._has_host_kernels():
            jitted, msgs_store = self._stage_fn(in_schema)
        else:
            from spark_rapids_tpu.compilecache.registry import cached_program

            key_parts, factory = self._program(in_schema)
            entry = cached_program(key_parts, factory,
                                   label=self.describe())
            jitted, msgs_store = entry.jitted, entry.aux

        def run(batch: ColumnarBatch) -> ColumnarBatch:
            cols, count, flags = jitted(
                tuple(batch.columns), jnp.int32(batch.num_rows))
            # row count + every ANSI error flag in ONE logical round
            # trip — a per-flag bool() was a device sync per flag per
            # batch (tracelint: trace-split-sync)
            host = sync_get((count,) + tuple(flags))
            for f, m in zip(host[1:], list(msgs_store)):
                if f:
                    raise SparkArithmeticException(m)
            return ColumnarBatch(list(cols), int(host[0]),
                                 self._out_schema)

        return run

    def fusion_segment(self):
        """Whole-plan fusion slice (exec/fusion.py): the stage's traced
        chain inlines into a larger fused program.  The ANSI message
        store ``_stage_fn`` fills at trace time travels with the fused
        executable as registry aux — the manifest's fusable-with-rewrite
        rewrite for Filter/Project.  Host-kernel stages must run
        eagerly, so they refuse."""
        if self._has_host_kernels():
            return None
        from spark_rapids_tpu.compilecache.keys import stage_ops_fp
        from spark_rapids_tpu.exec.fusion import PipelineSegment

        ops_fp = stage_ops_fp(self.ops)
        return PipelineSegment(
            name=self.describe(),
            fp=None if ops_fp is None else (
                "stage", ops_fp, bool(self.ansi)),
            make=self._stage_fn,
            out_schema=self._out_schema,
            count_map=None if self._aot_filters_rows()
            else (lambda n: n),
            programs_unfused=1)

    # -- plan-time AOT enumeration (compilecache/aot.py) -----------------
    def _aot_filters_rows(self) -> bool:
        return any(getattr(op, "condition", None) is not None
                   for op in self.ops)

    def aot_output_rows(self):
        # projections preserve row counts exactly; a filtering stage's
        # OUTPUT rows are data-dependent (a concat above would size its
        # capacity from the post-filter counts), though per-batch
        # capacity still passes through (aot_output_caps)
        if self._aot_filters_rows():
            return None
        return self.aot_input_rows()

    def aot_output_caps(self):
        return self.aot_input_caps()

    def aot_emits_single_batch(self):
        # one output batch per input batch
        return self.aot_child_single_batch()

    def aot_programs(self):
        from spark_rapids_tpu.compilecache.aot import (
            AotProgram,
            dummy_batch_args,
        )

        if self._has_host_kernels():
            return []
        caps = self.aot_input_caps()
        if not caps:
            return []
        in_schema = self.children[0].output
        key_parts, factory = self._program(in_schema)

        def args_factory():
            return [dummy_batch_args(in_schema, c) for c in caps]

        return [AotProgram(key_parts, factory, args_factory,
                           f"stage:{self.describe()[:48]}")]

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        child = self.children[0]
        self._offset_holder[0] = 0
        for batch in child.execute_columnar():
            if self._jitted is None:
                self._jitted = self._build(batch.schema)
            with self.metrics["opTime"].timed():
                out = self._jitted(batch)
            self._offset_holder[0] += batch.num_rows
            yield self._count_output(out)


class TpuProjectExec(TpuStageExec):
    def __init__(self, exprs: List[Expression], child: TpuExec,
                 ansi: bool = False):
        super().__init__([ProjectOp(exprs)], child, ansi)
        self.exprs = exprs

    def describe(self):
        return ("TpuProject [" +
                ", ".join(e.sql_string() for e in self.exprs) + "]")


class TpuFilterExec(TpuStageExec):
    def __init__(self, condition: Expression, child: TpuExec,
                 ansi: bool = False):
        super().__init__([FilterOp(condition)], child, ansi)
        self.condition = condition

    def describe(self):
        return f"TpuFilter ({self.condition.sql_string()})"


def fuse_stages(root: TpuExec) -> TpuExec:
    """Collapse adjacent TpuStageExec chains (whole-stage fusion pass).

    Reference analog: GpuTransitionOverrides' post-processing; here it turns
    Project(Filter(Project(x))) into one jitted XLA program.  A stage feeding
    a row-consuming aggregate is absorbed INTO the aggregate's program
    (mask mode): scan batch -> filter/project/partial-agg is then ONE XLA
    executable with no compaction scatter and no intermediate HBM round trip
    — strictly stronger than the reference's cuDF AST fusion."""
    from spark_rapids_tpu.exec.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.plan.nodes import AggregateMode

    root.children = [fuse_stages(c) for c in root.children]
    if isinstance(root, TpuStageExec):
        child = root.children[0]
        if isinstance(child, TpuStageExec) and child.ansi == root.ansi:
            merged = TpuStageExec(child.ops + root.ops, child.children[0],
                                  root.ansi)
            return fuse_stages(merged)
    if isinstance(root, TpuHashAggregateExec):
        child = root.children[0]
        if (isinstance(child, TpuStageExec) and not child.ansi
                and not root.ansi and not root.pre_ops
                and root.mode in (AggregateMode.PARTIAL,
                                  AggregateMode.COMPLETE)):
            root.pre_ops = list(child.ops)
            root.input_schema = child.children[0].output
            root.children = [child.children[0]]
    return root


class TpuLocalTableScanExec(TpuExec):
    def __init__(self, host_columns: List[HostColumn], schema: T.StructType,
                 target_batch_rows: Optional[int] = None,
                 cache_device: bool = False, cache_slot=None):
        super().__init__([])
        self.host_columns = host_columns
        self._schema = schema
        self.target_batch_rows = target_batch_rows
        self.cache_device = cache_device
        # cache lives on the plan node so it survives re-planning
        self._slot = cache_slot if cache_slot is not None else self

    @property
    def output(self):
        return self._schema

    def execute_columnar(self):
        cached = getattr(self._slot, "_device_cache", None)
        if cached is not None:
            for b in cached:
                yield self._count_output(b)
            return
        if self.cache_device:
            acc = []
            for b in self._materialize():
                acc.append(b)
                yield b
            self._slot._device_cache = acc
            return
        yield from self._materialize()

    def aot_output_rows(self):
        """Exact per-batch row counts (mirrors _materialize's chunking) —
        the AOT pipeline's ground truth for shape buckets."""
        n = self.host_columns[0].num_rows if self.host_columns else 0
        step = self.target_batch_rows or max(n, 1)
        out = []
        for start in range(0, max(n, 1), step):
            out.append(min(start + step, n) - start if n else 0)
            if n == 0:
                break
        return out

    def _materialize(self):
        n = self.host_columns[0].num_rows if self.host_columns else 0
        step = self.target_batch_rows or max(n, 1)
        names = self._schema.field_names()
        for start in range(0, max(n, 1), step):
            end = min(start + step, n)
            if n == 0 and start > 0:
                break
            chunk = [h.slice_rows(start, end) for h in self.host_columns]
            yield self._count_output(
                ColumnarBatch.from_host_columns(chunk, names))
            if n == 0:
                break


class TpuRangeExec(TpuExec):
    """GpuRangeExec analog: generate id column on device."""

    def __init__(self, start: int, end: int, step: int = 1,
                 batch_rows: int = 1 << 20):
        super().__init__([])
        self.start, self.end, self.step = start, end, step
        self.batch_rows = batch_rows

    @property
    def output(self):
        return T.StructType([T.StructField("id", T.LONG, nullable=False)])

    def aot_output_rows(self):
        total = max(0, -(-(self.end - self.start) // self.step))
        out, emitted = [], 0
        while emitted < total or (total == 0 and emitted == 0):
            count = min(self.batch_rows, total - emitted)
            out.append(count)
            emitted += count
            if total == 0:
                break
        return out

    def execute_columnar(self):
        total = max(0, -(-(self.end - self.start) // self.step))
        from spark_rapids_tpu.columnar.column import round_up_bucket, DEFAULT_ROW_BUCKETS

        emitted = 0
        while emitted < total or (total == 0 and emitted == 0):
            count = min(self.batch_rows, total - emitted)
            cap = round_up_bucket(max(count, 1), DEFAULT_ROW_BUCKETS)
            base = self.start + emitted * self.step
            data = base + jnp.arange(cap, dtype=jnp.int64) * self.step
            validity = jnp.arange(cap) < count
            col = DeviceColumn(T.LONG, validity, data=data)
            yield self._count_output(
                ColumnarBatch([col], count, self.output))
            emitted += count
            if total == 0:
                break


class TpuUnionExec(TpuExec):
    @property
    def output(self):
        return self.children[0].output

    def aot_output_rows(self):
        out = []
        for c in self.children:
            fn = getattr(c, "aot_output_rows", None)
            rows = fn() if fn is not None else None
            if rows is None:
                return None
            out.extend(rows)
        return out

    def execute_columnar(self):
        for c in self.children:
            for b in c.execute_columnar():
                yield self._count_output(b)


class TpuInMemoryTableScanExec(TpuExec):
    """df.cache() exec: first run materializes the child's batches into
    SPILLABLE handles stored on the plan node (so the cache survives
    re-planning and is reclaimable under memory pressure); later runs
    replay them.

    Reference analog: GpuInMemoryTableScanExec + ParquetCachedBatchSerializer
    (SURVEY.md §2.8) — device-resident cached batches instead of
    parquet-encoded host buffers (HBM spill handles play the same role)."""

    def __init__(self, child: TpuExec, cache_slot: dict):
        super().__init__([child])
        self.cache_slot = cache_slot

    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        state = "hit" if "tpu" in self.cache_slot else "cold"
        return f"TpuInMemoryTableScan [{state}]"

    def execute_columnar(self):
        from spark_rapids_tpu.memory.spill import get_spill_framework

        cached = self.cache_slot.get("tpu")
        if cached is None:
            # materialize eagerly BEFORE yielding: an abandoned generator
            # (e.g. a limit above the cache) must not leak tracked handles
            # or leave a partial cache
            fw = get_spill_framework()
            acc = []
            try:
                # persistent: cache handles intentionally outlive the
                # query (until unpersist), so query-end cleanup and the
                # leak gate must not reap them
                for b in self.children[0].execute_columnar():
                    acc.append(fw.track(b, persistent=True))
            except BaseException:
                for s in acc:
                    s.close()
                raise
            self.cache_slot["tpu"] = acc
            cached = acc
        for s in cached:
            s.pin()
            try:
                b = s.get_batch()
            finally:
                s.unpin()
            yield self._count_output(b)
