"""Join->Aggregate whole-stage fusion — the program-count killer.

Reference analog: none directly — the reference streams gather-map chunks
from GpuShuffledHashJoinExec into GpuHashAggregateExec as separate kernels
(SURVEY.md §2.4 Joins / hash aggregate); on a PCIe-local GPU the launch
boundary is ~10µs so fusing across it buys little.  On TPU every program
launch is a host round trip (hundreds of ms through a tunnel relay), so an
aggregate directly above an equi-join is compiled INTO the join's
materialization program:

  * general path: [build] [probe: lo/counts/sizes] -> ONE host sync for the
    pair count -> [materialize+aggregate fused].  3 programs, 1 sync.
  * unique-build fast path: when the build side's keys are unique (the
    star-schema dim-table case — learned from the first probe's size sync
    and cached on the exec), pairs == matched probe rows, so the output
    capacity is the probe capacity: probe search, build gather, and the
    whole aggregation run in ONE program with NO size sync.  The unmatched
    probe rows of a LEFT join stay in place with null build columns; an
    INNER join masks them out via the aggregate's row-validity mask —
    filtered rows never move (no compaction scatter at all).

Falls back to the unfused pair (agg over join output) when the build side
exceeds the sub-partition threshold (out-of-core joins keep their own
machinery) — correctness is identical either way.
"""
from __future__ import annotations

import itertools
import threading
from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import (
    DEFAULT_ROW_BUCKETS,
    DeviceColumn,
    round_up_bucket,
)
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.exec.join import (
    _BaseTpuJoinExec,
    _key_words_of,
    _multiword_searchsorted,
    _SortedBuildSide,
)
from spark_rapids_tpu.expr.base import EvalContext
from spark_rapids_tpu.perfcounters import sync_get, tpu_jit
from spark_rapids_tpu.plan.nodes import AggregateMode, JoinType


def _mask_col(c: DeviceColumn, keep) -> DeviceColumn:
    """AND a row mask into a column's validity (recursing into structs)."""
    if c.is_struct:
        return DeviceColumn(c.dtype, c.validity & keep,
                            children=tuple(_mask_col(k, keep)
                                           for k in c.children))
    return DeviceColumn(c.dtype, c.validity & keep, data=c.data,
                        chars=c.chars, lengths=c.lengths,
                        elem_valid=c.elem_valid)


# process-unique tags for unfingerprintable agg variants (never reused,
# unlike id(), which the allocator recycles after GC); the lock makes
# the lazy pin-on-object init atomic — two concurrent collects sharing
# one agg must agree on the tag or the loser retraces forever
_PRIVATE_TAGS = itertools.count()
_PRIVATE_TAG_LOCK = threading.Lock()


class TpuJoinAggFusedExec(TpuExec):
    """agg(join(probe, build)) in (at most) three XLA programs."""

    EXTRA_METRICS = {"buildTime": "MODERATE"}

    def __init__(self, agg, join: _BaseTpuJoinExec):
        super().__init__(list(join.children))
        self.agg = agg
        self.join = join
        self._jit_cache = {}
        # None = unknown; True/False learned from the first size sync and
        # reused across collects of the same plan (device-cached scans make
        # repeat execution the hot path)
        self._build_unique: Optional[bool] = None

    @property
    def output(self):
        return self.agg.output

    def describe(self):
        return (f"TpuJoinAggFused[{self.agg.describe()} <- "
                f"{self.join.describe()}]")

    def _registry_scope(self):
        cached = getattr(self, "_reg_scope", False)
        if cached is not False:
            return cached
        join_scope = self.join._registry_scope()
        agg_fp = self.agg._program_fp()
        scope = None
        if join_scope is not None and agg_fp is not None:
            scope = ("joinagg",) + join_scope + (agg_fp,)
        self._reg_scope = scope
        return scope

    def _agg_tag(self, agg):
        """Stable registry identity for the agg variant a key closes over
        (self.agg or its PARTIAL/FINAL twins).  An unfingerprintable agg
        gets a process-unique tag PINNED on the object: an ``id()`` here
        could be reused after GC, silently aliasing two different aggs
        to one registry program — and the private marker also forces the
        key out of the shared registry (see ``_cached``)."""
        fpp = agg._program_fp()
        if fpp is not None:
            return fpp
        tag = getattr(agg, "_joinagg_private_tag", None)
        if tag is None:
            with _PRIVATE_TAG_LOCK:
                tag = getattr(agg, "_joinagg_private_tag", None)
                if tag is None:
                    tag = ("private", next(_PRIVATE_TAGS))
                    agg._joinagg_private_tag = tag
        return tag

    def _cached(self, key, builder):
        if key not in self._jit_cache:
            from spark_rapids_tpu.compilecache.registry import (
                cached_jit_program,
            )

            scope = self._registry_scope()
            # a private (unfingerprintable-agg) tag must not enter the
            # process-wide registry: the tag is meaningless in another
            # process (persisted AOT) and would pin a never-shareable
            # program in the shared LRU
            private = isinstance(key, tuple) and any(
                isinstance(p, tuple) and p[:1] == ("private",)
                for p in key)
            self._jit_cache[key] = cached_jit_program(
                None if scope is None or private else scope + (key,),
                builder,
                label=f"joinagg:{key if isinstance(key, str) else key[0]}")
        return self._jit_cache[key]

    def aot_programs(self):
        """The fused path reuses the join's build-sort program verbatim —
        including the broadcast-side stage-absorbed (pre_ops) variant —
        while the fused probe/materialize programs have data-dependent
        operand shapes (pair counts, uniqueness) and compile inline."""
        self.join.children = list(self.children)
        build_src, pre_ops, pre_schema = self._build_source()
        if pre_ops is None:
            return [p for p in self.join.aot_programs()
                    if p.label.startswith("join-build")]
        from spark_rapids_tpu.compilecache.aot import (
            AotProgram,
            concat_caps,
            dummy_batch_args,
        )
        from spark_rapids_tpu.compilecache.keys import (
            schema_fp,
            stage_ops_fp,
        )
        from spark_rapids_tpu.perfcounters import tpu_jit as _tj

        join = self.join
        scope = join._registry_scope()
        ops_fp = stage_ops_fp(pre_ops)
        caps = concat_caps(build_src)
        if scope is None or ops_fp is None or not caps:
            return []
        cap = caps[0]
        key = ("build_preops", ops_fp, schema_fp(pre_schema))
        fn = join._build_fn(pre_schema, join.right_keys, pre_ops)

        def args_factory(_schema=pre_schema, _cap=cap):
            return [dummy_batch_args(_schema, _cap)]

        return [AotProgram(scope + (key,),
                           lambda _fn=fn: (_tj(_fn), None), args_factory,
                           f"join-build-preops:{self.describe()[:36]}")]

    # ------------------------------------------------------------------
    def _fallback(self) -> Iterator[ColumnarBatch]:
        # the agg's child is still the join exec — the unfused pipeline
        yield from self.agg.execute_columnar()

    def _build_source(self):
        """(exec to drive, stage ops to fuse into the build program, input
        schema) — absorbs BroadcastExchange(Stage(x)) into the build."""
        from spark_rapids_tpu.exec.basic import TpuStageExec
        from spark_rapids_tpu.exec.exchange import TpuBroadcastExchangeExec

        child = self.join._build_child()
        if isinstance(child, TpuBroadcastExchangeExec):
            inner = child.children[0]
            if (isinstance(inner, TpuStageExec) and not inner.ansi
                    and not inner._has_host_kernels()):
                return inner.children[0], inner.ops, inner.children[0].output
        return child, None, None

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.memory.spill import get_spill_framework

        join = self.join
        # later plan passes rewrite self.children in place; the join exec
        # must execute the rewritten subtrees, not its stale private copy
        join.children = list(self.children)
        fw = get_spill_framework()
        # broadcast-side stage absorption: drive the stage's CHILD and fuse
        # the project/filter ops into the build-sort program
        build_src, pre_ops, pre_schema = self._build_source()
        build_spill = []
        total_build_bytes = 0
        try:
            for b in build_src.execute_columnar():
                total_build_bytes += b.nbytes()
                build_spill.append(fw.track(b))
        except BaseException:
            for s in build_spill:
                s.close()
            raise
        if total_build_bytes > join.sub_partition_bytes:
            for s in build_spill:
                s.close()
            # out-of-core join path owns this size class; re-drive the
            # build child (scans re-stream; device cache makes it cheap)
            yield from self._fallback()
            return
        for s in build_spill:
            s.pin()
        try:
            build_batch = join._concat_or_empty(
                [s.get_batch() for s in build_spill],
                pre_schema if pre_schema is not None
                else join._build_child().output)
        finally:
            for s in build_spill:
                s.unpin()
                s.close()
        # timed on the FUSED exec's own metric: the inner join node is
        # not in this exec's children, so a metric written there would
        # never be harvested by collect_metrics / explain("analyze")
        with self.metric("buildTime").timed():
            build = join._prepare_build(build_batch, join.right_keys,
                                        pre_ops=pre_ops,
                                        in_schema=pre_schema)

        probe_it = join._probe_child().execute_columnar()
        first = next(probe_it, None)
        if first is None:
            from spark_rapids_tpu.columnar.batch import empty_batch

            if not self.agg.grouping:
                yield self.agg._global_agg_empty()
            else:
                yield empty_batch(self.agg._output)
            return
        from spark_rapids_tpu.memory.retry import (
            TpuSplitAndRetryOOM,
            with_retry,
            with_retry_no_split,
        )

        if self.agg.mode == AggregateMode.PARTIAL:
            # buffer-form output per probe batch; the surviving FINAL agg
            # above merges them (finalizing here would feed it avg-of-avgs)
            def feed_all():
                yield first
                yield from probe_it

            for probe in feed_all():
                with self.metrics["opTime"].timed():
                    for out in with_retry(
                            fw.track(probe),
                            lambda piece: self._probe_agg_one(
                                build, piece, self.agg)):
                        yield self._count_output(out)
            return

        second = next(probe_it, None)
        if second is None:
            try:
                with self.metrics["opTime"].timed():
                    out = with_retry_no_split(
                        lambda: self._probe_agg_one(build, first, self.agg))
                yield self._count_output(out)
                return
            except TpuSplitAndRetryOOM:
                # split the probe batch and continue on the two-phase path
                pass

        # multi-batch probe (or split-forced): per-batch PARTIAL buffers,
        # buffer merges, one FINAL finalize (the agg's COMPLETE twins)

        partial, final = self.agg._complete_twins()
        spillables = []

        def feed():
            yield first
            if second is not None:
                yield second
            yield from probe_it

        for probe in feed():
            with self.metrics["opTime"].timed():
                for out in with_retry(
                        fw.track(probe),
                        lambda piece: self._probe_agg_one(build, piece,
                                                          partial)):
                    spillables.append(fw.track(out))
        with self.metrics["opTime"].timed():
            while len(spillables) > 1:
                a, b2 = spillables.pop(0), spillables.pop(0)
                merged = with_retry_no_split(
                    lambda: final._merge_pair(a, b2))
                spillables.append(fw.track(merged))
            last = spillables[0]
            last.pin()
            try:
                buf = last.get_batch()
            finally:
                last.unpin()
            last.close()
            out = final._finalize(buf)
        yield self._count_output(out)

    # ------------------------------------------------------------------
    def _probe_agg_one(self, build: _SortedBuildSide, probe: ColumnarBatch,
                       agg) -> ColumnarBatch:
        if self._build_unique:
            return self._unique_probe_agg(build, probe, agg)
        lo, counts, unmatched, sizes = self._probe_sizes(build, probe)
        total, n_um, has_dup = (int(x) for x in sync_get(sizes))
        if self._build_unique is None:
            self._build_unique = has_dup == 0
        return self._mat_agg(build, probe, lo, counts, unmatched,
                             total, n_um, agg)

    def _probe_sizes(self, build: _SortedBuildSide, probe: ColumnarBatch):
        """Probe program: lo/counts plus ONE packed sizes vector
        [total_pairs, n_unmatched, build_has_dup] so sizing costs a single
        host round trip."""
        join = self.join
        schema = probe.schema
        ansi, left_keys = join.ansi, join.left_keys   # locals only

        def fn(bwords, n_valid, cols, num_rows):
            b = ColumnarBatch(list(cols), num_rows, schema)
            ctx = EvalContext(b, ansi=ansi)
            key_cols = [k.eval_tpu(ctx) for k in left_keys]
            valid = b.row_mask
            for kc in key_cols:
                valid = valid & kc.validity
            qwords = _key_words_of(key_cols)
            lo = _multiword_searchsorted(list(bwords), n_valid, qwords,
                                         "left")
            hi = _multiword_searchsorted(list(bwords), n_valid, qwords,
                                         "right")
            counts = jnp.where(valid, hi - lo, 0)
            total = jnp.sum(counts.astype(jnp.int64))
            unmatched = b.row_mask & (counts == 0)
            n_um = jnp.sum(unmatched.astype(jnp.int64))
            # build-key uniqueness: any adjacent equal pair among the first
            # n_valid sorted keys
            cap_b = bwords[0].shape[0]
            idx = jnp.arange(cap_b - 1)
            adj_eq = jnp.ones(cap_b - 1, jnp.bool_)
            for w in bwords:
                adj_eq = adj_eq & (w[:-1] == w[1:])
            in_valid = (idx + 1) < n_valid
            has_dup = jnp.any(adj_eq & in_valid).astype(jnp.int64)
            sizes = jnp.stack([total, n_um, has_dup])
            return lo, counts, unmatched, sizes

        jitted = self._cached("probe_sizes", fn)
        return jitted(tuple(build.words), build.n_valid,
                      tuple(probe.columns), jnp.int32(probe.num_rows))

    # ------------------------------------------------------------------
    def _finish(self, agg, cols, nrows) -> ColumnarBatch:
        n = 1 if not agg.grouping else int(nrows)
        return ColumnarBatch(list(cols), n, agg._output)

    def _mat_agg(self, build, probe, lo, counts, unmatched, total: int,
                 n_um: int, agg) -> ColumnarBatch:
        """General path: materialize pairs + aggregate in ONE program."""
        join = self.join
        with_um = join.join_type == JoinType.LEFT_OUTER
        out_rows = total + (n_um if with_um else 0)
        out_cap = round_up_bucket(max(out_rows, 1), DEFAULT_ROW_BUCKETS)
        agg_fn = agg.detached_for_trace()._agg_fn   # no subtree capture

        def fn(row_index, b_cols, p_cols, lo, counts, unmatched, total,
               nrows):
            lcols, bcols = _BaseTpuJoinExec.materialize_pairs(
                row_index, b_cols, p_cols, lo, counts, unmatched, total,
                nrows, out_cap, with_um)
            joined = tuple(list(lcols) + list(bcols))
            return agg_fn(joined, nrows.astype(jnp.int32))

        jitted = self._cached(("mat_agg", out_cap, with_um,
                               self._agg_tag(agg)), fn)
        cols, nrows = jitted(build.row_index, tuple(build.batch.columns),
                             tuple(probe.columns), lo, counts, unmatched,
                             jnp.int64(total), jnp.int64(out_rows))
        return self._finish(agg, cols, nrows)

    def _unique_probe_agg(self, build, probe, agg) -> ColumnarBatch:
        """Unique-build fast path: probe search + build gather + aggregate
        in ONE program; no size sync (output capacity == probe capacity).
        The aggregate runs through its bounded-cardinality ladder
        (groups_cap) — the synced output row count is the overflow
        check."""
        join = self.join
        left_outer = join.join_type == JoinType.LEFT_OUTER
        schema = probe.schema
        ansi, left_keys = join.ansi, join.left_keys
        agg_fn = agg.detached_for_trace()._agg_fn   # no subtree capture

        def mk(groups_cap):
            def fn(bwords, row_index, n_valid, b_cols, p_cols, num_rows):
                b = ColumnarBatch(list(p_cols), num_rows, schema)
                ctx = EvalContext(b, ansi=ansi)
                key_cols = [k.eval_tpu(ctx) for k in left_keys]
                valid = b.row_mask
                for kc in key_cols:
                    valid = valid & kc.validity
                qwords = _key_words_of(key_cols)
                lo = _multiword_searchsorted(list(bwords), n_valid, qwords,
                                             "left")
                cap_b = bwords[0].shape[0]
                loc = jnp.clip(lo, 0, cap_b - 1)
                # small build tables ride the MXU one-hot gather: a VPU
                # random gather costs ~300ms per column at 20M probe rows
                # while the fused one_hot@table contraction is ~5ms
                # (ops/mxugather.py)
                from spark_rapids_tpu.ops import mxugather as MG

                use_mxu = cap_b <= MG.MAX_TABLE_ROWS
                eq = jnp.ones(lo.shape, jnp.bool_)
                for w, q in zip(bwords, qwords):
                    wl = MG.mxu_gather(w, loc) if use_mxu else w[loc]
                    eq = eq & (wl == q)
                found = valid & (lo < n_valid) & eq
                if use_mxu:
                    brow = jnp.where(found, MG.mxu_gather(row_index, loc),
                                     0)
                    bcols = []
                    for c in b_cols:
                        g = MG.mxu_gather_col(c, brow)
                        if g is None:
                            g = c.gather(brow)
                        bcols.append(_mask_col(g, found))
                else:
                    brow = jnp.where(found, row_index[loc], 0)
                    bcols = [_mask_col(c.gather(brow), found)
                             for c in b_cols]
                joined = tuple(list(p_cols) + bcols)
                row_valid = b.row_mask if left_outer \
                    else (b.row_mask & found)
                return agg_fn(joined, num_rows, row_valid=row_valid,
                              groups_cap=groups_cap)

            return fn

        args = (tuple(build.words), build.row_index, build.n_valid,
                tuple(build.batch.columns), tuple(probe.columns),
                jnp.int32(probe.num_rows))
        cap = probe.capacity
        B = agg._bounded_groups_cap(cap)
        tag = self._agg_tag(agg)
        if B:
            cols, nrows = self._cached(("uniq_agg", tag, B),
                                       mk(B))(*args)
            n = int(nrows)
            while n > B:
                B2 = min(max(1 << (n - 1).bit_length(), B * 2), cap)
                agg._groups_cap_hint = B2
                if B2 >= cap:
                    B2 = None
                cols, nrows = self._cached(("uniq_agg", tag, B2),
                                           mk(B2))(*args)
                if B2 is None:
                    n = int(nrows)
                    break
                n = int(nrows)
                B = B2
            return self._finish(agg, cols, n)
        cols, nrows = self._cached(("uniq_agg", tag, None),
                                   mk(None))(*args)
        return self._finish(agg, cols, nrows)


class TpuWindowChainFusedExec(TpuExec):
    """[COMPLETE agg ->] window [-> project/filter stage] as ONE program.

    The window already runs in a single jitted function of
    (columns, num_rows-scalar); a grouped aggregate feeding it produces
    (columns, ngroups-scalar) — so the whole chain composes into one XLA
    program with zero host syncs between operators.  Only the final row
    count syncs (to label the output batch).  The reference runs these as
    three separate stages with exchange boundaries (SURVEY.md §2.4 Window).
    """

    def __init__(self, window, pre_agg=None, post_ops=None,
                 post_schema=None):
        child = pre_agg.children[0] if pre_agg is not None \
            else window.children[0]
        super().__init__([child])
        self.window = window
        self.pre_agg = pre_agg
        self.post_ops = list(post_ops or [])
        self._post_schema = post_schema
        self._jit_cache = {}

    @property
    def output(self):
        return self._post_schema if self._post_schema is not None \
            else self.window.output

    def describe(self):
        parts = []
        if self.pre_agg is not None:
            parts.append(self.pre_agg.describe())
        parts.append(self.window.describe())
        if self.post_ops:
            parts.append("+".join(type(o).__name__.replace("Op", "")
                                  for o in self.post_ops))
        return "TpuWindowChainFused[" + " -> ".join(parts) + "]"

    def _registry_scope(self):
        cached = getattr(self, "_reg_scope", False)
        if cached is not False:
            return cached
        from spark_rapids_tpu.compilecache.keys import (
            schema_fp,
            stage_ops_fp,
        )

        wkey, _ = self.window._window_program()
        ops_fp = stage_ops_fp(self.post_ops)
        agg_fp = (self.pre_agg._program_fp()
                  if self.pre_agg is not None else ())
        scope = None
        if wkey is not None and ops_fp is not None and agg_fp is not None:
            scope = ("windowchain", wkey, agg_fp, ops_fp,
                     schema_fp(self.output))
        self._reg_scope = scope
        return scope

    def _cached(self, key, builder):
        if key not in self._jit_cache:
            from spark_rapids_tpu.compilecache.registry import (
                cached_jit_program,
            )

            scope = self._registry_scope()
            self._jit_cache[key] = cached_jit_program(
                None if scope is None else scope + (key,), builder,
                label=f"windowchain:{key}")
        return self._jit_cache[key]

    def aot_programs(self):
        from spark_rapids_tpu.compilecache.aot import (
            AotProgram,
            dummy_batch_args,
        )

        scope = self._registry_scope()
        if scope is None:
            return []
        with_agg = self.pre_agg is not None
        if with_agg and not self.aot_child_single_batch():
            # multi-batch + pre-agg runs through the two-phase twins, not
            # the fused chain program
            return []
        caps = self.aot_input_concat_caps()
        if not caps:
            return []
        schema = self.children[0].output
        out = []
        for cap in caps:
            B = (self.pre_agg._bounded_groups_cap(cap)
                 if with_agg else None)
            key = ("chain", with_agg, cap, B)

            def factory(_b=B):
                return tpu_jit(self._chain_fn(with_agg, _b)), None

            def args_factory(_cap=cap):
                return [dummy_batch_args(schema, _cap)]

            out.append(AotProgram(scope + (key,), factory, args_factory,
                                  f"windowchain:{self.describe()[:44]}"))
        return out

    def _chain_fn(self, with_agg: bool, groups_cap=None):
        # detached clones: the registry-shared closure must not pin the
        # live window/agg execs (and through them the input subtree)
        window = self.window.detached_for_trace()
        pre_agg = (self.pre_agg.detached_for_trace()
                   if with_agg and self.pre_agg is not None else None)
        post_ops = self.post_ops

        def fn(cols, num_rows):
            ngroups = jnp.asarray(0, jnp.int32)
            if pre_agg is not None:
                # bounded-cardinality agg: the window then runs over the
                # B-wide grouped result instead of input-capacity columns
                cols, ngroups = pre_agg._agg_fn(cols, num_rows,
                                                groups_cap=groups_cap)
                num_rows = ngroups.astype(jnp.int32)
            wcols = window._window_fn(tuple(cols), num_rows)
            batch = ColumnarBatch(list(wcols), num_rows, window.output)
            if post_ops:
                ctx = EvalContext(batch, ansi=False)
                for op in post_ops:
                    batch = op.apply(ctx, batch)
            # ngroups reported separately: post_ops may filter rows, so
            # the final count cannot double as the ladder overflow check
            return (tuple(batch.columns), jnp.asarray(batch.num_rows),
                    jnp.asarray(ngroups, jnp.int32))

        return fn

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.memory.retry import (
            TpuSplitAndRetryOOM,
            with_retry_no_split,
        )
        from spark_rapids_tpu.memory.spill import get_spill_framework

        # keep the owned execs pointing at the (possibly rewritten) child
        owner = self.pre_agg if self.pre_agg is not None else self.window
        owner.children = list(self.children)

        def run(b, with_agg):
            args = (tuple(b.columns), jnp.int32(b.num_rows))
            B = (self.pre_agg._bounded_groups_cap(b.capacity)
                 if with_agg else None)
            if B:
                cols, count, ng = self._cached(
                    ("chain", with_agg, b.capacity, B),
                    self._chain_fn(with_agg, B))(*args)
                # ONE host round trip for both scalars: the output row
                # count and the ladder's overflow check used to sync
                # separately — BENCH_r05 counted the extra trip on every
                # qc_window run
                n, g = (int(x) for x in sync_get((count, ng)))
                while g > B:     # groups-cap ladder (see aggregate.py)
                    B2 = min(max(1 << (g - 1).bit_length(), B * 2),
                             b.capacity)
                    self.pre_agg._groups_cap_hint = B2
                    if B2 >= b.capacity:
                        B2 = None
                    cols, count, ng = self._cached(
                        ("chain", with_agg, b.capacity, B2),
                        self._chain_fn(with_agg, B2))(*args)
                    n, g = (int(x) for x in sync_get((count, ng)))
                    if B2 is None:
                        break
                    B = B2
                return ColumnarBatch(list(cols), n, self.output)
            cols, count, _ = self._cached(
                ("chain", with_agg, b.capacity, None),
                self._chain_fn(with_agg))(*args)
            # int(count) is irreducible here: it is the only scalar this
            # path reads back (ng is statically irrelevant without the
            # groups-cap ladder)
            return ColumnarBatch(list(cols), int(count), self.output)

        fw = get_spill_framework()
        batches = list(self.children[0].execute_columnar())
        if not batches:
            if self.pre_agg is None:
                return
            # aggregate-of-empty semantics, then window[+stage] over it
            from spark_rapids_tpu.columnar.batch import empty_batch

            if not self.pre_agg.grouping:
                b = self.pre_agg._global_agg_empty()
            else:
                b = empty_batch(self.pre_agg._output)
            with self.metrics["opTime"].timed():
                out = with_retry_no_split(lambda: run(b, False))
            yield self._count_output(out)
            return

        def agg_then_window(batch_list):
            """Aggregate the already-materialized batches through the
            two-phase twins (no re-execution of the child subtree), then
            window the grouped result."""
            agg_out = list(self.pre_agg._complete_two_phase(
                iter(batch_list), fw, []))
            b = (agg_out[0] if len(agg_out) == 1
                 else ColumnarBatch.concat(agg_out))
            return with_retry_no_split(lambda: run(b, False))

        run_agg = self.pre_agg is not None
        with self.metrics["opTime"].timed():
            if run_agg and len(batches) > 1:
                out = agg_then_window(batches)
            else:
                batch = (batches[0] if len(batches) == 1
                         else ColumnarBatch.concat(batches))
                try:
                    out = with_retry_no_split(lambda: run(batch, run_agg))
                except TpuSplitAndRetryOOM:
                    if not run_agg:
                        raise
                    out = agg_then_window(batches)
        yield self._count_output(out)
