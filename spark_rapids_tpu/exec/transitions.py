"""CPU<->TPU transitions.

Reference analog: GpuRowToColumnarExec / GpuColumnarToRowExec /
HostColumnarToGpu (SURVEY.md §2.4 Transitions) — the device boundary of the
plan.  Here the CPU side is the oracle executor; transitions convert between
its CpuCols (host) and device ColumnarBatches.

TpuColumnarToRowExec is what the session's collect() drives; its device->host
copy is the analog of the reference's accelerated columnar-to-row kernel
(the padded layout makes the host-side conversion a memcpy per column).
"""
from __future__ import annotations

from typing import Iterator, List

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import HostColumn
from spark_rapids_tpu.exec.base import TpuExec


class TpuRowToColumnarExec(TpuExec):
    """Wraps a CPU plan subtree; materializes it via the oracle and uploads
    batches to the device."""

    def __init__(self, cpu_plan, ansi: bool = False,
                 target_batch_rows: int = 1 << 20):
        super().__init__([])
        self.cpu_plan = cpu_plan
        self.ansi = ansi
        self.target_batch_rows = target_batch_rows

    @property
    def output(self):
        return self.cpu_plan.output

    def describe(self):
        return f"TpuRowToColumnar <- {self.cpu_plan.describe()}"

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        from spark_rapids_tpu.cpu.oracle import execute_cpu_plan

        cols, n = execute_cpu_plan(self.cpu_plan, ansi=self.ansi)
        host = [c.to_host() for c in cols]
        names = self.output.field_names()
        step = self.target_batch_rows

        for start in range(0, max(n, 1), step):
            end = min(start + step, n)
            chunk = [h.slice_rows(start, end) for h in host]
            yield self._count_output(
                ColumnarBatch.from_host_columns(chunk, names))
            if n == 0:
                break


class TpuColumnarToRowExec(TpuExec):
    """Device batches -> host rows (the top of every collected plan)."""

    def __init__(self, child: TpuExec):
        super().__init__([child])

    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        return "TpuColumnarToRow"

    def execute_columnar(self):
        yield from self.children[0].execute_columnar()

    def collect_host(self) -> List[HostColumn]:
        """Materialize all batches to host columns."""
        import numpy as np

        batches = list(self.children[0].execute_columnar())
        if not batches:
            schema = self.output
            return [HostColumn.from_pylist([], f.dataType)
                    for f in schema.fields]
        from spark_rapids_tpu.config import (
            FUSION_COLLECT_SHRINK_MAX_WASTE, get_conf)

        waste_cap = get_conf().get(FUSION_COLLECT_SHRINK_MAX_WASTE)
        per_batch = [b.to_host_columns(max_shrink_waste_bytes=waste_cap)
                     for b in batches]
        out = [_concat_host([pb[ci] for pb in per_batch])
               for ci in range(len(per_batch[0]))]
        return out


def _concat_host(hs: List[HostColumn]) -> HostColumn:
    """Concatenate host columns of one schema slot (all column kinds)."""
    import numpy as np

    dtype = hs[0].dtype
    validity = np.concatenate([h.validity for h in hs])
    if hs[0].is_struct:
        kids = [_concat_host([h.children[k] for h in hs])
                for k in range(len(hs[0].children))]
        lengths = (np.concatenate([h.lengths for h in hs])
                   if hs[0].lengths is not None else None)
        return HostColumn(dtype, validity, lengths=lengths, children=kids)
    if hs[0].is_string_array:
        ew = max(h.chars.shape[1] for h in hs)
        w = max(h.chars.shape[2] for h in hs)
        nrows = len(validity)
        chars = np.zeros((nrows, ew, w), np.uint8)
        elens = np.zeros((nrows, ew), np.int32)
        ev = np.zeros((nrows, ew), np.bool_)
        lengths = np.concatenate([h.lengths for h in hs])
        off = 0
        for h in hs:
            k = len(h.lengths)
            chars[off:off + k, :h.chars.shape[1], :h.chars.shape[2]] = h.chars
            elens[off:off + k, :h.data.shape[1]] = h.data
            ev[off:off + k, :h.elem_valid.shape[1]] = h.elem_valid
            off += k
        return HostColumn(dtype, validity, chars=chars, data=elens,
                          lengths=lengths, elem_valid=ev)
    if hs[0].is_string:
        width = max(h.chars.shape[1] for h in hs)
        chars = np.zeros((len(validity), width), np.uint8)
        lengths = np.concatenate([h.lengths for h in hs])
        off = 0
        for h in hs:
            chars[off: off + len(h.lengths), : h.chars.shape[1]] = h.chars
            off += len(h.lengths)
        return HostColumn(dtype, validity, chars=chars, lengths=lengths)
    if hs[0].is_array:
        ew = max(h.data.shape[1] for h in hs)
        n = len(validity)
        data = np.zeros((n, ew), hs[0].data.dtype)
        ev = np.zeros((n, ew), np.bool_)
        lengths = np.concatenate([h.lengths for h in hs])
        off = 0
        for h in hs:
            k = len(h.lengths)
            data[off: off + k, : h.data.shape[1]] = h.data
            ev[off: off + k, : h.elem_valid.shape[1]] = h.elem_valid
            off += k
        return HostColumn(dtype, validity, data=data, lengths=lengths,
                          elem_valid=ev)
    data = np.concatenate([h.data for h in hs])
    return HostColumn(dtype, validity, data=data)
