"""TpuHashAggregateExec — sort-based group-by aggregation.

Reference analog: GpuHashAggregateExec / GpuAggregateIterator /
GpuMergeAggregateIterator (SURVEY.md §2.4): batches are aggregated, partials
merged, with a sort-based fallback when merge output is too big.  TPU-first
redesign: the *primary* algorithm is sort-based (lax.sort by packed key words
+ segmented reductions) because Pallas/XLA favor sorting networks over
device-wide-atomic hash tables (SURVEY.md §7 hard part #3).  The reference's
"fall back to sort" becomes our main path; its hash fast-path can come later
as a Pallas kernel if profiling demands.

Partial/Final mode split matches Spark exactly (partial before the exchange,
final after), including avg -> (sum, count) partial buffers.

The entire aggregation — key packing, sort, segmentation, every aggregate
update — is one jitted XLA program per shape bucket.
"""
from __future__ import annotations

from typing import Iterator, List, Optional

import jax
from spark_rapids_tpu.perfcounters import tpu_jit
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.plan.nodes import REGR_FUNCS as PN_REGR_FUNCS
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.expr.base import BoundReference, EvalContext, Expression
from spark_rapids_tpu.ops import segment as SEG
from spark_rapids_tpu.ops.sortkeys import (
    SortSpec,
    _column_key_words,
    group_segments,
)
from spark_rapids_tpu.plan.nodes import (
    COVARIANCE_FUNCS,
    HIGHER_MOMENT_FUNCS,
    HLL_DEFAULT_P,
    MOMENT_BUFFERS,
    SINGLE_PHASE_FUNCS,
    VARIANCE_FUNCS,
    AggregateExpression,
    AggregateMode,
)


def _is_float(dt: T.DataType) -> bool:
    return isinstance(dt, (T.FloatType, T.DoubleType))


class TpuHashAggregateExec(TpuExec):
    def __init__(self, grouping: List[Expression],
                 aggregates: List[AggregateExpression],
                 mode: AggregateMode, child: TpuExec,
                 child_plan_output: T.StructType,
                 output_schema: T.StructType,
                 ansi: bool = False):
        super().__init__([child])
        self.grouping = grouping
        self.aggregates = aggregates
        self.mode = mode
        self.child_schema = child_plan_output
        self._output = output_schema
        self.ansi = ansi
        # whole-stage fusion (fuse_stages): narrow ops absorbed into this
        # node's jitted program, applied in selection-mask mode
        self.pre_ops = []
        self.input_schema = child_plan_output

    @property
    def output(self):
        return self._output

    def describe(self):
        g = ", ".join(e.sql_string() for e in self.grouping)
        a = ", ".join(a.describe() for a in self.aggregates)
        fused = ""
        if self.pre_ops:
            names = "+".join(type(o).__name__.replace("Op", "")
                             for o in self.pre_ops)
            fused = f" fused=[{names}]"
        return (f"TpuHashAggregate({self.mode.value}) keys=[{g}] "
                f"aggs=[{a}]{fused}")

    @property
    def _has_collect(self) -> bool:
        return any(a.func in SINGLE_PHASE_FUNCS for a in self.aggregates)

    # ------------------------------------------------------------------
    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        if self._has_collect:
            yield from self._execute_collect()
            return
        yield from self._execute_streaming()

    def _execute_collect(self) -> Iterator[ColumnarBatch]:
        """collect_list/collect_set: concat all input (a hash exchange has
        already co-located keys), ONE aggregate pass (array-buffer merges
        across partials are not implemented — reference: GpuCollectList is
        likewise a memory-hungry TypedImperativeAggregate)."""
        batches = list(self.children[0].execute_columnar())
        if not batches:
            from spark_rapids_tpu.columnar.batch import empty_batch

            if not self.grouping:
                yield self._count_output(self._collect_empty_global())
            else:
                yield self._count_output(empty_batch(self._output))
            return
        with self.metrics["opTime"].timed():
            batch = (batches[0] if len(batches) == 1
                     else ColumnarBatch.concat(batches))
            yield self._count_output(self._aggregate_batch(batch))

    def _collect_empty_global(self) -> ColumnarBatch:
        cols = []
        for a, f in zip(self.aggregates, self._output.fields):
            if a.func in ("collect_list", "collect_set"):
                # empty array, not null
                cols.append(DeviceColumn(
                    f.dataType, jnp.ones(1, jnp.bool_),
                    data=jnp.zeros((1, 1),
                                   T.storage_dtype(f.dataType.elementType)),
                    lengths=jnp.zeros(1, jnp.int32),
                    elem_valid=jnp.zeros((1, 1), jnp.bool_)))
            elif a.func == "bloom_filter_agg":
                words = int(a.args[1]) // 64
                cols.append(DeviceColumn(
                    f.dataType, jnp.ones(1, jnp.bool_),
                    data=jnp.zeros((1, words), jnp.int64),
                    lengths=jnp.full(1, words, jnp.int32),
                    elem_valid=jnp.ones((1, words), jnp.bool_)))
            elif a.func in ("count", "count_star"):
                cols.append(DeviceColumn(
                    f.dataType, jnp.ones(1, jnp.bool_),
                    data=jnp.zeros(1, T.storage_dtype(f.dataType))))
            else:
                cols.append(DeviceColumn(
                    f.dataType, jnp.zeros(1, jnp.bool_),
                    data=jnp.zeros(1, T.storage_dtype(f.dataType))))
        return ColumnarBatch(cols, 1, self._output)

    def _execute_streaming(self) -> Iterator[ColumnarBatch]:
        """Streaming aggregation with bounded memory.

        Reference analog: GpuAggregateIterator + GpuMergeAggregateIterator —
        each input batch is pre-aggregated on its own, the per-batch results
        (buffer form) are kept *spillable*, then merged pairwise; only at the
        end does FINAL mode apply the finalizing transform.  Peak HBM is
        ~2 batches regardless of input count, and every step runs inside the
        OOM-retry framework (split-and-retry on the pre-aggregation, since
        splitting input rows pre-agg is always sound)."""
        from spark_rapids_tpu.memory.retry import with_retry, with_retry_no_split
        from spark_rapids_tpu.memory.spill import get_spill_framework

        fw = get_spill_framework()
        if self.mode == AggregateMode.COMPLETE:
            yield from self._execute_complete(fw)
            return
        spillables = []
        any_input = False
        for b in self.children[0].execute_columnar():
            any_input = True
            with self.metrics["opTime"].timed():
                for out in with_retry(fw.track(b), self._preagg_batch):
                    spillables.append(fw.track(out))
        if not any_input:
            from spark_rapids_tpu.columnar.batch import empty_batch

            if not self.grouping:
                yield self._global_agg_empty()
            else:
                yield empty_batch(self._output)
            return
        with self.metrics["opTime"].timed():
            # pairwise merge tree over spillable partials
            while len(spillables) > 1:
                a, b2 = spillables.pop(0), spillables.pop(0)
                merged = with_retry_no_split(lambda: self._merge_pair(a, b2))
                spillables.append(fw.track(merged))
            last = spillables[0]
            buf = last.get_batch()
            last.close()
            out = self._finalize(buf)
        yield self._count_output(out)

    # -- streaming pieces ----------------------------------------------
    def _buffer_schema(self) -> T.StructType:
        """Schema of the intermediate buffer form (PARTIAL-shaped)."""
        if self.mode == AggregateMode.FINAL:
            return self.child_schema
        return self._output  # PARTIAL output is the buffer form

    # -- COMPLETE mode --------------------------------------------------
    def _complete_twins(self):
        """PARTIAL/FINAL twin execs for multi-batch COMPLETE execution.

        A COMPLETE aggregate cannot merge its own finalized outputs
        (avg/variance would average averages), so when more than one input
        batch arrives the work routes through a PARTIAL twin (buffer form
        per batch), buffer-form merges, and one FINAL finalize — exactly
        the two-phase plan, minus the exchange."""
        cached = getattr(self, "_twin_cache", None)
        if cached is not None:
            return cached
        from spark_rapids_tpu.expr.base import AttributeReference
        from spark_rapids_tpu.plan.nodes import partial_buffer_schema

        buf_schema = partial_buffer_schema(self.grouping, self.aggregates)
        p = TpuHashAggregateExec(self.grouping, self.aggregates,
                                 AggregateMode.PARTIAL, self.children[0],
                                 self.child_schema, buf_schema, self.ansi)
        p.pre_ops = self.pre_ops
        p.input_schema = self.input_schema
        fkeys = [AttributeReference(g.name).resolve(buf_schema)
                 for g in self.grouping]
        faggs = [AggregateExpression(a.func, a.child, a.result_name,
                                     a.result_type, child2=a.child2,
                                     args=a.args)
                 for a in self.aggregates]
        f = TpuHashAggregateExec(fkeys, faggs, AggregateMode.FINAL,
                                 self.children[0], buf_schema, self._output,
                                 self.ansi)
        self._twin_cache = (p, f)
        return self._twin_cache

    def _execute_complete(self, fw) -> Iterator[ColumnarBatch]:
        """COMPLETE: one input batch -> ONE fused program (aggregate +
        finalize); multiple batches -> two-phase via twins."""
        from spark_rapids_tpu.memory.retry import (
            with_retry,
            with_retry_no_split,
        )

        it = self.children[0].execute_columnar()
        first = next(it, None)
        if first is None:
            from spark_rapids_tpu.columnar.batch import empty_batch

            if not self.grouping:
                yield self._global_agg_empty()
            else:
                yield empty_batch(self._output)
            return
        second = next(it, None)
        if second is None:
            from spark_rapids_tpu.memory.retry import TpuSplitAndRetryOOM

            s = fw.track(first)
            try:
                with self.metrics["opTime"].timed():
                    s.pin()
                    try:
                        out = with_retry_no_split(
                            lambda: self._aggregate_batch(s.get_batch()))
                    finally:
                        s.unpin()
            except TpuSplitAndRetryOOM:
                # the fused single program cannot split; the two-phase
                # twins can (PARTIAL buffers merge correctly over pieces)
                yield from self._complete_two_phase(iter(()), fw, [s])
                return
            except BaseException:
                s.close()
                raise
            s.close()
            yield self._count_output(out)
            return

        def feed():
            yield first
            yield second
            yield from it

        yield from self._complete_two_phase(feed(), fw, [])

    def _complete_two_phase(self, batches, fw,
                            tracked) -> Iterator[ColumnarBatch]:
        """Multi-batch (or split-forced) COMPLETE: PARTIAL per batch ->
        buffer merges -> one FINAL finalize."""
        from spark_rapids_tpu.memory.retry import (
            with_retry,
            with_retry_no_split,
        )

        partial, final = self._complete_twins()
        spillables = []
        for s in tracked:
            with self.metrics["opTime"].timed():
                for out in with_retry(s, partial._aggregate_batch):
                    spillables.append(fw.track(out))
        for b in batches:
            with self.metrics["opTime"].timed():
                for out in with_retry(fw.track(b), partial._aggregate_batch):
                    spillables.append(fw.track(out))
        with self.metrics["opTime"].timed():
            while len(spillables) > 1:
                a, b2 = spillables.pop(0), spillables.pop(0)
                merged = with_retry_no_split(lambda: final._merge_pair(a, b2))
                spillables.append(fw.track(merged))
            last = spillables[0]
            last.pin()
            try:
                buf = last.get_batch()
            finally:
                last.unpin()
            last.close()
            out = final._finalize(buf)
        yield self._count_output(out)

    def _preagg_batch(self, batch: ColumnarBatch) -> ColumnarBatch:
        """One input batch -> buffer-form partial result."""
        if self.mode == AggregateMode.FINAL:
            # child feeds buffer rows: reduce them with merge semantics
            return self._merge_batch(batch)
        return self._aggregate_batch(batch)

    def _merge_pair(self, a, b) -> ColumnarBatch:
        # inputs close only AFTER the merge succeeds: callers run this under
        # with_retry_no_split, whose contract requires the block to be
        # re-runnable — closing first would hand a retry freed buffers
        a.pin()
        b.pin()
        try:
            cat = ColumnarBatch.concat([a.get_batch(), b.get_batch()])
            out = self._merge_batch(cat)
        finally:
            a.unpin()
            b.unpin()
        a.close()
        b.close()
        return out

    def _program_fp(self):
        """Registry fingerprint parts for this aggregate's programs, or
        None when an expression is not safely fingerprintable (then every
        jit stays instance-private)."""
        from spark_rapids_tpu.compilecache.keys import (
            aggs_fp,
            conf_fp,
            exprs_fp,
            schema_fp,
            stage_ops_fp,
        )

        g = exprs_fp(self.grouping)
        a = aggs_fp(self.aggregates)
        p = stage_ops_fp(self.pre_ops)
        if g is None or a is None or p is None:
            return None
        return ("agg", g, a, p, self.mode.value,
                schema_fp(self.input_schema), schema_fp(self.child_schema),
                schema_fp(self._output), bool(self.ansi), conf_fp())

    def _merge_jit(self):
        if getattr(self, "_merge_jitted", None) is None:
            from spark_rapids_tpu.compilecache.registry import (
                cached_program,
            )

            fpp = self._program_fp()
            key_parts = None if fpp is None else fpp + ("mergefn",)
            self._merge_jitted = cached_program(
                key_parts,
                lambda: (tpu_jit(self.detached_for_trace()._merge_fn),
                         None),
                label=f"agg-merge:{self.describe()[:40]}").jitted
        return self._merge_jitted

    def _merge_batch(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Re-aggregate buffer-form rows with per-agg merge functions."""
        cols, nrows = self._merge_jit()(tuple(batch.columns),
                                        jnp.int32(batch.num_rows))
        # global aggregates have a statically known single output row —
        # skip the device sync (int(nrows) blocks on tunnel latency)
        n = 1 if not self.grouping else int(nrows)
        return ColumnarBatch(list(cols), n, self._buffer_schema())

    def _finalize(self, buf: ColumnarBatch) -> ColumnarBatch:
        """Buffer form -> this node's output form."""
        if self.mode == AggregateMode.FINAL:
            return self._aggregate_batch(buf)
        return buf  # PARTIAL / COMPLETE buffers are the output

    def _merge_fn(self, cols, num_rows, row_valid=None):
        schema = self._buffer_schema()
        batch = ColumnarBatch(list(cols), num_rows, schema)
        ctx = EvalContext(batch, ansi=self.ansi)
        k = len(self.grouping)
        key_cols = list(batch.columns[:k])
        cap = batch.capacity
        mask = batch.row_mask
        if row_valid is not None:
            # mesh epoching: accumulator + all-to-all-received rows carry an
            # explicit occupancy mask instead of a dense [0, num_rows) prefix
            mask = mask & row_valid
        if not key_cols:
            seg = jnp.where(mask, 0, 1).astype(jnp.int32)
            perm = None
            mask_sorted = mask
            group_valid = jnp.ones(1, jnp.bool_)
            ngroups = jnp.int32(1)
            nseg = 1
        else:
            keys: List[jax.Array] = []
            hi = jnp.int64(9223372036854775807)
            for kc in key_cols:
                nullk = jnp.where(kc.validity, 0, -1).astype(jnp.int64)
                keys.append(jnp.where(mask, nullk, hi))
                for w in _column_key_words(kc):
                    keys.append(jnp.where(mask, jnp.where(kc.validity, w, 0), hi))
            perm = jax.lax.sort(
                tuple(keys) + (jnp.arange(cap, dtype=jnp.int32),),
                num_keys=len(keys), is_stable=True)[-1]
            sorted_keys = [kk[perm] for kk in keys]
            mask_sorted = mask[perm]
            seg, ngroups = group_segments(sorted_keys, mask_sorted)
            seg = jnp.where(mask_sorted, seg, cap - 1)
            group_valid = jnp.arange(cap) < ngroups
            nseg = cap
        out_cols: List[DeviceColumn] = []
        if key_cols:
            first_idx = SEG.seg_first_index(seg, mask_sorted, cap)
            safe_first = jnp.clip(first_idx, 0, cap - 1)
            for kc in key_cols:
                kcs = _gather_col(kc, perm)
                g = _gather_col(kcs, safe_first)
                out_cols.append(DeviceColumn(
                    g.dtype, g.validity & group_valid, data=g.data,
                    chars=g.chars, lengths=g.lengths))
        pos = k
        for a, nbuf in zip(self.aggregates, self._buffer_widths()):
            bufs = [batch.columns[pos + i] for i in range(nbuf)]
            fields = [schema.fields[pos + i] for i in range(nbuf)]
            pos += nbuf
            out_cols.extend(self._eval_merge(
                a, bufs, fields, perm, seg, mask_sorted, cap, group_valid,
                nseg))
        return tuple(out_cols), (ngroups.astype(jnp.int32)
                                 if key_cols else jnp.int32(1))

    def _buffer_widths(self) -> List[int]:
        return [len(MOMENT_BUFFERS[a.func]) if a.func in MOMENT_BUFFERS
                else (2 if a.func == "avg" else 1)
                for a in self.aggregates]

    def _eval_merge(self, a, bufs, fields, perm, seg, mask_sorted, cap,
                    group_valid, nseg) -> List[DeviceColumn]:
        """Merge semantics per aggregate: sum->sum, count->sum, min->min,
        max->max, first->first, last->last, avg(sum,count)->(sum,sum)."""
        func = ("count" if a.func in ("count_star", "count_if")
                else a.func)
        if func == "any_value":
            func = "first"
        if func in ("bool_and", "bool_or"):
            func = "min" if func == "bool_and" else "max"
        if func in VARIANCE_FUNCS:
            cn, ca, cm = (c if perm is None else _gather_col(c, perm)
                          for c in bufs)
            ntot, nz, mean, m2tot = _chan_merge(cn, ca, cm, mask_sorted,
                                                seg, nseg)
            fn_, fa, fm = fields
            return [
                DeviceColumn(fn_.dataType, group_valid, data=ntot),
                DeviceColumn(fa.dataType, group_valid & nz, data=mean),
                DeviceColumn(fm.dataType, group_valid & nz, data=m2tot),
            ]
        if func in HIGHER_MOMENT_FUNCS:
            cs = [c if perm is None else _gather_col(c, perm) for c in bufs]
            merged = _merge_moment_bufs(cs, mask_sorted, seg, nseg)
            ntot, nz = merged[0], merged[1]
            out = [DeviceColumn(fields[0].dataType, group_valid, data=ntot)]
            for f, arr in zip(fields[1:], merged[2:]):
                out.append(DeviceColumn(f.dataType, group_valid & nz,
                                        data=arr))
            return out
        if func in COVARIANCE_FUNCS or func in PN_REGR_FUNCS:
            cs = [c if perm is None else _gather_col(c, perm) for c in bufs]
            merged = _merge_cov_bufs(cs, mask_sorted, seg, nseg)
            ntot, nz = merged[0], merged[1]
            out = [DeviceColumn(fields[0].dataType, group_valid, data=ntot)]
            for f, arr in zip(fields[1:], merged[2:]):
                out.append(DeviceColumn(f.dataType, group_valid & nz,
                                        data=arr))
            return out
        if a.func == "approx_count_distinct":
            c = bufs[0] if perm is None else _gather_col(bufs[0], perm)
            ok = c.validity & mask_sorted
            m = c.ewidth
            seg_safe = jnp.where(ok, seg, nseg)
            regs = jnp.zeros((nseg, m), jnp.int32).at[seg_safe].max(
                c.data.astype(jnp.int32), mode="drop")
            lengths = jnp.full(nseg, m, jnp.int32)
            ev = jnp.ones((nseg, m), jnp.bool_)
            return [DeviceColumn(fields[0].dataType, group_valid, data=regs,
                                 lengths=lengths, elem_valid=ev)]
        out = []
        for f, c in zip(fields, bufs):
            cs = c if perm is None else _gather_col(c, perm)
            validity = cs.validity & mask_sorted
            if (func in ("sum", "avg") and isinstance(f.dataType, T.DecimalType)
                    and (f.dataType.is_128 or cs.is_dec128)):
                out.append(_sum_dec128(cs, validity, seg, nseg, group_valid,
                                       f.dataType))
                continue
            if func in ("min", "max") and cs.is_dec128:
                out.append(_minmax_dec128(cs, func, seg, validity, nseg,
                                          group_valid, f))
                continue
            if func in ("sum", "count", "avg"):
                s, has = SEG.seg_sum(
                    cs.data.astype(jnp.float64)
                    if _is_float(f.dataType) else cs.data.astype(jnp.int64),
                    validity, seg, nseg)
                if func == "count" or f.name.endswith("_count"):
                    out.append(DeviceColumn(
                        f.dataType, group_valid,
                        data=s.astype(T.storage_dtype(f.dataType))))
                else:
                    out.append(DeviceColumn(
                        f.dataType, group_valid & has,
                        data=s.astype(T.storage_dtype(f.dataType))))
            elif func in ("min", "max"):
                if cs.is_string:
                    out.append(self._minmax_string(
                        cs, func, seg, validity, cap, group_valid, f, nseg))
                else:
                    fn = SEG.seg_min if func == "min" else SEG.seg_max
                    m, has = fn(cs.data, validity, seg, nseg,
                                _is_float(f.dataType))
                    out.append(DeviceColumn(
                        f.dataType, group_valid & has,
                        data=m.astype(T.storage_dtype(f.dataType))
                        if not isinstance(f.dataType, T.BooleanType) else m))
            elif func in ("first", "last"):
                idx_fn = (SEG.seg_first_index if func == "first"
                          else _seg_last_index)
                idx = idx_fn(seg, mask_sorted, nseg)
                g = _gather_col(cs, jnp.clip(idx, 0, cap - 1))
                out.append(DeviceColumn(f.dataType, g.validity & group_valid,
                                        data=g.data, chars=g.chars,
                                        lengths=g.lengths))
            elif func in ("bit_and", "bit_or", "bit_xor"):
                op = {"bit_and": (lambda x, y: x & y, -1),
                      "bit_or": (lambda x, y: x | y, 0),
                      "bit_xor": (lambda x, y: x ^ y, 0)}[func]
                m, has = SEG.seg_fold(cs.data, validity, seg, nseg,
                                      op[0], op[1])
                out.append(DeviceColumn(
                    f.dataType, group_valid & has,
                    data=m.astype(T.storage_dtype(f.dataType))))
            else:
                raise NotImplementedError(f"merge for {func}")
        return out

    def _global_agg_empty(self) -> ColumnarBatch:
        """Zero input batches, no grouping keys -> one row of initial agg
        values, in buffer form for PARTIAL (so multi-wide avg/variance
        buffers stay aligned with the declared schema)."""
        cols = []
        for a, fields in zip(self.aggregates, self._agg_fields()):
            for fi, f in enumerate(fields):
                # position within the buffer group decides the initial
                # value: counts start at valid 0, everything else NULL
                if a.func in ("count", "count_star"):
                    zero_valued = True
                elif a.func == "avg" and len(fields) == 2:
                    zero_valued = fi == 1  # (sum, count)
                elif a.func in VARIANCE_FUNCS and len(fields) == 3:
                    zero_valued = fi == 0  # (n, avg, m2)
                else:
                    zero_valued = False
                shape = ((1, 2) if isinstance(f.dataType, T.DecimalType)
                         and f.dataType.is_128 else (1,))
                if zero_valued:
                    cols.append(DeviceColumn(
                        f.dataType, jnp.ones(1, jnp.bool_),
                        data=jnp.zeros(shape, T.storage_dtype(f.dataType))))
                elif isinstance(f.dataType, T.StringType):
                    cols.append(DeviceColumn(
                        f.dataType, jnp.zeros(1, jnp.bool_),
                        chars=jnp.zeros((1, 8), jnp.uint8),
                        lengths=jnp.zeros(1, jnp.int32)))
                else:
                    cols.append(DeviceColumn(
                        f.dataType, jnp.zeros(1, jnp.bool_),
                        data=jnp.zeros(shape, T.storage_dtype(f.dataType))))
        return ColumnarBatch(cols, 1, self._output)

    # ------------------------------------------------------------------
    def _aggregate_batch(self, batch: ColumnarBatch) -> ColumnarBatch:
        if self._has_collect:
            # array output width must be static: pre-pass for the largest
            # group's row count, bucketed (jit cached per bucket)
            from spark_rapids_tpu.columnar.column import (
                DEFAULT_WIDTH_BUCKETS,
                round_up_bucket,
            )

            if getattr(self, "_maxgrp_jit", None) is None:
                self._maxgrp_jit = tpu_jit(self._max_group_rows_fn)
            mx = int(self._maxgrp_jit(tuple(batch.columns),
                                      jnp.int32(batch.num_rows)))
            self._collect_ewidth = round_up_bucket(
                max(mx, 1), DEFAULT_WIDTH_BUCKETS)
            cache = getattr(self, "_collect_jits", None)
            if cache is None:
                cache = self._collect_jits = {}
            if self._collect_ewidth not in cache:
                cache[self._collect_ewidth] = tpu_jit(self._agg_fn)
            jitted = cache[self._collect_ewidth]
            cols, nrows = jitted(tuple(batch.columns),
                                 jnp.int32(batch.num_rows))
            n = 1 if not self.grouping else int(nrows)
            return ColumnarBatch(list(cols), n, self._output)
        args = (tuple(batch.columns), jnp.int32(batch.num_rows))
        B = self._bounded_groups_cap(batch.capacity)
        if B:
            # bounded-cardinality ladder (VERDICT r5 perf): run the
            # B-wide boundary-form program; the output row count (synced
            # anyway) doubles as the overflow check, growing B to the
            # next power of two when the data has more groups
            cols, nrows = self._agg_jit(B)(*args)
            n = int(nrows)
            while n > B:
                B2 = min(max(1 << (n - 1).bit_length(), B * 2),
                         batch.capacity)
                self._groups_cap_hint = B2
                if B2 >= batch.capacity:
                    cols, nrows = self._agg_jit(None)(*args)
                    n = int(nrows)
                    break
                cols, nrows = self._agg_jit(B2)(*args)
                n = int(nrows)
                B = B2
            return ColumnarBatch(list(cols), n, self._output)
        cols, nrows = self._agg_jit(None)(*args)
        n = 1 if not self.grouping else int(nrows)
        return ColumnarBatch(list(cols), n, self._output)

    def _agg_program(self, groups_cap=None):
        """(registry key parts, factory) for the aggregation program at
        one groups-cap rung — shared by runtime and AOT enumeration."""
        fpp = self._program_fp()
        key_parts = None if fpp is None else fpp + ("aggfn", groups_cap)

        def factory():
            # detached clone: registry entries outlive the query and must
            # not pin the input subtree through the bound method
            clone = self.detached_for_trace()
            if groups_cap is None:
                return tpu_jit(clone._agg_fn), None

            def fn(cols, num_rows, _b=groups_cap):
                return clone._agg_fn(cols, num_rows, groups_cap=_b)

            return tpu_jit(fn), None

        return key_parts, factory

    def _agg_jit(self, groups_cap=None):
        cache = getattr(self, "_agg_jits", None)
        if cache is None:
            cache = self._agg_jits = {}
        if groups_cap not in cache:
            from spark_rapids_tpu.compilecache.registry import (
                cached_program,
            )

            key_parts, factory = self._agg_program(groups_cap)
            cache[groups_cap] = cached_program(
                key_parts, factory,
                label=f"agg:{self.describe()[:40]}").jitted
        return cache[groups_cap]

    # -- plan-time AOT enumeration (compilecache/aot.py) -----------------
    def aot_output_caps(self):
        """Output capacity is predictable even though the group COUNT is
        not: the bounded-groups ladder emits B-capacity batches on its
        first rung, the full-width path keeps the input capacity — this
        is what lets a window/sort ABOVE an aggregate enumerate its
        program at plan time."""
        if self._has_collect:
            return None
        in_caps = self.aot_input_caps()
        if not in_caps:
            return None
        out = set()
        for c in in_caps:
            B = self._bounded_groups_cap(c)
            out.add(B if B else c)
        return sorted(out)

    def aot_emits_single_batch(self):
        # streaming/COMPLETE merge down to one output batch; PARTIAL
        # emits one buffer batch per input batch
        return self.mode != AggregateMode.PARTIAL

    def aot_programs(self):
        from spark_rapids_tpu.compilecache.aot import (
            AotProgram,
            dummy_batch_args,
        )

        if self._has_collect:
            return []
        caps = self.aot_input_caps()
        if not caps:
            return []
        if self.mode == AggregateMode.COMPLETE \
                and not self.aot_child_single_batch():
            # multi-batch COMPLETE runs through the two-phase twins, not
            # this node's fused program
            return []
        if self.mode == AggregateMode.FINAL:
            return []  # consumes data-dependent buffer rows
        schema = self.input_schema
        out = []
        for B in {self._bounded_groups_cap(c) for c in caps}:
            key_parts, factory = self._agg_program(B)
            # only the capacities whose ladder rung IS this B — the
            # runtime pairs each batch capacity with exactly its rung, so
            # warming the (B x capacity) cross-product would burn pool
            # time on specializations nothing ever dispatches
            b_caps = tuple(c for c in caps
                           if self._bounded_groups_cap(c) == B)

            def args_factory(_caps=b_caps):
                return [dummy_batch_args(schema, c) for c in _caps]

            out.append(AotProgram(
                key_parts, factory, args_factory,
                f"agg:{self.describe()[:48]}"))
        return out

    def _bounded_groups_cap(self, cap: int):
        """The groups-cap ladder rung for this batch, or None when the
        bounded path does not apply (no grouping / collect aggs / conf
        off / batch small enough that full width is already cheap)."""
        if not self.grouping or self._has_collect:
            return None
        from spark_rapids_tpu.config import AGG_SMALL_GROUPS_CAP, get_conf

        B = get_conf().get(AGG_SMALL_GROUPS_CAP)
        if not B:
            return None
        B = max(B, getattr(self, "_groups_cap_hint", 0))
        return B if B < cap else None

    def _max_group_rows_fn(self, cols, num_rows):
        """Largest per-group row count (the collect array width bound)."""
        batch = ColumnarBatch(list(cols), num_rows, self.input_schema)
        ctx = EvalContext(batch, ansi=self.ansi)
        mask = batch.row_mask
        for op in self.pre_ops:
            batch, mask = op.apply_masked(ctx, batch, mask)
        ctx.batch = batch
        key_cols = [g.eval_tpu(ctx) for g in self.grouping]
        if not key_cols:
            return jnp.sum(mask.astype(jnp.int32))
        cap = batch.capacity
        keys: List[jax.Array] = []
        hi = jnp.int64(9223372036854775807)
        for kc in key_cols:
            nullk = jnp.where(kc.validity, 0, -1).astype(jnp.int64)
            keys.append(jnp.where(mask, nullk, hi))
            for w in _column_key_words(kc):
                keys.append(jnp.where(mask, jnp.where(kc.validity, w, 0), hi))
        sorted_keys = jax.lax.sort(tuple(keys), num_keys=len(keys))
        mask_sorted = jnp.sort(~mask)  # row_mask sorted: valid first
        seg, _ = group_segments(list(sorted_keys), ~mask_sorted)
        seg = jnp.where(~mask_sorted, seg, cap - 1)
        cnt = jax.ops.segment_sum((~mask_sorted).astype(jnp.int32), seg,
                                  num_segments=cap)
        return jnp.max(cnt)

    def _agg_fn(self, cols, num_rows, row_valid=None, groups_cap=None):
        batch = ColumnarBatch(list(cols), num_rows, self.input_schema)
        ctx = EvalContext(batch, ansi=self.ansi)
        mask = batch.row_mask
        if row_valid is not None:
            # mesh execution: rows received over the ICI all-to-all carry an
            # explicit occupancy mask instead of a dense [0, num_rows) prefix
            mask = mask & row_valid
        for op in self.pre_ops:
            batch, mask = op.apply_masked(ctx, batch, mask)
        ctx.batch = batch
        key_cols = [g.eval_tpu(ctx) for g in self.grouping]
        if not key_cols:
            return self._global_agg(ctx, batch, mask)
        cap = batch.capacity
        # ---- sort rows by group keys (stable, padding last) ----
        keys: List[jax.Array] = []
        hi = jnp.int64(9223372036854775807)
        for kc in key_cols:
            nullk = jnp.where(kc.validity, 0, -1).astype(jnp.int64)
            keys.append(jnp.where(mask, nullk, hi))
            for w in _column_key_words(kc):
                keys.append(jnp.where(mask, jnp.where(kc.validity, w, 0), hi))
        # CO-SORT the aggregate-input payloads with the keys: one fused
        # sorting network moves the data, replacing one full-width random
        # gather PER INPUT (each ~380ms at 20M rows on v5e — round-5
        # calibration) with a small per-operand sort cost
        payload = self._presortable_inputs(ctx)
        extra_ops: List[jax.Array] = []
        layout = []
        for pk, c, arrs in payload:
            layout.append((pk, c, len(arrs)))
            extra_ops.extend(arrs)
        iota = jnp.arange(cap, dtype=jnp.int32)
        sorted_all = jax.lax.sort(
            tuple(keys) + (iota, mask) + tuple(extra_ops),
            num_keys=len(keys), is_stable=True)
        nk = len(keys)
        sorted_keys = list(sorted_all[:nk])
        perm = sorted_all[nk]
        mask_sorted = sorted_all[nk + 1]
        rest = sorted_all[nk + 2:]
        self._presorted = {}
        pos = 0
        for pk, c, k in layout:
            self._presorted[pk] = _rebuild_flat_col(c, rest[pos:pos + k])
            pos += k
        try:
            seg, ngroups = group_segments(sorted_keys, mask_sorted)
            seg = jnp.where(mask_sorted, seg, cap - 1)  # padding -> last
            nseg = cap
            bscope = None
            if groups_cap:
                # bounded-cardinality mode (VERDICT r5 perf): outputs are
                # groups_cap wide; every SEG primitive in this trace takes
                # the boundary form (no full-width scatters).  The caller
                # verifies ngroups <= groups_cap from the synced row count
                # and re-runs on the next ladder rung if not.
                nseg = groups_cap
                bscope = SEG.bounds_scope(SEG.SegBounds(seg, nseg))
                bscope.__enter__()
            try:
                # ---- group-key output columns ----
                first_idx = SEG.seg_first_index(seg, mask_sorted, nseg)
                safe_first = jnp.clip(first_idx, 0, cap - 1)
                out_cols: List[DeviceColumn] = []
                group_valid = jnp.arange(nseg) < ngroups
                for kc in key_cols:
                    g = _gather_col(kc, perm[safe_first])
                    out_cols.append(DeviceColumn(
                        g.dtype, g.validity & group_valid, data=g.data,
                        chars=g.chars, lengths=g.lengths))
                # ---- aggregates ----
                for a, f in zip(self.aggregates, self._agg_fields()):
                    out_cols.extend(self._eval_agg(
                        a, f, ctx, perm, seg, mask_sorted, cap,
                        group_valid, nseg=nseg))
            finally:
                if bscope is not None:
                    bscope.__exit__()
        finally:
            self._presorted = None
        return tuple(out_cols), ngroups.astype(jnp.int32)

    _PRESORTABLE_FUNCS = frozenset({
        "sum", "count", "min", "max", "avg", "first", "last",
        "any_value", "bool_and", "bool_or", "bit_and", "bit_or",
        "bit_xor", "count_if"})

    def _presortable_inputs(self, ctx):
        """Aggregate-input columns eligible for key co-sorting, with their
        flat operand arrays.  Strings/nested stay on the gather path."""
        out = []
        for a in self.aggregates:
            if a.func not in self._PRESORTABLE_FUNCS:
                continue
            suffixes = [None]
            if self.mode == AggregateMode.FINAL and a.func == "avg":
                suffixes = ["_sum", "_count"]
            if a.child is None and self.mode != AggregateMode.FINAL:
                continue     # count(*): a constant ones column
            for sfx in suffixes:
                c = self._input_col(a, ctx, None, sfx)
                arrs = _flat_sort_operands(c)
                if arrs is not None:
                    out.append(((a.result_name, sfx), c, arrs))
        return out

    def _agg_fields(self):
        """Output fields per aggregate (partial avg takes two)."""
        fields = list(self._output.fields[len(self.grouping):])
        out = []
        i = 0
        for a in self.aggregates:
            if a.func == "avg" and self.mode == AggregateMode.PARTIAL:
                out.append((fields[i], fields[i + 1]))
                i += 2
            elif (a.func in MOMENT_BUFFERS
                  and self.mode == AggregateMode.PARTIAL):
                k = len(MOMENT_BUFFERS[a.func])
                out.append(tuple(fields[i:i + k]))
                i += k
            else:
                out.append((fields[i],))
                i += 1
        return out

    # -- per-aggregate evaluation --------------------------------------
    def _input_col(self, a: AggregateExpression, ctx, perm,
                   suffix: Optional[str] = None):
        """Column holding this aggregate's input (already sorted via perm).

        When the enclosing _agg_fn co-sorted this input with the keys the
        presorted column comes back directly — no gather."""
        pres = getattr(self, "_presorted", None)
        if perm is not None and pres is not None:
            hit = pres.get((a.result_name, suffix))
            if hit is not None:
                return hit
        if self.mode == AggregateMode.FINAL:
            # inputs are the partial buffers by position in child schema
            name = a.result_name + (suffix or "")
            names = self.child_schema.field_names()
            ord_ = names.index(name)
            c = ctx.batch.columns[ord_]
        else:
            if a.child is None:
                c = DeviceColumn(T.LONG,
                                 jnp.ones(ctx.batch.capacity, jnp.bool_),
                                 data=jnp.ones(ctx.batch.capacity, jnp.int64))
            else:
                c = a.child.eval_tpu(ctx)
        return c if perm is None else _gather_col(c, perm)

    def _eval_agg(self, a: AggregateExpression, fields, ctx, perm, seg,
                  mask_sorted, cap, group_valid,
                  nseg: int = None) -> List[DeviceColumn]:
        nseg = cap if nseg is None else nseg
        mode = self.mode
        func = a.func
        if func == "count_star":
            func = "count"
        if func == "any_value":
            func = "first"          # Spark AnyValue == First(ignoreNulls=F)
        if func in ("bool_and", "bool_or"):
            func = "min" if func == "bool_and" else "max"
        out = []
        if func in VARIANCE_FUNCS:
            return self._eval_variance(a, fields, ctx, perm, seg, mask_sorted,
                                       cap, group_valid, nseg)
        if func in HIGHER_MOMENT_FUNCS:
            return self._eval_higher_moment(a, fields, ctx, perm, seg,
                                            mask_sorted, cap, group_valid,
                                            nseg)
        if func in COVARIANCE_FUNCS or func in PN_REGR_FUNCS:
            return self._eval_covariance(a, fields, ctx, perm, seg,
                                         mask_sorted, cap, group_valid, nseg)
        if func in ("bit_and", "bit_or", "bit_xor"):
            (f,) = fields
            c = self._input_col(a, ctx, perm)
            validity = c.validity & mask_sorted
            op = {"bit_and": (lambda x, y: x & y, -1),
                  "bit_or": (lambda x, y: x | y, 0),
                  "bit_xor": (lambda x, y: x ^ y, 0)}[func]
            m, has = SEG.seg_fold(c.data, validity, seg, nseg,
                                  op[0], op[1])
            return [DeviceColumn(f.dataType, group_valid & has,
                                 data=m.astype(T.storage_dtype(f.dataType)))]
        if func == "count_if":
            (f,) = fields
            if mode == AggregateMode.FINAL:
                c = self._input_col(a, ctx, perm)
                s, _ = SEG.seg_sum(c.data, c.validity & mask_sorted, seg,
                                   nseg)
                cnt = s
            else:
                c = self._input_col(a, ctx, perm)
                hit = c.validity & mask_sorted & c.data.astype(jnp.bool_)
                cnt = SEG.seg_count(hit, seg, nseg)
            return [DeviceColumn(T.LONG, group_valid, data=cnt)]
        if func == "approx_count_distinct":
            return self._eval_hll(a, fields, ctx, perm, seg, mask_sorted,
                                  cap, group_valid, nseg)
        if func in ("percentile", "approx_percentile", "median"):
            return self._eval_percentile(a, fields, ctx, perm, seg,
                                         mask_sorted, cap, group_valid, nseg)
        if func == "bloom_filter_agg":
            return self._eval_bloom(a, fields, ctx, perm, seg, mask_sorted,
                                    cap, group_valid, nseg)
        if func == "avg":
            sum_dt = (fields[0].dataType if mode == AggregateMode.PARTIAL
                      else (self.child_schema.fields[
                          self.child_schema.field_names().index(
                              a.result_name + "_sum")].dataType
                          if mode == AggregateMode.FINAL else None))
            dec_in = (a.child is not None
                      and isinstance(a.child.dataType, T.DecimalType)) \
                if mode != AggregateMode.FINAL else isinstance(
                    sum_dt, T.DecimalType)
            buf128 = (isinstance(sum_dt, T.DecimalType) and sum_dt.is_128) \
                if sum_dt is not None else (
                    dec_in and a.child.dataType.precision + 10 > 18)
            if mode == AggregateMode.PARTIAL:
                c = self._input_col(a, ctx, perm)
                sum_f, cnt_f = fields
                validity = c.validity & mask_sorted
                if buf128:
                    out.append(_sum_dec128(c, validity, seg, nseg,
                                           group_valid, sum_f.dataType))
                else:
                    s, has = SEG.seg_sum(_sum_input(c, sum_f.dataType),
                                         validity, seg, nseg)
                    out.append(DeviceColumn(sum_f.dataType, group_valid & has,
                                            data=s))
                cnt = SEG.seg_count(validity, seg, nseg)
                out.append(DeviceColumn(cnt_f.dataType, group_valid, data=cnt))
                return out
            (f,) = fields
            if mode == AggregateMode.FINAL:
                cs = self._input_col(a, ctx, perm, "_sum")
                cc = self._input_col(a, ctx, perm, "_count")
                n, _ = SEG.seg_sum(cc.data, cc.validity & mask_sorted, seg,
                                   nseg)
                if buf128:
                    scol = _sum_dec128(cs, cs.validity & mask_sorted, seg,
                                       nseg, group_valid, sum_dt)
                    return [_avg_div_dec128(scol, n, sum_dt.scale,
                                            f.dataType, group_valid)]
                s, _ = SEG.seg_sum(cs.data, cs.validity & mask_sorted, seg, nseg)
            else:
                c = self._input_col(a, ctx, perm)
                validity = c.validity & mask_sorted
                n = SEG.seg_count(validity, seg, nseg)
                if buf128:
                    buf_dt = T.DecimalType(
                        min(a.child.dataType.precision + 10, 38),
                        a.child.dataType.scale)
                    scol = _sum_dec128(c, validity, seg, nseg, group_valid,
                                       buf_dt)
                    return [_avg_div_dec128(scol, n, buf_dt.scale,
                                            f.dataType, group_valid)]
                s, _ = SEG.seg_sum(_sum_input(c, None), validity, seg, nseg)
            nz = n > 0
            if isinstance(f.dataType, T.DecimalType):
                in_scale = (a.child.dataType.scale
                            if a.child is not None else 0)
                shift = f.dataType.scale - in_scale
                num = s * (10 ** min(max(shift, 0), 18))
                den = jnp.where(nz, n, 1)
                q = num // den
                rem = num - q * den
                q = q + jnp.where((rem != 0) & (num < 0), 1, 0)
                rem2 = num - q * den
                half_up = (jnp.abs(rem2) * 2 >= den) & (rem2 != 0)
                q = q + jnp.where(half_up, jnp.sign(num), 0)
                out.append(DeviceColumn(f.dataType, group_valid & nz, data=q))
            else:
                avg = s.astype(jnp.float64) / jnp.where(nz, n, 1)
                out.append(DeviceColumn(T.DOUBLE, group_valid & nz, data=avg))
            return out
        (f,) = fields
        if func == "count":
            c = self._input_col(a, ctx, perm)
            if mode == AggregateMode.FINAL:
                s, _ = SEG.seg_sum(c.data, c.validity & mask_sorted, seg, nseg)
                cnt = s
            else:
                cnt = SEG.seg_count(c.validity & mask_sorted, seg, nseg)
            out.append(DeviceColumn(T.LONG, group_valid, data=cnt))
            return out
        if func in ("collect_list", "collect_set"):
            c = self._input_col(a, ctx, perm)
            return [self._eval_collect(a, fields[0], c,
                                       c.validity & mask_sorted, seg,
                                       mask_sorted, cap, group_valid, nseg)]
        c = self._input_col(a, ctx, perm)
        validity = c.validity & mask_sorted
        if func == "sum":
            if (isinstance(f.dataType, T.DecimalType)
                    and (f.dataType.is_128 or c.is_dec128)):
                out.append(_sum_dec128(c, validity, seg, nseg, group_valid,
                                       f.dataType))
                return out
            s, has = SEG.seg_sum(_sum_input(c, f.dataType), validity, seg, nseg)
            out.append(DeviceColumn(f.dataType, group_valid & has,
                                    data=s.astype(T.storage_dtype(f.dataType))))
            return out
        if func in ("min", "max"):
            isf = _is_float(f.dataType)
            if c.is_string:
                return [self._minmax_string(c, func, seg, validity, cap,
                                            group_valid, f, nseg)]
            if c.is_dec128:
                return [_minmax_dec128(c, func, seg, validity, nseg,
                                       group_valid, f)]
            fn = SEG.seg_min if func == "min" else SEG.seg_max
            m, has = fn(c.data, validity, seg, nseg, isf)
            out.append(DeviceColumn(f.dataType, group_valid & has,
                                    data=m.astype(T.storage_dtype(f.dataType))
                                    if not isinstance(f.dataType, T.BooleanType)
                                    else m))
            return out
        if func in ("first", "last"):
            idx_fn = SEG.seg_first_index if func == "first" else _seg_last_index
            idx = idx_fn(seg, mask_sorted, nseg)
            g = _gather_col(c, jnp.clip(idx, 0, cap - 1))
            out.append(DeviceColumn(f.dataType, g.validity & group_valid,
                                    data=g.data, chars=g.chars,
                                    lengths=g.lengths))
            return out
        raise NotImplementedError(f"aggregate {func}")

    def _eval_variance(self, a, fields, ctx, perm, seg, mask_sorted, cap,
                       group_valid, nseg) -> List[DeviceColumn]:
        """Central moments (n, avg, m2).  PARTIAL emits the buffer triple;
        FINAL Chan-merges child buffers and finalizes; COMPLETE does both.
        Matches Spark's CentralMomentAgg: n==0 -> NULL, samp with n==1 ->
        NULL (default nullOnDivideByZero)."""
        if self.mode == AggregateMode.FINAL:
            cn = self._input_col(a, ctx, perm, "_n")
            ca = self._input_col(a, ctx, perm, "_avg")
            cm = self._input_col(a, ctx, perm, "_m2")
            ntot, nz, mean, m2 = _chan_merge(cn, ca, cm, mask_sorted, seg,
                                             nseg)
        else:
            c = self._input_col(a, ctx, perm)
            valid = c.validity & mask_sorted
            x = jnp.where(valid, c.data.astype(jnp.float64), 0.0)
            if isinstance(c.dtype, T.DecimalType):
                # unscaled storage -> numeric value (Spark casts to double)
                x = x * jnp.float64(10.0 ** -c.dtype.scale)
            ntot = SEG.seg_count(valid, seg, nseg).astype(jnp.float64)
            s, _ = SEG.seg_sum(x, valid, seg, nseg)
            nz = ntot > 0
            mean = s / jnp.where(nz, ntot, 1.0)
            d = jnp.where(valid, x - mean[seg], 0.0)
            m2, _ = SEG.seg_sum(d * d, valid, seg, nseg)
        if self.mode == AggregateMode.PARTIAL:
            fn_, fa, fm = fields
            return [
                DeviceColumn(fn_.dataType, group_valid, data=ntot),
                DeviceColumn(fa.dataType, group_valid & nz, data=mean),
                DeviceColumn(fm.dataType, group_valid & nz, data=m2),
            ]
        (f,) = fields
        pop = a.func.endswith("_pop")
        den = ntot if pop else ntot - 1.0
        # Spark 3.1+ default nullOnDivideByZero: samp with n==1 -> NULL
        ok = den > 0.0
        var = m2 / jnp.where(ok, den, 1.0)
        res = var if a.func.startswith("var") else jnp.sqrt(var)
        return [DeviceColumn(f.dataType, group_valid & nz & ok, data=res)]

    def _numeric_f64(self, c: DeviceColumn) -> jax.Array:
        x = c.data.astype(jnp.float64)
        if isinstance(c.dtype, T.DecimalType):
            x = x * jnp.float64(10.0 ** -c.dtype.scale)
        return x

    def _eval_higher_moment(self, a, fields, ctx, perm, seg, mask_sorted,
                            cap, group_valid, nseg) -> List[DeviceColumn]:
        """skewness / kurtosis: central moments up to m3/m4.

        Reference analog: Spark Skewness/Kurtosis (CentralMomentAgg with
        momentOrder 3/4), GPU'd in org/apache/spark/sql/rapids/aggregate.
        Merging uses the closed forms m3 = Σm3_i + 3Σm2_i·d_i + Σn_i·d_i³
        (and the order-4 analog), which are plain segmented sums — no
        sequential pairwise Chan recursion needed."""
        want_m4 = a.func == "kurtosis"
        if self.mode == AggregateMode.FINAL:
            from spark_rapids_tpu.plan.nodes import MOMENT_BUFFERS as _MB

            bufs = [self._input_col(a, ctx, perm, s)
                    for s in _MB[a.func]]
            merged = _merge_moment_bufs(bufs, mask_sorted, seg, nseg)
            if want_m4:
                ntot, nz, mean, m2, m3, m4 = merged
            else:
                ntot, nz, mean, m2, m3 = merged
        else:
            c = self._input_col(a, ctx, perm)
            valid = c.validity & mask_sorted
            x = jnp.where(valid, self._numeric_f64(c), 0.0)
            ntot = SEG.seg_count(valid, seg, nseg).astype(jnp.float64)
            s, _ = SEG.seg_sum(x, valid, seg, nseg)
            nz = ntot > 0
            mean = s / jnp.where(nz, ntot, 1.0)
            d = jnp.where(valid, x - mean[seg], 0.0)
            m2, _ = SEG.seg_sum(d * d, valid, seg, nseg)
            m3, _ = SEG.seg_sum(d ** 3, valid, seg, nseg)
            if want_m4:
                m4, _ = SEG.seg_sum(d ** 4, valid, seg, nseg)
        if self.mode == AggregateMode.PARTIAL:
            cols = [ntot, mean, m2, m3] + ([m4] if want_m4 else [])
            out = [DeviceColumn(fields[0].dataType, group_valid, data=ntot)]
            for f, arr in zip(fields[1:], cols[1:]):
                out.append(DeviceColumn(f.dataType, group_valid & nz,
                                        data=arr))
            return out
        (f,) = fields
        # Spark nullOnDivideByZero: m2 == 0 (or empty) -> NULL
        ok_res = nz & (m2 != 0.0)
        safe_m2 = jnp.where(ok_res, m2, 1.0)
        if want_m4:
            res = ntot * m4 / (safe_m2 * safe_m2) - 3.0
        else:
            res = jnp.sqrt(ntot) * m3 / jnp.power(safe_m2, 1.5)
        return [DeviceColumn(f.dataType, group_valid & ok_res, data=res)]

    def _eval_covariance(self, a, fields, ctx, perm, seg, mask_sorted, cap,
                         group_valid, nseg) -> List[DeviceColumn]:
        """covar_pop / covar_samp / corr — Spark Covariance/Corr buffers
        (n, xAvg, yAvg, ck [, xMk, yMk]); rows count only when BOTH inputs
        are non-null."""
        is_regr = a.func in PN_REGR_FUNCS
        is_corr = a.func == "corr" or is_regr   # 6-channel buffers
        if self.mode == AggregateMode.FINAL:
            from spark_rapids_tpu.plan.nodes import MOMENT_BUFFERS as _MB

            bufs = [self._input_col(a, ctx, perm, s)
                    for s in _MB[a.func]]
            merged = _merge_cov_bufs(bufs, mask_sorted, seg, nseg)
            if is_corr:
                ntot, nz, xavg, yavg, ck, xm2, ym2 = merged
            else:
                ntot, nz, xavg, yavg, ck = merged
        else:
            # regr_f(y, x): the DEPENDENT y is the first argument; the
            # covariance stats' x must be the independent (second)
            x_expr = a.child2 if is_regr else a.child
            y_expr = a.child if is_regr else a.child2
            x_col = x_expr.eval_tpu(ctx)
            y_col = y_expr.eval_tpu(ctx)
            if perm is not None:
                x_col = _gather_col(x_col, perm)
                y_col = _gather_col(y_col, perm)
            valid = x_col.validity & y_col.validity & mask_sorted
            x = jnp.where(valid, self._numeric_f64(x_col), 0.0)
            y = jnp.where(valid, self._numeric_f64(y_col), 0.0)
            ntot = SEG.seg_count(valid, seg, nseg).astype(jnp.float64)
            nz = ntot > 0
            sx, _ = SEG.seg_sum(x, valid, seg, nseg)
            sy, _ = SEG.seg_sum(y, valid, seg, nseg)
            xavg = sx / jnp.where(nz, ntot, 1.0)
            yavg = sy / jnp.where(nz, ntot, 1.0)
            dx = jnp.where(valid, x - xavg[seg], 0.0)
            dy = jnp.where(valid, y - yavg[seg], 0.0)
            ck, _ = SEG.seg_sum(dx * dy, valid, seg, nseg)
            if is_corr:
                xm2, _ = SEG.seg_sum(dx * dx, valid, seg, nseg)
                ym2, _ = SEG.seg_sum(dy * dy, valid, seg, nseg)
        if self.mode == AggregateMode.PARTIAL:
            bufs = [ntot, xavg, yavg, ck] + ([xm2, ym2] if is_corr else [])
            out = [DeviceColumn(fields[0].dataType, group_valid, data=ntot)]
            for f, arr in zip(fields[1:], bufs[1:]):
                out.append(DeviceColumn(f.dataType, group_valid & nz,
                                        data=arr))
            return out
        (f,) = fields
        if is_regr:
            func = a.func
            if func == "regr_count":
                return [DeviceColumn(T.LONG, group_valid,
                                     data=jnp.where(
                                         group_valid, ntot, 0.0).astype(
                                         jnp.int64))]
            if func == "regr_avgx":
                return [DeviceColumn(f.dataType, group_valid & nz,
                                     data=xavg)]
            if func == "regr_avgy":
                return [DeviceColumn(f.dataType, group_valid & nz,
                                     data=yavg)]
            if func == "regr_sxx":
                return [DeviceColumn(f.dataType, group_valid & nz,
                                     data=xm2)]
            if func == "regr_syy":
                return [DeviceColumn(f.dataType, group_valid & nz,
                                     data=ym2)]
            if func == "regr_sxy":
                return [DeviceColumn(f.dataType, group_valid & nz,
                                     data=ck)]
            ok = nz & (xm2 != 0.0)
            slope = ck / jnp.where(xm2 != 0.0, xm2, 1.0)
            if func == "regr_slope":
                return [DeviceColumn(f.dataType, group_valid & ok,
                                     data=slope)]
            if func == "regr_intercept":
                return [DeviceColumn(f.dataType, group_valid & ok,
                                     data=yavg - slope * xavg)]
            # regr_r2: syy==0 -> 1.0; else ck^2/(sxx*syy)
            r2 = jnp.where(ym2 == 0.0, 1.0,
                           (ck * ck) / jnp.where(
                               (xm2 * ym2) != 0.0, xm2 * ym2, 1.0))
            return [DeviceColumn(f.dataType, group_valid & ok, data=r2)]
        if is_corr:
            # zero variance -> NaN via natural fp division (Spark Corr)
            res = ck / jnp.sqrt(xm2 * ym2)
            return [DeviceColumn(f.dataType, group_valid & nz, data=res)]
        if a.func == "covar_pop":
            res = ck / jnp.where(nz, ntot, 1.0)
            return [DeviceColumn(f.dataType, group_valid & nz, data=res)]
        ok_res = ntot > 1.0
        res = ck / jnp.where(ok_res, ntot - 1.0, 1.0)
        return [DeviceColumn(f.dataType, group_valid & ok_res, data=res)]

    def _eval_hll(self, a, fields, ctx, perm, seg, mask_sorted, cap,
                  group_valid, nseg) -> List[DeviceColumn]:
        """approx_count_distinct — HyperLogLog++ registers per group.

        Reference analog: GpuHyperLogLogPlusPlus (spark-rapids-jni HLL
        sketch, SURVEY.md §2.4).  TPU design: registers live as a padded
        list column (one m-wide int32 row per group), built with one
        scatter-max; partial merge is another scatter-max.  Estimation uses
        the standard HLL++ raw/linear-counting split WITHOUT Spark's
        empirical bias tables (documented TypeSig note)."""
        from spark_rapids_tpu.ops.hashing import xxhash64_column

        p = HLL_DEFAULT_P
        m = 1 << p
        (f,) = fields
        if self.mode == AggregateMode.FINAL:
            c = self._input_col(a, ctx, perm, "_hll")  # list col (cap, m)
            ok = c.validity & mask_sorted
            seg_safe = jnp.where(ok, seg, nseg)
            regs = jnp.zeros((nseg, m), jnp.int32).at[seg_safe].max(
                c.data.astype(jnp.int32), mode="drop")
        else:
            c = self._input_col(a, ctx, perm)
            valid = c.validity & mask_sorted
            h = xxhash64_column(c, jnp.full(cap, jnp.uint64(42)))
            h = h.view(jnp.int64)
            idx = jnp.right_shift(h, 64 - p) & (m - 1)
            w = jnp.left_shift(h, p)
            rank = jnp.minimum(jax.lax.clz(w) + 1, 65 - p).astype(jnp.int32)
            seg_safe = jnp.where(valid, seg, nseg)
            regs = jnp.zeros((nseg, m), jnp.int32).at[
                seg_safe, idx].max(rank, mode="drop")
        if self.mode == AggregateMode.PARTIAL:
            lengths = jnp.full(nseg, m, jnp.int32)
            ev = jnp.ones((nseg, m), jnp.bool_)
            return [DeviceColumn(f.dataType, group_valid, data=regs,
                                 lengths=lengths, elem_valid=ev)]
        alpha = 0.7213 / (1.0 + 1.079 / m)
        inv = jnp.sum(jnp.exp2(-regs.astype(jnp.float64)), axis=1)
        raw = alpha * m * m / inv
        zeros = jnp.sum(regs == 0, axis=1).astype(jnp.float64)
        lin = m * jnp.log(m / jnp.maximum(zeros, 1.0))
        est = jnp.where((raw <= 2.5 * m) & (zeros > 0), lin, raw)
        cnt = jnp.round(est).astype(jnp.int64)
        return [DeviceColumn(T.LONG, group_valid, data=cnt)]

    def _eval_percentile(self, a, fields, ctx, perm, seg, mask_sorted, cap,
                         group_valid, nseg) -> List[DeviceColumn]:
        """percentile (exact, interpolated) / approx_percentile (element at
        floor(p*(n-1)), exact while the group fits in one batch — the GK
        summary is uncompressed below the accuracy threshold, which is the
        same answer).  Single-phase COMPLETE (planned like collect_list)."""
        (f,) = fields
        pct = jnp.float64(0.5 if a.func == "median" else a.args[0])
        c = self._input_col(a, ctx, perm)
        valid = c.validity & mask_sorted
        # sort values within their (already sorted) segments; invalid last
        tier = (~valid).astype(jnp.int32)
        vkey = c.data.astype(jnp.int64) if not _is_float(c.dtype) else None
        if vkey is None:
            from spark_rapids_tpu.ops.sortkeys import _float_total_order

            f64 = c.data.astype(jnp.float64)
            bits = jax.lax.bitcast_convert_type(f64, jnp.int64)
            bits = jnp.where(jnp.isnan(f64),
                             jnp.int64(0x7FF8000000000000), bits)
            vkey = _float_total_order(bits)
        seg_key = jnp.where(mask_sorted, seg, nseg)
        _, _, _, sdata = jax.lax.sort(
            (seg_key.astype(jnp.int32), tier, vkey, c.data),
            dimension=0, num_keys=3, is_stable=True)
        nv = SEG.seg_count(valid, seg, nseg)
        starts = SEG.seg_first_index(seg, mask_sorted, nseg)
        has = nv > 0
        r = pct * (jnp.maximum(nv, 1) - 1).astype(jnp.float64)
        lo = jnp.floor(r).astype(jnp.int64)
        hi = jnp.ceil(r).astype(jnp.int64)
        frac = r - lo.astype(jnp.float64)
        gi_lo = jnp.clip(starts + lo, 0, cap - 1)
        gi_hi = jnp.clip(starts + hi, 0, cap - 1)
        v_lo = sdata[gi_lo]
        v_hi = sdata[gi_hi]
        validity = group_valid & has
        if a.func == "approx_percentile":
            return [DeviceColumn(f.dataType, validity,
                                 data=v_lo.astype(T.storage_dtype(
                                     f.dataType)))]
        scale = (jnp.float64(10.0 ** -c.dtype.scale)
                 if isinstance(c.dtype, T.DecimalType) else jnp.float64(1.0))
        res = (v_lo.astype(jnp.float64) * (1.0 - frac)
               + v_hi.astype(jnp.float64) * frac) * scale
        return [DeviceColumn(T.DOUBLE, validity, data=res)]

    def _eval_bloom(self, a, fields, ctx, perm, seg, mask_sorted, cap,
                    group_valid, nseg) -> List[DeviceColumn]:
        """bloom_filter_agg — the GpuBloomFilterAggregate analog.

        Layout: array<long> of num_bits/64 words (double hashing with
        xxhash64 seeds 42 and 77; NOT byte-compatible with Spark's sketch
        serialization — probed by BloomFilterMightContain with the same
        parameters)."""
        import math as _math

        from spark_rapids_tpu.ops.hashing import xxhash64_column

        (f,) = fields
        num_items, num_bits = int(a.args[0]), int(a.args[1])
        words = num_bits // 64
        k = max(1, round(num_bits / num_items * _math.log(2)))
        c = self._input_col(a, ctx, perm)
        valid = c.validity & mask_sorted
        h1 = xxhash64_column(c, jnp.full(cap, jnp.uint64(42))).view(jnp.int64)
        h2 = xxhash64_column(c, jnp.full(cap, jnp.uint64(77))).view(jnp.int64)
        bits = jnp.zeros((nseg, num_bits), jnp.bool_)
        seg_safe = jnp.where(valid, seg, nseg)
        for j in range(k):
            bit = jnp.remainder(h1 + j * h2, num_bits)
            bits = bits.at[seg_safe, bit].set(True, mode="drop")
        packed = bits.reshape(nseg, words, 64)
        weights = jnp.left_shift(jnp.int64(1), jnp.arange(64, dtype=jnp.int64))
        data = jnp.sum(packed.astype(jnp.int64) * weights[None, None, :],
                       axis=2)
        lengths = jnp.full(nseg, words, jnp.int32)
        ev = jnp.ones((nseg, words), jnp.bool_)
        return [DeviceColumn(f.dataType, group_valid, data=data,
                             lengths=lengths, elem_valid=ev)]

    def _eval_collect(self, a, f, c: DeviceColumn, validity, seg,
                      mask_sorted, cap, group_valid, nseg) -> DeviceColumn:
        """collect_list / collect_set into a padded list column.

        Reference analog: GpuCollectList/GpuCollectSet (SURVEY.md §2.4).
        Nulls are skipped (Spark).  collect_list keeps input order (rows
        are key-sorted STABLY, so within-group order is arrival order);
        collect_set emits values ASCENDING (Spark's set order is
        unspecified; the oracle sorts the same way so differential tests
        are deterministic)."""
        ew = self._collect_ewidth
        if a.func == "collect_set":
            # second sort by (segment, value words) + first-of-run mask
            words = _column_key_words(c)
            keyseq = [seg.astype(jnp.int64),
                      (~validity).astype(jnp.int64)] + \
                     [jnp.where(validity, w, 0) for w in words]
            iota = jnp.arange(cap, dtype=jnp.int32)
            perm2 = jax.lax.sort(tuple(keyseq) + (iota,),
                                 num_keys=len(keyseq), is_stable=True)[-1]
            seg = seg[perm2]
            validity = validity[perm2]
            c = _gather_col(c, perm2)
            words2 = [w[perm2] for w in keyseq[2:]]
            same = jnp.ones(cap, jnp.bool_)
            for w in words2:
                prev = jnp.concatenate([w[:1] - 1, w[:-1]])
                same = same & (w == prev)
            same_seg = jnp.concatenate(
                [jnp.zeros(1, jnp.bool_), seg[1:] == seg[:-1]])
            validity = validity & ~(same & same_seg)
        # within-group rank among VALID rows; for the global (nseg==1)
        # case seg may be unsorted (fused-filter mask), so scan globally
        if nseg == 1:
            starts = jnp.zeros(cap, jnp.bool_).at[0].set(True)
        else:
            starts = jnp.concatenate([jnp.ones(1, jnp.bool_),
                                      seg[1:] != seg[:-1]])
        rank = (SEG.seg_scan_sum(jnp.ones(cap, jnp.int64), validity,
                                 starts)[1] - 1).astype(jnp.int32)
        elem_dt = f.dataType.elementType
        seg_out = seg if nseg != 1 else jnp.zeros(cap, jnp.int32)
        flat_idx = jnp.where(validity & (rank < ew),
                             seg_out.astype(jnp.int64) * ew + rank,
                             cap * ew).astype(jnp.int64)
        sdt = T.storage_dtype(elem_dt)
        data = jnp.zeros(cap * ew, sdt).at[flat_idx].set(
            c.data.astype(sdt), mode="drop")
        ev = jnp.zeros(cap * ew, jnp.bool_).at[flat_idx].set(
            True, mode="drop")
        lengths = jnp.clip(SEG.seg_count(validity, seg, nseg), 0, ew)
        out_rows = int(lengths.shape[0])
        return DeviceColumn(
            f.dataType, group_valid,
            data=data.reshape(cap, ew)[:out_rows],
            lengths=lengths.astype(jnp.int32),
            elem_valid=ev.reshape(cap, ew)[:out_rows])

    def _minmax_string(self, c: DeviceColumn, func, seg, validity, cap,
                       group_valid, f, nseg):
        """min/max on strings: argmin over packed key words per segment."""
        words = _column_key_words(c)
        # build a composite: use first word as primary ordering; resolve ties
        # via iterative refinement is complex — instead sort-based: rows are
        # already sorted by GROUP key, not value; do an argmin via two-pass
        # lexicographic reduction over words.
        n = c.capacity
        best = jnp.arange(n, dtype=jnp.int32)
        # iterative: compute rank by sorting (value words, index) within seg
        # null rows must sort after every valid row: a value-word sentinel
        # can collide with real key words, so nullness is its own sort key
        keyseq = [seg.astype(jnp.int64), (~validity).astype(jnp.int64)]
        for w in words:
            keyseq.append(w if func == "min" else ~w)
        perm2 = jax.lax.sort(tuple(keyseq) + (best,),
                             num_keys=len(keyseq), is_stable=True)[-1]
        # after sort by (seg, value): first row of each seg = min (or max)
        seg_sorted = seg[perm2]
        first = SEG.seg_first_index(seg_sorted, jnp.ones(n, jnp.bool_), nseg)
        take = perm2[jnp.clip(first, 0, n - 1)]
        g = _gather_col(c, take)
        has = jax.ops.segment_sum(validity.astype(jnp.int32), seg,
                                  num_segments=nseg) > 0
        return DeviceColumn(f.dataType, group_valid & has & g.validity,
                            chars=g.chars, lengths=g.lengths)

    # -- global (no grouping keys) -------------------------------------
    def _global_agg(self, ctx, batch, mask=None):
        """No grouping keys: a single-segment reduction (XLA lowers this to
        a plain tree-reduce; no sort, no scatter)."""
        if mask is None:
            mask = batch.row_mask
        perm = None  # no sort needed for a single segment
        seg = jnp.where(mask, 0, 1).astype(jnp.int32)  # padding dropped
        group_valid = jnp.ones(1, jnp.bool_)
        out_cols: List[DeviceColumn] = []
        for a, f in zip(self.aggregates, self._agg_fields()):
            out_cols.extend(self._eval_agg(a, f, ctx, perm, seg, mask,
                                           batch.capacity, group_valid,
                                           nseg=1))
        return tuple(out_cols), jnp.int32(1)


def _sum_input(c: DeviceColumn, out_dtype):
    if _is_float(c.dtype) or (out_dtype is not None and _is_float(out_dtype)):
        return c.data.astype(jnp.float64)
    return c.data.astype(jnp.int64)


def _sum_dec128(c: DeviceColumn, validity, seg, nseg, group_valid,
                dt: T.DecimalType) -> DeviceColumn:
    """sum over a decimal column into a >18-digit result: exact 128-bit limb
    sums; overflow past 10^precision yields NULL (Spark nullOnOverflow).

    Reference analog: GpuSum's DECIMAL128 buffer (GpuAggregateExec.scala) +
    decimal_utils.cu overflow checks."""
    from spark_rapids_tpu.expr import decimal128 as D

    hi, lo = D.column_limbs(c)
    ok, has, sh, sl = D.sum128_segments(hi, lo, validity, seg, nseg)
    ok = ok & D.in_bounds(sh, sl, dt.precision)
    data = D.pack(sh, sl) if dt.is_128 else sl
    return DeviceColumn(dt, group_valid & has & ok, data=data)


def _avg_div_dec128(scol: DeviceColumn, n, in_scale: int,
                    dt: T.DecimalType, group_valid) -> DeviceColumn:
    """Finalize decimal avg from a 128-bit sum buffer: sum/count with
    HALF_UP at the result scale (Spark Average.evaluateExpression).

    Exact integer path: q, r = divmod(|sum|, count); result =
    q*10^shift + round_half_up(r*10^shift / count).  The remainder term
    stays under 2^31 * 10^4 so it fits int64.  The long division's divisor
    contract is d < 2^31; FINAL-mode merged counts could exceed it, so such
    groups yield NULL rather than a silently wrong quotient."""
    from spark_rapids_tpu.expr import decimal128 as D

    sh, sl = D.column_limbs(scol)
    nz = n > 0
    n_ok = n < jnp.int64(2 ** 31)
    d = jnp.where(nz & n_ok, n, 1)
    neg = D.is_neg(sh, sl)
    uh, ul = D.abs128(sh, sl)
    qh, ql, rem = D.udivmod128_by_u32(uh, ul, d)
    shift = dt.scale - in_scale            # in [0, 4]
    over, qh, ql = D.mul128_pow10(qh, ql, shift)
    p10 = 10 ** max(shift, 0)
    num = rem * p10
    eq = num // d
    er = num - eq * d
    eq = eq + ((2 * er) >= d).astype(jnp.int64)
    qh, ql = D.add128(qh, ql, *D.from64(eq))
    ok = D.in_bounds(qh, ql, dt.precision) & ~over
    rh, rl = D.neg128(qh, ql)
    hi = jnp.where(neg, rh, qh)
    lo = jnp.where(neg, rl, ql)
    data = D.pack(hi, lo) if dt.is_128 else lo
    return DeviceColumn(dt, group_valid & nz & n_ok & ok & scol.validity,
                        data=data)


def _minmax_dec128(c: DeviceColumn, func, seg, validity, nseg,
                   group_valid, f) -> DeviceColumn:
    """min/max on decimal128: lexicographic two-word reduction.

    First reduce the high word; then reduce the low word among rows whose
    high word hit the optimum — two segment_min passes, no sort."""
    from spark_rapids_tpu.expr import decimal128 as D

    hi, lo = D.unpack(c.data)
    kh, kl = D.key_words(hi, lo)
    if func == "max":
        kh, kl = ~kh, ~kl
    big = jnp.int64(9223372036854775807)
    kh_m = jnp.where(validity, kh, big)
    mh = SEG._seg_min_raw(kh_m, seg, nseg)
    tie = validity & (kh_m == (mh[seg] if nseg > 1 else mh[0]))
    kl_m = jnp.where(tie, kl, big)
    ml = SEG._seg_min_raw(kl_m, seg, nseg)
    has = SEG._seg_isum(validity.astype(jnp.int32), seg, nseg) > 0
    if func == "max":
        mh, ml = ~mh, ~ml
    out_hi = mh
    out_lo = ml ^ jnp.int64(-0x8000000000000000)
    return DeviceColumn(f.dataType, group_valid & has,
                        data=D.pack(out_hi, out_lo))


def _chan_merge(cn: DeviceColumn, ca: DeviceColumn, cm: DeviceColumn,
                mask_sorted, seg, nseg):
    """Chan's parallel merge of (n, avg, m2) buffer rows per segment.

    -> (ntot, nonzero_mask, mean, m2) per group."""
    valid = cn.validity & mask_sorted & (cn.data > 0)
    n_r = jnp.where(valid, cn.data, 0.0)
    ntot, _ = SEG.seg_sum(n_r, valid, seg, nseg)
    wsum, _ = SEG.seg_sum(n_r * jnp.where(valid, ca.data, 0.0),
                          valid, seg, nseg)
    nz = ntot > 0
    mean = wsum / jnp.where(nz, ntot, 1.0)
    d = jnp.where(valid, ca.data, 0.0) - mean[seg]
    m2, _ = SEG.seg_sum(jnp.where(valid, cm.data + n_r * d * d, 0.0),
                        valid, seg, nseg)
    return ntot, nz, mean, m2


def _merge_moment_bufs(cs, mask_sorted, seg, nseg):
    """Merge (n, avg, m2, m3[, m4]) buffer columns per segment using the
    order-independent closed forms (Pébay's formulas reduced to segmented
    sums).  -> (ntot, nz, mean, m2, m3[, m4])."""
    cn, ca, cm2, cm3 = cs[:4]
    cm4 = cs[4] if len(cs) > 4 else None
    ok = cn.validity & mask_sorted
    ni = jnp.where(ok, cn.data, 0.0)
    ntot, _ = SEG.seg_sum(ni, ok, seg, nseg)
    nz = ntot > 0
    s, _ = SEG.seg_sum(ni * jnp.where(ok, ca.data, 0.0), ok, seg, nseg)
    mean = s / jnp.where(nz, ntot, 1.0)
    d = jnp.where(ok, ca.data - mean[seg], 0.0)
    m2i = jnp.where(ok, cm2.data, 0.0)
    m3i = jnp.where(ok, cm3.data, 0.0)
    m2, _ = SEG.seg_sum(m2i + ni * d * d, ok, seg, nseg)
    m3, _ = SEG.seg_sum(m3i + 3.0 * m2i * d + ni * d ** 3, ok, seg, nseg)
    if cm4 is None:
        return ntot, nz, mean, m2, m3
    m4i = jnp.where(ok, cm4.data, 0.0)
    m4, _ = SEG.seg_sum(
        m4i + 4.0 * m3i * d + 6.0 * m2i * d * d + ni * d ** 4, ok, seg,
        nseg)
    return ntot, nz, mean, m2, m3, m4


def _merge_cov_bufs(cs, mask_sorted, seg, nseg):
    """Merge (n, xavg, yavg, ck[, xm2, ym2]) covariance buffers per
    segment. -> (ntot, nz, xavg, yavg, ck[, xm2, ym2])."""
    cn, cx, cy, cc = cs[:4]
    ok = cn.validity & mask_sorted
    ni = jnp.where(ok, cn.data, 0.0)
    ntot, _ = SEG.seg_sum(ni, ok, seg, nseg)
    nz = ntot > 0
    sx, _ = SEG.seg_sum(ni * jnp.where(ok, cx.data, 0.0), ok, seg, nseg)
    sy, _ = SEG.seg_sum(ni * jnp.where(ok, cy.data, 0.0), ok, seg, nseg)
    xavg = sx / jnp.where(nz, ntot, 1.0)
    yavg = sy / jnp.where(nz, ntot, 1.0)
    dx = jnp.where(ok, cx.data - xavg[seg], 0.0)
    dy = jnp.where(ok, cy.data - yavg[seg], 0.0)
    cki = jnp.where(ok, cc.data, 0.0)
    ck, _ = SEG.seg_sum(cki + ni * dx * dy, ok, seg, nseg)
    if len(cs) <= 4:
        return ntot, nz, xavg, yavg, ck
    xm2, _ = SEG.seg_sum(jnp.where(ok, cs[4].data, 0.0) + ni * dx * dx,
                         ok, seg, nseg)
    ym2, _ = SEG.seg_sum(jnp.where(ok, cs[5].data, 0.0) + ni * dy * dy,
                         ok, seg, nseg)
    return ntot, nz, xavg, yavg, ck, xm2, ym2


def _seg_last_index(seg, row_mask, num_segments):
    n = seg.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    v = jnp.where(row_mask, iota, -1)
    return jax.ops.segment_max(v, seg, num_segments=num_segments)


def _gather_col(c: DeviceColumn, idx) -> DeviceColumn:
    return c.gather(idx)


def _flat_sort_operands(c: DeviceColumn):
    """1-D operand arrays of a flat (or dec128 two-limb) column for key
    co-sorting; None when the column needs the gather path (strings,
    arrays, structs)."""
    if c.chars is not None or c.children is not None \
            or c.elem_valid is not None or c.data is None:
        return None
    if c.data.ndim == 1:
        return [c.data, c.validity]
    if c.data.ndim == 2 and c.data.shape[1] == 2:     # decimal128 limbs
        return [c.data[:, 0], c.data[:, 1], c.validity]
    return None


def _rebuild_flat_col(c: DeviceColumn, arrs) -> DeviceColumn:
    """Inverse of _flat_sort_operands over the sorted operand slices."""
    if len(arrs) == 2:
        return DeviceColumn(c.dtype, arrs[1], data=arrs[0])
    return DeviceColumn(c.dtype, arrs[2],
                        data=jnp.stack([arrs[0], arrs[1]], axis=1))
