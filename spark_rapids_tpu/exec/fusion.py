"""Whole-plan subtree fusion (ISSUE 17) — maximal pipeline-able chains
as ONE jitted program.

Reference analog: none — the reference accelerates per-operator kernels
and eats a ~10µs launch per edge; on a compile-tunnel TPU every program
launch is a host round trip, so the engine is launch/sync-bound
(BENCH: multi-program queries at 0.0005-0.01 eff_gbps next to 1.27 for
a single-program scan).  ``fuse_stages`` (exec/basic.py) already merges
adjacent project/filter stages and absorbs a stage into the aggregate
above it; this pass closes the remaining pipeline breaks — an Expand
between stages, a multi-projection Expand by itself — by compiling each
maximal chain of segment-capable operators into one XLA program routed
through the compilecache registry.

Eligibility is the intersection of three gates:

* the fusibility manifest (analysis/fusibility.py, committed at
  ``tools/fusibility_manifest.json``): only exec classes classified
  ``fusable`` or ``fusable-with-rewrite`` may join a chain —
  :data:`MANIFEST_ELIGIBLE` mirrors the committed manifest and
  tests/test_fusion_pipeline.py pins the two identical;
* segment capability: the exec provides :meth:`TpuExec.fusion_segment`
  (a traceable ``(cols, num_rows) -> (cols, num_rows, flags)`` piece);
* the cost model's boundary rule: the chain fuses through an edge only
  while ``profiling.model.predicted_intermediate_bytes`` for that edge
  stays within ``spark.rapids.tpu.fusion.maxIntermediateFraction`` of
  the HBM pool — a predicted-oversized intermediate splits the chain at
  the predicted boundary (exec/partition_sizing.py supplies the
  estimate ladder: static AOT rows, calibrated rows EWMA, capacity).

Docs: docs/whole_plan_fusion.md.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from spark_rapids_tpu import perfcounters as PC
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.expr.base import SparkArithmeticException
from spark_rapids_tpu.perfcounters import sync_get, tpu_jit

# Exec classes the committed fusibility manifest classifies ``fusable``
# or ``fusable-with-rewrite`` — the manifest half of the eligibility
# intersection.  tests/test_fusion_pipeline.py regenerates the manifest
# and pins this set to it exactly, so a reclassified exec cannot keep
# fusing (or stay excluded) silently.
MANIFEST_ELIGIBLE = frozenset({
    "TpuBroadcastNestedLoopJoinExec",
    "TpuExpandExec",
    "TpuFusedPipelineExec",
    "TpuGenerateExec",
    "TpuHashAggregateExec",
    "TpuIciShuffleAggExec",
    "TpuIciShuffleJoinExec",
    "TpuIciSortExec",
    "TpuIciWindowExec",
    "TpuJoinAggFusedExec",
    "TpuShuffleExchangeExec",
    "TpuSortExec",
    "TpuStageExec",
    "TpuWindowChainFusedExec",
    "TpuWindowExec",
    "_BaseTpuJoinExec",
})


def manifest_eligible(node: TpuExec) -> bool:
    """Manifest gate: some class in the exec's MRO is classified fusable
    / fusable-with-rewrite (subclasses run their base's kernels — the
    same inheritance rule ``build_manifest`` applies)."""
    return any(c.__name__ in MANIFEST_ELIGIBLE for c in type(node).__mro__)


@dataclasses.dataclass
class PipelineSegment:
    """One operator's traceable slice of a fused pipeline.

    ``make(in_schema)`` returns ``(fn, msgs_store)`` where ``fn(cols,
    num_rows) -> (cols, num_rows, flags)`` is pure traced compute over
    device columns and ``msgs_store`` is the ANSI error-message aux the
    trace fills — it travels WITH the fused executable as part of the
    registry entry's aux (the manifest's fusable-with-rewrite rewrite).

    ``fp`` is the segment's registry fingerprint parts (None → the fused
    program stays instance-private, never shared).  ``count_map`` maps
    the input batch's host row count to the output count when that is
    statically derivable (projections preserve it, expand multiplies
    it); None means data-dependent (filters) and the fused program must
    sync the count.  ``programs_unfused`` is how many programs the
    operator launches per input batch UNFUSED — the pass only installs
    a fused node when the chain saves launches."""

    name: str
    fp: Optional[tuple]
    make: Callable[[T.StructType], tuple]
    out_schema: T.StructType
    count_map: Optional[Callable[[int], int]] = None
    programs_unfused: int = 1


class TpuFusedPipelineExec(TpuExec):
    """A chain of pipeline segments compiled as ONE jitted program.

    ``describe()`` lists every constituent operator, so ``df.explain()``
    shows the fused subtree as a single node with constituent
    attribution, and the diagnostics operator span / progress pull for
    the fused node carries the same constituent list (recorder spans key
    on ``node_name``/``describe``)."""

    def __init__(self, segments: Sequence[PipelineSegment],
                 constituents: Sequence[str], child: TpuExec):
        super().__init__([child])
        self.segments = list(segments)      # bottom-up application order
        self.constituents = list(constituents)
        self._jitted = None

    @property
    def output(self) -> T.StructType:
        return self.segments[-1].out_schema

    @property
    def node_name(self) -> str:
        return "TpuFusedPipelineExec"

    def describe(self) -> str:
        return "TpuFusedPipeline[" + " -> ".join(self.constituents) + "]"

    # -- AOT shape propagation ---------------------------------------
    def aot_output_rows(self):
        rows = self.aot_input_rows()
        if rows is None:
            return None
        for seg in self.segments:
            if seg.count_map is None:
                return None
            rows = [seg.count_map(r) for r in rows]
        return rows

    def aot_emits_single_batch(self) -> bool:
        # one output batch per input batch (expand's variants concat
        # INSIDE the program), so batch count passes through
        return self.aot_child_single_batch()

    # -- program construction ----------------------------------------
    def _program(self, in_schema: T.StructType):
        """(registry key parts, factory) — shared by the runtime build
        and AOT enumeration so both land on the same entry."""
        from spark_rapids_tpu.compilecache.keys import conf_fp, schema_fp

        fps = [s.fp for s in self.segments]
        key_parts = None if any(f is None for f in fps) else (
            "fusedpipe", schema_fp(in_schema), tuple(fps), conf_fp())
        segments = self.segments

        def factory():
            fns, stores = [], []
            schema = in_schema
            for seg in segments:
                fn, store = seg.make(schema)
                fns.append(fn)
                stores.append(store)
                schema = seg.out_schema

            def fused(cols, num_rows):
                flags_all: tuple = ()
                for fn in fns:
                    cols, num_rows, flags = fn(cols, num_rows)
                    cols = tuple(cols)
                    flags_all = flags_all + tuple(flags)
                return cols, jnp.asarray(num_rows), flags_all

            return tpu_jit(fused), stores

        return key_parts, factory

    def aot_programs(self):
        from spark_rapids_tpu.compilecache.aot import (
            AotProgram,
            dummy_batch_args,
        )

        caps = self.aot_input_caps()
        if not caps:
            return []
        in_schema = self.children[0].output
        key_parts, factory = self._program(in_schema)
        if key_parts is None:
            return []

        def args_factory():
            return [dummy_batch_args(in_schema, c) for c in caps]

        return [AotProgram(key_parts, factory, args_factory,
                           f"fusedpipe:{self.describe()[:44]}")]

    def _build(self, in_schema: T.StructType):
        from spark_rapids_tpu.compilecache.registry import cached_program

        key_parts, factory = self._program(in_schema)
        entry = cached_program(key_parts, factory, label=self.describe())
        jitted, stores = entry.jitted, entry.aux
        static_maps = [s.count_map for s in self.segments]
        count_static = all(m is not None for m in static_maps)
        out_schema = self.output

        def run(batch: ColumnarBatch) -> ColumnarBatch:
            cols, count, flags = jitted(
                tuple(batch.columns), jnp.int32(batch.num_rows))
            if flags or not count_static:
                # count + every ANSI flag in ONE logical round trip —
                # the whole chain's only host sync
                host = sync_get((count,) + tuple(flags))
                msgs = [m for store in stores for m in store]
                for f, m in zip(host[1:], msgs):
                    if f:
                        raise SparkArithmeticException(m)
                n = int(host[0])
            else:
                # every segment's count is host-derivable: zero syncs
                n = batch.num_rows
                for m in static_maps:
                    n = m(n)
            return ColumnarBatch(list(cols), n, out_schema)

        return run

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        child = self.children[0]
        for batch in child.execute_columnar():
            if self._jitted is None:
                self._jitted = self._build(batch.schema)
            with self.metrics["opTime"].timed():
                out = self._jitted(batch)
            yield self._count_output(out)


# ---------------------------------------------------------------------
# the plan-time fusion pass
# ---------------------------------------------------------------------

def fusion_budget_bytes(conf) -> int:
    """The HBM budget a fused chain's predicted intermediates must stay
    within: pool * fusion.maxIntermediateFraction."""
    from spark_rapids_tpu.config import FUSION_MAX_INTERMEDIATE_FRACTION
    from spark_rapids_tpu.memory.device_manager import get_device_manager

    pool = get_device_manager().pool_bytes
    frac = float(conf.get(FUSION_MAX_INTERMEDIATE_FRACTION))
    return max(int(pool * frac), 1 << 16)


def _segment_of(node) -> Optional[PipelineSegment]:
    """The node's pipeline segment when ALL eligibility gates short of
    the cost model pass: single child, manifest-eligible class, and a
    non-None fusion_segment."""
    if not (isinstance(node, TpuExec) and len(node.children) == 1):
        return None
    if not manifest_eligible(node):
        return None
    fn = getattr(node, "fusion_segment", None)
    if fn is None:
        return None
    return fn()


def _build_fused(chain: List[Tuple[TpuExec, PipelineSegment]],
                 child, conf) -> TpuExec:
    """Split a top-down chain at predicted-oversized edges, then install
    one TpuFusedPipelineExec per group that saves launches."""
    from spark_rapids_tpu.overrides.transitions import _record
    from spark_rapids_tpu.profiling.model import (
        predicted_intermediate_bytes,
    )

    budget = fusion_budget_bytes(conf)
    bottom_up = list(reversed(chain))
    groups: List[List[Tuple[TpuExec, PipelineSegment]]] = [[bottom_up[0]]]
    for lower, upper in zip(bottom_up, bottom_up[1:]):
        est = predicted_intermediate_bytes(lower[0], conf)
        if est is not None and est > budget:
            _record("TpuFusedPipelineExec", False,
                    f"predicted intermediate {est}B above {lower[0].node_name} "
                    f"exceeds fusion budget {budget}B — chain split at the "
                    "predicted boundary")
            groups.append([upper])
        else:
            groups[-1].append(upper)

    out = child
    for group in groups:          # bottom-most group first
        launches = sum(seg.programs_unfused for _, seg in group)
        if launches >= 2:
            fused = TpuFusedPipelineExec(
                [seg for _, seg in group],
                [ex.describe() for ex, _ in group], out)
            _record("TpuFusedPipelineExec", True)
            PC.bump("subtrees_fused")
            out = fused
        else:
            # a lone single-program stage gains nothing from the fused
            # wrapper; keep the original exec (rewired onto the chain)
            for ex, _ in group:       # group is a single member here
                ex.children = [out]
                out = ex
    return out


def fuse_pipelines(root: TpuExec, conf) -> TpuExec:
    """The pass: walk the exec tree, collapse every maximal eligible
    chain (TpuTransitionOverrides.apply, after the specialized join-agg
    / window-chain fusions so they keep first claim)."""
    from spark_rapids_tpu.config import FUSION_ENABLED
    from spark_rapids_tpu.overrides.transitions import _record

    enabled = conf.get(FUSION_ENABLED)

    def rewrite(node):
        if not isinstance(node, TpuExec):
            return node
        seg = _segment_of(node)
        if seg is not None:
            chain = [(node, seg)]
            cur = node.children[0]
            while True:
                s = _segment_of(cur)
                if s is None:
                    break
                chain.append((cur, s))
                cur = cur.children[0]
            below = rewrite(cur)
            if sum(s.programs_unfused for _, s in chain) >= 2:
                if enabled:
                    return _build_fused(chain, below, conf)
                _record("TpuFusedPipelineExec", False,
                        f"{FUSION_ENABLED.key} is false")
            # nothing to fuse (or disabled): rewire the chain unchanged
            for ex, _ in reversed(chain):
                ex.children = [below]
                below = ex
            return below
        node.children = [rewrite(c) for c in node.children]
        return node

    return rewrite(root)
