"""TpuExec — base of all TPU operators.

Reference analog: the GpuExec trait (SURVEY.md §1 L4):
``internalDoExecuteColumnar(): RDD[ColumnarBatch]`` plus GpuMetrics.  Here an
operator yields an iterator of device ColumnarBatches; device work happens in
jit-compiled stage functions cached per shape bucket (see basic.py), so the
per-batch Python cost is one dispatch.

Metrics mirror the reference's standard names (GpuMetric / GpuTaskMetrics):
opTime, numOutputRows, numOutputBatches, sortTime, joinTime, concatTime,
semaphoreWaitTime, spillTime, retryCount — surfaced via .metrics and the
explain output.
Tracing (SURVEY.md §5.1): with ``spark.rapids.profile.enabled`` every
operator's batch iteration is wrapped in a ``jax.profiler.TraceAnnotation``
named after the operator — the NVTX-range analog, visible in XProf /
Perfetto captures via ``jax.profiler.trace``.
"""
from __future__ import annotations

import time
from typing import Dict, Iterator, List, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config import METRICS_LEVEL, get_conf


class TpuMetric:
    ESSENTIAL = "ESSENTIAL"
    MODERATE = "MODERATE"
    DEBUG = "DEBUG"

    def __init__(self, name: str, level: str = "MODERATE"):
        self.name = name
        self.level = level
        self.value = 0

    def add(self, v):
        self.value += v

    def __iadd__(self, v):
        self.value += v
        return self

    class _Timer:
        def __init__(self, metric):
            self.metric = metric

        def __enter__(self):
            self.t0 = time.perf_counter_ns()
            return self

        def __exit__(self, *a):
            self.metric.value += time.perf_counter_ns() - self.t0

    def timed(self):
        return TpuMetric._Timer(self)


def enable_operator_tracing(root: "TpuExec", on: bool = True) -> None:
    """Mark an exec tree for jax.profiler TraceAnnotations (driven by
    spark.rapids.profile.enabled; scoped per plan, not process-global, so
    concurrent sessions with different settings do not interfere)."""
    root._trace_on = on
    for c in root.children:
        if isinstance(c, TpuExec):
            enable_operator_tracing(c, on)


class _SchemaOnlyExec:
    """Stand-in child inside a detached trace clone (detached_for_trace):
    registry-shared stage functions only ever read ``.output`` from their
    children at trace time."""

    __slots__ = ("_schema",)

    def __init__(self, schema):
        self._schema = schema

    @property
    def output(self):
        return self._schema


class TpuExec:
    """Base TPU operator; children may be TpuExec or transition nodes.

    Metric registration mirrors the reference's GpuExec pattern: the
    three standard metrics register here with their reference levels
    (numOutputRows ESSENTIAL; opTime / numOutputBatches MODERATE), and a
    subclass declares its operator-specific metrics up front via
    ``EXTRA_METRICS`` (name -> level) — so the diagnostics layer and
    ``explain("analyze")`` can filter on ``spark.rapids.sql.metrics.
    level`` without guessing.  ``metric()`` still creates undeclared
    names on the fly (at DEBUG level, the reference's default for ad-hoc
    metrics)."""

    EXTRA_METRICS: Dict[str, str] = {}

    def __init__(self, children: Sequence["TpuExec"]):
        self.children: List[TpuExec] = list(children)
        self.metrics: Dict[str, TpuMetric] = {}
        self.metrics["numOutputRows"] = TpuMetric(
            "numOutputRows", TpuMetric.ESSENTIAL)
        for m in ("opTime", "numOutputBatches"):
            self.metrics[m] = TpuMetric(m, TpuMetric.MODERATE)
        for m, level in self.EXTRA_METRICS.items():
            self.metrics[m] = TpuMetric(m, level)

    # ad-hoc metrics created by the fault domain record operator-level
    # failures — ESSENTIAL like the resilience events themselves, so
    # explain("analyze") at the default level never hides a retry/fallback
    _ADHOC_METRIC_LEVELS = {
        "transientRetries": TpuMetric.ESSENTIAL,
        "retryCount": TpuMetric.ESSENTIAL,
        "runtimeFallbacks": TpuMetric.ESSENTIAL,
        "breakerTrips": TpuMetric.ESSENTIAL,
        # I/O fault domain (ISSUE 5): skipped files and per-file device
        # ->native decoder retries are resilience events too
        "filesSkipped": TpuMetric.ESSENTIAL,
        "fileDecoderFallbacks": TpuMetric.ESSENTIAL,
    }

    def metric(self, name: str) -> TpuMetric:
        if name not in self.metrics:
            self.metrics[name] = TpuMetric(
                name, self._ADHOC_METRIC_LEVELS.get(name, TpuMetric.DEBUG))
        return self.metrics[name]

    @property
    def output(self) -> T.StructType:
        raise NotImplementedError

    @property
    def node_name(self) -> str:
        return type(self).__name__

    def describe(self) -> str:
        return self.node_name

    def pretty(self, indent: int = 0) -> str:
        s = "  " * indent + self.describe()
        for c in self.children:
            s += "\n" + c.pretty(indent + 1)
        return s

    def metrics_report(self, indent: int = 0) -> str:
        """Per-operator metric rollup after execution — the Spark SQL UI
        metrics surface (GpuMetric / GpuTaskMetrics analog, SURVEY §5.5).
        Time metrics render in ms; zero-valued metrics are elided."""
        parts = []
        for name, m in sorted(self.metrics.items()):
            if not m.value:
                continue
            if name.endswith(("Time", "time")):
                parts.append(f"{name}={m.value / 1e6:.1f}ms")
            else:
                parts.append(f"{name}={m.value}")
        s = "  " * indent + self.describe()
        if parts:
            s += "  [" + ", ".join(parts) + "]"
        for c in self.children:
            if hasattr(c, "metrics_report"):
                s += "\n" + c.metrics_report(indent + 1)
        return s

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        """Yield device batches; implemented by subclasses."""
        raise NotImplementedError(self.node_name)

    def detached_for_trace(self) -> "TpuExec":
        """A shallow clone safe to capture in a registry-shared jit
        closure.  The process-global program registry keeps entries alive
        across queries; a bound-method closure over ``self`` would pin
        the whole exec subtree — scan host columns, plan-node twins,
        device caches — for as long as the entry lives.  The clone keeps
        only the semantic fields the trace reads; children become schema
        stubs and every cache/plan back-reference is dropped."""
        import copy

        clone = copy.copy(self)
        clone.children = [_SchemaOnlyExec(c.output) for c in self.children]
        clone.metrics = {}
        # sweep cache/back-reference attrs by convention so a subclass
        # adding a new per-instance cache cannot silently re-introduce
        # the leak; plus the known non-conforming names
        drop = {"_origin_plan", "_aot_submission", "_twin_cache",
                "_reg_scope", "_device_cache", "_slot"}
        for name in list(clone.__dict__):
            if name in drop or name.endswith(
                    ("_jit", "_jits", "_jitted", "_jit_cache", "_cache")):
                clone.__dict__.pop(name, None)
        return clone

    # -- plan-time AOT compilation (compilecache/aot.py) ----------------
    def aot_output_rows(self):
        """Per-batch row counts this operator will emit, when derivable
        from the plan alone (local/range scans and the narrow operators
        above them); None when data-dependent (exchange partitions,
        aggregate groups, join pair counts...).  Drives shape-bucket
        prediction for the AOT pipeline."""
        return None

    def aot_output_caps(self):
        """Predicted output batch CAPACITIES (shape buckets) — what
        programs actually specialize on.  Default: derived from the row
        estimate; operators whose output capacity is predictable even
        when row counts are not (aggregates under a groups cap) override
        this directly."""
        rows = self.aot_output_rows()
        if rows is None:
            return None
        from spark_rapids_tpu.compilecache.aot import bucket_of

        return sorted({bucket_of(r) for r in rows})

    def aot_emits_single_batch(self) -> bool:
        """True when this operator emits exactly one batch regardless of
        input batching (concat-style operators, non-partial aggregates) —
        lets a concat consumer above trust aot_output_caps even without a
        row estimate."""
        return False

    def aot_input_rows(self):
        """First child's static row estimate (the common input shape)."""
        if not self.children:
            return None
        child = self.children[0]
        fn = getattr(child, "aot_output_rows", None)
        return fn() if fn is not None else None

    def aot_input_caps(self):
        """Capacities of the batches the first child will emit — for
        PER-BATCH consumers (stage/aggregate programs run once per input
        batch, so any batch count works)."""
        if not self.children:
            return None
        fn = getattr(self.children[0], "aot_output_caps", None)
        return fn() if fn is not None else None

    def aot_input_concat_caps(self):
        """Capacity of the CONCATENATION of the first child's batches —
        for concat consumers (sort/window); see compilecache.aot
        concat_caps for the rule."""
        if not self.children:
            return None
        from spark_rapids_tpu.compilecache.aot import concat_caps

        return concat_caps(self.children[0])

    def aot_child_single_batch(self) -> bool:
        """True when the first child is known to emit exactly one batch."""
        rows = self.aot_input_rows()
        if rows is not None:
            return len(rows) == 1
        if not self.children:
            return False
        single = getattr(self.children[0], "aot_emits_single_batch", None)
        return bool(single()) if single is not None else False

    def aot_programs(self):
        """The (stage function x shape-bucket) programs this operator
        will need, as compilecache.aot.AotProgram items; default: none
        enumerable.  Implementations MUST derive key parts and factories
        from the same helpers the runtime path uses, so an AOT-compiled
        entry is exactly the one the first batch looks up."""
        return []

    def fusion_segment(self):
        """This operator's traceable pipeline slice for whole-plan
        fusion (exec/fusion.PipelineSegment), or None when it cannot be
        inlined into a larger traced region.  Only implemented by execs
        the fusibility manifest classifies fusable / fusable-with-
        rewrite (the pass checks both)."""
        return None

    def _count_output(self, b: ColumnarBatch) -> ColumnarBatch:
        self.metrics["numOutputRows"] += b.num_rows
        self.metrics["numOutputBatches"] += 1
        return b

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        # install the unified operator runtime (exec/runtime.py): ONE
        # batch loop dispatching every registered per-batch concern —
        # cancel, governor, progress, diagnostics, fault domain, trace —
        # in the order the runtime's CONCERNS registry pins (ISSUE 17;
        # previously a six-deep wrapper stack built here)
        if "execute_columnar" in cls.__dict__:
            from spark_rapids_tpu.exec.runtime import make_operator_runtime

            cls.execute_columnar = make_operator_runtime(
                cls.execute_columnar)

    def collect_metrics(self, into=None) -> Dict[str, int]:
        into = into if into is not None else {}
        for m in self.metrics.values():
            into[f"{self.node_name}.{m.name}"] = (
                into.get(f"{self.node_name}.{m.name}", 0) + m.value)
        for c in self.children:
            if isinstance(c, TpuExec):
                c.collect_metrics(into)
        return into
