"""TpuExec — base of all TPU operators.

Reference analog: the GpuExec trait (SURVEY.md §1 L4):
``internalDoExecuteColumnar(): RDD[ColumnarBatch]`` plus GpuMetrics.  Here an
operator yields an iterator of device ColumnarBatches; device work happens in
jit-compiled stage functions cached per shape bucket (see basic.py), so the
per-batch Python cost is one dispatch.

Metrics mirror the reference's standard names (GpuMetric / GpuTaskMetrics):
opTime, numOutputRows, numOutputBatches, sortTime, joinTime, concatTime,
semaphoreWaitTime, spillTime, retryCount — surfaced via .metrics and the
explain output.
Tracing (SURVEY.md §5.1): with ``spark.rapids.profile.enabled`` every
operator's batch iteration is wrapped in a ``jax.profiler.TraceAnnotation``
named after the operator — the NVTX-range analog, visible in XProf /
Perfetto captures via ``jax.profiler.trace``.
"""
from __future__ import annotations

import time
from typing import Dict, Iterator, List, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.config import METRICS_LEVEL, get_conf


class TpuMetric:
    ESSENTIAL = "ESSENTIAL"
    MODERATE = "MODERATE"
    DEBUG = "DEBUG"

    def __init__(self, name: str, level: str = "MODERATE"):
        self.name = name
        self.level = level
        self.value = 0

    def add(self, v):
        self.value += v

    def __iadd__(self, v):
        self.value += v
        return self

    class _Timer:
        def __init__(self, metric):
            self.metric = metric

        def __enter__(self):
            self.t0 = time.perf_counter_ns()
            return self

        def __exit__(self, *a):
            self.metric.value += time.perf_counter_ns() - self.t0

    def timed(self):
        return TpuMetric._Timer(self)


def enable_operator_tracing(root: "TpuExec", on: bool = True) -> None:
    """Mark an exec tree for jax.profiler TraceAnnotations (driven by
    spark.rapids.profile.enabled; scoped per plan, not process-global, so
    concurrent sessions with different settings do not interfere)."""
    root._trace_on = on
    for c in root.children:
        if isinstance(c, TpuExec):
            enable_operator_tracing(c, on)


def _traced(fn):
    import functools

    @functools.wraps(fn)
    def wrapper(self, *a, **kw):
        if not getattr(self, "_trace_on", False):
            yield from fn(self, *a, **kw)
            return
        import jax.profiler

        it = fn(self, *a, **kw)
        name = self.node_name
        while True:
            with jax.profiler.TraceAnnotation(name):
                try:
                    b = next(it)
                except StopIteration:
                    return
            yield b

    return wrapper


def _fault_domain(fn):
    """Wrap an operator's batch iterator in the stage-level fault domain
    (resilience/domain.py): failure classification, bounded transient /
    OOM restarts, runtime CPU fallback, circuit-breaker recording, and the
    chaos-injection hooks.  The reference's RmmRapidsRetryIterator analog,
    generalized past OOM."""
    import functools

    @functools.wraps(fn)
    def wrapper(self, *a, **kw):
        from spark_rapids_tpu.resilience.domain import run_fault_domain

        yield from run_fault_domain(self, fn, a, kw)

    return wrapper


class TpuExec:
    """Base TPU operator; children may be TpuExec or transition nodes."""

    def __init__(self, children: Sequence["TpuExec"]):
        self.children: List[TpuExec] = list(children)
        self.metrics: Dict[str, TpuMetric] = {}
        for m in ("opTime", "numOutputRows", "numOutputBatches"):
            self.metrics[m] = TpuMetric(m)

    def metric(self, name: str) -> TpuMetric:
        if name not in self.metrics:
            self.metrics[name] = TpuMetric(name)
        return self.metrics[name]

    @property
    def output(self) -> T.StructType:
        raise NotImplementedError

    @property
    def node_name(self) -> str:
        return type(self).__name__

    def describe(self) -> str:
        return self.node_name

    def pretty(self, indent: int = 0) -> str:
        s = "  " * indent + self.describe()
        for c in self.children:
            s += "\n" + c.pretty(indent + 1)
        return s

    def metrics_report(self, indent: int = 0) -> str:
        """Per-operator metric rollup after execution — the Spark SQL UI
        metrics surface (GpuMetric / GpuTaskMetrics analog, SURVEY §5.5).
        Time metrics render in ms; zero-valued metrics are elided."""
        parts = []
        for name, m in sorted(self.metrics.items()):
            if not m.value:
                continue
            if name.endswith(("Time", "time")):
                parts.append(f"{name}={m.value / 1e6:.1f}ms")
            else:
                parts.append(f"{name}={m.value}")
        s = "  " * indent + self.describe()
        if parts:
            s += "  [" + ", ".join(parts) + "]"
        for c in self.children:
            if hasattr(c, "metrics_report"):
                s += "\n" + c.metrics_report(indent + 1)
        return s

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        """Yield device batches; implemented by subclasses."""
        raise NotImplementedError(self.node_name)

    def _count_output(self, b: ColumnarBatch) -> ColumnarBatch:
        self.metrics["numOutputRows"] += b.num_rows
        self.metrics["numOutputBatches"] += 1
        return b

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        # wrap execute_columnar with per-operator trace annotations
        # (NvtxRange analog); zero overhead unless profiling is enabled
        # fault domain outermost: it must see failures escaping the whole
        # iteration, trace annotations included
        if "execute_columnar" in cls.__dict__:
            cls.execute_columnar = _fault_domain(
                _traced(cls.execute_columnar))

    def collect_metrics(self, into=None) -> Dict[str, int]:
        into = into if into is not None else {}
        for m in self.metrics.values():
            into[f"{self.node_name}.{m.name}"] = (
                into.get(f"{self.node_name}.{m.name}", 0) + m.value)
        for c in self.children:
            if isinstance(c, TpuExec):
                c.collect_metrics(into)
        return into
