"""TpuWindowExec — window functions via segmented scans.

Reference analog (SURVEY.md §2.4 Window): GpuWindowExec with three
strategies — running window (cumulative batch-streaming), double-pass
cached, and batched bounded-window.  TPU redesign folds all three into one
jitted program built on `lax.associative_scan` segmented scans:

  * rank/dense_rank/row_number/ntile/percent_rank/cume_dist: order-key
    change flags + segmented cumsums / peer-group reductions
  * running frames (UNBOUNDED PRECEDING..CURRENT ROW): segmented inclusive
    scans (sum/count/min/max/avg)
  * unbounded frames: segment totals broadcast back
  * bounded ROWS frames (a PRECEDING..b FOLLOWING, both finite): statically
    unrolled shifted combines masked at partition boundaries — the TPU
    counterpart of the reference's batched bounded-window kernel (window
    width is a plan-time constant; widths above _MAX_BOUNDED_WINDOW fall
    back at tag time)
  * lead/lag: shifted gathers with partition-boundary masking and literal
    defaults (strings included)

Rows are sorted by (partition keys, order keys), computed, and scattered
back to the original order through the inverse permutation, so output row
order matches the child (Spark's WindowExec contract).
"""
from __future__ import annotations

from typing import Iterator, List, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.expr.base import EvalContext, Expression
from spark_rapids_tpu.ops import segment as SEG
from spark_rapids_tpu.ops.sortkeys import SortSpec, _column_key_words, pack_sort_keys
from spark_rapids_tpu.plan.nodes import WindowFunction


class TpuWindowExec(TpuExec):
    def __init__(self, functions: List[WindowFunction],
                 partition_by: List[Expression],
                 order_by: List[Tuple[Expression, SortSpec]],
                 child: TpuExec, output_schema: T.StructType,
                 frame: str = "running", ansi: bool = False):
        super().__init__([child])
        self.functions = functions
        self.partition_by = partition_by
        self.order_by = order_by
        self._output = output_schema
        self.frame = frame
        self.ansi = ansi

    @property
    def output(self):
        return self._output

    def describe(self):
        fns = ", ".join(f.func for f in self.functions)
        return f"TpuWindow [{fns}] frame={self.frame}"

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        batches = list(self.children[0].execute_columnar())
        if not batches:
            return
        batch = (batches[0] if len(batches) == 1
                 else ColumnarBatch.concat(batches))
        with self.metrics["opTime"].timed():
            if getattr(self, "_jitted", None) is None:
                self._jitted = jax.jit(self._window_fn)
            cols = self._jitted(tuple(batch.columns),
                                jnp.int32(batch.num_rows))
            out = ColumnarBatch(list(cols), batch.num_rows, self._output)
        yield self._count_output(out)

    def _window_fn(self, cols, num_rows):
        schema = self.children[0].output
        batch = ColumnarBatch(list(cols), num_rows, schema)
        ctx = EvalContext(batch, ansi=self.ansi)
        cap = batch.capacity
        mask = batch.row_mask
        pcols = [e.eval_tpu(ctx) for e in self.partition_by]
        ocols = [e.eval_tpu(ctx) for e, _ in self.order_by]
        ospecs = [s for _, s in self.order_by]
        # sort by (partition, order)
        keys = pack_sort_keys(pcols, [SortSpec()] * len(pcols), mask) if pcols \
            else []
        keys += pack_sort_keys(ocols, ospecs, mask)
        iota = jnp.arange(cap, dtype=jnp.int32)
        if keys:
            perm = jax.lax.sort(tuple(keys) + (iota,), num_keys=len(keys),
                                is_stable=True)[-1]
        else:
            perm = iota
        inv_perm = jnp.zeros(cap, jnp.int32).at[perm].set(iota)
        mask_s = mask[perm]
        # partition-start flags (in sorted order)
        if pcols:
            pwords = []
            for pc in pcols:
                nullbit = jnp.where(pc.validity, 0, 1).astype(jnp.int64)
                pwords.append(nullbit[perm])
                for w in _column_key_words(pc):
                    pwords.append(jnp.where(pc.validity, w, 0)[perm])
            starts = jnp.zeros(cap, jnp.bool_)
            for w in pwords:
                prev = jnp.concatenate([w[:1], w[:-1]])
                starts = starts | (w != prev)
            starts = starts.at[0].set(True)
        else:
            starts = jnp.zeros(cap, jnp.bool_).at[0].set(True)
        seg = jnp.cumsum(starts.astype(jnp.int32)) - 1
        seg = jnp.where(mask_s, seg, cap - 1)
        # order-key change flags (for rank/dense_rank)
        owords = []
        for oc, spec in zip(ocols, ospecs):
            nullbit = jnp.where(oc.validity, 0, 1).astype(jnp.int64)
            owords.append(nullbit[perm])
            for w in _column_key_words(oc):
                owords.append(jnp.where(oc.validity, w, 0)[perm])
        ochange = jnp.zeros(cap, jnp.bool_)
        for w in owords:
            prev = jnp.concatenate([w[:1], w[:-1]])
            ochange = ochange | (w != prev)
        ochange = ochange | starts
        out_cols = list(batch.columns)
        # row position within partition (0-based), in sorted order
        pos_in_part = SEG.seg_scan_sum(
            jnp.ones(cap, jnp.int64), jnp.ones(cap, jnp.bool_), starts)[0] - 1
        for wf in self.functions:
            res = self._one_function(
                wf, ctx, perm, seg, starts, ochange, pos_in_part, mask_s, cap)
            if isinstance(res, DeviceColumn):
                # column result (lead/lag incl. strings): gather back
                out_cols.append(res.gather(inv_perm))
                out_cols[-1] = DeviceColumn(
                    res.dtype, out_cols[-1].validity & mask,
                    data=out_cols[-1].data, chars=out_cols[-1].chars,
                    lengths=out_cols[-1].lengths)
                continue
            vals_sorted, valid_sorted = res
            # scatter back to original order
            vals = vals_sorted[inv_perm]
            valid = valid_sorted[inv_perm] & mask
            sdt = T.storage_dtype(wf.result_type)
            out_cols.append(DeviceColumn(wf.result_type, valid,
                                         data=vals.astype(sdt)))
        return tuple(out_cols)

    def _part_sizes(self, seg, mask_s, pos_in_part, cap):
        """Rows per partition, broadcast back to every row (sorted order)."""
        cnt = jax.ops.segment_sum(mask_s.astype(jnp.int64), seg,
                                  num_segments=cap)
        return cnt[seg]

    def _one_function(self, wf: WindowFunction, ctx, perm, seg, starts,
                      ochange, pos_in_part, mask_s, cap):
        ones = jnp.ones(cap, jnp.bool_)
        if wf.func == "row_number":
            return pos_in_part + 1, ones
        if wf.func == "rank":
            # rank = index of last order-change within partition + 1
            anchor = jnp.where(ochange, pos_in_part, jnp.int64(-1))
            last_anchor = SEG.seg_scan_max(
                anchor, ones, starts, is_float=False)[0]
            return last_anchor + 1, ones
        if wf.func == "dense_rank":
            d = SEG.seg_scan_sum(ochange.astype(jnp.int64), ones, starts)[0]
            return d, ones
        if wf.func == "percent_rank":
            anchor = jnp.where(ochange, pos_in_part, jnp.int64(-1))
            rank = SEG.seg_scan_max(anchor, ones, starts,
                                    is_float=False)[0] + 1
            nrows = self._part_sizes(seg, mask_s, pos_in_part, cap)
            den = jnp.maximum(nrows - 1, 1)
            return (rank - 1).astype(jnp.float64) / den, ones
        if wf.func == "cume_dist":
            # rows whose order key <= current = last row of the peer group
            peer = jnp.cumsum(ochange.astype(jnp.int32)) - 1
            peer = jnp.where(mask_s, peer, cap - 1)
            last_pos = jax.ops.segment_max(
                jnp.where(mask_s, pos_in_part, -1), peer, num_segments=cap)
            nrows = self._part_sizes(seg, mask_s, pos_in_part, cap)
            return ((last_pos[peer] + 1).astype(jnp.float64)
                    / jnp.maximum(nrows, 1)), ones
        if wf.func == "ntile":
            nb = jnp.int64(max(int(wf.buckets), 1))
            nrows = self._part_sizes(seg, mask_s, pos_in_part, cap)
            q = nrows // nb
            r = nrows % nb
            p = pos_in_part
            big = r * (q + 1)
            bucket = jnp.where(
                p < big, p // jnp.maximum(q + 1, 1),
                r + (p - big) // jnp.maximum(q, 1))
            return bucket + 1, ones
        if wf.func in ("lead", "lag"):
            c = wf.child.eval_tpu(ctx)
            cs = c.gather(perm)
            off = int(wf.offset) * (1 if wf.func == "lead" else -1)
            iota = jnp.arange(cap, dtype=jnp.int32)
            idx = iota + off
            inb = (idx >= 0) & (idx < cap)
            safe = jnp.clip(idx, 0, cap - 1)
            same_part = inb & (seg[safe] == seg) & mask_s & mask_s[safe]
            shifted = cs.gather(safe)
            validity = jnp.where(same_part, shifted.validity, False)
            if wf.default is not None:
                from spark_rapids_tpu.expr.base import Literal

                dflt = Literal(wf.default, wf.result_type).eval_tpu(ctx)
                if cs.is_string:
                    w = max(shifted.width, dflt.width)
                    from spark_rapids_tpu.expr.predicates import _pad_to

                    chars = jnp.where(same_part[:, None],
                                      _pad_to(shifted.chars, w),
                                      _pad_to(dflt.chars, w))
                    lengths = jnp.where(same_part, shifted.lengths,
                                        dflt.lengths)
                    return DeviceColumn(wf.result_type,
                                        validity | (~same_part & mask_s),
                                        chars=chars, lengths=lengths)
                data = jnp.where(same_part, shifted.data, dflt.data)
                return DeviceColumn(wf.result_type,
                                    validity | (~same_part & mask_s),
                                    data=data)
            if cs.is_string:
                return DeviceColumn(wf.result_type, validity,
                                    chars=shifted.chars,
                                    lengths=shifted.lengths)
            return DeviceColumn(wf.result_type, validity, data=shifted.data)
        c = wf.child.eval_tpu(ctx)
        vals = (c.data if not c.is_string else None)
        if vals is None:
            raise NotImplementedError("string window aggregates")
        vals_s = vals[perm]
        valid_s = (c.validity & ctx.batch.row_mask)[perm]
        is_f = isinstance(wf.result_type, (T.FloatType, T.DoubleType))
        acc_vals = vals_s.astype(jnp.float64 if is_f else jnp.int64)
        if isinstance(self.frame, tuple):
            return self._bounded_frame(wf, acc_vals, valid_s, seg, mask_s,
                                       cap, is_f)
        if self.frame == "running":
            if wf.func == "count":
                _, cnt = SEG.seg_scan_sum(acc_vals, valid_s, starts)
                return cnt, ones
            if wf.func == "sum":
                s, cnt = SEG.seg_scan_sum(acc_vals, valid_s, starts)
                return s, cnt > 0
            if wf.func == "avg":
                s, cnt = SEG.seg_scan_sum(acc_vals, valid_s, starts)
                return s.astype(jnp.float64) / jnp.maximum(cnt, 1), cnt > 0
            if wf.func == "min":
                return SEG.seg_scan_min(acc_vals, valid_s, starts, is_f)
            if wf.func == "max":
                return SEG.seg_scan_max(acc_vals, valid_s, starts, is_f)
            raise NotImplementedError(wf.func)
        # unbounded frame: segment totals broadcast back via seg gather
        if wf.func == "count":
            cnt = SEG.seg_count(valid_s, seg, cap)
            return cnt[seg], ones
        if wf.func == "sum":
            s, has = SEG.seg_sum(acc_vals, valid_s, seg, cap)
            return s[seg], has[seg]
        if wf.func == "avg":
            s, has = SEG.seg_sum(acc_vals, valid_s, seg, cap)
            cnt = SEG.seg_count(valid_s, seg, cap)
            return (s.astype(jnp.float64) / jnp.maximum(cnt, 1))[seg], has[seg]
        if wf.func == "min":
            m, has = SEG.seg_min(acc_vals, valid_s, seg, cap, is_f)
            return m[seg], has[seg]
        if wf.func == "max":
            m, has = SEG.seg_max(acc_vals, valid_s, seg, cap, is_f)
            return m[seg], has[seg]
        raise NotImplementedError(wf.func)

    def _bounded_frame(self, wf, acc_vals, valid_s, seg, mask_s, cap, is_f):
        """ROWS BETWEEN a PRECEDING AND b FOLLOWING via statically unrolled
        shifted combines (window width is a plan-time constant)."""
        a, b = self.frame
        iota = jnp.arange(cap, dtype=jnp.int32)
        total = jnp.zeros(cap, acc_vals.dtype)
        cnt = jnp.zeros(cap, jnp.int64)
        if is_f:
            mn = jnp.full(cap, jnp.inf)
            mx = jnp.full(cap, -jnp.inf)
            cnt_nonnan = jnp.zeros(cap, jnp.int64)
        else:
            mn = jnp.full(cap, jnp.iinfo(acc_vals.dtype).max, acc_vals.dtype)
            mx = jnp.full(cap, jnp.iinfo(acc_vals.dtype).min, acc_vals.dtype)
        for d in range(-int(a), int(b) + 1):
            idx = iota + d
            inb = (idx >= 0) & (idx < cap)
            safe = jnp.clip(idx, 0, cap - 1)
            ok = inb & (seg[safe] == seg) & mask_s & mask_s[safe] \
                & valid_s[safe]
            v = acc_vals[safe]
            total = total + jnp.where(ok, v, 0)
            cnt = cnt + ok.astype(jnp.int64)
            if is_f:
                nan = jnp.isnan(v)
                mn = jnp.where(ok & ~nan, jnp.minimum(mn, v), mn)
                mx = jnp.where(ok & nan, jnp.nan,
                               jnp.where(ok, jnp.maximum(mx, v), mx))
                cnt_nonnan = cnt_nonnan + (ok & ~nan).astype(jnp.int64)
            else:
                mn = jnp.where(ok, jnp.minimum(mn, v), mn)
                mx = jnp.where(ok, jnp.maximum(mx, v), mx)
        has = cnt > 0
        if wf.func == "count":
            return cnt, jnp.ones(cap, jnp.bool_)
        if wf.func == "sum":
            return total, has
        if wf.func == "avg":
            return (total.astype(jnp.float64)
                    / jnp.maximum(cnt, 1)), has
        if wf.func == "min":
            if is_f:
                # all-NaN window -> NaN (NaN greatest, min only if nothing else)
                only_nan = has & (cnt_nonnan == 0)
                return jnp.where(only_nan, jnp.nan, mn), has
            return mn, has
        if wf.func == "max":
            return mx, has
        raise NotImplementedError(f"bounded frame {wf.func}")
