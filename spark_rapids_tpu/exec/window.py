"""TpuWindowExec — window functions via segmented scans.

Reference analog (SURVEY.md §2.4 Window): GpuWindowExec with three
strategies — running window (cumulative batch-streaming), double-pass
cached, and batched bounded-window.  TPU redesign folds all three into one
jitted program built on `lax.associative_scan` segmented scans:

  * rank/dense_rank/row_number/ntile/percent_rank/cume_dist: order-key
    change flags + segmented cumsums / peer-group reductions
  * running ROWS frames (UNBOUNDED PRECEDING..CURRENT ROW): segmented
    inclusive scans (sum/count/min/max/avg/var/stddev)
  * RANGE running (Spark's default frame with ORDER BY): the running scan
    result gathered at the last order-key *peer* of each row
  * unbounded frames: segment totals broadcast back
  * bounded ROWS frames (a PRECEDING..b FOLLOWING, both finite): statically
    unrolled shifted combines masked at partition boundaries — the TPU
    counterpart of the reference's batched bounded-window kernel (window
    width is a plan-time constant; widths above the tag-time cap fall back)
  * bounded RANGE frames over a single numeric order key: per-row frame
    boundaries found with a vectorized merged-sort searchsorted (data and
    query keys share one `lax.sort`), then prefix-sum differences for
    sum/count/avg/var and a sparse table (doubling min/max levels) for
    min/max over the variable-width contiguous ranges
  * lead/lag: shifted gathers with partition-boundary masking and literal
    defaults (strings included)
  * first_value/last_value (incl. IGNORE NULLS): frame-boundary gathers
    through next-valid/prev-valid index scans — strings included
  * string min/max (running/range-running/unbounded frames): segmented
    lexicographic arg-min/max scans over the packed sort-key words, then a
    chars gather

Rows are sorted by (partition keys, order keys), computed, and scattered
back to the original order through the inverse permutation, so output row
order matches the child (Spark's WindowExec contract).

Every unsupported (function, frame, type) combination is rejected at *tag*
time by overrides._window_check — no execution-time NotImplementedError is
reachable from a converted plan (the RapidsMeta tag-or-fallback contract).
"""
from __future__ import annotations

from typing import Iterator, List, Tuple

import jax
from spark_rapids_tpu.perfcounters import tpu_jit
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.expr.base import EvalContext, Expression
from spark_rapids_tpu.ops import segment as SEG
from spark_rapids_tpu.ops.sortkeys import (SortSpec, _column_key_words,
                                           float_order_key, pack_sort_keys)
from spark_rapids_tpu.plan.nodes import WindowFunction

_VAR_FUNCS = ("var_pop", "var_samp", "stddev_pop", "stddev_samp")
_I64_MAX = 9223372036854775807
_I64_MIN = -9223372036854775808


def _g(geom, key):
    """Memoizing accessor for the lazily-built frame-geometry thunks."""
    v = geom[key]
    if callable(v):
        v = v()
        geom[key] = v
    return v


def _peer_first(geom):
    return _g(geom, "peers")[0]


def _peer_last(geom):
    return _g(geom, "peers")[1]


def _chan_merge(na, ma, m2a, nb, mb, m2b):
    """Chan's pairwise (n, mean, M2) merge — numerically stable variance
    combination (the reference gets this from Spark's CentralMomentAgg)."""
    n = na + nb
    nsafe = jnp.maximum(n, 1.0)
    d = mb - ma
    mean = ma + d * nb / nsafe
    m2 = m2a + m2b + d * d * na * nb / nsafe
    return n, mean, m2


def _lex_lt(aw, bw):
    """Lexicographic a < b over equal-length int64 word tuples."""
    lt = jnp.zeros_like(aw[0], jnp.bool_)
    done = jnp.zeros_like(aw[0], jnp.bool_)
    for x, y in zip(aw, bw):
        lt = jnp.where(~done & (x < y), True, lt)
        done = done | (x != y)
    return lt


class TpuWindowExec(TpuExec):
    def __init__(self, functions: List[WindowFunction],
                 partition_by: List[Expression],
                 order_by: List[Tuple[Expression, SortSpec]],
                 child: TpuExec, output_schema: T.StructType,
                 frame="running", ansi: bool = False):
        super().__init__([child])
        self.functions = functions
        self.partition_by = partition_by
        self.order_by = order_by
        self._output = output_schema
        self.frame = frame
        self.ansi = ansi

    @property
    def output(self):
        return self._output

    def describe(self):
        fns = ", ".join(f.func for f in self.functions)
        return f"TpuWindow [{fns}] frame={self.frame}"

    def _window_program(self):
        """(registry key parts, factory) for the single fused window
        program — shared by runtime, chain fusion and AOT enumeration."""
        from spark_rapids_tpu.compilecache.keys import (
            conf_fp,
            exprs_fp,
            schema_fp,
            window_fns_fp,
        )

        fns = window_fns_fp(self.functions)
        pby = exprs_fp(self.partition_by)
        oby = exprs_fp([e for e, _ in self.order_by])
        key_parts = None
        if fns is not None and pby is not None and oby is not None:
            key_parts = (
                "window", schema_fp(self.children[0].output), fns, pby,
                oby,
                tuple((s.ascending, s.nulls_first)
                      for _, s in self.order_by),
                str(self.frame), bool(self.ansi),
                schema_fp(self._output), conf_fp())

        def factory():
            # detached clone: a registry entry outliving this query must
            # not pin the scan subtree through the bound method
            return tpu_jit(self.detached_for_trace()._window_fn), None

        return key_parts, factory

    def _window_jit(self):
        if getattr(self, "_jitted", None) is None:
            from spark_rapids_tpu.compilecache.registry import (
                cached_program,
            )

            key_parts, factory = self._window_program()
            self._jitted = cached_program(key_parts, factory,
                                          label=self.describe()).jitted
        return self._jitted

    def aot_output_rows(self):
        rows = self.aot_input_rows()
        return None if rows is None else [sum(rows)]

    def aot_output_caps(self):
        caps = super().aot_output_caps()
        return caps if caps is not None else self.aot_input_concat_caps()

    def aot_emits_single_batch(self):
        return True

    def aot_programs(self):
        from spark_rapids_tpu.compilecache.aot import (
            AotProgram,
            dummy_batch_args,
        )

        caps = self.aot_input_concat_caps()
        if not caps:
            return []
        schema = self.children[0].output
        key_parts, factory = self._window_program()

        def args_factory():
            return [dummy_batch_args(schema, c) for c in caps]

        return [AotProgram(key_parts, factory, args_factory,
                           f"window:{self.describe()[:48]}")]

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        batches = list(self.children[0].execute_columnar())
        if not batches:
            return
        batch = (batches[0] if len(batches) == 1
                 else ColumnarBatch.concat(batches))
        with self.metrics["opTime"].timed():
            cols = self._window_jit()(tuple(batch.columns),
                                      jnp.int32(batch.num_rows))
            out = ColumnarBatch(list(cols), batch.num_rows, self._output)
        yield self._count_output(out)

    def _window_fn(self, cols, num_rows):
        schema = self.children[0].output
        batch = ColumnarBatch(list(cols), num_rows, schema)
        ctx = EvalContext(batch, ansi=self.ansi)
        cap = batch.capacity
        mask = batch.row_mask
        pcols = [e.eval_tpu(ctx) for e in self.partition_by]
        ocols = [e.eval_tpu(ctx) for e, _ in self.order_by]
        ospecs = [s for _, s in self.order_by]
        # sort by (partition, order)
        keys = pack_sort_keys(pcols, [SortSpec()] * len(pcols), mask) if pcols \
            else []
        keys += pack_sort_keys(ocols, ospecs, mask)
        iota = jnp.arange(cap, dtype=jnp.int32)
        if keys:
            perm = jax.lax.sort(tuple(keys) + (iota,), num_keys=len(keys),
                                is_stable=True)[-1]
        else:
            perm = iota
        inv_perm = jnp.zeros(cap, jnp.int32).at[perm].set(iota)
        mask_s = mask[perm]
        # partition-start flags (in sorted order)
        if pcols:
            pwords = []
            for pc in pcols:
                nullbit = jnp.where(pc.validity, 0, 1).astype(jnp.int64)
                pwords.append(nullbit[perm])
                for w in _column_key_words(pc):
                    pwords.append(jnp.where(pc.validity, w, 0)[perm])
            starts = jnp.zeros(cap, jnp.bool_)
            for w in pwords:
                prev = jnp.concatenate([w[:1], w[:-1]])
                starts = starts | (w != prev)
            starts = starts.at[0].set(True)
        else:
            starts = jnp.zeros(cap, jnp.bool_).at[0].set(True)
        seg = jnp.cumsum(starts.astype(jnp.int32)) - 1
        seg = jnp.where(mask_s, seg, cap - 1)
        # order-key change flags (for rank/dense_rank/peer groups)
        owords = []
        for oc, spec in zip(ocols, ospecs):
            nullbit = jnp.where(oc.validity, 0, 1).astype(jnp.int64)
            owords.append(nullbit[perm])
            for w in _column_key_words(oc):
                owords.append(jnp.where(oc.validity, w, 0)[perm])
        ochange = jnp.zeros(cap, jnp.bool_)
        for w in owords:
            prev = jnp.concatenate([w[:1], w[:-1]])
            ochange = ochange | (w != prev)
        ochange = ochange | starts
        out_cols = list(batch.columns)
        # row position within partition (0-based), in sorted order:
        # iota minus the running segment-start position (cummax is a
        # compact reduce-window; a segmented scan costs ~20s of compile)
        seg_first0 = jax.lax.cummax(jnp.where(starts, iota, 0))
        pos32 = iota - seg_first0
        pos_in_part = pos32.astype(jnp.int64)
        # frame geometry shared by all functions (sorted order); the
        # reductions/gathers are thunks so a ranking-only window (row_number/
        # rank/lead/lag) never pays for peer/segment-end indices
        seg_first = seg_first0

        def _suffix_min(marks):
            """Running min from the right (cheap reduce-window scan —
            segment_min/max scatters measured ~480ms at 2M in the
            round-4 microbench; rows are sorted so segments are
            contiguous runs)."""
            return jax.lax.cummin(marks, reverse=True)

        def _run_last(run_starts):
            """Index of the last VALID row of each contiguous run,
            broadcast to its rows (garbage past the valid prefix)."""
            nxt_start = jnp.concatenate([run_starts[1:],
                                         jnp.ones(1, jnp.bool_)])
            nxt_invalid = jnp.concatenate([~mask_s[1:],
                                           jnp.ones(1, jnp.bool_)])
            is_last = mask_s & (nxt_start | nxt_invalid)
            return _suffix_min(jnp.where(is_last, iota, cap))

        def _seg_last():
            return _run_last(starts)

        def _peers():
            last = _run_last(ochange)
            first = jax.lax.cummax(jnp.where(ochange, iota, -1))
            return first, last

        geom = dict(iota=iota, seg_first=seg_first,
                    seg_last=_seg_last,
                    peers=_peers,
                    ocols_sorted=lambda: [c.gather(perm) for c in ocols],
                    ospecs=ospecs)
        for wf in self.functions:
            res = self._one_function(
                wf, ctx, perm, seg, starts, ochange, pos_in_part, mask_s,
                cap, geom)
            if isinstance(res, DeviceColumn):
                # column result (lead/lag/first/last/string min-max): gather
                # back to input row order
                out_cols.append(res.gather(inv_perm))
                out_cols[-1] = DeviceColumn(
                    res.dtype, out_cols[-1].validity & mask,
                    data=out_cols[-1].data, chars=out_cols[-1].chars,
                    lengths=out_cols[-1].lengths)
                continue
            vals_sorted, valid_sorted = res
            # scatter back to original order
            vals = vals_sorted[inv_perm]
            valid = valid_sorted[inv_perm] & mask
            sdt = T.storage_dtype(wf.result_type)
            out_cols.append(DeviceColumn(wf.result_type, valid,
                                         data=vals.astype(sdt)))
        return tuple(out_cols)

    def _part_sizes(self, geom, pos_in_part, cap):
        """Rows per partition, broadcast back to every row (sorted order):
        the 0-based position of the segment's last row, plus one (free
        gather instead of a segment_sum scatter)."""
        sl = jnp.clip(_g(geom, "seg_last"), 0, cap - 1)
        return pos_in_part[sl] + 1

    # -- frame boundaries ----------------------------------------------------

    def _frame_start_end(self, frame, mask_s, seg, cap, geom):
        """Per-row [fs, fe) frame boundaries as sorted-space indices
        (memoized in ``geom`` — identical for every window function)."""
        if "fs" in geom:
            return geom["fs"], geom["fe"]
        iota = geom["iota"]
        seg_first = geom["seg_first"]
        if frame == "running":
            fs, fe = seg_first, iota + 1
        elif frame == "range_running":
            fs, fe = seg_first, _peer_last(geom) + 1
        elif frame == "unbounded":
            fs, fe = seg_first, _g(geom, "seg_last") + 1
        else:
            kind, a, b = frame
            if kind == "rows":
                seg_last = _g(geom, "seg_last")
                fs = jnp.maximum(seg_first, iota - jnp.int32(int(a)))
                fe = jnp.minimum(seg_last + 1, iota + jnp.int32(int(b) + 1))
                fe = jnp.maximum(fe, fs)
            else:
                fs, fe = self._range_bounds(a, b, mask_s, seg, cap, geom)
        geom["fs"], geom["fe"] = fs, fe
        return fs, fe

    def _order_value_key(self, vals, dtype, asc):
        """Physical-sort-compatible key word for an order value array."""
        if isinstance(dtype, (T.FloatType, T.DoubleType)):
            k = float_order_key(vals)
        else:
            k = vals.astype(jnp.int64)
        return k if asc else ~k

    def _range_bounds(self, lo_off, hi_off, mask_s, seg, cap, geom):
        """Bounded RANGE frame boundaries via merged-sort searchsorted.

        The data rows are physically sorted by (segment, null-flag,
        order-key); query keys (value ± offset) of non-null rows are sorted
        the same way, so one stable `lax.sort` over the 2N concatenation
        yields every searchsorted position at once (GpuRangePartitioner-
        style binary search, vectorized the XLA way).  Null order keys
        frame their null peer group (Spark RANGE semantics).
        """
        oc = _g(geom, "ocols_sorted")[0]
        spec: SortSpec = geom["ospecs"][0]
        asc = spec.ascending
        dt = oc.dtype
        iota = geom["iota"]
        # value-space bounds; "PRECEDING" points to the partition start so
        # the bounds flip for descending order
        if isinstance(dt, (T.FloatType, T.DoubleType)):
            v = oc.data.astype(jnp.float64)

            def sub(x, k):
                return x - jnp.float64(float(k))

            def add(x, k):
                return x + jnp.float64(float(k))
        else:
            v = oc.data.astype(jnp.int64)

            # saturating: an int64 boundary that would wrap clamps to the
            # type extreme, which frames the same row set as the exact
            # (unbounded-overflowing) arithmetic would
            def sub(x, k):
                k = int(k)
                return jnp.where(x < _I64_MIN + k, jnp.int64(_I64_MIN),
                                 x - jnp.int64(k))

            def add(x, k):
                k = int(k)
                return jnp.where(x > _I64_MAX - k, jnp.int64(_I64_MAX),
                                 x + jnp.int64(k))
        left_val = sub(v, lo_off) if asc else add(v, lo_off)
        right_val = add(v, hi_off) if asc else sub(v, hi_off)
        qL = self._order_value_key(left_val, dt, asc)
        qR = self._order_value_key(right_val, dt, asc)
        # data keys exactly as pack_sort_keys built them
        null_key = jnp.where(oc.validity, 0,
                             -1 if spec.nulls_first else 1).astype(jnp.int64)
        dk = self._order_value_key(oc.data, dt, asc)
        dk = jnp.where(oc.validity, dk, 0)
        segk = jnp.where(mask_s, seg.astype(jnp.int64), _I64_MAX)
        q_segk = segk
        q_null = jnp.zeros(cap, jnp.int64)
        fs = self._sorted_bound(segk, null_key, dk, q_segk, q_null, qL,
                                True, cap)
        fe = self._sorted_bound(segk, null_key, dk, q_segk, q_null, qR,
                                False, cap)
        # null order keys: frame = the null peer group
        fs = jnp.where(oc.validity, fs, _peer_first(geom))
        fe = jnp.where(oc.validity, fe, _peer_last(geom) + 1)
        return fs, jnp.maximum(fe, fs)

    def _sorted_bound(self, dk1, dk2, dk3, qk1, qk2, qk3, left, cap):
        """searchsorted of sorted queries into sorted data (both length cap,
        lexicographic 3-word keys) via one merged stable sort."""
        iota = jnp.arange(cap, dtype=jnp.int32)
        tie_d = jnp.full(cap, 1 if left else 0, jnp.int64)
        tie_q = jnp.full(cap, 0 if left else 1, jnp.int64)
        k1 = jnp.concatenate([dk1, qk1])
        k2 = jnp.concatenate([dk2, qk2])
        k3 = jnp.concatenate([dk3, qk3])
        k4 = jnp.concatenate([tie_d, tie_q])
        payload = jnp.concatenate(
            [jnp.zeros(cap, jnp.int32), iota + 1])
        sp = jax.lax.sort((k1, k2, k3, k4, payload), num_keys=4,
                          is_stable=True)[-1]
        is_q = sp > 0
        pos = jnp.arange(2 * cap, dtype=jnp.int32)
        csq = jnp.cumsum(is_q.astype(jnp.int32))
        ndata_before = pos + 1 - csq
        idx = jnp.where(is_q, sp - 1, cap)
        return jnp.zeros(cap, jnp.int32).at[idx].set(
            jnp.where(is_q, ndata_before, 0), mode="drop")

    # -- function dispatch ---------------------------------------------------

    def _one_function(self, wf: WindowFunction, ctx, perm, seg, starts,
                      ochange, pos_in_part, mask_s, cap, geom):
        ones = jnp.ones(cap, jnp.bool_)
        if wf.func == "row_number":
            return pos_in_part + 1, ones
        iota = _g(geom, "iota")
        if wf.func == "rank":
            # rank = position of the last order-change row + 1 (running
            # max of GLOBAL row index resets naturally: partition starts
            # are ochange rows and iota is globally increasing)
            anchor_row = jax.lax.cummax(jnp.where(ochange, iota, -1))
            return (pos_in_part[jnp.clip(anchor_row, 0, cap - 1)]
                    + 1), ones
        if wf.func == "dense_rank":
            d = SEG.seg_scan_sum(ochange.astype(jnp.int64), ones, starts)[0]
            return d, ones
        if wf.func == "percent_rank":
            anchor_row = jax.lax.cummax(jnp.where(ochange, iota, -1))
            rank = pos_in_part[jnp.clip(anchor_row, 0, cap - 1)] + 1
            nrows = self._part_sizes(geom, pos_in_part, cap)
            den = jnp.maximum(nrows - 1, 1)
            return (rank - 1).astype(jnp.float64) / den, ones
        if wf.func == "cume_dist":
            last_pos = pos_in_part[_peer_last(geom)]
            nrows = self._part_sizes(geom, pos_in_part, cap)
            return ((last_pos + 1).astype(jnp.float64)
                    / jnp.maximum(nrows, 1)), ones
        if wf.func == "ntile":
            nb = jnp.int64(max(int(wf.buckets), 1))
            nrows = self._part_sizes(geom, pos_in_part, cap)
            q = nrows // nb
            r = nrows % nb
            p = pos_in_part
            big = r * (q + 1)
            bucket = jnp.where(
                p < big, p // jnp.maximum(q + 1, 1),
                r + (p - big) // jnp.maximum(q, 1))
            return bucket + 1, ones
        if wf.func in ("lead", "lag"):
            return self._lead_lag(wf, ctx, perm, seg, mask_s, cap)
        if wf.func in ("first_value", "last_value"):
            return self._first_last(wf, ctx, perm, seg, mask_s, cap, geom)
        c = wf.child.eval_tpu(ctx)
        if c.is_string and wf.func in ("min", "max"):
            return self._string_minmax(wf, c, perm, seg, starts, mask_s,
                                       cap, geom)
        return self._numeric_agg(wf, c, ctx, perm, seg, starts, mask_s,
                                 cap, geom)

    def _lead_lag(self, wf, ctx, perm, seg, mask_s, cap):
        c = wf.child.eval_tpu(ctx)
        cs = c.gather(perm)
        off = int(wf.offset) * (1 if wf.func == "lead" else -1)
        iota = jnp.arange(cap, dtype=jnp.int32)
        idx = iota + off
        inb = (idx >= 0) & (idx < cap)
        safe = jnp.clip(idx, 0, cap - 1)
        same_part = inb & (seg[safe] == seg) & mask_s & mask_s[safe]
        shifted = cs.gather(safe)
        validity = jnp.where(same_part, shifted.validity, False)
        if wf.default is not None:
            from spark_rapids_tpu.expr.base import Literal

            dflt = Literal(wf.default, wf.result_type).eval_tpu(ctx)
            if cs.is_string:
                w = max(shifted.width, dflt.width)
                from spark_rapids_tpu.expr.predicates import _pad_to

                chars = jnp.where(same_part[:, None],
                                  _pad_to(shifted.chars, w),
                                  _pad_to(dflt.chars, w))
                lengths = jnp.where(same_part, shifted.lengths,
                                    dflt.lengths)
                return DeviceColumn(wf.result_type,
                                    validity | (~same_part & mask_s),
                                    chars=chars, lengths=lengths)
            data = jnp.where(same_part, shifted.data, dflt.data)
            return DeviceColumn(wf.result_type,
                                validity | (~same_part & mask_s),
                                data=data)
        if cs.is_string:
            return DeviceColumn(wf.result_type, validity,
                                chars=shifted.chars,
                                lengths=shifted.lengths)
        return DeviceColumn(wf.result_type, validity, data=shifted.data)

    def _first_last(self, wf, ctx, perm, seg, mask_s, cap, geom):
        """first_value/last_value: a frame-boundary gather (strings too)."""
        c = wf.child.eval_tpu(ctx)
        cs = c.gather(perm)
        valid_s = cs.validity & mask_s
        fs, fe = self._frame_start_end(self.frame, mask_s, seg, cap, geom)
        nonempty = fe > fs
        iota = geom["iota"]
        if wf.ignore_nulls:
            if wf.func == "first_value":
                nxt = jax.lax.associative_scan(
                    jnp.minimum, jnp.where(valid_s, iota, cap), reverse=True)
                at = nxt[jnp.clip(fs, 0, cap - 1)]
                ok = nonempty & (at <= fe - 1)
            else:
                prv = jax.lax.associative_scan(
                    jnp.maximum, jnp.where(valid_s, iota, -1))
                at = prv[jnp.clip(fe - 1, 0, cap - 1)]
                ok = nonempty & (at >= fs)
            at = jnp.clip(at, 0, cap - 1)
            res = cs.gather(at)
            return DeviceColumn(wf.result_type, ok & mask_s,
                                data=res.data, chars=res.chars,
                                lengths=res.lengths)
        at = fs if wf.func == "first_value" else fe - 1
        at = jnp.clip(at, 0, cap - 1)
        res = cs.gather(at)
        return DeviceColumn(wf.result_type,
                            nonempty & res.validity & mask_s,
                            data=res.data, chars=res.chars,
                            lengths=res.lengths)

    # -- string min/max ------------------------------------------------------

    def _string_minmax(self, wf, c, perm, seg, starts, mask_s, cap, geom):
        """Segmented lexicographic argmin/argmax scan over sort-key words,
        then a chars gather (running / range_running / unbounded frames —
        bounded frames fall back at tag time)."""
        cs = c.gather(perm)
        valid_s = cs.validity & mask_s
        want_min = wf.func == "min"
        words = _column_key_words(cs)
        # leading word: invalid rows always lose the comparison
        lead = jnp.where(valid_s, jnp.int64(0),
                         jnp.int64(_I64_MAX if want_min else _I64_MIN))
        iota = geom["iota"]
        elems = (starts,) + (lead,) + tuple(words) + (iota,)

        def op(a, b):
            af, bf = a[0], b[0]
            aw, bw = a[1:-1], b[1:-1]
            ai, bi = a[-1], b[-1]
            if want_min:
                b_better = _lex_lt(bw, aw)
            else:
                b_better = _lex_lt(aw, bw)
            take_b = bf | b_better
            w = tuple(jnp.where(take_b, y, x) for x, y in zip(aw, bw))
            return (af | bf,
                    *w,
                    jnp.where(take_b, bi, ai))

        scanned = jax.lax.associative_scan(op, elems)
        arg_running = scanned[-1]
        # invalid rows always lose the comparison, so arg_running points at
        # a valid row iff any valid row was seen in the segment prefix
        seen = valid_s[arg_running]
        if self.frame == "running":
            arg, ok = arg_running, seen
        elif self.frame == "range_running":
            pl = _peer_last(geom)
            arg, ok = arg_running[pl], seen[pl]
        else:  # unbounded
            sl = _g(geom, "seg_last")
            arg, ok = arg_running[sl], seen[sl]
        res = cs.gather(jnp.clip(arg, 0, cap - 1))
        return DeviceColumn(wf.result_type, ok & mask_s,
                            chars=res.chars, lengths=res.lengths)

    # -- numeric aggregates --------------------------------------------------

    def _numeric_agg(self, wf, c, ctx, perm, seg, starts, mask_s, cap, geom):
        # count over strings has no data array — only validity matters
        vals = c.data if not c.is_string else jnp.zeros(cap, jnp.int64)
        vals_s = vals[perm]
        valid_s = (c.validity & ctx.batch.row_mask)[perm]
        is_f = isinstance(wf.result_type, (T.FloatType, T.DoubleType))
        acc_vals = vals_s.astype(jnp.float64 if is_f else jnp.int64)
        frame = self.frame
        ones = jnp.ones(cap, jnp.bool_)
        if frame in ("running", "range_running"):
            res, ok = self._running_agg(wf, acc_vals, valid_s, starts, is_f,
                                        cap)
            if frame == "range_running":
                pl = _peer_last(geom)
                res, ok = res[pl], ok[pl]
            return res, ok
        if isinstance(frame, tuple) and frame[0] == "rows":
            return self._bounded_rows_frame(wf, acc_vals, valid_s, seg,
                                            mask_s, cap, is_f, frame)
        if isinstance(frame, tuple) and frame[0] == "range":
            return self._bounded_range_frame(wf, acc_vals, valid_s, seg,
                                             mask_s, cap, is_f, geom)
        # unbounded frame = the segmented RUNNING scan's value at each
        # segment's last row (one free associative scan + one gather;
        # the previous per-function segment_* scatters measured 83-483ms
        # each at 2M rows in the round-4 microbench).  The variance
        # family rides the same path: the running Chan (n, mean, M2)
        # merge is numerically stable at the segment end too.
        res, ok = self._running_agg(wf, acc_vals, valid_s, starts, is_f,
                                    cap)
        sl = jnp.clip(_g(geom, "seg_last"), 0, cap - 1)
        return res[sl], ok[sl]

    def _running_agg(self, wf, acc_vals, valid_s, starts, is_f, cap):
        ones = jnp.ones(cap, jnp.bool_)
        if wf.func == "count":
            _, cnt = SEG.seg_scan_sum(acc_vals, valid_s, starts)
            return cnt, ones
        if wf.func == "sum":
            s, cnt = SEG.seg_scan_sum(acc_vals, valid_s, starts)
            return s, cnt > 0
        if wf.func == "avg":
            s, cnt = SEG.seg_scan_sum(acc_vals, valid_s, starts)
            return s.astype(jnp.float64) / jnp.maximum(cnt, 1), cnt > 0
        if wf.func == "min":
            return SEG.seg_scan_min(acc_vals, valid_s, starts, is_f)
        if wf.func == "max":
            return SEG.seg_scan_max(acc_vals, valid_s, starts, is_f)
        # variance family — segmented associative scan of Chan (n, mean, M2)
        # triples (the merge is associative, so lax.associative_scan applies;
        # numerically stable where a running Σx² would cancel)
        x = acc_vals.astype(jnp.float64)
        n0 = jnp.where(valid_s, 1.0, 0.0)
        m0 = jnp.where(valid_s, x, 0.0)
        z = jnp.zeros(cap, jnp.float64)

        def op(a, b):
            af, an, am, am2 = a
            bf, bn, bm, bm2 = b
            n, mean, m2 = _chan_merge(an, am, am2, bn, bm, bm2)
            return (af | bf,
                    jnp.where(bf, bn, n),
                    jnp.where(bf, bm, mean),
                    jnp.where(bf, bm2, m2))

        _, n, _, m2 = jax.lax.associative_scan(op, (starts, n0, m0, z))
        return self._var_from_m2(wf.func, m2, n)

    def _var_from_m2(self, func, m2, n):
        """var/stddev from Σ(x−μ)² and n — Spark nullOnDivideByZero: samp
        with n<=1 (and anything with n==0) yields NULL; pop w/ n==1 is 0."""
        den = n if func.endswith("pop") else n - 1.0
        ok = den > 0.0
        var = jnp.maximum(m2, 0.0) / jnp.where(ok, den, 1.0)
        res = var if func.startswith("var") else jnp.sqrt(var)
        return res, ok

    def _bounded_rows_frame(self, wf, acc_vals, valid_s, seg, mask_s, cap,
                            is_f, frame):
        """ROWS BETWEEN a PRECEDING AND b FOLLOWING via statically unrolled
        shifted combines (window width is a plan-time constant)."""
        _, a, b = frame
        iota = jnp.arange(cap, dtype=jnp.int32)
        total = jnp.zeros(cap, acc_vals.dtype)
        cnt = jnp.zeros(cap, jnp.int64)
        if is_f:
            mn = jnp.full(cap, jnp.inf)
            mx = jnp.full(cap, -jnp.inf)
            cnt_nonnan = jnp.zeros(cap, jnp.int64)
        else:
            mn = jnp.full(cap, jnp.iinfo(acc_vals.dtype).max, acc_vals.dtype)
            mx = jnp.full(cap, jnp.iinfo(acc_vals.dtype).min, acc_vals.dtype)
        for d in range(-int(a), int(b) + 1):
            idx = iota + d
            inb = (idx >= 0) & (idx < cap)
            safe = jnp.clip(idx, 0, cap - 1)
            ok = inb & (seg[safe] == seg) & mask_s & mask_s[safe] \
                & valid_s[safe]
            v = acc_vals[safe]
            total = total + jnp.where(ok, v, 0)
            cnt = cnt + ok.astype(jnp.int64)
            if is_f:
                nan = jnp.isnan(v)
                mn = jnp.where(ok & ~nan, jnp.minimum(mn, v), mn)
                mx = jnp.where(ok & nan, jnp.nan,
                               jnp.where(ok, jnp.maximum(mx, v), mx))
                cnt_nonnan = cnt_nonnan + (ok & ~nan).astype(jnp.int64)
            else:
                mn = jnp.where(ok, jnp.minimum(mn, v), mn)
                mx = jnp.where(ok, jnp.maximum(mx, v), mx)
        has = cnt > 0
        if wf.func == "count":
            return cnt, jnp.ones(cap, jnp.bool_)
        if wf.func == "sum":
            return total, has
        if wf.func == "avg":
            return (total.astype(jnp.float64)
                    / jnp.maximum(cnt, 1)), has
        if wf.func == "min":
            if is_f:
                # all-NaN window -> NaN (NaN greatest, min only if nothing else)
                only_nan = has & (cnt_nonnan == 0)
                return jnp.where(only_nan, jnp.nan, mn), has
            return mn, has
        if wf.func == "max":
            return mx, has
        # variance: second unrolled pass over deviations from the frame
        # mean — two-pass conditioning, same as the oracle
        mean = total.astype(jnp.float64) / jnp.maximum(cnt, 1)
        m2 = jnp.zeros(cap, jnp.float64)
        for d in range(-int(a), int(b) + 1):
            idx = iota + d
            inb = (idx >= 0) & (idx < cap)
            safe = jnp.clip(idx, 0, cap - 1)
            ok = inb & (seg[safe] == seg) & mask_s & mask_s[safe] \
                & valid_s[safe]
            dev = acc_vals[safe].astype(jnp.float64) - mean
            m2 = m2 + jnp.where(ok, dev * dev, 0.0)
        return self._var_from_m2(wf.func, m2, cnt.astype(jnp.float64))

    def _build_levels(self, base, merge, ident, cap):
        """Doubling level tables: level k at i = merge over [i, i+2^k)
        (identity-padded past the end).  Shared by the block-decomposition
        query and the idempotent sparse-table min/max query."""
        iota32 = jnp.arange(cap, dtype=jnp.int32)
        L = max(1, (cap - 1).bit_length())
        levels = [base]
        for k in range(1, L + 1):
            prev = levels[-1]
            shift = 1 << (k - 1)
            idx = jnp.minimum(iota32 + shift, cap - 1)
            inb = iota32 + shift < cap
            shifted = tuple(
                jnp.where(inb, p[idx], jnp.asarray(iv, p.dtype))
                for p, iv in zip(prev, ident))
            levels.append(merge(prev, shifted))
        return levels, L

    def _range_block_merge(self, base, merge, ident, fs, fe, cap):
        """Aggregate tuples over per-row [fs, fe) ranges via binary block
        decomposition of the range: level-k tables hold the merge of
        [i, i+2^k), and each query greedily consumes the bits of its width
        high-to-low — at most L+1 merges per row, no global prefix sums
        (a single inf/overflow row would poison every later frame through
        prefix-difference cancellation)."""
        levels, L = self._build_levels(base, merge, ident, cap)
        acc = tuple(jnp.full(cap, iv, b.dtype) for b, iv in zip(base, ident))
        pos = fs
        rem = fe - fs
        for k in range(L, -1, -1):
            size = jnp.int32(1 << k)
            take = rem >= size
            at = jnp.clip(pos, 0, cap - 1)
            blk = tuple(lv[at] for lv in levels[k])
            merged = merge(acc, blk)
            acc = tuple(jnp.where(take, m, a) for m, a in zip(merged, acc))
            pos = pos + jnp.where(take, size, 0)
            rem = rem - jnp.where(take, size, 0)
        return acc

    def _bounded_range_frame(self, wf, acc_vals, valid_s, seg, mask_s, cap,
                             is_f, geom):
        """Bounded RANGE frame aggregates over the per-row contiguous
        [fs, fe) ranges: prefix-sum differences for integer sum/count
        (modular wrap cancels exactly), block-decomposed stable merges for
        float sums and variance (Chan's pairwise update), and a doubling
        sparse table for min/max."""
        fs, fe = self._frame_start_end(self.frame, mask_s, seg, cap, geom)
        x = acc_vals

        def pref(arr):
            return jnp.concatenate([jnp.zeros((1,), arr.dtype),
                                    jnp.cumsum(arr)])

        pcnt = pref(valid_s.astype(jnp.int64))
        cnt = pcnt[fe] - pcnt[fs]
        has = cnt > 0
        if wf.func == "count":
            return cnt, jnp.ones(cap, jnp.bool_)
        if wf.func in ("sum", "avg"):
            if x.dtype == jnp.int64:
                psum = pref(jnp.where(valid_s, x, jnp.zeros_like(x)))
                total = psum[fe] - psum[fs]
            else:
                def add(a, b):
                    return (a[0] + b[0],)

                (total,) = self._range_block_merge(
                    (jnp.where(valid_s, x, jnp.zeros_like(x)),),
                    add, (0.0,), fs, fe, cap)
            if wf.func == "sum":
                return total, has
            return total.astype(jnp.float64) / jnp.maximum(cnt, 1), has
        if wf.func in _VAR_FUNCS:
            xf = x.astype(jnp.float64)
            base = (valid_s.astype(jnp.float64),
                    jnp.where(valid_s, xf, 0.0),
                    jnp.zeros(cap, jnp.float64))

            def chan(a, b):
                return _chan_merge(*a, *b)

            n, _, m2 = self._range_block_merge(
                base, chan, (0.0, 0.0, 0.0), fs, fe, cap)
            return self._var_from_m2(wf.func, m2, n)
        # min/max over variable contiguous ranges: sparse table
        want_min = wf.func == "min"
        if is_f:
            nanmask = valid_s & jnp.isnan(x)
            usable = valid_s & ~jnp.isnan(x)
            ident = jnp.inf if want_min else -jnp.inf
            base = jnp.where(usable, x, ident)
            pnan = pref(nanmask.astype(jnp.int64))
            pnonnan = pref(usable.astype(jnp.int64))
        else:
            ident = (jnp.iinfo(x.dtype).max if want_min
                     else jnp.iinfo(x.dtype).min)
            base = jnp.where(valid_s, x, ident)
        combine = jnp.minimum if want_min else jnp.maximum
        levels, L = self._build_levels(
            (base,), lambda a, b: (combine(a[0], b[0]),), (ident,), cap)
        stacked = jnp.stack([lv[0] for lv in levels])  # (L+1, cap)
        w = fe - fs
        k = jnp.zeros(cap, jnp.int32)
        for j in range(1, L + 1):
            k = k + (w >= (1 << j)).astype(jnp.int32)
        span = jnp.left_shift(jnp.int32(1), k)
        i1 = jnp.clip(fs, 0, cap - 1)
        i2 = jnp.clip(fe - span, 0, cap - 1)
        m = combine(stacked[k, i1], stacked[k, i2])
        m = jnp.where(w > 0, m, jnp.asarray(ident, base.dtype))
        if is_f:
            n_nan = pnan[fe] - pnan[fs]
            n_nonnan = pnonnan[fe] - pnonnan[fs]
            if want_min:
                m = jnp.where(has & (n_nonnan == 0), jnp.nan, m)
            else:
                m = jnp.where(n_nan > 0, jnp.nan, m)
        return m, has
