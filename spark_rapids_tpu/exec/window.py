"""TpuWindowExec — window functions via segmented scans.

Reference analog (SURVEY.md §2.4 Window): GpuWindowExec with three
strategies — running window (cumulative batch-streaming), double-pass
cached, and batched bounded-window.  TPU redesign folds the first two into
one jitted program built on `lax.associative_scan` segmented scans:

  * rank/dense_rank/row_number: order-key change flags + segmented cumsum
  * running frames (UNBOUNDED PRECEDING..CURRENT ROW): segmented inclusive
    scans (sum/count/min/max)
  * unbounded frames: segment totals broadcast back
  * bounded row frames: windowed differences of the running scan
    (sum[i] - sum[i-k-1]) — the TPU counterpart of the reference's batched
    bounded-window kernel.

Rows are sorted by (partition keys, order keys), computed, and scattered
back to the original order through the inverse permutation, so output row
order matches the child (Spark's WindowExec contract).
"""
from __future__ import annotations

from typing import Iterator, List, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import DeviceColumn
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.expr.base import EvalContext, Expression
from spark_rapids_tpu.ops import segment as SEG
from spark_rapids_tpu.ops.sortkeys import SortSpec, _column_key_words, pack_sort_keys
from spark_rapids_tpu.plan.nodes import WindowFunction


class TpuWindowExec(TpuExec):
    def __init__(self, functions: List[WindowFunction],
                 partition_by: List[Expression],
                 order_by: List[Tuple[Expression, SortSpec]],
                 child: TpuExec, output_schema: T.StructType,
                 frame: str = "running", ansi: bool = False):
        super().__init__([child])
        self.functions = functions
        self.partition_by = partition_by
        self.order_by = order_by
        self._output = output_schema
        self.frame = frame
        self.ansi = ansi

    @property
    def output(self):
        return self._output

    def describe(self):
        fns = ", ".join(f.func for f in self.functions)
        return f"TpuWindow [{fns}] frame={self.frame}"

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        batches = list(self.children[0].execute_columnar())
        if not batches:
            return
        batch = (batches[0] if len(batches) == 1
                 else ColumnarBatch.concat(batches))
        with self.metrics["opTime"].timed():
            if getattr(self, "_jitted", None) is None:
                self._jitted = jax.jit(self._window_fn)
            cols = self._jitted(tuple(batch.columns),
                                jnp.int32(batch.num_rows))
            out = ColumnarBatch(list(cols), batch.num_rows, self._output)
        yield self._count_output(out)

    def _window_fn(self, cols, num_rows):
        schema = self.children[0].output
        batch = ColumnarBatch(list(cols), num_rows, schema)
        ctx = EvalContext(batch, ansi=self.ansi)
        cap = batch.capacity
        mask = batch.row_mask
        pcols = [e.eval_tpu(ctx) for e in self.partition_by]
        ocols = [e.eval_tpu(ctx) for e, _ in self.order_by]
        ospecs = [s for _, s in self.order_by]
        # sort by (partition, order)
        keys = pack_sort_keys(pcols, [SortSpec()] * len(pcols), mask) if pcols \
            else []
        keys += pack_sort_keys(ocols, ospecs, mask)
        iota = jnp.arange(cap, dtype=jnp.int32)
        if keys:
            perm = jax.lax.sort(tuple(keys) + (iota,), num_keys=len(keys),
                                is_stable=True)[-1]
        else:
            perm = iota
        inv_perm = jnp.zeros(cap, jnp.int32).at[perm].set(iota)
        mask_s = mask[perm]
        # partition-start flags (in sorted order)
        if pcols:
            pwords = []
            for pc in pcols:
                nullbit = jnp.where(pc.validity, 0, 1).astype(jnp.int64)
                pwords.append(nullbit[perm])
                for w in _column_key_words(pc):
                    pwords.append(jnp.where(pc.validity, w, 0)[perm])
            starts = jnp.zeros(cap, jnp.bool_)
            for w in pwords:
                prev = jnp.concatenate([w[:1], w[:-1]])
                starts = starts | (w != prev)
            starts = starts.at[0].set(True)
        else:
            starts = jnp.zeros(cap, jnp.bool_).at[0].set(True)
        seg = jnp.cumsum(starts.astype(jnp.int32)) - 1
        seg = jnp.where(mask_s, seg, cap - 1)
        # order-key change flags (for rank/dense_rank)
        owords = []
        for oc, spec in zip(ocols, ospecs):
            nullbit = jnp.where(oc.validity, 0, 1).astype(jnp.int64)
            owords.append(nullbit[perm])
            for w in _column_key_words(oc):
                owords.append(jnp.where(oc.validity, w, 0)[perm])
        ochange = jnp.zeros(cap, jnp.bool_)
        for w in owords:
            prev = jnp.concatenate([w[:1], w[:-1]])
            ochange = ochange | (w != prev)
        ochange = ochange | starts
        out_cols = list(batch.columns)
        # row position within partition (0-based), in sorted order
        pos_in_part = SEG.seg_scan_sum(
            jnp.ones(cap, jnp.int64), jnp.ones(cap, jnp.bool_), starts)[0] - 1
        for wf in self.functions:
            vals_sorted, valid_sorted = self._one_function(
                wf, ctx, perm, seg, starts, ochange, pos_in_part, mask_s, cap)
            # scatter back to original order
            vals = vals_sorted[inv_perm]
            valid = valid_sorted[inv_perm] & mask
            sdt = T.storage_dtype(wf.result_type)
            out_cols.append(DeviceColumn(wf.result_type, valid,
                                         data=vals.astype(sdt)))
        return tuple(out_cols)

    def _one_function(self, wf: WindowFunction, ctx, perm, seg, starts,
                      ochange, pos_in_part, mask_s, cap):
        ones = jnp.ones(cap, jnp.bool_)
        if wf.func == "row_number":
            return pos_in_part + 1, ones
        if wf.func == "rank":
            # rank = index of last order-change within partition + 1
            anchor = jnp.where(ochange, pos_in_part, jnp.int64(-1))
            last_anchor = SEG.seg_scan_max(
                anchor, ones, starts, is_float=False)[0]
            return last_anchor + 1, ones
        if wf.func == "dense_rank":
            d = SEG.seg_scan_sum(ochange.astype(jnp.int64), ones, starts)[0]
            return d, ones
        c = wf.child.eval_tpu(ctx)
        vals = (c.data if not c.is_string else None)
        if vals is None:
            raise NotImplementedError("string window aggregates")
        vals_s = vals[perm]
        valid_s = (c.validity & ctx.batch.row_mask)[perm]
        is_f = isinstance(wf.result_type, (T.FloatType, T.DoubleType))
        acc_vals = vals_s.astype(jnp.float64 if is_f else jnp.int64)
        if self.frame == "running":
            if wf.func == "count":
                _, cnt = SEG.seg_scan_sum(acc_vals, valid_s, starts)
                return cnt, ones
            if wf.func == "sum":
                s, cnt = SEG.seg_scan_sum(acc_vals, valid_s, starts)
                return s, cnt > 0
            if wf.func == "avg":
                s, cnt = SEG.seg_scan_sum(acc_vals, valid_s, starts)
                return s.astype(jnp.float64) / jnp.maximum(cnt, 1), cnt > 0
            if wf.func == "min":
                return SEG.seg_scan_min(acc_vals, valid_s, starts, is_f)
            if wf.func == "max":
                return SEG.seg_scan_max(acc_vals, valid_s, starts, is_f)
            raise NotImplementedError(wf.func)
        # unbounded frame: segment totals broadcast back via seg gather
        if wf.func == "count":
            cnt = SEG.seg_count(valid_s, seg, cap)
            return cnt[seg], ones
        if wf.func == "sum":
            s, has = SEG.seg_sum(acc_vals, valid_s, seg, cap)
            return s[seg], has[seg]
        if wf.func == "avg":
            s, has = SEG.seg_sum(acc_vals, valid_s, seg, cap)
            cnt = SEG.seg_count(valid_s, seg, cap)
            return (s.astype(jnp.float64) / jnp.maximum(cnt, 1))[seg], has[seg]
        if wf.func == "min":
            m, has = SEG.seg_min(acc_vals, valid_s, seg, cap, is_f)
            return m[seg], has[seg]
        if wf.func == "max":
            m, has = SEG.seg_max(acc_vals, valid_s, seg, cap, is_f)
            return m[seg], has[seg]
        raise NotImplementedError(wf.func)
