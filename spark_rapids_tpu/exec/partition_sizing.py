"""Size-aware exchange partitioning (ISSUE 10) — the planner half of
out-of-core execution.

Reference analog: AQE's coalesce/split of shuffle partitions from map
output statistics (SURVEY §2.4) — except here the FIRST estimate is
plan-static, before a single batch runs: the AOT shape predictor
(``aot_output_rows`` / ``aot_output_caps``, compilecache/aot.py) already
walks row counts and capacities through the plan, and the PR 8
calibration store carries a measured ``rows`` EWMA per (operator,
expression-fingerprint, shape-bucket) that refines the static guess when
profile data exists.

The rule: one exchange partition's working set should fit
``spark.rapids.tpu.exchange.targetPartitionFraction`` of the HBM pool, so

    partitions = clamp(ceil(estimated_bytes / (pool * fraction)),
                       planned, exchange.maxPartitions)

Only ever GROWS the planned count — a dataset far larger than HBM then
streams through the spill-backed exchange partition-by-partition with
each partition's reduce side fitting comfortably on device, while small
inputs keep their planned (often already coalesced) counts.  Sized
exchanges are marked ``_ooc_sized`` so the single-device partition
collapse leaves them alone: with one chip the partitions ARE the
out-of-core schedule, not parallelism.
"""
from __future__ import annotations

import math
from typing import Optional

from spark_rapids_tpu import perfcounters as PC
from spark_rapids_tpu import types as T


def row_width_bytes(schema: T.StructType) -> int:
    """Approximate device bytes one row of this schema occupies:
    storage dtype + 1 validity byte per field; variable-width fields
    (strings/arrays) count their smallest width bucket as a floor — an
    underestimate only makes partitions somewhat larger than the
    target, never incorrect."""
    import numpy as np

    total = 0
    for f in schema.fields:
        dt = f.dataType
        total += 1  # validity
        if isinstance(dt, T.StringType):
            total += 8 + 4          # min chars bucket + lengths(int32)
        elif isinstance(dt, T.ArrayType):
            try:
                total += 8 * np.dtype(
                    T.storage_dtype(dt.elementType)).itemsize + 4
            except TypeError:
                total += 68
        elif isinstance(dt, (T.MapType, T.StructType)):
            total += 16             # children estimated flat elsewhere
        elif isinstance(dt, T.DecimalType) and dt.is_128:
            total += 16
        else:
            try:
                total += np.dtype(T.storage_dtype(dt)).itemsize
            except TypeError:
                total += 8
    return max(total, 1)


def _static_rows(child) -> Optional[int]:
    """Plan-static row estimate: exact when ``aot_output_rows`` is
    derivable (scans and the narrow operators above them)."""
    from spark_rapids_tpu.lifecycle import QueryCancelled

    try:
        fn = getattr(child, "aot_output_rows", None)
        rows = fn() if fn is not None else None
        if rows:
            return int(sum(rows))
    except QueryCancelled:
        raise
    except Exception:
        pass
    return None


def _static_caps(child) -> Optional[int]:
    """Capacity upper bound (aggregates propagate CAPACITY even when
    group counts are data-dependent)."""
    from spark_rapids_tpu.lifecycle import QueryCancelled

    try:
        fn = getattr(child, "aot_output_caps", None)
        caps = fn() if fn is not None else None
        if caps:
            return int(sum(caps))
    except QueryCancelled:
        raise
    except Exception:
        pass
    return None


def _calibrated_rows(child, conf) -> Optional[int]:
    """PR 8 refinement: the calibration store's measured ``rows`` EWMA
    for this operator's (class, expr-fp, bucket) identity, when a store
    exists.  Swallows every failure except cancellation — profiling
    must never fail a plan."""
    from spark_rapids_tpu.lifecycle import QueryCancelled

    try:
        from spark_rapids_tpu.config import PROFILE_DIR, PROFILE_EWMA_ALPHA

        prof_dir = conf.get(PROFILE_DIR)
        if not prof_dir:
            return None
        from spark_rapids_tpu.profiling.store import CalibrationStore
        from spark_rapids_tpu.resilience.domain import _breaker_key_of

        key = _breaker_key_of(child)
        if key is None:
            return None
        op_class, fp = key
        store = CalibrationStore.load_cached(
            prof_dir, alpha=float(conf.get(PROFILE_EWMA_ALPHA)))
        from spark_rapids_tpu.profiling.model import _planned_bucket

        ent, _kind = store.match(op_class, fp, _planned_bucket(child))
        if ent is None:
            return None
        rows = float((ent.get("ewma") or {}).get("rows", 0.0))
        return int(rows) if rows > 0 else None
    except QueryCancelled:
        raise
    except Exception:
        return None


def estimate_input_bytes(child, conf) -> Optional[int]:
    """Estimated bytes the exchange will move: exact static rows win
    (scan-derived counts are the truth), then the calibrated rows EWMA,
    then the capacity upper bound; None when nothing is derivable."""
    rows = _static_rows(child)
    if rows is None:
        rows = _calibrated_rows(child, conf)
    if rows is None:
        rows = _static_caps(child)
    if rows is None:
        return None
    return rows * row_width_bytes(child.output)


def target_partition_bytes(conf) -> int:
    """The per-partition working-set budget: pool * fraction.  Under
    governor YELLOW/RED pressure (ISSUE 13) the budget shrinks by
    ``governor.degradeBatchFraction`` — more, smaller partitions keep
    each reduce step's residency bounded while the pool is contended."""
    from spark_rapids_tpu.config import EXCHANGE_TARGET_PARTITION_FRACTION
    from spark_rapids_tpu.governor import context as _GOV
    from spark_rapids_tpu.memory.device_manager import get_device_manager

    pool = get_device_manager().pool_bytes
    frac = conf.get(EXCHANGE_TARGET_PARTITION_FRACTION)
    target = max(int(pool * frac), 1 << 16)
    gov = _GOV.GOVERNOR
    if gov is not None:
        target = gov.degraded_partition_target(target)
    return target


def choose_partition_count(exchange, conf) -> Optional[int]:
    """The sized partition count for one exchange, or None when the
    planned count should stand (no estimate, or the estimate already
    fits).  Never shrinks the planned count."""
    from spark_rapids_tpu.config import EXCHANGE_MAX_PARTITIONS

    est = estimate_input_bytes(exchange.children[0], conf)
    if est is None:
        return None
    target = target_partition_bytes(conf)
    want = max(int(math.ceil(est / float(target))), 1)
    want = min(want, conf.get(EXCHANGE_MAX_PARTITIONS))
    cur = exchange.num_partitions
    if want <= cur:
        return None
    exchange._ooc_est_bytes = est
    return want


def size_exchange_partitions(node, conf):
    """Plan rewrite (TpuTransitionOverrides): grow hash/round-robin
    exchange partition counts so per-partition working sets fit the
    pool-fraction target.  Returns the (mutated-in-place) node."""
    from spark_rapids_tpu.config import EXCHANGE_SIZED_PARTITIONS
    from spark_rapids_tpu.exec.base import TpuExec
    from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.plan.nodes import (
        HashPartitioning,
        RoundRobinPartitioning,
    )

    if not conf.get(EXCHANGE_SIZED_PARTITIONS):
        return node
    node.children = [size_exchange_partitions(c, conf)
                     if isinstance(c, TpuExec) else c
                     for c in node.children]
    if not (isinstance(node, TpuShuffleExchangeExec)
            and isinstance(node.partitioning,
                           (HashPartitioning, RoundRobinPartitioning))):
        return node
    want = choose_partition_count(node, conf)
    if want is None:
        return node
    prev = node.num_partitions
    node.partitioning.num_partitions = want
    node._ooc_sized = True
    node.sized_decision = (f"sized {prev}->{want} partitions "
                           f"(est {node._ooc_est_bytes}B)")
    PC.bump("exchange_partitions_planned")
    return node
