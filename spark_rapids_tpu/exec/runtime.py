"""The one operator runtime — a single per-batch dispatch loop.

Reference analog: GpuExec's ``internalDoExecuteColumnar`` plus the
wrapper conventions scattered through the reference (NvtxRange,
RmmRapidsRetryIterator, GpuMetric update sites).  Before ISSUE 17 every
``execute_columnar`` was wrapped SIX deep by ``exec/base.py``
(``_cancel_guard(_governor_checkpoint(_progress(_diag(_fault_domain(
_traced(...))))))``) — five delegating generator frames resumed per
batch on every operator edge, each re-checking one ambient slot.  Here
one runtime generator owns the batch loop and dispatches every
registered per-batch concern from the flat :data:`CONCERNS` list; the
fault domain remains the sole inner iterator (it must restart the raw
operator), so the per-batch Python cost drops from eight generator
resumes to three (runtime -> fault domain -> operator).

Each concern keeps its exact pre-unification semantics, pinned by the
existing suites (tests/test_lifecycle.py, test_governor.py,
test_progress.py, test_diagnostics.py, test_resilience.py) plus the
strictly-fewer-calls pin in tests/test_operator_runtime.py:

* ``cancel`` — outermost of all: ONE ambient contextvar check per batch
  pull against the current query's CancelToken.  A tripped token raises
  QueryCancelled / QueryDeadlineExceeded from the pull site BEFORE any
  more work starts, never wrapped in a diagnostics span it would not
  close, and before ``begin_pull`` so the in-flight progress stack
  never holds a pull that was never started (ISSUE 4).
* ``governor`` — after the cancel check, before the progress span: with
  an active governor every batch pull runs one rate-limited pressure
  update and, when THIS query is the armed preemption target, the
  cooperative pause-and-spill.  A pause happens OUTSIDE the progress
  pull span (a paused query is degrading gracefully, not stalled) and
  AFTER the cancel check (a tripped token raises instead of pausing).
  Disabled: one ambient attribute check, zero governor-module calls
  (ISSUE 13).
* ``progress`` — its pull span covers the whole recorded batch,
  retries included; StopIteration closes the span ``finished=True``, an
  escaping exception closes it ``finished=False`` without counting an
  advance (ISSUE 12).  Disabled: one ambient attribute check.
* ``diagnostics`` — the operator span opens INSIDE the progress pull
  and covers the fault domain (retries / fallbacks attribute here);
  ``end_op`` runs on success, StopIteration, and every unwind (ISSUE
  3).  Disabled: one ambient attribute check.
* ``fault_domain`` — the stage-level fault domain
  (resilience/domain.py) drives the operator's raw iterator:
  classification, bounded transient/OOM restarts, runtime CPU
  fallback, breaker recording, chaos hooks.
* ``trace`` — innermost: with ``spark.rapids.profile.enabled`` each
  pull runs under a jax.profiler.TraceAnnotation named after the
  operator; the check happens once per iterator start (so a fault-
  domain restart re-reads it), and the untraced path adds ZERO frames
  (the raw generator is returned as-is, not delegated to).

Docs: docs/whole_plan_fusion.md (the runtime dispatch contract).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Optional

from spark_rapids_tpu.diagnostics import context as _DIAG
from spark_rapids_tpu.governor import context as _GOV
from spark_rapids_tpu.lifecycle.context import CURRENT as _QCTX
from spark_rapids_tpu.progress import context as _PROG


@dataclasses.dataclass(frozen=True)
class Concern:
    """One registered per-batch concern.

    ``ambient`` returns the concern's active ambient state (or None when
    disabled) — the probes the runtime loop calls each batch come FROM
    this registry, so the list is the dispatch order, not documentation.
    ``kind`` is ``"per-pull"`` (probed around every batch pull) or
    ``"iterator"`` (owns/wraps the operator's iterator itself)."""

    name: str
    kind: str
    doc: str
    ambient: Optional[Callable[[], object]] = None


CONCERNS = (
    Concern("cancel", "per-pull",
            "CancelToken check before any per-batch work",
            _QCTX.get),
    Concern("governor", "per-pull",
            "pressure checkpoint + cooperative pause-and-spill",
            lambda: _GOV.GOVERNOR),
    Concern("progress", "per-pull",
            "live pull span: begin_pull/end_pull around the batch",
            lambda: _PROG.TRACKER),
    Concern("diagnostics", "per-pull",
            "operator span + attribution slot for the whole pull",
            lambda: _DIAG.RECORDER),
    Concern("fault_domain", "iterator",
            "classification / retries / CPU fallback / breaker"),
    Concern("trace", "iterator",
            "jax.profiler.TraceAnnotation per pull when enabled"),
)

# the runtime loop's probes, bound once from the registry: dispatch
# order IS the tuple order above (pinned by tests/test_operator_runtime)
_AMBIENT_CANCEL = CONCERNS[0].ambient
_AMBIENT_GOVERNOR = CONCERNS[1].ambient
_AMBIENT_PROGRESS = CONCERNS[2].ambient
_AMBIENT_DIAGNOSTICS = CONCERNS[3].ambient


def _trace_pulls(op, raw_fn, a, kw):
    """The enabled-trace inner iterator: each pull of the operator's raw
    generator runs under a TraceAnnotation (NvtxRange analog)."""
    import jax.profiler

    it = raw_fn(op, *a, **kw)
    name = op.node_name
    try:
        while True:
            with jax.profiler.TraceAnnotation(name):
                try:
                    b = next(it)
                except StopIteration:
                    return
            yield b
    finally:
        close = getattr(it, "close", None)
        if close is not None:  # the raw iterator need not be a generator
            close()


def _traced_start(raw_fn):
    """The ``trace`` concern: returns the function the fault domain
    (re)starts.  Untraced operators get the RAW generator — no
    delegating frame — and the ``_trace_on`` flag is re-read on every
    (re)start, matching the pre-unification wrapper."""

    def start(op, *a, **kw):
        if getattr(op, "_trace_on", False):
            return _trace_pulls(op, raw_fn, a, kw)
        return raw_fn(op, *a, **kw)

    return start


def make_operator_runtime(raw_fn):
    """Wrap a subclass's raw ``execute_columnar`` in the unified
    runtime (installed by ``TpuExec.__init_subclass__``)."""
    inner_fn = _traced_start(raw_fn)

    @functools.wraps(raw_fn)
    def execute_columnar(self, *a, **kw):
        from spark_rapids_tpu.resilience.domain import run_fault_domain

        it = run_fault_domain(self, inner_fn, a, kw)
        try:
            while True:
                # -- per-pull concerns, in CONCERNS order ------------
                ctx = _AMBIENT_CANCEL()
                if ctx is not None:
                    ctx.token.check()
                gov = _AMBIENT_GOVERNOR()
                if gov is not None:
                    gov.batch_pull_checkpoint()
                trk = _AMBIENT_PROGRESS()
                rec = _AMBIENT_DIAGNOSTICS()
                if trk is None and rec is None:
                    # disabled fast path: four ambient checks, one pull
                    try:
                        b = next(it)
                    except StopIteration:
                        return
                    yield b
                    continue
                h = trk.begin_pull(self) if trk is not None else None
                span = rec.begin_op(self) if rec is not None else None
                rows = None
                done = False
                b = None
                try:
                    try:
                        try:
                            b = next(it)
                            rows = b.num_rows
                        except StopIteration:
                            done = True
                    finally:
                        # the diagnostics span closes FIRST (it opened
                        # last), on success, exhaustion, and unwind
                        if span is not None:
                            path, token, t0 = span
                            rec.end_op(path, token, t0, rows)
                except BaseException:
                    # the pull died (cancel trip, operator failure):
                    # close the in-flight progress entry without
                    # counting an advance, then let the unwind proceed
                    if h is not None:
                        trk.end_pull(h, None, 0, finished=False)
                    raise
                if done:
                    if h is not None:
                        trk.end_pull(h, None, 0, finished=True)
                    return
                if h is not None:
                    trk.end_pull(h, rows, b.nbytes(), finished=False)
                yield b
        finally:
            it.close()

    return execute_columnar
