"""TpuShuffleExchangeExec — partitioning + shuffle boundary.

Reference analog: GpuShuffleExchangeExecBase + GpuPartitioning
(SURVEY.md §2.4 Exchange, §2.7): slices each batch by partition id and hands
the slices to the shuffle manager.  Partition ids are Spark-exact
(murmur3-based pmod — ops/hashing.py) so a TPU stage can interoperate with
CPU stages, exactly as the reference's GpuHashPartitioning matches Spark's
Murmur3 partitioning.

In-process execution pushes slices through the shuffle manager
(shuffle/manager.py) which serializes batches in the concat-friendly layout
(Kudo analog) or keeps them device-resident; on a mesh the ICI mode turns
this into an XLA all-to-all (parallel/).
"""
from __future__ import annotations

import time
from typing import Iterator, List, Tuple

import jax
from spark_rapids_tpu import perfcounters as PC
from spark_rapids_tpu.perfcounters import tpu_jit
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.expr.base import EvalContext
from spark_rapids_tpu.ops.filterops import compact_columns
from spark_rapids_tpu.ops.hashing import spark_partition_ids
from spark_rapids_tpu.plan.nodes import (
    HashPartitioning,
    RangePartitioning,
    RoundRobinPartitioning,
    SinglePartitioning,
)


class TpuShuffleExchangeExec(TpuExec):
    # GpuShuffleExchangeExec write/fetch metric pair, plus the ISSUE 10
    # decomposition: wall inside the partition-id/slice programs vs wall
    # inside the spill-backed queue (serialize/track/materialize)
    EXTRA_METRICS = {"shuffleWriteTime": "MODERATE",
                     "shuffleReadTime": "MODERATE",
                     "exchangePartitionTime": "MODERATE",
                     "exchangeSpillTime": "MODERATE"}

    def __init__(self, partitioning, child: TpuExec, ansi: bool = False,
                 conf=None):
        super().__init__([child])
        self.partitioning = partitioning
        self.ansi = ansi
        self.conf = conf

    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        d = getattr(self, "sized_decision", None)
        return (f"TpuShuffleExchange {self.partitioning.describe()}"
                + (f" [{d}]" if d else ""))

    @property
    def num_partitions(self) -> int:
        return getattr(self.partitioning, "num_partitions", 1)

    def aot_output_rows(self):
        # single partition = identity pipe over the child; hash/rr/range
        # partition splits are data-dependent
        if isinstance(self.partitioning, SinglePartitioning) \
                or self.num_partitions == 1:
            return self.aot_input_rows()
        return None

    def aot_output_caps(self):
        if isinstance(self.partitioning, SinglePartitioning) \
                or self.num_partitions == 1:
            return self.aot_input_caps()
        return None

    def aot_emits_single_batch(self):
        return (isinstance(self.partitioning, SinglePartitioning)
                or self.num_partitions == 1) \
            and self.aot_child_single_batch()

    def _registry_scope(self, kind: str):
        from spark_rapids_tpu.compilecache.keys import (
            conf_fp,
            exprs_fp,
            schema_fp,
        )

        p = self.partitioning
        if isinstance(p, HashPartitioning):
            efp = exprs_fp(p.keys)
        elif isinstance(p, RangePartitioning):
            efp = exprs_fp([e for e, _ in p.orders])
            if efp is not None:
                efp = efp + tuple((s.ascending, s.nulls_first)
                                  for _, s in p.orders)
        else:
            efp = ()
        if efp is None:
            return None
        return ("exchange", kind, type(p).__name__, efp,
                self.num_partitions, schema_fp(self.output),
                bool(self.ansi), conf_fp())

    def _cached_jit(self, attr: str, kind: str, builder):
        jitted = getattr(self, attr, None)
        if jitted is None:
            from spark_rapids_tpu.compilecache.registry import (
                cached_jit_program,
            )

            jitted = cached_jit_program(self._registry_scope(kind),
                                        builder, label=f"exchange:{kind}")
            setattr(self, attr, jitted)
        return jitted

    def partition_batch(self, batch: ColumnarBatch) -> List[ColumnarBatch]:
        """Every partition slice of one batch as a list (the legacy
        shuffle-manager contract: index == pid, empties included)."""
        return [sl for _, sl in self.partition_slices(batch)]

    def partition_slices(
            self, batch: ColumnarBatch
    ) -> Iterator[Tuple[int, ColumnarBatch]]:
        """Slice one batch into per-partition batches, LAZILY — yielded
        one (pid, slice) at a time in pid order so the consumer can
        serialize/spill each slice before the next materializes instead
        of holding every output slice live at once (ISSUE 10).

        Reference analog: GpuPartitioning.sliceInternalGpuOrCpu."""
        p = self.partitioning
        if isinstance(p, SinglePartitioning) or self.num_partitions == 1:
            yield 0, batch
            return
        t0 = time.perf_counter_ns()
        if isinstance(p, HashPartitioning):
            ids = self._hash_ids(batch)
        elif isinstance(p, RoundRobinPartitioning):
            ids = (jnp.arange(batch.capacity, dtype=jnp.int32)
                   % self.num_partitions)
        elif isinstance(p, RangePartitioning):
            ids = self._range_ids(batch)
        else:
            raise NotImplementedError(type(p).__name__)
        # ONE device program: stable-sort rows by partition id; each
        # partition is then a contiguous range (searchsorted bounds since
        # ids are sorted).  One host sync for the boundary vector instead of
        # num_partitions sequential compactions (VERDICT r1 weak #4).
        n_parts = self.num_partitions
        schema = batch.schema   # capture only the schema, not the batch

        def sort_fn(cols, ids, num_rows):
            b = ColumnarBatch(list(cols), num_rows, schema)
            cap = b.capacity
            key = jnp.where(b.row_mask, ids.astype(jnp.int32), n_parts)
            perm = jax.lax.sort(
                (key, jnp.arange(cap, dtype=jnp.int32)),
                num_keys=1, is_stable=True)[1]
            from spark_rapids_tpu.ops.filterops import gather_columns

            sorted_cols = gather_columns(perm, b.row_mask[perm], b.columns)
            sorted_key = key[perm]
            bounds = jnp.searchsorted(
                sorted_key, jnp.arange(n_parts + 1, dtype=jnp.int32),
                side="left").astype(jnp.int32)
            return tuple(sorted_cols), bounds

        cols, bounds = self._cached_jit("_sort_jit", "partsort", sort_fn)(
            tuple(batch.columns), ids, jnp.int32(batch.num_rows))
        import numpy as _np

        bounds_np = _np.asarray(bounds).tolist()   # one transfer
        dt = time.perf_counter_ns() - t0
        PC.bump("exchange_partition_ns", dt)
        self.metric("exchangePartitionTime").add(dt)
        sorted_batch = ColumnarBatch(list(cols), batch.num_rows, schema)
        for pid in range(n_parts):
            lo, hi = bounds_np[pid], bounds_np[pid + 1]
            yield pid, (sorted_batch.slice_rows(lo, hi - lo)
                        if hi > lo else
                        ColumnarBatch([c.slice_to(1) for c in cols], 0,
                                      batch.schema))

    def _hash_ids(self, batch: ColumnarBatch):
        schema = batch.schema
        keys, n_parts, ansi = (self.partitioning.keys,
                               self.num_partitions, self.ansi)

        def fn(cols, num_rows):
            b = ColumnarBatch(list(cols), num_rows, schema)
            ctx = EvalContext(b, ansi=ansi)
            key_cols = [k.eval_tpu(ctx) for k in keys]
            return spark_partition_ids(key_cols, n_parts)

        return self._cached_jit("_ids_jit", "hashids", fn)(
            tuple(batch.columns), jnp.int32(batch.num_rows))

    def _range_ids(self, batch: ColumnarBatch):
        """Range partitioning via sampled bounds (GpuRangePartitioner).

        Round-1 simplification: bounds from this batch's sorted sample."""
        from spark_rapids_tpu.ops.sortkeys import sort_permutation

        orders = self.partitioning.orders

        schema = batch.schema
        n_parts, ansi = self.num_partitions, self.ansi

        def fn(cols, num_rows):
            b = ColumnarBatch(list(cols), num_rows, schema)
            ctx = EvalContext(b, ansi=ansi)
            key_cols = [e.eval_tpu(ctx) for e, _ in orders]
            specs = [s for _, s in orders]
            perm = sort_permutation(key_cols, specs, b.row_mask)
            # rank of each row / rows-per-partition
            cap = b.capacity
            inv = jnp.zeros(cap, jnp.int32).at[perm].set(
                jnp.arange(cap, dtype=jnp.int32))
            per = jnp.maximum(
                (num_rows + n_parts - 1) // n_parts, 1)
            return jnp.clip(inv // per, 0, n_parts - 1)

        return self._cached_jit("_range_jit", "rangeids", fn)(
            tuple(batch.columns), jnp.int32(batch.num_rows))

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        """Shuffle the input, partition boundaries preserved in output
        order so downstream per-partition operators see real reduce
        partitions.

        Default path (ISSUE 10): partition slices stream through
        spill-backed partition queues — device residency bounded by the
        queue budget + the SpillFramework pool, host-boundary blocks
        CRC-framed — so an exchange input far larger than HBM completes
        instead of materializing whole.  Legacy path
        (exchange.spill.enabled=false or CACHE_ONLY mode): the shuffle
        manager, each input batch a "map task" whose slices are written
        (serialized in MULTITHREADED mode — the Kudo wire-format path)
        and each reduce partition assembled by the concat-friendly
        reader."""
        from spark_rapids_tpu.config import (
            DISTRIBUTED_ENABLED,
            EXCHANGE_SPILL_ENABLED,
            SHUFFLE_MODE,
            get_conf,
        )
        from spark_rapids_tpu.plan.nodes import SinglePartitioning
        from spark_rapids_tpu.shuffle.manager import get_shuffle_manager

        if isinstance(self.partitioning, SinglePartitioning):
            # device-resident pipe: a single reduce partition receives every
            # map output in order, so the exchange is an identity over the
            # child's batches — no serialize/deserialize round trip (the
            # degenerate case of ICI shuffle mode 2's device-resident design)
            for b in self.children[0].execute_columnar():
                yield self._count_output(b)
            return
        c = self.conf if self.conf is not None else get_conf()
        # Crash-consistent recovery (ISSUE 16, docs/recovery.md): with
        # recovery on, this stage boundary is a durable checkpoint —
        # serve a prior incarnation's committed output instead of
        # re-executing the child, and commit this incarnation's output
        # once the write phase lands.  Off (default): one conf read,
        # zero journal-module calls (cProfile-pinned by
        # tests/test_recovery.py).
        ckpt = None
        from spark_rapids_tpu.config import RECOVERY_ENABLED

        if bool(c.get(RECOVERY_ENABLED)):
            ckpt = self._recovery_ckpt(c)
            if ckpt is not None:
                served = self._serve_recovered(c, *ckpt)
                if served is not None:
                    yield from served
                    return
        if c.get(DISTRIBUTED_ENABLED):
            # cross-host tier (ISSUE 14): route reduce partitions over
            # the worker processes when a coordinator with placeable
            # workers exists; otherwise fall through to the in-process
            # paths (elastic membership — zero workers is a valid state
            # between queries, not an error)
            from spark_rapids_tpu.distributed import peek_coordinator

            coord = peek_coordinator()
            if coord is not None and coord.placeable_workers():
                yield from self._execute_distributed(c, coord, ckpt)
                return
        if c.get(EXCHANGE_SPILL_ENABLED) \
                and str(c.get(SHUFFLE_MODE)).upper() != "CACHE_ONLY":
            yield from self._execute_spill_backed(c, ckpt)
            return
        mgr = get_shuffle_manager(self.conf)
        shuffle_id = mgr.register_shuffle()
        try:
            with self.metric("shuffleWriteTime").timed():
                for map_id, b in enumerate(
                        self.children[0].execute_columnar()):
                    mgr.write_map_output(shuffle_id, map_id,
                                         self.partition_batch(b))
            schema = self.output
            from spark_rapids_tpu.lifecycle.context import check_cancel

            for pid in range(self.num_partitions):
                # cooperative cancellation between reduce partitions: a
                # wide shuffle read must not outlive its query's deadline
                check_cancel()
                with self.metric("shuffleReadTime").timed():
                    out = mgr.read_partition(shuffle_id, pid, schema)
                if out is not None and out.num_rows > 0:
                    yield self._count_output(out)
        finally:
            mgr.unregister_shuffle(shuffle_id)

    # -- crash-consistent recovery (ISSUE 16) ---------------------------
    def _recovery_ckpt(self, c):
        """(journal, plan-stage fingerprint) for this exchange, or None
        when recovery cannot apply: unsafe partitioning exprs (no
        stable fingerprint) or a journal root that cannot open.  The
        fingerprint extends the compile-registry scope with the CHILD
        SUBTREE's plan identity — two exchanges with identical
        partitioning + output schema but different children must never
        trade checkpoints."""
        from spark_rapids_tpu.lifecycle import journal as _jn

        scope = self._registry_scope("ckpt")
        if scope is None:
            return None
        from spark_rapids_tpu.compilecache.keys import fingerprint

        fp = fingerprint(scope, _jn.plan_tree_fp(self.children[0]))
        try:
            return _jn.get_journal(c), fp
        # tpulint: disable=cancel-swallow (durability isolation: an
        # unopenable journal disables recovery for this query, never
        # fails it)
        except Exception:
            return None

    def _serve_recovered(self, c, jn, fp):
        """A generator over a prior incarnation's committed output for
        this stage, or None (no adoptable checkpoint — execute
        normally).  Local checkpoints are fully CRC-validated before
        the first yield; lease serves stream from the re-attached
        workers (a worker dying mid-serve raises WorkerLost into the
        fault domain like any distributed read)."""
        from spark_rapids_tpu.shuffle.partition_queues import (
            host_boundary_codec,
        )

        hit = jn.lookup_stage(fp)
        if hit is None:
            return None
        from spark_rapids_tpu.lifecycle.context import current

        ctx = current()
        qid = ctx.query_id if ctx is not None else "-"
        codec = host_boundary_codec(c)
        if hit[0] == "local":
            return self._gen_recovered_local(jn, fp, qid, codec, hit[1])
        _, wire, _placement, counts = hit
        return self._gen_recovered_lease(c, jn, fp, qid, codec, wire,
                                         counts)

    def _gen_recovered_local(self, jn, fp, qid, codec, parts):
        from spark_rapids_tpu.shuffle.serializer import deserialize_concat

        for pid in range(self.num_partitions):
            blobs = parts.get(pid) or []
            if not blobs:
                continue
            with self.metric("shuffleReadTime").timed():
                out = deserialize_concat(blobs, self.output, codec=codec)
            if out.num_rows > 0:
                yield self._count_output(out)
        jn.mark_recovered(fp, qid, len(parts))

    def _gen_recovered_lease(self, c, jn, fp, qid, codec, wire, counts):
        from spark_rapids_tpu.config import BATCH_SIZE_BYTES
        from spark_rapids_tpu.distributed import (
            ProtocolCorruption,
            peek_coordinator,
        )
        from spark_rapids_tpu.lifecycle.context import check_cancel
        from spark_rapids_tpu.shuffle.serializer import deserialize_concat

        coord = peek_coordinator()
        goal = int(c.get(BATCH_SIZE_BYTES))
        try:
            for pid in sorted(counts):
                check_cancel()
                expected = counts[pid]
                next_seq = 0
                while next_seq < expected:
                    with self.metric("shuffleReadTime").timed():
                        seqs, blobs, _n = coord.fetch_blocks(
                            wire, pid, after_seq=next_seq - 1,
                            max_bytes=goal)
                    if not seqs:
                        raise ProtocolCorruption(
                            f"recovered stage {fp}: worker returned no "
                            f"blocks for pid {pid} at seq "
                            f"{next_seq}/{expected}")
                    next_seq = seqs[-1] + 1
                    out = deserialize_concat(blobs, self.output,
                                             codec=codec)
                    if out.num_rows > 0:
                        yield self._count_output(out)
        finally:
            # adopted placements must not outlive the serve — release
            # on success AND on unwind (a failed serve re-executes; the
            # workers' copies are no longer adoptable either way)
            coord.release_exchange(wire)
        jn.mark_recovered(fp, qid, len(counts))

    def _commit_stage(self, ckpt, commit_fn) -> None:
        """Run one checkpoint commit, isolating durability failures
        from the query (a stage that cannot commit simply is not
        recoverable)."""
        from spark_rapids_tpu.lifecycle import QueryCancelled

        try:
            commit_fn()
        except QueryCancelled:
            raise
        # tpulint: disable=cancel-swallow (durability isolation: a
        # failed checkpoint commit must never fail the query)
        except Exception:
            pass

    def _execute_distributed(self, c, coord,
                             ckpt=None) -> Iterator[ColumnarBatch]:
        """Cross-host execution (ISSUE 14): partition slices are framed
        once (TKU2), shipped to coordinator-placed worker processes,
        AND retained in a producer-side spill-backed queue (device
        budget 0 — every entry a wire block) until the consuming side
        commits each partition.  A worker lost mid-shuffle is recovered
        by re-placement + re-drive of the retained blocks; the shuffle
        manager registration ties remote holdings to this query, so the
        query-end cleanup sweep releases them even on a mid-batch
        unwind."""
        from spark_rapids_tpu.config import (
            BATCH_SIZE_BYTES,
            DISTRIBUTED_REDRIVE_MAX,
            SPILL_DIR,
        )
        from spark_rapids_tpu.distributed.client import DistributedExchange
        from spark_rapids_tpu.exec.partition_sizing import (
            estimate_input_bytes,
        )
        from spark_rapids_tpu.lifecycle import QueryCancelled
        from spark_rapids_tpu.lifecycle.context import check_cancel
        from spark_rapids_tpu.shuffle.manager import get_shuffle_manager
        from spark_rapids_tpu.shuffle.partition_queues import (
            SpillBackedPartitionQueues,
            host_boundary_codec,
        )

        mgr = get_shuffle_manager(self.conf)
        exch_id = mgr.register_shuffle()
        # everything fallible — incl. placement inside
        # DistributedExchange.__init__, which raises WorkerLost when the
        # last placeable worker died since the execute_columnar check —
        # sits inside the try so the finally always unregisters the
        # shuffle id and closes whatever was built
        queues = None
        dist = None
        try:
            try:
                est = estimate_input_bytes(self.children[0], c)
            except QueryCancelled:
                raise
            except Exception:
                est = None
            # lineage buffer: device budget 0 (every entry a wire
            # block), host residency bounded by the shuffle host-store
            # limit with disk overflow — retaining a whole exchange
            # until its partitions commit must not pin the driver's RAM
            from spark_rapids_tpu.shuffle.manager import (
                SHUFFLE_HOST_STORE_LIMIT,
            )

            queues = SpillBackedPartitionQueues(
                self.num_partitions, self.output, device_budget=0,
                codec=host_boundary_codec(c),
                host_budget=int(c.get(SHUFFLE_HOST_STORE_LIMIT)),
                spill_dir=c.get(SPILL_DIR))
            dist = DistributedExchange(
                coord, exch_id, self.num_partitions, self.output,
                host_boundary_codec(c), queues, est_bytes=est,
                redrive_max_attempts=int(c.get(DISTRIBUTED_REDRIVE_MAX)))
            goal = int(c.get(BATCH_SIZE_BYTES))
            from spark_rapids_tpu.governor import context as _GOV

            _gov = _GOV.GOVERNOR
            if _gov is not None:
                goal = _gov.degraded_goal(goal)
            with self.metric("shuffleWriteTime").timed():
                for b in self.children[0].execute_columnar():
                    for pid, sl in self.partition_slices(b):
                        with self.metric("exchangeSpillTime").timed():
                            dist.add_slice(pid, sl)
            if ckpt is not None:
                # stage boundary reached: the worker-held partitions
                # ARE the checkpoint — journal a lease pinning them
                # past driver death (ISSUE 16).  The read phase below
                # does not release worker copies (only dist.close()
                # does), so a driver killed ANY time after this record
                # finds the full inventory on re-attach
                jn, fp = ckpt
                from spark_rapids_tpu.lifecycle.context import current

                _ctx = current()
                self._commit_stage(ckpt, lambda: jn.commit_lease(
                    fp, _ctx.query_id if _ctx is not None else "-",
                    coord.wire_of(exch_id), coord.placement_of(exch_id),
                    dist.block_counts()))
            for pid in range(self.num_partitions):
                check_cancel()
                it = dist.read_partition_chunks(pid, target_bytes=goal)
                while True:
                    with self.metric("shuffleReadTime").timed():
                        out = next(it, None)
                    if out is None:
                        break
                    if out.num_rows > 0:
                        yield self._count_output(out)
        finally:
            if dist is not None:
                dist.close()
            elif queues is not None:
                queues.close()
            mgr.unregister_shuffle(exch_id)

    def _execute_spill_backed(self, c,
                              ckpt=None) -> Iterator[ColumnarBatch]:
        """Stream partition slices through spill-backed queues: per
        input batch ONE partition program, each slice registered (or
        CRC-framed to host past the device budget) before the next
        materializes; reduce partitions drain in pid order — in
        batch-size-goal CHUNKS, never one whole-partition concat (a
        partition larger than the pool would re-materialize as a single
        unspillable batch and bust the residency bound) — released as
        they are read.  CancelToken observed at every append/read."""
        from spark_rapids_tpu.config import BATCH_SIZE_BYTES
        from spark_rapids_tpu.shuffle.partition_queues import (
            SpillBackedPartitionQueues,
            host_boundary_codec,
            queue_device_budget,
        )

        queues = SpillBackedPartitionQueues(
            self.num_partitions, self.output, queue_device_budget(c),
            codec=host_boundary_codec(c))
        goal = int(c.get(BATCH_SIZE_BYTES))
        # overload governor (ISSUE 13): under YELLOW/RED the drain
        # chunks shrink so each reduce step pins a smaller working set
        from spark_rapids_tpu.governor import context as _GOV

        _gov = _GOV.GOVERNOR
        if _gov is not None:
            goal = _gov.degraded_goal(goal)
        try:
            with self.metric("shuffleWriteTime").timed():
                for b in self.children[0].execute_columnar():
                    for pid, sl in self.partition_slices(b):
                        with self.metric("exchangeSpillTime").timed():
                            queues.append(pid, sl)
            if ckpt is not None:
                # stage boundary reached: snapshot every partition as
                # framed blobs and commit durably (atomic tmp+rename +
                # journal record) BEFORE the read phase drains the
                # queues — a driver killed past this point resumes by
                # serving the checkpoint instead of re-executing the
                # child (ISSUE 16)
                jn, fp = ckpt
                from spark_rapids_tpu.lifecycle.context import current

                _ctx = current()
                self._commit_stage(ckpt, lambda: jn.commit_local_stage(
                    fp, _ctx.query_id if _ctx is not None else "-",
                    {pid: queues.snapshot_framed(pid)
                     for pid in range(self.num_partitions)}))
            for pid in range(self.num_partitions):
                it = queues.read_chunks(pid, target_bytes=goal)
                while True:
                    with self.metric("shuffleReadTime").timed(), \
                            self.metric("exchangeSpillTime").timed():
                        out = next(it, None)
                    if out is None:
                        break
                    if out.num_rows > 0:
                        yield self._count_output(out)
        finally:
            queues.close()


class TpuBroadcastExchangeExec(TpuExec):
    """GpuBroadcastExchangeExec analog: materialize + (on mesh) replicate."""

    def __init__(self, child: TpuExec):
        super().__init__([child])

    @property
    def output(self):
        return self.children[0].output

    def aot_output_rows(self):
        rows = self.aot_input_rows()
        return None if rows is None else [sum(rows)]

    def aot_output_caps(self):
        caps = super().aot_output_caps()
        return caps if caps is not None else self.aot_input_concat_caps()

    def aot_emits_single_batch(self):
        return True

    def execute_columnar(self):
        batches = list(self.children[0].execute_columnar())
        if not batches:
            return
        out = (batches[0] if len(batches) == 1
               else ColumnarBatch.concat(batches))
        yield self._count_output(out)


class TpuAdaptiveShuffleReaderExec(TpuExec):
    """GpuCustomShuffleReaderExec analog (general AQE, VERDICT r3 Next
    #8): reads an exchange's reduce partitions while RECORDING their
    measured rows/bytes, then coalesces ADJACENT SMALL partitions
    (below ``spark.rapids.tpu.exchange.coalesceSmallPartitionBytes``)
    into one read window up to the batch-size goal before emitting —
    the runtime-stats partition coalescing AQE performs on real
    clusters (SURVEY §2.4; fewer, right-sized batches for every
    downstream operator; on a compile-tunnel chip each elided partition
    is one fewer program launch).  Partitions at or above the small
    threshold emit alone (an already-right-sized partition must not
    drag its neighbors into a doubled window).  Each window of k>1
    partitions bumps ``partitions_coalesced`` by k-1.

    ``stats`` (per-partition (rows, bytes)) and ``decision`` are exposed
    for explain/metrics, mirroring TpuAdaptiveJoinExec."""

    EXTRA_METRICS = {"partitionsCoalesced": "MODERATE"}

    def __init__(self, exchange: TpuShuffleExchangeExec,
                 target_bytes: int, small_bytes: int = 4 << 20):
        super().__init__([exchange])
        self.target_bytes = target_bytes
        self.small_bytes = small_bytes
        self.stats = []
        self.decision = None

    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        d = f" decided={self.decision}" if self.decision else ""
        return (f"TpuAdaptiveShuffleReader(target="
                f"{self.target_bytes}B small={self.small_bytes}B){d}")

    def _flush(self, pending):
        from spark_rapids_tpu.columnar.batch import ColumnarBatch

        if len(pending) > 1:
            PC.bump("partitions_coalesced", len(pending) - 1)
            self.metric("partitionsCoalesced").add(len(pending) - 1)
        return (pending[0] if len(pending) == 1
                else ColumnarBatch.concat(pending))

    def execute_columnar(self):
        pending = []
        pending_bytes = 0
        n_in = 0
        n_out = 0
        for b in self.children[0].execute_columnar():
            n_in += 1
            nb = b.nbytes()
            self.stats.append((b.num_rows, nb))
            if nb >= self.small_bytes:
                # right-sized already: flush the open window, emit alone
                if pending:
                    n_out += 1
                    out = self._flush(pending)
                    pending, pending_bytes = [], 0
                    yield self._count_output(out)
                n_out += 1
                yield self._count_output(b)
                continue
            if pending and pending_bytes + nb > self.target_bytes:
                n_out += 1
                out = self._flush(pending)
                pending, pending_bytes = [], 0
                yield self._count_output(out)
            pending.append(b)
            pending_bytes += nb
            if pending_bytes >= self.target_bytes:
                n_out += 1
                out = self._flush(pending)
                pending, pending_bytes = [], 0
                yield self._count_output(out)
        if pending:
            n_out += 1
            yield self._count_output(self._flush(pending))
        self.decision = f"coalesced {n_in}->{n_out} partitions"
