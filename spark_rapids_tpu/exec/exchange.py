"""TpuShuffleExchangeExec — partitioning + shuffle boundary.

Reference analog: GpuShuffleExchangeExecBase + GpuPartitioning
(SURVEY.md §2.4 Exchange, §2.7): slices each batch by partition id and hands
the slices to the shuffle manager.  Partition ids are Spark-exact
(murmur3-based pmod — ops/hashing.py) so a TPU stage can interoperate with
CPU stages, exactly as the reference's GpuHashPartitioning matches Spark's
Murmur3 partitioning.

In-process execution pushes slices through the shuffle manager
(shuffle/manager.py) which serializes batches in the concat-friendly layout
(Kudo analog) or keeps them device-resident; on a mesh the ICI mode turns
this into an XLA all-to-all (parallel/).
"""
from __future__ import annotations

from typing import Iterator, List

import jax
from spark_rapids_tpu.perfcounters import tpu_jit
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.expr.base import EvalContext
from spark_rapids_tpu.ops.filterops import compact_columns
from spark_rapids_tpu.ops.hashing import spark_partition_ids
from spark_rapids_tpu.plan.nodes import (
    HashPartitioning,
    RangePartitioning,
    RoundRobinPartitioning,
    SinglePartitioning,
)


class TpuShuffleExchangeExec(TpuExec):
    # GpuShuffleExchangeExec write/fetch metric pair
    EXTRA_METRICS = {"shuffleWriteTime": "MODERATE",
                     "shuffleReadTime": "MODERATE"}

    def __init__(self, partitioning, child: TpuExec, ansi: bool = False,
                 conf=None):
        super().__init__([child])
        self.partitioning = partitioning
        self.ansi = ansi
        self.conf = conf

    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        return f"TpuShuffleExchange {self.partitioning.describe()}"

    @property
    def num_partitions(self) -> int:
        return getattr(self.partitioning, "num_partitions", 1)

    def aot_output_rows(self):
        # single partition = identity pipe over the child; hash/rr/range
        # partition splits are data-dependent
        if isinstance(self.partitioning, SinglePartitioning) \
                or self.num_partitions == 1:
            return self.aot_input_rows()
        return None

    def aot_output_caps(self):
        if isinstance(self.partitioning, SinglePartitioning) \
                or self.num_partitions == 1:
            return self.aot_input_caps()
        return None

    def aot_emits_single_batch(self):
        return (isinstance(self.partitioning, SinglePartitioning)
                or self.num_partitions == 1) \
            and self.aot_child_single_batch()

    def _registry_scope(self, kind: str):
        from spark_rapids_tpu.compilecache.keys import (
            conf_fp,
            exprs_fp,
            schema_fp,
        )

        p = self.partitioning
        if isinstance(p, HashPartitioning):
            efp = exprs_fp(p.keys)
        elif isinstance(p, RangePartitioning):
            efp = exprs_fp([e for e, _ in p.orders])
            if efp is not None:
                efp = efp + tuple((s.ascending, s.nulls_first)
                                  for _, s in p.orders)
        else:
            efp = ()
        if efp is None:
            return None
        return ("exchange", kind, type(p).__name__, efp,
                self.num_partitions, schema_fp(self.output),
                bool(self.ansi), conf_fp())

    def _cached_jit(self, attr: str, kind: str, builder):
        jitted = getattr(self, attr, None)
        if jitted is None:
            from spark_rapids_tpu.compilecache.registry import (
                cached_jit_program,
            )

            jitted = cached_jit_program(self._registry_scope(kind),
                                        builder, label=f"exchange:{kind}")
            setattr(self, attr, jitted)
        return jitted

    def partition_batch(self, batch: ColumnarBatch) -> List[ColumnarBatch]:
        """Slice one batch into per-partition batches (device-resident).

        Reference analog: GpuPartitioning.sliceInternalGpuOrCpu."""
        p = self.partitioning
        if isinstance(p, SinglePartitioning) or self.num_partitions == 1:
            return [batch]
        if isinstance(p, HashPartitioning):
            ids = self._hash_ids(batch)
        elif isinstance(p, RoundRobinPartitioning):
            ids = (jnp.arange(batch.capacity, dtype=jnp.int32)
                   % self.num_partitions)
        elif isinstance(p, RangePartitioning):
            ids = self._range_ids(batch)
        else:
            raise NotImplementedError(type(p).__name__)
        # ONE device program: stable-sort rows by partition id; each
        # partition is then a contiguous range (searchsorted bounds since
        # ids are sorted).  One host sync for the boundary vector instead of
        # num_partitions sequential compactions (VERDICT r1 weak #4).
        n_parts = self.num_partitions
        schema = batch.schema   # capture only the schema, not the batch

        def sort_fn(cols, ids, num_rows):
            b = ColumnarBatch(list(cols), num_rows, schema)
            cap = b.capacity
            key = jnp.where(b.row_mask, ids.astype(jnp.int32), n_parts)
            perm = jax.lax.sort(
                (key, jnp.arange(cap, dtype=jnp.int32)),
                num_keys=1, is_stable=True)[1]
            from spark_rapids_tpu.ops.filterops import gather_columns

            sorted_cols = gather_columns(perm, b.row_mask[perm], b.columns)
            sorted_key = key[perm]
            bounds = jnp.searchsorted(
                sorted_key, jnp.arange(n_parts + 1, dtype=jnp.int32),
                side="left").astype(jnp.int32)
            return tuple(sorted_cols), bounds

        cols, bounds = self._cached_jit("_sort_jit", "partsort", sort_fn)(
            tuple(batch.columns), ids, jnp.int32(batch.num_rows))
        import numpy as _np

        bounds_np = _np.asarray(bounds).tolist()   # one transfer
        sorted_batch = ColumnarBatch(list(cols), batch.num_rows, schema)
        out = []
        for pid in range(n_parts):
            lo, hi = bounds_np[pid], bounds_np[pid + 1]
            out.append(sorted_batch.slice_rows(lo, hi - lo)
                       if hi > lo else
                       ColumnarBatch([c.slice_to(1) for c in cols], 0,
                                     batch.schema))
        return out

    def _hash_ids(self, batch: ColumnarBatch):
        schema = batch.schema
        keys, n_parts, ansi = (self.partitioning.keys,
                               self.num_partitions, self.ansi)

        def fn(cols, num_rows):
            b = ColumnarBatch(list(cols), num_rows, schema)
            ctx = EvalContext(b, ansi=ansi)
            key_cols = [k.eval_tpu(ctx) for k in keys]
            return spark_partition_ids(key_cols, n_parts)

        return self._cached_jit("_ids_jit", "hashids", fn)(
            tuple(batch.columns), jnp.int32(batch.num_rows))

    def _range_ids(self, batch: ColumnarBatch):
        """Range partitioning via sampled bounds (GpuRangePartitioner).

        Round-1 simplification: bounds from this batch's sorted sample."""
        from spark_rapids_tpu.ops.sortkeys import sort_permutation

        orders = self.partitioning.orders

        schema = batch.schema
        n_parts, ansi = self.num_partitions, self.ansi

        def fn(cols, num_rows):
            b = ColumnarBatch(list(cols), num_rows, schema)
            ctx = EvalContext(b, ansi=ansi)
            key_cols = [e.eval_tpu(ctx) for e, _ in orders]
            specs = [s for _, s in orders]
            perm = sort_permutation(key_cols, specs, b.row_mask)
            # rank of each row / rows-per-partition
            cap = b.capacity
            inv = jnp.zeros(cap, jnp.int32).at[perm].set(
                jnp.arange(cap, dtype=jnp.int32))
            per = jnp.maximum(
                (num_rows + n_parts - 1) // n_parts, 1)
            return jnp.clip(inv // per, 0, n_parts - 1)

        return self._cached_jit("_range_jit", "rangeids", fn)(
            tuple(batch.columns), jnp.int32(batch.num_rows))

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        """Shuffle through the manager: each input batch is a "map task"
        whose partition slices are written (serialized in MULTITHREADED
        mode — the Kudo wire-format path), then each reduce partition is
        assembled by the concat-friendly reader.

        Partition boundaries are preserved in output order so downstream
        per-partition operators see real reduce partitions."""
        from spark_rapids_tpu.plan.nodes import SinglePartitioning
        from spark_rapids_tpu.shuffle.manager import get_shuffle_manager

        if isinstance(self.partitioning, SinglePartitioning):
            # device-resident pipe: a single reduce partition receives every
            # map output in order, so the exchange is an identity over the
            # child's batches — no serialize/deserialize round trip (the
            # degenerate case of ICI shuffle mode 2's device-resident design)
            for b in self.children[0].execute_columnar():
                yield self._count_output(b)
            return
        mgr = get_shuffle_manager(self.conf)
        shuffle_id = mgr.register_shuffle()
        try:
            with self.metric("shuffleWriteTime").timed():
                for map_id, b in enumerate(
                        self.children[0].execute_columnar()):
                    mgr.write_map_output(shuffle_id, map_id,
                                         self.partition_batch(b))
            schema = self.output
            from spark_rapids_tpu.lifecycle.context import check_cancel

            for pid in range(self.num_partitions):
                # cooperative cancellation between reduce partitions: a
                # wide shuffle read must not outlive its query's deadline
                check_cancel()
                with self.metric("shuffleReadTime").timed():
                    out = mgr.read_partition(shuffle_id, pid, schema)
                if out is not None and out.num_rows > 0:
                    yield self._count_output(out)
        finally:
            mgr.unregister_shuffle(shuffle_id)


class TpuBroadcastExchangeExec(TpuExec):
    """GpuBroadcastExchangeExec analog: materialize + (on mesh) replicate."""

    def __init__(self, child: TpuExec):
        super().__init__([child])

    @property
    def output(self):
        return self.children[0].output

    def aot_output_rows(self):
        rows = self.aot_input_rows()
        return None if rows is None else [sum(rows)]

    def aot_output_caps(self):
        caps = super().aot_output_caps()
        return caps if caps is not None else self.aot_input_concat_caps()

    def aot_emits_single_batch(self):
        return True

    def execute_columnar(self):
        batches = list(self.children[0].execute_columnar())
        if not batches:
            return
        out = (batches[0] if len(batches) == 1
               else ColumnarBatch.concat(batches))
        yield self._count_output(out)


class TpuAdaptiveShuffleReaderExec(TpuExec):
    """GpuCustomShuffleReaderExec analog (general AQE, VERDICT r3 Next
    #8): reads an exchange's reduce partitions while RECORDING their
    measured rows/bytes, then coalesces adjacent small partitions up to
    the batch-size goal before emitting — the runtime-stats partition
    coalescing AQE performs on real clusters (fewer, right-sized batches
    for every downstream operator; on a compile-tunnel chip each elided
    partition is one fewer program launch).

    ``stats`` (per-partition (rows, bytes)) and ``decision`` are exposed
    for explain/metrics, mirroring TpuAdaptiveJoinExec."""

    def __init__(self, exchange: TpuShuffleExchangeExec,
                 target_bytes: int):
        super().__init__([exchange])
        self.target_bytes = target_bytes
        self.stats = []
        self.decision = None

    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        d = f" decided={self.decision}" if self.decision else ""
        return (f"TpuAdaptiveShuffleReader(target="
                f"{self.target_bytes}B){d}")

    def execute_columnar(self):
        from spark_rapids_tpu.columnar.batch import ColumnarBatch

        pending = []
        pending_bytes = 0
        n_in = 0
        n_out = 0
        for b in self.children[0].execute_columnar():
            n_in += 1
            nb = b.nbytes()
            self.stats.append((b.num_rows, nb))
            pending.append(b)
            pending_bytes += nb
            if pending_bytes >= self.target_bytes:
                n_out += 1
                out = (pending[0] if len(pending) == 1
                       else ColumnarBatch.concat(pending))
                pending, pending_bytes = [], 0
                yield self._count_output(out)
        if pending:
            n_out += 1
            yield self._count_output(
                pending[0] if len(pending) == 1
                else ColumnarBatch.concat(pending))
        self.decision = f"coalesced {n_in}->{n_out} partitions"
