"""TPU joins — sort-based gather-map equi-joins.

Reference analog (SURVEY.md §2.4 Joins): GpuHashJoin / GpuShuffledHashJoinExec
/ GpuBroadcastHashJoinExec / JoinGatherer / AbstractGpuJoinIterator, where
cuDF produces gather maps that are materialized in size-bounded chunks.

TPU-first redesign: the build side is compacted (valid keys only) and sorted
by packed key words; probes binary-search it (vectorized multiword
searchsorted — log2(n) lexicographic compare rounds, all rows in parallel).
The gather-map materialization is the same two-index expansion cuDF uses
(probe index from searchsorted over the pair-count prefix sum, build index
by offset within the match run).  Everything is jitted; only the total pair
count syncs to host (to pick the output capacity bucket) — the exact analog
of the reference's JoinGatherer.getTotalRows sizing step.

Sort-merge join at the plan level is converted to this shuffled-sort join —
mirroring GpuSortMergeJoinMeta, which converts SMJ to shuffled-hash on GPU.
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

import jax
from spark_rapids_tpu.perfcounters import tpu_jit
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import (
    DEFAULT_ROW_BUCKETS,
    DeviceColumn,
    round_up_bucket,
)
from spark_rapids_tpu.exec.base import TpuExec
from spark_rapids_tpu.expr.base import EvalContext, Expression
from spark_rapids_tpu.ops.filterops import compact_columns, gather_columns
from spark_rapids_tpu.ops.sortkeys import _column_key_words
from spark_rapids_tpu.plan.nodes import JoinType


def _lex_less(a_words: List[jax.Array], b_words: List[jax.Array],
              or_equal: bool) -> jax.Array:
    lt = jnp.zeros(a_words[0].shape, jnp.bool_)
    eq = jnp.ones(a_words[0].shape, jnp.bool_)
    for a, b in zip(a_words, b_words):
        lt = lt | (eq & (a < b))
        eq = eq & (a == b)
    return lt | eq if or_equal else lt


def _multiword_searchsorted(sorted_words: List[jax.Array], n_valid,
                            query_words: List[jax.Array],
                            side: str) -> jax.Array:
    """For each query row, the insertion point into the sorted build keys.

    Two strategies (perf-critical — the probe of every hash join):

    * merge-rank for large inputs: concat build+query words, ONE
      lax.sort, exclusive cumsum of build flags at query positions.
      lax.sort is a fused sorting network on TPU (~the cost of a few
      elementwise passes) while each binary-search step is a full-width
      gather; at 2M probe rows the gather loop measured ~800ms device
      time vs ~100ms for the shared sort (round-4 microbench).
    * the O(log n) gather loop for small inputs, where the sort's
      fixed cost would dominate.
    """
    n = sorted_words[0].shape[0]
    nq = query_words[0].shape[0]
    if n >= (1 << 14) or nq >= (1 << 14):
        return _merge_rank(sorted_words, n_valid, query_words, side)
    lo = jnp.zeros(nq, jnp.int32)
    hi = jnp.broadcast_to(n_valid.astype(jnp.int32), (nq,))
    steps = max(1, int(n).bit_length())
    for _ in range(steps):
        mid = (lo + hi) // 2
        midc = jnp.clip(mid, 0, n - 1)
        mid_words = [w[midc] for w in sorted_words]
        if side == "left":
            go_right = _lex_less(mid_words, query_words, or_equal=False)
        else:
            go_right = _lex_less(mid_words, query_words, or_equal=True)
        go_right = go_right & (mid < hi)
        lo = jnp.where(go_right, mid + 1, lo)
        hi = jnp.where(go_right, hi, mid)
    return lo


def _merge_rank(sorted_words: List[jax.Array], n_valid,
                query_words: List[jax.Array], side: str) -> jax.Array:
    """searchsorted via one shared sort: rank of each query among the
    valid sorted build keys.  Key layout per row:

      (invalid, word_0..word_k, tie) + iota payload

    where ``invalid`` pushes the build tail (rows >= n_valid) after every
    query and valid build row so they are never counted, and ``tie``
    orders a query before equal build keys for side=left (strict rank)
    or after them for side=right (inclusive rank)."""
    n = sorted_words[0].shape[0]
    nq = query_words[0].shape[0]
    b_inv = (jnp.arange(n, dtype=jnp.int32)
             >= n_valid.astype(jnp.int32)).astype(jnp.int32)
    q_inv = jnp.zeros(nq, jnp.int32)
    tie_b = jnp.full(n, 0 if side == "right" else 1, jnp.int32)
    tie_q = jnp.full(nq, 1 if side == "right" else 0, jnp.int32)
    words = [jnp.concatenate([b_inv, q_inv])]
    for sw, qw in zip(sorted_words, query_words):
        words.append(jnp.concatenate([sw, qw]))
    words.append(jnp.concatenate([tie_b, tie_q]))
    iota = jnp.arange(n + nq, dtype=jnp.int32)
    srt = jax.lax.sort(tuple(words) + (iota,), num_keys=len(words),
                       is_stable=False)
    pos = srt[-1]
    is_build = (pos < n).astype(jnp.int32)
    nb_before = jnp.cumsum(is_build) - is_build
    qpos = jnp.where(is_build == 1, nq, pos - n)
    return jnp.zeros(nq, jnp.int32).at[qpos].set(nb_before, mode="drop")


def _slots_to_probe_rows(excl, counts, out_cap: int) -> jax.Array:
    """probe_row[j] for every output pair slot j: scatter each matched
    probe row's index at its first slot, then a running-max scan.
    Replaces jnp.searchsorted(offsets, j) — the binary-search gather loop
    measured ~700ms device time at 2M rows while scatter+scan is ~80ms
    (round-4 microbench); scans and sorts are near-free on TPU."""
    n = counts.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    # excl is strictly increasing over count>0 rows -> distinct slots
    scatter_idx = jnp.where(counts > 0, excl, out_cap).astype(jnp.int64)
    m = jnp.full(out_cap, -1, jnp.int32).at[scatter_idx].set(
        iota, mode="drop")
    # lax.cummax lowers to a compact reduce-window; the generic
    # associative_scan's unrolled log-depth graph took ~100s of XLA
    # compile time at 1M rows on TPU (round-4 hang)
    pr = jax.lax.cummax(m)
    return jnp.clip(pr, 0, jnp.int32(max(n - 1, 0)))


def _key_words_of(key_cols: List[DeviceColumn]) -> List[jax.Array]:
    words: List[jax.Array] = []
    for kc in key_cols:
        words.extend(_column_key_words(kc))
    return words


class _SortedBuildSide:
    """Build-side state: valid-key rows sorted by key words."""

    def __init__(self, words, row_index, n_valid, batch):
        self.words = words            # sorted key words (capacity,)
        self.row_index = row_index    # original row per sorted pos
        self.n_valid = n_valid        # device scalar
        self.batch = batch            # the materialized build batch


_SUB_PARTITION_SEED = 100407   # decorrelated from exchange partitioning


class _MaterializedExec(TpuExec):
    """Leaf exec replaying already-materialized spillable batches (the
    per-bucket children of a sub-partitioned join)."""

    def __init__(self, spillables, schema: T.StructType):
        super().__init__([])
        self._spillables = spillables
        self._schema = schema

    @property
    def output(self):
        return self._schema

    def execute_columnar(self):
        for s in self._spillables:
            s.pin()
            try:
                b = s.get_batch()
            finally:
                s.unpin()
            yield b


class _BaseTpuJoinExec(TpuExec):
    # GpuShuffledHashJoinExec metric set: build + stream/probe time
    EXTRA_METRICS = {"buildTime": "MODERATE",
                     "joinTime": "MODERATE"}

    def __init__(self, left: TpuExec, right: TpuExec,
                 left_keys: List[Expression], right_keys: List[Expression],
                 join_type: JoinType, condition: Optional[Expression],
                 output_schema: T.StructType, ansi: bool = False,
                 sub_partition_bytes: int = 1 << 30):
        super().__init__([left, right])
        self.left_keys = left_keys
        self.right_keys = right_keys
        self.join_type = join_type
        self.condition = condition
        self._output = output_schema
        self.ansi = ansi
        self.sub_partition_bytes = sub_partition_bytes
        self._jit_cache = {}

    def _registry_scope(self):
        """Fingerprint prefix identifying this join's program family (the
        compilecache registry shares programs across exec instances with
        identical scope + local key), or None when an expression is not
        safely fingerprintable."""
        cached = getattr(self, "_reg_scope", False)
        if cached is not False:
            return cached
        from spark_rapids_tpu.compilecache.keys import (
            conf_fp,
            exprs_fp,
            schema_fp,
        )

        lk = exprs_fp(self.left_keys)
        rk = exprs_fp(self.right_keys)
        cond = exprs_fp(
            [self.condition] if self.condition is not None else [])
        scope = None
        if lk is not None and rk is not None and cond is not None:
            scope = ("join", type(self).__name__, self.join_type.value,
                     lk, rk, cond,
                     schema_fp(self.children[0].output),
                     schema_fp(self.children[1].output),
                     schema_fp(self._output), bool(self.ansi), conf_fp())
        self._reg_scope = scope
        return scope

    def _cached_jit(self, key, builder, unsafe=False, **jit_kw):
        if key not in self._jit_cache:
            from spark_rapids_tpu.compilecache.registry import (
                cached_jit_program,
            )

            scope = None if unsafe else self._registry_scope()
            self._jit_cache[key] = cached_jit_program(
                None if scope is None else scope + (key,), builder,
                label=f"{type(self).__name__}:{key}", **jit_kw)
        return self._jit_cache[key]

    @property
    def output(self):
        return self._output

    def describe(self):
        keys = ", ".join(f"{l.sql_string()}={r.sql_string()}"
                         for l, r in zip(self.left_keys, self.right_keys))
        return f"{self.node_name} {self.join_type.value} [{keys}]"

    # -- build side -----------------------------------------------------
    def _prepare_build(self, batch: ColumnarBatch, keys: List[Expression],
                       pre_ops=None, in_schema=None) -> _SortedBuildSide:
        """Sort the build side by packed key words (ONE program).

        ``pre_ops`` fuses a broadcast-side project/filter stage into this
        program in selection-mask mode: filtered rows sort to the invalid
        tail and are never probed — the stage costs no extra launch and no
        compaction scatter."""
        schema = in_schema or batch.schema
        fn = self._build_fn(schema, keys, pre_ops)
        if pre_ops is None:
            jitted = self._cached_jit(self._build_key(schema), fn)
            words, row_index, n_valid = jitted(tuple(batch.columns),
                                               jnp.int32(batch.num_rows))
            return _SortedBuildSide(words, row_index, n_valid, batch)
        from spark_rapids_tpu.compilecache.keys import (
            schema_fp,
            stage_ops_fp,
        )

        ops_fp = stage_ops_fp(pre_ops)
        jitted = self._cached_jit(
            ("build_preops", ops_fp, schema_fp(schema)), fn,
            unsafe=ops_fp is None)
        words, row_index, n_valid, bcols = jitted(
            tuple(batch.columns), jnp.int32(batch.num_rows))
        out_batch = ColumnarBatch(list(bcols), batch.num_rows,
                                  self._build_child().output)
        return _SortedBuildSide(words, row_index, n_valid, out_batch)

    def _build_key(self, schema):
        from spark_rapids_tpu.compilecache.keys import schema_fp

        return ("build", schema_fp(schema))

    def _build_fn(self, schema, keys, pre_ops=None):
        """The build-sort program body — shared by runtime and AOT.
        Captures only locals (never ``self``): the registry keeps these
        closures alive across queries and a self-reference would pin the
        whole exec subtree."""
        key_cols_src = keys
        ansi = self.ansi

        def fn(cols, num_rows):
            b = ColumnarBatch(list(cols), num_rows, schema)
            ctx = EvalContext(b, ansi=ansi)
            mask = b.row_mask
            for op in (pre_ops or []):
                b, mask = op.apply_masked(ctx, b, mask)
            ctx.batch = b
            key_cols = [k.eval_tpu(ctx) for k in key_cols_src]
            valid = mask
            for kc in key_cols:
                valid = valid & kc.validity
            words = _key_words_of(key_cols)
            # sort valid rows first by (is_invalid, words...)
            inv = (~valid).astype(jnp.int64)
            iota = jnp.arange(b.capacity, dtype=jnp.int32)
            out = jax.lax.sort(tuple([inv] + words + [iota]),
                               num_keys=1 + len(words), is_stable=True)
            sorted_words = list(out[1:-1])
            row_index = out[-1]
            n_valid = jnp.sum(valid.astype(jnp.int32))
            if pre_ops is None:
                return sorted_words, row_index, n_valid
            return sorted_words, row_index, n_valid, tuple(b.columns)

        return fn

    # -- probe ----------------------------------------------------------
    def _probe_fn(self, schema):
        """The probe-search program body — shared by runtime and AOT.
        Locals only; no ``self`` capture (see _build_fn)."""
        left_keys = self.left_keys
        ansi = self.ansi

        def fn(bwords, n_valid, cols, num_rows):
            b = ColumnarBatch(list(cols), num_rows, schema)
            ctx = EvalContext(b, ansi=ansi)
            key_cols = [k.eval_tpu(ctx) for k in left_keys]
            valid = b.row_mask
            for kc in key_cols:
                valid = valid & kc.validity
            qwords = _key_words_of(key_cols)
            lo = _multiword_searchsorted(list(bwords), n_valid, qwords, "left")
            hi = _multiword_searchsorted(list(bwords), n_valid, qwords, "right")
            counts = jnp.where(valid, hi - lo, 0)
            total = jnp.sum(counts.astype(jnp.int64))
            unmatched = b.row_mask & (counts == 0)
            n_unmatched = jnp.sum(unmatched.astype(jnp.int64))
            return lo, counts, total, unmatched, n_unmatched

        return fn

    def _probe_key(self, schema):
        from spark_rapids_tpu.compilecache.keys import schema_fp

        return ("probe", schema_fp(schema))

    def _probe_counts(self, build: _SortedBuildSide, batch: ColumnarBatch):
        jitted = self._cached_jit(self._probe_key(batch.schema),
                                  self._probe_fn(batch.schema))
        return jitted(tuple(build.words), build.n_valid,
                      tuple(batch.columns), jnp.int32(batch.num_rows))

    # -- materialization (gather maps -> output batch) -------------------
    @staticmethod
    def materialize_pairs(bwords_row_index, b_cols, p_cols, lo, counts,
                          unmatched, total, nrows, out_cap: int,
                          with_unmatched_probe: bool):
        """Traced gather-map expansion: (probe, build) pair columns for the
        matched pairs [+ null-extended unmatched probe rows].  Pure function
        of device operands + static (out_cap, with_unmatched_probe) so it
        can be inlined into a consumer's program (join->agg fusion)."""
        n = counts.shape[0]
        offsets = jnp.cumsum(counts.astype(jnp.int64))
        excl = offsets - counts.astype(jnp.int64)
        j = jnp.arange(out_cap, dtype=jnp.int64)
        probe_row = _slots_to_probe_rows(excl, counts, out_cap)
        k = j - excl[probe_row]
        build_pos = lo[probe_row].astype(jnp.int64) + k
        build_cap = bwords_row_index.shape[0]
        build_row = bwords_row_index[
            jnp.clip(build_pos, 0, build_cap - 1).astype(jnp.int32)]
        in_pairs = j < total
        probe_idx = jnp.where(in_pairs, probe_row, 0)
        if with_unmatched_probe:
            # unmatched probe rows appended after the pairs
            um_positions = jnp.cumsum(unmatched.astype(jnp.int64)) - 1
            um_slot = total + um_positions
            scatter_to = jnp.where(unmatched, um_slot,
                                   out_cap).astype(jnp.int64)
            probe_idx_full = jnp.zeros(out_cap, jnp.int32).at[
                jnp.clip(scatter_to, 0, out_cap)].set(
                jnp.arange(n, dtype=jnp.int32), mode="drop")
            probe_idx = jnp.where(in_pairs, probe_row, probe_idx_full)
        row_valid = j < nrows
        lcols = gather_columns(probe_idx, row_valid, list(p_cols))
        bcols = gather_columns(
            jnp.where(in_pairs, build_row, 0), row_valid & in_pairs,
            list(b_cols))
        return lcols, bcols

    def _materialize(self, build: _SortedBuildSide, probe: ColumnarBatch,
                     lo, counts, total_host: int, unmatched,
                     with_unmatched_probe: bool, unmatched_host: int):
        out_rows = total_host + (unmatched_host if with_unmatched_probe else 0)
        out_cap = round_up_bucket(max(out_rows, 1), DEFAULT_ROW_BUCKETS)

        def fn(bwords_row_index, b_cols, p_cols, lo, counts, unmatched,
               total, nrows):
            return _BaseTpuJoinExec.materialize_pairs(
                bwords_row_index, b_cols, p_cols, lo, counts, unmatched,
                total, nrows, out_cap, with_unmatched_probe)

        jitted = self._cached_jit(("mat", out_cap, with_unmatched_probe), fn)
        lcols, bcols = jitted(build.row_index,
                              tuple(build.batch.columns),
                              tuple(probe.columns), lo, counts, unmatched,
                              jnp.int64(total_host), jnp.int64(out_rows))
        return lcols, bcols, out_rows

    def _semi_anti(self, probe: ColumnarBatch, counts, anti: bool):
        schema = probe.schema   # never capture the device batch itself

        def fn(cols, counts, num_rows):
            b = ColumnarBatch(list(cols), num_rows, schema)
            keep = (counts == 0) if anti else (counts > 0)
            keep = keep & b.row_mask
            out, cnt = compact_columns(keep, b.columns)
            return tuple(out), cnt

        from spark_rapids_tpu.compilecache.keys import schema_fp

        jitted = self._cached_jit(("semi", anti, schema_fp(probe.schema)),
                                  fn)
        out, cnt = jitted(tuple(probe.columns), counts,
                          jnp.int32(probe.num_rows))
        # int(cnt) is irreducible: the compacted row count labels the
        # output batch and nothing else in this path syncs to fold it into
        return ColumnarBatch(list(out), int(cnt), self._output)

    # -- driver ----------------------------------------------------------
    @staticmethod
    def _concat_or_empty(batches, schema) -> ColumnarBatch:
        if not batches:
            from spark_rapids_tpu.columnar.batch import empty_batch

            return empty_batch(schema)
        return (batches[0] if len(batches) == 1
                else ColumnarBatch.concat(batches))

    def _build_child(self) -> TpuExec:
        return self.children[1]

    def _probe_child(self) -> TpuExec:
        return self.children[0]

    # -- plan-time AOT enumeration (compilecache/aot.py) -----------------
    def aot_programs(self):
        """Build-sort program (always enumerable when the build side's
        shape is static) and the probe-search program (enumerable when
        every key packs to one sort-key word, so the build-words operand
        shape is predictable).  The pair-materialization program is NOT
        enumerable: its output capacity is the runtime pair count."""
        from spark_rapids_tpu.compilecache.aot import (
            AotProgram,
            batch_caps,
            concat_caps,
            dummy_batch_args,
            dummy_columns,
            single_word_keys,
        )
        from spark_rapids_tpu.compilecache.registry import registry_enabled

        scope = self._registry_scope()
        if scope is None or not registry_enabled():
            return []
        out = []
        bchild, pchild = self._build_child(), self._probe_child()
        bschema = bchild.output
        bcaps = concat_caps(bchild)  # build side concats whole
        bcap = bcaps[0] if bcaps else None
        if bcap is not None:
            key = self._build_key(bschema)
            fn = self._build_fn(bschema, self.right_keys)

            def b_args(_cap=bcap, _schema=bschema):
                return [dummy_batch_args(_schema, _cap)]

            out.append(AotProgram(
                scope + (key,), lambda _fn=fn: (tpu_jit(_fn), None),
                b_args, f"join-build:{self.describe()[:40]}"))
        pcaps = batch_caps(pchild)
        if bcap is not None and pcaps \
                and single_word_keys(self.right_keys):
            pschema = pchild.output
            key = self._probe_key(pschema)
            fn = self._probe_fn(pschema)
            nwords = len(self.right_keys)

            def p_args(_bcap=bcap, _n=nwords, _schema=pschema,
                       _caps=tuple(pcaps)):
                import jax.numpy as jnp

                from spark_rapids_tpu.compilecache.aot import (
                    abstract_array,
                    abstract_scalar,
                )

                sets = []
                for c in _caps:
                    cols = dummy_columns(_schema, c)
                    if cols is None:
                        continue
                    bwords = tuple(abstract_array((_bcap,), jnp.int64)
                                   for _ in range(_n))
                    sets.append((bwords, abstract_scalar(jnp.int32),
                                 cols, abstract_scalar(jnp.int32)))
                return sets

            out.append(AotProgram(
                scope + (key,), lambda _fn=fn: (tpu_jit(_fn), None),
                p_args, f"join-probe:{self.describe()[:40]}"))
        return out

    # -- sub-partitioning (GpuSubPartitionHashJoin analog) ----------------
    def _sub_partition(self, spillables, keys, n_parts: int, side: str,
                       schema, fw):
        """Hash-bucket rows of spillable ``spillables`` into n_parts
        spillable lists.  Partition ids are computed ONCE per batch; the
        per-bucket compactions reuse them."""
        from spark_rapids_tpu.ops.hashing import spark_partition_ids

        ansi = self.ansi   # locals only: closures outlive the exec

        def ids_fn(cols, num_rows):
            b = ColumnarBatch(list(cols), num_rows, schema)
            ctx = EvalContext(b, ansi=ansi)
            key_cols = [k.eval_tpu(ctx) for k in keys]
            return spark_partition_ids(key_cols, n_parts,
                                       seed=_SUB_PARTITION_SEED)

        def slice_fn(cols, ids, num_rows, pid):
            b = ColumnarBatch(list(cols), num_rows, schema)
            keep = (ids == pid) & b.row_mask
            out, cnt = compact_columns(keep, b.columns)
            return tuple(out), cnt

        # side in the cache key: build and probe close over different key
        # expressions and schemas
        from spark_rapids_tpu.compilecache.keys import schema_fp

        sfp = schema_fp(schema)
        ids_j = self._cached_jit(("subpart_ids", n_parts, side, sfp),
                                 ids_fn)
        slice_j = self._cached_jit(("subpart_slice", n_parts, side, sfp),
                                   slice_fn)
        buckets = [[] for _ in range(n_parts)]
        for s in spillables:
            s.pin()
            try:
                b = s.get_batch()
                ids = ids_j(tuple(b.columns), jnp.int32(b.num_rows))
                for pid in range(n_parts):
                    cols, cnt = slice_j(tuple(b.columns), ids,
                                        jnp.int32(b.num_rows),
                                        jnp.int32(pid))
                    n = int(cnt)
                    if n:
                        buckets[pid].append(
                            fw.track(ColumnarBatch(list(cols), n, schema)))
            finally:
                s.unpin()
            s.close()
        return buckets

    def _execute_sub_partitioned(self, build_spillables,
                                 total_bytes: int) -> Iterator[ColumnarBatch]:
        """Build side exceeds the goal: hash both sides into buckets and
        join bucket-by-bucket so only ~1/P of the build is live at once."""
        from spark_rapids_tpu.memory.spill import get_spill_framework

        fw = get_spill_framework()
        n_parts = 1
        while n_parts * self.sub_partition_bytes < total_bytes:
            n_parts <<= 1
        n_parts = max(2, n_parts)
        bschema = self._build_child().output
        pschema = self._probe_child().output
        build_buckets = self._sub_partition(build_spillables,
                                            self.right_keys, n_parts,
                                            "build", bschema, fw)
        del build_spillables
        probe_buckets = self._sub_partition(
            [fw.track(b) for b in self._probe_child().execute_columnar()],
            self.left_keys, n_parts, "probe", pschema, fw)
        try:
            for pid in range(n_parts):
                if not build_buckets[pid] and not probe_buckets[pid]:
                    continue
                sub = TpuShuffledSymmetricHashJoinExec(
                    _MaterializedExec(probe_buckets[pid], pschema),
                    _MaterializedExec(build_buckets[pid], bschema),
                    self.left_keys, self.right_keys, self.join_type,
                    self.condition, self._output, self.ansi,
                    sub_partition_bytes=1 << 62)  # buckets never re-partition
                for out in sub.execute_columnar():
                    yield self._count_output(out)
                for s in build_buckets[pid] + probe_buckets[pid]:
                    s.close()
                build_buckets[pid] = []
                probe_buckets[pid] = []
        finally:
            # an abandoned generator (limit above the join) must not leave
            # tracked handles registered for the session
            for pid in range(n_parts):
                for s in build_buckets[pid] + probe_buckets[pid]:
                    s.close()

    def execute_columnar(self) -> Iterator[ColumnarBatch]:
        jt = self.join_type
        if jt == JoinType.RIGHT_OUTER:
            yield from self._execute_right_outer()
            return
        from spark_rapids_tpu.memory.retry import with_retry
        from spark_rapids_tpu.memory.spill import get_spill_framework

        fw0 = get_spill_framework()
        # track build batches as they stream in so the spill framework can
        # shed them during ingest (the oversized-build case is exactly when
        # that matters)
        build_spill = []
        total_build_bytes = 0
        try:
            for b in self._build_child().execute_columnar():
                total_build_bytes += b.nbytes()
                build_spill.append(fw0.track(b))
        except BaseException:
            for s in build_spill:
                s.close()
            raise
        if (total_build_bytes > self.sub_partition_bytes and self.left_keys
                and jt != JoinType.CROSS):
            yield from self._execute_sub_partitioned(build_spill,
                                                     total_build_bytes)
            return
        for s in build_spill:
            s.pin()
        try:
            build_batch = self._concat_or_empty(
                [s.get_batch() for s in build_spill],
                self._build_child().output)
        finally:
            for s in build_spill:
                s.unpin()
                s.close()
        del build_spill
        with self.metric("buildTime").timed():
            build = self._prepare_build(build_batch, self.right_keys)
        matched_build_any = None
        if jt == JoinType.FULL_OUTER:
            matched_build_any = jnp.zeros(build_batch.capacity, jnp.bool_)
        fw = get_spill_framework()

        def probe_one(probe: ColumnarBatch):
            """Per-probe-batch join; re-runnable and probe-splittable (the
            reference splits the stream side on SplitAndRetryOOM; FULL
            OUTER's coverage update is an idempotent OR)."""
            nonlocal matched_build_any
            lo, counts, total, unmatched, n_um = self._probe_counts(
                build, probe)
            if jt == JoinType.LEFT_SEMI:
                return self._semi_anti(probe, counts, anti=False)
            if jt == JoinType.LEFT_ANTI:
                return self._semi_anti(probe, counts, anti=True)
            with_um = jt in (JoinType.LEFT_OUTER, JoinType.FULL_OUTER)
            # ONE host round trip for both sizing scalars (the seed synced
            # total and n_um separately — BENCH_r05 counted the extra
            # round trip on every probe batch of qb_left_join); semi/anti
            # return above without paying the total sync at all
            from spark_rapids_tpu.perfcounters import sync_get

            total_host, um_host = (int(x)
                                   for x in sync_get((total, n_um)))
            if not with_um:
                um_host = 0
            if jt == JoinType.FULL_OUTER:
                matched_build_any = matched_build_any | \
                    self._covered_build_rows(build, lo, counts)
            if total_host + um_host == 0:
                return None
            lcols, bcols, nrows = self._materialize(
                build, probe, lo, counts, total_host, unmatched,
                with_um, um_host)
            out = ColumnarBatch(list(lcols) + list(bcols), nrows,
                                self._output)
            return self._apply_condition(out)

        for probe in self._probe_child().execute_columnar():
            with self.metric("joinTime").timed():
                outs = list(with_retry(fw.track(probe), probe_one))
            for out in outs:
                if out is not None:
                    yield self._count_output(out)
        if jt == JoinType.FULL_OUTER:
            tail = self._unmatched_build_tail(build_batch, build,
                                              matched_build_any)
            if tail is not None:
                yield self._count_output(tail)

    def _covered_build_rows(self, build: _SortedBuildSide, lo, counts):
        """bool per original build row: appeared in some pair (diff-array)."""
        def fn(row_index, lo, counts):
            n = row_index.shape[0]
            diff = jnp.zeros(n + 1, jnp.int32)
            has = counts > 0
            start = jnp.where(has, lo, n)
            end = jnp.where(has, lo + counts, n)
            diff = diff.at[start].add(1, mode="drop")
            diff = diff.at[end].add(-1, mode="drop")
            covered_sorted = jnp.cumsum(diff[:-1]) > 0
            out = jnp.zeros(n, jnp.bool_).at[row_index].set(
                covered_sorted, mode="drop")
            return out

        return self._cached_jit("covered", fn)(build.row_index, lo, counts)

    def _unmatched_build_tail(self, build_batch, build, matched_any):
        schema = build_batch.schema   # never capture the device batch

        def fn(cols, matched, num_rows):
            b = ColumnarBatch(list(cols), num_rows, schema)
            keep = b.row_mask & ~matched
            out, cnt = compact_columns(keep, b.columns)
            return tuple(out), cnt

        out, cnt = self._cached_jit("build_tail", fn)(
            tuple(build_batch.columns), matched_any,
            jnp.int32(build_batch.num_rows))
        n = int(cnt)
        if n == 0:
            return None
        # null left side
        lfields = self._output.fields[: len(self._probe_child().output)]
        lcols = []
        cap = build_batch.capacity
        for f in lfields:
            if isinstance(f.dataType, T.StringType):
                lcols.append(DeviceColumn(f.dataType,
                                          jnp.zeros(cap, jnp.bool_),
                                          chars=jnp.zeros((cap, 8), jnp.uint8),
                                          lengths=jnp.zeros(cap, jnp.int32)))
            else:
                lcols.append(DeviceColumn(
                    f.dataType, jnp.zeros(cap, jnp.bool_),
                    data=jnp.zeros(cap, T.storage_dtype(f.dataType))))
        return ColumnarBatch(lcols + list(out), n, self._output)

    def _execute_right_outer(self):
        """RIGHT OUTER = LEFT OUTER with sides swapped, columns reordered."""
        swapped_schema = T.StructType(
            list(self._build_child().output.fields)
            + [T.StructField(f.name, f.dataType, True)
               for f in self._probe_child().output.fields])
        swapped = TpuShuffledSymmetricHashJoinExec(
            self.children[1], self.children[0],
            self.right_keys, self.left_keys,
            JoinType.LEFT_OUTER, self.condition,
            swapped_schema, self.ansi,
            sub_partition_bytes=self.sub_partition_bytes)
        nl = len(self._build_child().output.fields)
        for b in swapped.execute_columnar():
            cols = b.columns[nl:] + b.columns[:nl]
            # right-outer output: left cols (nullable) then right cols
            reordered = T.StructType(
                [T.StructField(f.name, f.dataType, True)
                 for f in self._probe_child().output.fields]
                + list(self._build_child().output.fields))
            out = ColumnarBatch(cols, b.num_rows, reordered)
            yield self._count_output(self._apply_condition(out))

    def _apply_condition(self, batch: ColumnarBatch) -> ColumnarBatch:
        if self.condition is None or self.join_type != JoinType.INNER:
            return batch
        out_schema, cond, ansi = self._output, self.condition, self.ansi

        def fn(cols, num_rows):
            b = ColumnarBatch(list(cols), num_rows, out_schema)
            ctx = EvalContext(b, ansi=ansi)
            pred = cond.eval_tpu(ctx)
            keep = pred.data & pred.validity & b.row_mask
            out, cnt = compact_columns(keep, b.columns)
            return tuple(out), cnt

        jitted = self._cached_jit("cond", fn)
        out, cnt = jitted(tuple(batch.columns), jnp.int32(batch.num_rows))
        return ColumnarBatch(list(out), int(cnt), self._output)


class TpuShuffledSymmetricHashJoinExec(_BaseTpuJoinExec):
    """Shuffled join (post-exchange).  Name mirrors the reference's newer
    GpuShuffledSymmetricHashJoinExec; algorithm is the sorted-build probe."""


class TpuBroadcastHashJoinExec(_BaseTpuJoinExec):
    """Join against a broadcast build side (small table).  Single-process:
    the build child is materialized whole, exactly like the broadcast table
    the reference collects; on a mesh the build batch is replicated to every
    device (parallel/bcast)."""


class TpuCartesianProductExec(TpuExec):
    """CROSS join: index-arithmetic expansion (GpuCartesianProductExec)."""

    def __init__(self, left: TpuExec, right: TpuExec,
                 output_schema: T.StructType,
                 condition: Optional[Expression] = None, ansi: bool = False):
        super().__init__([left, right])
        self._output = output_schema
        self.condition = condition
        self.join_type = JoinType.INNER  # for _apply_condition reuse
        self.ansi = ansi
        self._jit_cache = {}

    _cached_jit = _BaseTpuJoinExec._cached_jit
    _apply_condition = _BaseTpuJoinExec._apply_condition

    def _registry_scope(self):
        cached = getattr(self, "_reg_scope", False)
        if cached is not False:
            return cached
        from spark_rapids_tpu.compilecache.keys import (
            conf_fp,
            exprs_fp,
            schema_fp,
        )

        cond = exprs_fp(
            [self.condition] if self.condition is not None else [])
        scope = None
        if cond is not None:
            scope = ("cartesian", cond,
                     schema_fp(self.children[0].output),
                     schema_fp(self.children[1].output),
                     schema_fp(self._output), bool(self.ansi), conf_fp())
        self._reg_scope = scope
        return scope

    @property
    def output(self):
        return self._output

    def execute_columnar(self):
        right_batches = list(self.children[1].execute_columnar())
        if not right_batches:
            return
        rbatch = (right_batches[0] if len(right_batches) == 1
                  else ColumnarBatch.concat(right_batches))
        for lb in self.children[0].execute_columnar():
            total = lb.num_rows * rbatch.num_rows
            if total == 0:
                continue
            out_cap = round_up_bucket(total, DEFAULT_ROW_BUCKETS)

            def fn(lcols, rcols, nright, total):
                j = jnp.arange(out_cap, dtype=jnp.int64)
                li = (j // nright).astype(jnp.int32)
                ri = (j % nright).astype(jnp.int32)
                valid = j < total
                lo = gather_columns(li, valid, list(lcols))
                ro = gather_columns(ri, valid, list(rcols))
                return tuple(lo + ro)

            jitted = self._cached_jit(("cart", out_cap), fn)
            cols = jitted(tuple(lb.columns), tuple(rbatch.columns),
                          jnp.int64(rbatch.num_rows), jnp.int64(total))
            out = ColumnarBatch(list(cols), total, self._output)
            if self.condition is not None:
                out = self._apply_condition(out)
            yield self._count_output(out)


class _ReplayExec(TpuExec):
    """Re-emits batches already materialized by the adaptive planner.

    Batches arrive as SPILLABLE handles (tracked while the runtime
    decision was pending, so an oversized build side can shed to host/disk
    instead of pinning HBM) and are closed once replayed."""

    def __init__(self, handles, output_schema):
        super().__init__([])
        self._handles = handles
        self._output = output_schema

    @property
    def output(self):
        return self._output

    def describe(self):
        return f"Replay[{len(self._handles)} batches]"

    def execute_columnar(self):
        for h in self._handles:
            yield h.get_batch()
            h.close()
        self._handles = []


def _logical_bytes(batches) -> int:
    """Row-weighted bytes (padding capacity excluded)."""
    total = 0
    for b in batches:
        cap = max(b.capacity, 1)
        total += int(sum(c.nbytes() for c in b.columns)
                     * (b.num_rows / cap))
    return total


class TpuAdaptiveJoinExec(TpuExec):
    """AQE runtime join-strategy switch (GpuCustomShuffleReaderExec /
    AQE re-optimization analog, SURVEY.md §2.2).

    Wraps a planned shuffled join whose children are exchanges.  At
    EXECUTION time the build side below its exchange materializes first;
    if its measured bytes fall under spark.sql.autoBroadcastJoinThreshold
    the join re-plans itself as a broadcast join with BOTH exchanges
    elided (runtime statistics beating the static planner — the point of
    AQE); otherwise the shuffled plan runs with the materialized batches
    replayed into its exchange, so nothing is computed twice."""

    def __init__(self, shuffled: "TpuShuffledSymmetricHashJoinExec",
                 threshold: int):
        super().__init__(list(shuffled.children))
        self.shuffled = shuffled
        self.threshold = threshold
        self.decision: Optional[str] = None

    @property
    def output(self):
        return self.shuffled.output

    def describe(self):
        d = f" decided={self.decision}" if self.decision else ""
        return (f"TpuAdaptiveJoin(threshold={self.threshold})"
                f"[{self.shuffled.describe()}]{d}")

    def execute_columnar(self):
        from spark_rapids_tpu.memory.spill import get_spill_framework

        left_ex, right_ex = self.shuffled.children
        build_inner = right_ex.children[0]
        fw = get_spill_framework()
        handles = []
        size = 0
        for b in build_inner.execute_columnar():
            size += _logical_bytes([b])
            handles.append(fw.track(b))
        if 0 <= self.threshold and size <= self.threshold:
            self.decision = f"broadcast({size}B)"
            bj = TpuBroadcastHashJoinExec(
                left_ex.children[0], _ReplayExec(handles,
                                                 build_inner.output),
                self.shuffled.left_keys, self.shuffled.right_keys,
                self.shuffled.join_type, self.shuffled.condition,
                self.shuffled.output, self.shuffled.ansi,
                sub_partition_bytes=self.shuffled.sub_partition_bytes)
            self.metrics.update(bj.metrics)
            yield from bj.execute_columnar()
            return
        self.decision = f"shuffled({size}B)"
        # the replay child is single-shot (handles close as they re-emit):
        # restore the real build subtree afterwards so a REPEATED execute
        # of this plan re-materializes instead of replaying closed handles
        # (round-5 on-chip finding: the second collect of a 20M-row qb
        # joined an EMPTY build side and silently dropped every match)
        right_ex.children[0] = _ReplayExec(handles, build_inner.output)
        try:
            yield from self.shuffled.execute_columnar()
        finally:
            right_ex.children[0] = build_inner
