"""Limit operators (GpuLocalLimitExec / GpuGlobalLimitExec analogs)."""
from __future__ import annotations

from spark_rapids_tpu.exec.base import TpuExec


class TpuLocalLimitExec(TpuExec):
    def __init__(self, n: int, child: TpuExec):
        super().__init__([child])
        self.n = n

    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        return f"TpuLocalLimit {self.n}"

    def execute_columnar(self):
        remaining = self.n
        for b in self.children[0].execute_columnar():
            if remaining <= 0:
                break
            if b.num_rows <= remaining:
                remaining -= b.num_rows
                yield self._count_output(b)
            else:
                yield self._count_output(b.slice_rows(0, remaining))
                remaining = 0


class TpuGlobalLimitExec(TpuLocalLimitExec):
    def describe(self):
        return f"TpuGlobalLimit {self.n}"


class TpuSampleExec(TpuExec):
    """Bernoulli sample (GpuSampleExec analog): one jitted program per
    batch computes the splitmix64 draw (same spec as Rand, offset by the
    running row position) and compacts kept rows."""

    def __init__(self, fraction: float, seed: int, child: TpuExec):
        super().__init__([child])
        self.fraction = fraction
        self.seed = seed

    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        return f"TpuSample fraction={self.fraction} seed={self.seed}"

    def execute_columnar(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        from spark_rapids_tpu.expr.misc import Rand
        from spark_rapids_tpu.ops.filterops import compact_columns

        offset = 0
        for b in self.children[0].execute_columnar():
            with self.metrics["opTime"].timed():
                z = Rand._u64_for_rows(self.seed, offset, b.capacity)
                u = (z >> np.uint64(11)).astype(np.float64) / float(1 << 53)
                keep = jnp.asarray(u < self.fraction) & b.row_mask
                cols, count = compact_columns(keep, b.columns)
                out = ColumnarBatch(list(cols), int(count), b.schema)
            offset += b.num_rows
            if out.num_rows:
                yield self._count_output(out)
