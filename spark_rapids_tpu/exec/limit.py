"""Limit operators (GpuLocalLimitExec / GpuGlobalLimitExec analogs)."""
from __future__ import annotations

from spark_rapids_tpu.exec.base import TpuExec


class TpuLocalLimitExec(TpuExec):
    def __init__(self, n: int, child: TpuExec):
        super().__init__([child])
        self.n = n

    @property
    def output(self):
        return self.children[0].output

    def describe(self):
        return f"TpuLocalLimit {self.n}"

    def execute_columnar(self):
        remaining = self.n
        for b in self.children[0].execute_columnar():
            if remaining <= 0:
                break
            if b.num_rows <= remaining:
                remaining -= b.num_rows
                yield self._count_output(b)
            else:
                yield self._count_output(b.slice_rows(0, remaining))
                remaining = 0


class TpuGlobalLimitExec(TpuLocalLimitExec):
    def describe(self):
        return f"TpuGlobalLimit {self.n}"
