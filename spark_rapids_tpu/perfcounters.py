"""Tunnel-independent performance accounting.

Reference analog: the reference tracks per-task GPU time / semaphore wait
(GpuTaskMetrics, SURVEY.md §5.5) but has no notion of *how many* kernel
launches or host round-trips a query costs, because on a local PCIe GPU
those are ~10µs.  On a tunnel-relayed TPU every program launch and every
device->host sync costs hundreds of ms, so the counts themselves — not the
wall time — are the portable truth about engine quality (VERDICT r3 Next
#1a).  These counters are identical on any backend; only per-event latency
differs.

Counters (process-global, reset per query via ``snapshot``/``since``):

- ``programs_launched`` — calls into a jitted stage function (every XLA
  executable dispatch the framework makes).
- ``compiles``          — launches that triggered a fresh XLA compile
  (jit cache miss), detected via the jit function's cache-size delta.
- ``host_syncs``        — device->host materializations: ``np.asarray`` /
  ``jax.device_get`` / ``int()``/``bool()``/``float()`` on device arrays.
  Counted by patching ``ArrayImpl.__array__``/``__index__``/scalar dunders.
- ``bytes_d2h`` / ``bytes_h2d`` — transfer volume in each direction.
- ``launch_wall_ns``    — wall time inside jitted calls (dispatch +, when
  the result is consumed synchronously, device compute).

Use :func:`tpu_jit` instead of ``jax.jit`` inside exec nodes; it is a
drop-in wrapper.  The dunder patches are installed at import and cost one
Python increment per event (~100ns) — negligible beside the 10µs-to-300ms
events they count.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

import jax

from spark_rapids_tpu.diagnostics import context as _DIAG

_LOCK = threading.Lock()

COUNTERS: Dict[str, int] = {
    "programs_launched": 0,
    "compiles": 0,
    "host_syncs": 0,
    "bytes_d2h": 0,
    "bytes_h2d": 0,
    "launch_wall_ns": 0,
    # compile cache (compilecache/): registry-level program reuse + wall
    # time spent inside fresh XLA compiles (inline or AOT-pool)
    "compile_cache_hits": 0,
    "compile_cache_misses": 0,
    "compile_wall_ns": 0,        # inline (critical-path) compile wall
    "aot_compiles": 0,
    "aot_compile_wall_ns": 0,    # background-pool compile wall
    "aot_compile_errors": 0,
    # resilience (stage-level fault domains, resilience/domain.py)
    "transient_retries": 0,
    "oom_restarts": 0,
    "runtime_fallbacks": 0,
    "breaker_trips": 0,
    "breaker_plan_fallbacks": 0,
    "query_fallbacks": 0,
    # I/O fault domain (io/faults.py, ISSUE 5): per-file scan tolerance
    # and the per-file device->native decoder fallback
    "files_skipped_corrupt": 0,
    "files_skipped_missing": 0,
    "file_decoder_fallbacks": 0,
    # query lifecycle (admission control / deadlines / cancellation,
    # lifecycle/ package)
    "queries_admitted": 0,
    "queries_rejected": 0,
    "queries_cancelled": 0,
    "deadline_trips": 0,
    "admission_wait_ns": 0,
    # transport-aware scan pipeline (ISSUE 6): bytes_h2d counts PHYSICAL
    # link bytes (compressed payloads count their compressed size);
    # bytes_h2d_logical counts the decoded/useful bytes those transfers
    # represent — the ratio is the transport win
    "bytes_h2d_logical": 0,
    "scan_transfer_ns": 0,        # wall inside scan H2D upload sites
    "pages_device_decompressed": 0,
    "chunk_decode_fallbacks": 0,  # compressed->decoded per-chunk falls
    # H2D prefetch ring (io/scan.py): bytes whose transfer fully
    # overlapped query compute, and wall the consumer stalled waiting on
    # an in-flight prefetch
    "bytes_h2d_overlapped": 0,
    "prefetch_stall_ns": 0,
    # device-resident hot-table cache (io/hot_cache.py)
    "hot_cache_hits": 0,
    "hot_cache_misses": 0,
    "hot_cache_evictions": 0,
    # telemetry tier (ISSUE 7, telemetry/): per-query SLO-target misses
    # and flight-recorder post-mortem bundles produced
    "slo_violations": 0,
    "postmortem_dumps": 0,
    # profile-driven cost model (ISSUE 8, profiling/): plan nodes the
    # calibration store matched / missed at plan time, the summed
    # predicted self-wall of the matched nodes, the measured self-wall
    # of those same nodes (the apples-to-apples denominator for
    # prediction error), and operator classes the qualification
    # advisory routed off the device at plan time
    "cost_model_hits": 0,
    "cost_model_misses": 0,
    "cost_model_predicted_wall_ns": 0,
    "cost_model_matched_actual_wall_ns": 0,
    "advisor_plan_fallbacks": 0,
    # out-of-core partitioned exchange (ISSUE 10): plan-time partition
    # sizing, wall inside partition-id/slice programs vs wall inside the
    # spill-backed queue (serialize/track/materialize), host-boundary
    # CRC blocks the queues produced, and AQE shuffle-read coalescing
    "exchange_partitions_planned": 0,
    "exchange_partition_ns": 0,
    "exchange_spill_ns": 0,
    "exchange_host_blocks": 0,
    "exchange_host_block_bytes": 0,
    "partitions_coalesced": 0,
    # whole-plan fusion (ISSUE 17, exec/fusion.py): pipeline-able
    # subtrees compiled as ONE jitted program at plan time, and collect
    # -boundary shrink programs elided because the padded transfer waste
    # stayed under fusion.collectShrinkMaxWasteBytes
    "subtrees_fused": 0,
    "collect_shrinks_elided": 0,
    # live progress tracking (ISSUE 12, progress/): watchdog-detected
    # query stalls (no operator advanced for progress.stallMs) and live
    # snapshots served (session.progress() + the /progress endpoint)
    "stalls_detected": 0,
    "progress_snapshots": 0,
    # overload governor (ISSUE 13, governor/): pressure state machine
    # transitions, deadline-aware queries shed at admission under RED,
    # cooperative pause-and-spill preemptions taken at batch-pull
    # boundaries, batch-size-goal shrinks applied under YELLOW/RED, and
    # the OOM-retry outcome split — a RED preemption pass taken instead
    # of halving vs a batch actually split
    "governor_transitions": 0,
    "queries_shed": 0,
    "preempt_pauses": 0,
    "degraded_batches": 0,
    "oom_retry_preempts": 0,
    "oom_retry_splits": 0,
    # ICI multi-chip shuffle (ISSUE 10): per-query collective-exchange
    # accounting — epochs through the mesh all-to-all stages, rows/bytes
    # exchanged device-to-device (never through the host), and the wall
    # inside the collective programs
    "ici_epochs": 0,
    "ici_rows_exchanged": 0,
    "ici_bytes_moved": 0,
    "ici_shuffle_ns": 0,
    # distributed cross-host tier (ISSUE 14, distributed/): elastic
    # membership (every worker join, incl. quarantined rejoins), LOST
    # declarations (missed heartbeats past workerLostMs or a dead
    # socket past the transient budget), monitor ticks that caught a
    # late heartbeat, reduce partitions re-placed + re-driven from the
    # producer-side spilled partition queues after a loss, and the
    # block traffic shipped to workers
    "workers_joined": 0,
    "worker_lost": 0,
    "worker_heartbeat_misses": 0,
    "partitions_replayed": 0,
    "dist_blocks_shipped": 0,
    "dist_block_bytes": 0,
    # gray-failure resilience (ISSUE 20, docs/distributed.md): hedged
    # page fetches launched after a soft-deadline miss, hedges the
    # producer-side lineage buffer won (first-complete-wins against
    # the slow remote), DEGRADED declarations (straggler demotion, not
    # loss), and pending partitions speculatively re-driven off a
    # DEGRADED worker onto healthy survivors
    "fetch_hedges": 0,
    "hedges_won": 0,
    "workers_degraded": 0,
    "speculative_redrives": 0,
    # cluster observability (ISSUE 15, docs/cluster_observability.md):
    # on-demand DUMP pulls of a worker's telemetry (ring + counters)
    # by the coordinator, and worker-side span events merged into
    # driver query event logs by trace id at collect end
    "dist_worker_dumps": 0,
    "dist_worker_spans_merged": 0,
    # crash-consistent driver recovery (ISSUE 16, docs/recovery.md):
    # journal WAL appends, exchange stages served from a prior
    # incarnation's committed checkpoint instead of re-executing,
    # queries that recovered at least one stage, damaged/unreadable
    # journal or checkpoint artifacts discarded during replay (each a
    # clean degrade to full re-execution), and checkpoint leases
    # retired past recovery.leaseTtlMs
    "journal_records_written": 0,
    "stages_recovered": 0,
    "queries_resumed": 0,
    "journal_recovery_discards": 0,
    "recovery_leases_expired": 0,
    # per-query resource accounting (ISSUE 18, accounting/): the global
    # halves of the bill exact-sum invariant — every spill-framework
    # charge site bumps the acct_* counter AND the owning query's bill
    # by the same amount, so summing bills reconciles against these
    # since() deltas exactly — plus bills retired at lifecycle exit and
    # regressions the sentinel flagged against signature baselines
    "acct_device_bytes_charged": 0,
    "acct_device_bytes_released": 0,
    "acct_spill_bytes_host": 0,
    "acct_spill_bytes_disk": 0,
    "acct_bytes_restored": 0,
    "bills_settled": 0,
    "perf_regressions_flagged": 0,
    # multi-tenant serving tier (ISSUE 19, serving/): fair-share
    # admissions granted by the weighted scheduler (vs plain FIFO),
    # result-fragment cache traffic, tenant-aware governor actions
    # (sheds targeting an over-quota tenant, preemptions targeting the
    # most over-share runner), and serving-session lifecycle
    "fair_share_admissions": 0,
    "serving_sessions_opened": 0,
    "serving_sessions_closed": 0,
    "result_cache_hits": 0,
    "result_cache_misses": 0,
    "result_cache_evictions": 0,
    "tenant_sheds": 0,
    "tenant_preempts": 0,
}


def bump(key: str, n: int = 1) -> None:
    """Thread-safe increment.  ``COUNTERS[k] += n`` is three bytecodes
    (load / add / store) and CPython may switch threads between them, so
    concurrent unguarded increments lose updates; every write in this
    module routes through ``_LOCK``."""
    # attribution happens INSIDE the counter lock so a bump is atomic
    # with respect to the diagnostics window: the recorder installs /
    # snapshots / closes under this same lock, so every bump lands
    # either fully inside the window (global delta AND per-op bucket) or
    # fully outside (neither) — the exact-sum invariant survives racing
    # background threads (lock order: _LOCK -> recorder._lock)
    with _LOCK:
        COUNTERS[key] = COUNTERS.get(key, 0) + n
        rec = _DIAG.RECORDER
        if rec is not None:
            rec.attribute(key, n)


def bump_unattributed(key: str, n: int = 1) -> None:
    """Global-only increment that deliberately BYPASSES recorder
    attribution: for values produced OUTSIDE any query window (e.g. a
    finish hook running after its own recorder already closed), where
    routing through ``bump`` would attribute them to a concurrently
    installed OTHER query's recorder and contaminate that query's log.
    The global delta of such a key can therefore exceed a window's
    attributed per-op sums.  Users: the profiling finish hook's
    matched-actual bump and an UNRECORDED collect's cost_model_*
    prediction bumps (docs/profiling.md)."""
    with _LOCK:
        COUNTERS[key] = COUNTERS.get(key, 0) + n


def snapshot() -> Dict[str, int]:
    with _LOCK:
        return dict(COUNTERS)


def since(snap: Dict[str, int]) -> Dict[str, int]:
    cur = snapshot()
    return {k: cur[k] - snap.get(k, 0) for k in cur}


def reset() -> None:
    with _LOCK:
        for k in COUNTERS:
            COUNTERS[k] = 0


class _CountingJit:
    """Wraps a ``jax.jit``-ed callable; counts launches and compiles.

    Compile detection is serialized per wrapper: the monotonic
    ``_seen`` high-water mark of the jit cache size is advanced under
    ``_detect_lock``, taken only on the miss path (cache size grew), so
    two threads racing the same uncompiled program attribute exactly one
    compile between them instead of two (or zero).  The compile COUNT is
    exact; ``compile_wall_ns`` attribution is approximate under
    concurrent mixed-shape calls on one wrapper (a cached call landing
    right after another thread's cache insertion can claim the compile
    and contribute its own small wall) — the count, not the wall, is the
    portable signal (module docstring)."""

    __slots__ = ("_jitted", "_detect_lock", "_seen")

    def __init__(self, jitted):
        self._jitted = jitted
        self._detect_lock = threading.Lock()
        try:
            self._seen = jitted._cache_size()
        except Exception:
            self._seen = 0

    def __call__(self, *args, **kwargs):
        jitted = self._jitted
        t0 = time.perf_counter_ns()
        out = jitted(*args, **kwargs)
        dt = time.perf_counter_ns() - t0
        compiled = 0
        n1 = jitted._cache_size()
        if n1 != self._seen:         # miss path only: serialize detection
            with self._detect_lock:
                if n1 > self._seen:
                    compiled = n1 - self._seen
                    self._seen = n1
                elif n1 < self._seen:
                    # the jit cache SHRANK (jax.clear_caches): this call
                    # re-traced, so count one compile and re-anchor the
                    # high-water mark instead of going silent until the
                    # cache regrows past the stale value
                    compiled = 1
                    self._seen = n1
        with _LOCK:
            COUNTERS["programs_launched"] += 1
            COUNTERS["launch_wall_ns"] += dt
            if compiled:
                COUNTERS["compiles"] += compiled
                # the compiling call's wall is ~all trace+XLA-compile time
                # (dispatch+execute are orders of magnitude smaller); this
                # is the inline twin of the AOT pool's measured wall
                COUNTERS["compile_wall_ns"] += dt
            # inside _LOCK: atomic with the diagnostics window (see bump)
            rec = _DIAG.RECORDER
            if rec is not None:
                rec.launch(dt, compiled)
        return out

    def __getattr__(self, name):  # lower/trace/eval_shape passthrough
        return getattr(self._jitted, name)


def tpu_jit(fn, **jit_kwargs):
    """Drop-in ``jax.jit`` replacement that feeds the perf counters."""
    return _CountingJit(jax.jit(fn, **jit_kwargs))


# ---------------------------------------------------------------------------
# host-sync counting: patch the device array's host-materialization dunders
# ---------------------------------------------------------------------------

def _install_sync_counters() -> bool:
    try:
        from jax._src import array as _jarray

        impl = _jarray.ArrayImpl
    except Exception:
        return False

    def _count(self):
        try:
            nbytes = self.nbytes
        except Exception:
            nbytes = 0
        counted_sync = not _in_sync_event()
        with _LOCK:
            if counted_sync:
                COUNTERS["host_syncs"] += 1
            COUNTERS["bytes_d2h"] += nbytes
            # inside _LOCK: atomic with the diagnostics window (see bump)
            rec = _DIAG.RECORDER
            if rec is not None:
                rec.d2h(nbytes, counted_sync)

    try:
        real_array = impl.__array__

        def counted_array(self, *a, **kw):
            _count(self)
            return real_array(self, *a, **kw)

        impl.__array__ = counted_array

        for dunder in ("__int__", "__float__", "__bool__", "__index__"):
            real = getattr(impl, dunder, None)
            if real is None:
                continue

            def make(real):
                def counted(self):
                    _count(self)
                    return real(self)

                return counted

            setattr(impl, dunder, make(real))
        return True
    except Exception:
        return False


SYNC_COUNTING = _install_sync_counters()


def count_h2d(nbytes: int, logical: Optional[int] = None) -> None:
    """Host->device transfer accounting (called from upload sites).

    ``nbytes`` is the PHYSICAL byte count crossing the link (for a
    compressed-transfer payload: the compressed size + descriptor
    arrays); ``logical`` is the decoded/useful size those bytes
    represent (defaults to ``nbytes`` for plain uploads)."""
    bump("bytes_h2d", int(nbytes))
    bump("bytes_h2d_logical", int(nbytes if logical is None else logical))


_tls = threading.local()


class sync_event:
    """Count one LOGICAL host round trip for a batched fetch.

    ``jax.device_get`` over a pytree materializes every leaf; counting each
    leaf's ``__array__`` as a separate sync would overstate the round trips
    the engine design costs.  Inside this context the per-buffer patch
    still accounts bytes_d2h but not host_syncs.

    Nested events count ONCE: a ``sync_get`` issued from inside another
    ``sync_event`` is part of the same logical round trip, so only the
    depth-0 entry bumps ``host_syncs`` (ISSUE 3 satellite — the old code
    double-counted every nested batched fetch)."""

    def __enter__(self):
        depth = getattr(_tls, "in_sync_event", 0)
        _tls.in_sync_event = depth + 1
        if depth == 0:
            self._t0 = time.perf_counter_ns()
            bump("host_syncs")
        return self

    def __exit__(self, *a):
        _tls.in_sync_event -= 1
        if _tls.in_sync_event == 0:
            rec = _DIAG.RECORDER
            if rec is not None:
                rec.sync_batched(time.perf_counter_ns() - self._t0)


def _in_sync_event() -> bool:
    return getattr(_tls, "in_sync_event", 0) > 0


def sync_get(tree):
    """Fetch a pytree of device arrays as ONE logical host sync."""
    with sync_event():
        return jax.device_get(tree)
