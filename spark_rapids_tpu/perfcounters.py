"""Tunnel-independent performance accounting.

Reference analog: the reference tracks per-task GPU time / semaphore wait
(GpuTaskMetrics, SURVEY.md §5.5) but has no notion of *how many* kernel
launches or host round-trips a query costs, because on a local PCIe GPU
those are ~10µs.  On a tunnel-relayed TPU every program launch and every
device->host sync costs hundreds of ms, so the counts themselves — not the
wall time — are the portable truth about engine quality (VERDICT r3 Next
#1a).  These counters are identical on any backend; only per-event latency
differs.

Counters (process-global, reset per query via ``snapshot``/``since``):

- ``programs_launched`` — calls into a jitted stage function (every XLA
  executable dispatch the framework makes).
- ``compiles``          — launches that triggered a fresh XLA compile
  (jit cache miss), detected via the jit function's cache-size delta.
- ``host_syncs``        — device->host materializations: ``np.asarray`` /
  ``jax.device_get`` / ``int()``/``bool()``/``float()`` on device arrays.
  Counted by patching ``ArrayImpl.__array__``/``__index__``/scalar dunders.
- ``bytes_d2h`` / ``bytes_h2d`` — transfer volume in each direction.
- ``launch_wall_ns``    — wall time inside jitted calls (dispatch +, when
  the result is consumed synchronously, device compute).

Use :func:`tpu_jit` instead of ``jax.jit`` inside exec nodes; it is a
drop-in wrapper.  The dunder patches are installed at import and cost one
Python increment per event (~100ns) — negligible beside the 10µs-to-300ms
events they count.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict

import jax

_LOCK = threading.Lock()

COUNTERS: Dict[str, int] = {
    "programs_launched": 0,
    "compiles": 0,
    "host_syncs": 0,
    "bytes_d2h": 0,
    "bytes_h2d": 0,
    "launch_wall_ns": 0,
    # compile cache (compilecache/): registry-level program reuse + wall
    # time spent inside fresh XLA compiles (inline or AOT-pool)
    "compile_cache_hits": 0,
    "compile_cache_misses": 0,
    "compile_wall_ns": 0,        # inline (critical-path) compile wall
    "aot_compiles": 0,
    "aot_compile_wall_ns": 0,    # background-pool compile wall
    "aot_compile_errors": 0,
    # resilience (stage-level fault domains, resilience/domain.py)
    "transientRetries": 0,
    "oomRestarts": 0,
    "runtimeFallbacks": 0,
    "breakerTrips": 0,
    "breakerPlanFallbacks": 0,
    "queryFallbacks": 0,
}


def bump(key: str, n: int = 1) -> None:
    """Thread-safe increment.  ``COUNTERS[k] += n`` is three bytecodes
    (load / add / store) and CPython may switch threads between them, so
    concurrent unguarded increments lose updates; every write in this
    module routes through ``_LOCK``."""
    with _LOCK:
        COUNTERS[key] = COUNTERS.get(key, 0) + n


def snapshot() -> Dict[str, int]:
    with _LOCK:
        return dict(COUNTERS)


def since(snap: Dict[str, int]) -> Dict[str, int]:
    cur = snapshot()
    return {k: cur[k] - snap.get(k, 0) for k in cur}


def reset() -> None:
    with _LOCK:
        for k in COUNTERS:
            COUNTERS[k] = 0


class _CountingJit:
    """Wraps a ``jax.jit``-ed callable; counts launches and compiles."""

    __slots__ = ("_jitted",)

    def __init__(self, jitted):
        self._jitted = jitted

    def __call__(self, *args, **kwargs):
        jitted = self._jitted
        n0 = jitted._cache_size()
        t0 = time.perf_counter_ns()
        out = jitted(*args, **kwargs)
        dt = time.perf_counter_ns() - t0
        compiled = jitted._cache_size() > n0
        with _LOCK:
            COUNTERS["programs_launched"] += 1
            COUNTERS["launch_wall_ns"] += dt
            if compiled:
                COUNTERS["compiles"] += 1
                # the compiling call's wall is ~all trace+XLA-compile time
                # (dispatch+execute are orders of magnitude smaller); this
                # is the inline twin of the AOT pool's measured wall
                COUNTERS["compile_wall_ns"] += dt
        return out

    def __getattr__(self, name):  # lower/trace/eval_shape passthrough
        return getattr(self._jitted, name)


def tpu_jit(fn, **jit_kwargs):
    """Drop-in ``jax.jit`` replacement that feeds the perf counters."""
    return _CountingJit(jax.jit(fn, **jit_kwargs))


# ---------------------------------------------------------------------------
# host-sync counting: patch the device array's host-materialization dunders
# ---------------------------------------------------------------------------

def _install_sync_counters() -> bool:
    try:
        from jax._src import array as _jarray

        impl = _jarray.ArrayImpl
    except Exception:
        return False

    def _count(self):
        try:
            nbytes = self.nbytes
        except Exception:
            nbytes = 0
        with _LOCK:
            if not _in_sync_event():
                COUNTERS["host_syncs"] += 1
            COUNTERS["bytes_d2h"] += nbytes

    try:
        real_array = impl.__array__

        def counted_array(self, *a, **kw):
            _count(self)
            return real_array(self, *a, **kw)

        impl.__array__ = counted_array

        for dunder in ("__int__", "__float__", "__bool__", "__index__"):
            real = getattr(impl, dunder, None)
            if real is None:
                continue

            def make(real):
                def counted(self):
                    _count(self)
                    return real(self)

                return counted

            setattr(impl, dunder, make(real))
        return True
    except Exception:
        return False


SYNC_COUNTING = _install_sync_counters()


def count_h2d(nbytes: int) -> None:
    """Host->device transfer accounting (called from upload sites)."""
    bump("bytes_h2d", int(nbytes))


_tls = threading.local()


class sync_event:
    """Count one LOGICAL host round trip for a batched fetch.

    ``jax.device_get`` over a pytree materializes every leaf; counting each
    leaf's ``__array__`` as a separate sync would overstate the round trips
    the engine design costs.  Inside this context the per-buffer patch
    still accounts bytes_d2h but not host_syncs."""

    def __enter__(self):
        bump("host_syncs")
        _tls.in_sync_event = getattr(_tls, "in_sync_event", 0) + 1
        return self

    def __exit__(self, *a):
        _tls.in_sync_event -= 1


def _in_sync_event() -> bool:
    return getattr(_tls, "in_sync_event", 0) > 0


def sync_get(tree):
    """Fetch a pytree of device arrays as ONE logical host sync."""
    with sync_event():
        return jax.device_get(tree)
