"""Match-span extraction on the byte DFA — regexp_replace / regexp_extract.

Reference analog: RegexParser.scala consumers GpuRegExpReplace /
GpuRegExpExtract (SURVEY.md §2.5).  The reference transpiles Java regex to
cuDF's backtracking VM; the TPU engine is a DFA, which yields
leftmost-LONGEST spans.  Java's backtracking engine yields leftmost-FIRST.
The two agree exactly on the subset accepted by ``compile_for_spans``:

  * no alternation anywhere (``a|b`` prefers the first branch in Java even
    when the second is longer);
  * greedy quantifiers only over SINGLE-BYTE atoms (a quantified group like
    ``(aaa){0,1}(aa){0,2}`` can backtrack to a shorter total than the
    longest);
  * no anchors (span search is positional);
  * no lazy/possessive quantifiers (already rejected by the parser).

Everything else falls back to CPU at plan time — the same
transpiler-reject contract RLike uses.

``match_lengths`` runs the anchored DFA from EVERY start position
simultaneously: a (rows, width) state matrix advanced over match offsets
with one `lax.scan`; step l gathers byte p+l for every start p.  O(width)
steps of O(rows*width) vector work — dense, scatter-free, TPU-shaped.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.regex.transpiler import (
    CompiledRegex,
    RAlt,
    RLit,
    RRep,
    RSeq,
    RegexUnsupported,
    _Parser,
    compile_regex,
)


def _check_spans_safe(node) -> None:
    if isinstance(node, RAlt):
        raise RegexUnsupported(
            "alternation is not supported for span extraction (Java is "
            "leftmost-first, the DFA is leftmost-longest)")
    if isinstance(node, RRep):
        if not isinstance(node.node, RLit):
            raise RegexUnsupported(
                "quantifier over a multi-byte atom is not supported for "
                "span extraction (backtracking may pick a shorter total)")
        return
    if isinstance(node, RSeq):
        for p in node.parts:
            if p == "$":
                raise RegexUnsupported("`$` inside a span pattern")
            _check_spans_safe(p)


def compile_for_spans(pattern: str) -> CompiledRegex:
    node, anchored_start, anchored_end = _Parser(pattern).parse()
    if anchored_start or anchored_end:
        raise RegexUnsupported(
            "anchors are not supported for span extraction")
    _check_spans_safe(node)
    return compile_regex(pattern, full_match=True)


def match_lengths(dfa: CompiledRegex, chars: jax.Array,
                  lengths: jax.Array) -> jax.Array:
    """Longest match length starting at each byte position.

    chars: (rows, w) uint8; lengths: (rows,) int32.
    Returns (rows, w+1) int32: best[p] = longest l with chars[p:p+l]
    matching the (fully anchored) DFA, or -1; column w covers the
    end-of-string position (zero-width matches there)."""
    rows, w = chars.shape
    table = jnp.asarray(dfa.table)          # (n_states, 256) int32
    accept = jnp.asarray(dfa.accept)
    start_accepts = bool(np.asarray(dfa.accept)[0])
    pos = jnp.arange(w + 1, dtype=jnp.int32)[None, :]      # start positions
    started = pos <= lengths[:, None]
    best0 = jnp.where(started & start_accepts, 0, -1).astype(jnp.int32)
    states0 = jnp.zeros((rows, w + 1), jnp.int32)          # DFA start = 0

    def step(carry, l):
        states, best = carry
        idx = pos[0][None, :] + l                          # byte p + l
        inb = idx < lengths[:, None]
        safe = jnp.clip(idx, 0, w - 1)
        byte = jnp.take_along_axis(chars, safe, axis=1).astype(jnp.int32)
        nxt = table[states, byte]
        # out-of-string bytes kill the run (no byte to consume)
        states = jnp.where(inb & started, nxt, jnp.int32(dfa.n_states - 2))
        acc = accept[states] & inb & started
        best = jnp.where(acc, l + 1, best)
        return (states, best), None

    (_, best), _ = jax.lax.scan(step, (states0, best0),
                                jnp.arange(w, dtype=jnp.int32))
    return best


def greedy_match_starts(best: jax.Array, lengths: jax.Array):
    """Java replaceAll scan: non-overlapping leftmost matches.

    Returns (matched, mlen): (rows, w+1) bool / int32.  A zero-width match
    consumes nothing but blocks another match at the same position."""
    rows, wp1 = best.shape

    def step(carry, p):
        next_allowed = carry
        b = best[:, p]
        can = (b >= 0) & (p >= next_allowed) & (p <= lengths)
        adv = jnp.maximum(b, 1)
        next_allowed = jnp.where(can, p + adv, next_allowed)
        return next_allowed, (can, jnp.where(can, b, -1))

    _, (matched, mlen) = jax.lax.scan(
        step, jnp.zeros(rows, jnp.int32),
        jnp.arange(wp1, dtype=jnp.int32))
    return matched.T, mlen.T


def match_length_bounds(pattern: str):
    """(min_len, max_len) of strings the span-safe pattern can match;
    max_len is None for unbounded quantifiers.  Used by
    regexp_extract_all's tag check (bounded element widths)."""
    node, _, _ = _Parser(pattern).parse()

    def bounds(nd):
        if isinstance(nd, RLit):
            return 1, 1
        if isinstance(nd, RSeq):
            lo = hi = 0
            for p in nd.parts:
                l2, h2 = bounds(p)
                lo += l2
                hi = None if hi is None or h2 is None else hi + h2
            return lo, hi
        if isinstance(nd, RAlt):
            los, his = zip(*(bounds(o) for o in nd.options))
            return min(los), (None if any(h is None for h in his)
                              else max(his))
        if isinstance(nd, RRep):
            l2, h2 = bounds(nd.node)
            return (l2 * nd.lo,
                    None if nd.hi is None or h2 is None else h2 * nd.hi)
        raise RegexUnsupported(f"bounds: {type(nd).__name__}")

    return bounds(node)
